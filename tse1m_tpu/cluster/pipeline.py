"""End-to-end device clustering pipeline with mesh sharding.

Single-device: one jitted chain items -> signatures -> band keys -> bucket
reps -> verified edges -> propagated labels, fed over the H2D link by a
wire-size-aware streaming layer: ids optionally quantized into a smaller
universe (encode.quantize_ids — b-bit-minwise argument, lossy but
ARI-neutral), near-duplicate rows base-delta encoded (cluster/encode.py)
when it pays, and every chunk bit-packed at its own adaptive width
(encode.pack_chunk).  Chunks stream double-buffered: a producer thread
packs chunk k+1 and has its device_put in flight while the main thread
runs MinHash on chunk k, so encode/transfer/compute overlap instead of
serializing (BENCH_r05: 1.86 s compute inside a 15.2 s wall — the wire
was the bottleneck).  Per-stage walls land in observability.StageRecorder
and `last_run_info["stages"]`.

Multi-device: MinHash + band keys stay row-sharded (embarrassingly
data-parallel); the bucket/verify/propagate tail is band-sharded with an
explicit `shard_map` kernel (cluster/sharded.py) — `all_to_all` re-shards
the keys so each device sorts only B/d bands, and label propagation
reduces across devices with `pmin`.  The mesh feed ships 24-bit packed
bytes (unpacked inside the shard_map kernel) when ids allow.  Labels are
bit-identical to the single-device path in both cases.
"""

from __future__ import annotations

import contextlib
import os
import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import StageRecorder, record_degradation, \
    record_last_stages
from ..resilience import (StageWatchdog, fault_point, is_device_loss,
                          is_resource_exhausted, run_with_deadline,
                          watchdog_enabled)
from ..utils.logging import get_logger
from .encode import (_AUTO_MIN_BYTES, _AUTO_MIN_DELTA_FRACTION,
                     _AUTO_QUANT_BITS, ChunkWire, chunk_wire_bits,
                     encode_delta, pack_bits_host, pack_chunk,
                     pack_delta_meta, quantize_ids, width_bits)
from .lsh import bucket_representatives, estimated_jaccard, propagate_labels
from .minhash import band_keys
from .schemes import (get_scheme, make_params, scheme_sig_and_keys,
                      scheme_sig_and_keys_packed)

log = get_logger("cluster.pipeline")


@dataclass(frozen=True)
class ClusterParams:
    n_hashes: int = 128
    n_bands: int = 16
    threshold: float = 0.5       # min estimated Jaccard to accept an edge
    n_iters: int = 12            # label-propagation safety cap (propagation
    #                              converges early via its global all-done
    #                              check, see lsh.propagate_labels; 12 jumps
    #                              bound worst-case 2^12-long rep chains)
    seed: int = 0
    use_pallas: str = "auto"     # auto | never | force | interpret
    block_n: int = 512
    # H2D double-buffering: split the item axis into this many chunks and
    # stream each one — chunk i+1's host pack + device_put run on a
    # producer thread while MinHash runs on chunk i.  0 = auto (chunk when
    # items exceed _CHUNK_BYTES), 1 = off.
    h2d_chunks: int = 0
    # Producer-thread overlap for the chunked stream.  False falls back to
    # the sequential per-chunk loop (same chunks, same labels) — the A/B
    # lever for the chaos tests and for debugging thread-related issues.
    overlap: bool = True
    # H2D payload encoding (cluster/encode.py): 'auto' base-delta-encodes
    # large inputs when enough rows are near-duplicates; 'delta' forces
    # it; 'pack24' (historical name) ships the plain lane.  Either way
    # every lane is adaptively bit-packed per chunk.  Labels are
    # bit-identical across encodings (hub election is by original index —
    # lsh.bucket_representatives).
    encoding: str = "auto"
    # Lossy wire quantization: hash ids into a 2^b universe before
    # anything ships (encode.quantize_ids).  0 = auto (engage
    # _AUTO_QUANT_BITS when items exceed _AUTO_MIN_BYTES), -1 = never,
    # 1..32 = forced width.  Applied identically to every encoding path,
    # so cross-encoding label parity is preserved; accuracy is gated by
    # the bench's ari_vs_planted >= 0.98.
    wire_quant_bits: int = 0
    # Persistent content-addressed signature store (cluster/store.py):
    # a directory path enables the warm path — probe cached MinHash
    # signatures by row content hash, ship only the novel tail, and on
    # an accreted re-run merge labels on host instead of rebuilding band
    # tables.  None (default) = the cold path, byte-for-byte unchanged.
    sig_store: str | None = None
    # Warm-merge engagement ceiling: the host union-find label merge
    # runs when the appended tail is at most this fraction of the input
    # (the ≤1%-novel continuous-fuzzing case, with headroom); beyond it
    # the store still reuses cached signatures but re-runs banded LSH +
    # propagation on device over the full union.
    merge_max_novel: float = 0.05
    # Wire v3, lever 1 — host-side one-permutation LSH prefilter
    # (cluster/prefilter.py): bucket rows by cheap b-bit band keys on
    # host and drop rows bucketed singleton in every band (they gain no
    # verified edge on device and label themselves).  'auto' engages on
    # large storeless runs with a positive threshold; 'on' forces it
    # (still storeless-only — it refuses under a mesh or a sig_store);
    # 'off' never.  Labels are CI-asserted elementwise-equal to the
    # unfiltered path.
    prefilter: str = "auto"
    # Wire v3, lever 2 — static-table rANS entropy coding of the wire
    # lanes (cluster/entropy.py): 'auto' codes any lane/chunk whose
    # measured frame beats its bit-packed form (uniform lanes fall back
    # to the plain pack, so v3 never regresses v2); 'force' codes every
    # lane regardless of the win threshold (tests/CI); 'off' ships the
    # v2 bit-packed format.  Choice is per chunk/lane and label-
    # invariant either way.
    entropy: str = "auto"
    # Signature kernel family (cluster/schemes.py): 'kminhash' is the
    # original K-permutation multiply-shift family (bit-compatible with
    # every pre-scheme store/checkpoint); 'cminhash' is one-permutation
    # C-MinHash + densification (~n_hashes x fewer hash evaluations per
    # row); 'weighted' runs the one-permutation kernel over host-side
    # replica-expanded rows (schemes.expand_weighted) for hit-count-
    # weighted coverage similarity.  Joins the store/checkpoint policy
    # tuple, so mixed-scheme stores refuse exactly like mixed-seed ones.
    scheme: str = "kminhash"


# Observability surface for bench.py: stats of the last single-host
# cluster_sessions call (encoding chosen, lane sizes, wire bytes, host
# encode seconds, per-stage walls under "stages").  A plain dict,
# overwritten per call — not an API.
last_run_info: dict = {}


def _cluster_from_sig(sig, keys, threshold: float, n_iters: int):
    reps = bucket_representatives(keys)
    est = estimated_jaccard(sig, reps)
    self_idx = jnp.arange(sig.shape[0], dtype=jnp.int32)[:, None]
    valid = (est >= threshold) & (reps != self_idx)
    return propagate_labels(reps, valid, n_iters=n_iters)


# Module-level jit wrappers: wrapping inside cluster_sessions would key the
# compile cache to a fresh function object per call and retrace every time.
_cluster_from_sig_jit = jax.jit(
    _cluster_from_sig, static_argnames=("threshold", "n_iters"))


@jax.jit
def _decode_delta_raw(full_d, rep_d, counts_d, pos_d, val_d):
    """Delta lane -> [D, S] uint32 rows, on device.

    Gather each delta row's base from the decoded full lane, then scatter
    its (position, value) diffs.  Flat diff stream is CSR-style: per-row
    counts cumsum to offsets; each flat slot finds its row by searchsorted.
    """
    offsets = jnp.cumsum(counts_d.astype(jnp.int32))
    t = jnp.arange(pos_d.shape[0], dtype=jnp.int32)
    row = jnp.searchsorted(offsets, t, side="right").astype(jnp.int32)
    base = full_d[rep_d.astype(jnp.int32)]
    return base.at[row, pos_d.astype(jnp.int32)].set(
        val_d.astype(jnp.uint32), mode="drop")


@partial(jax.jit, static_argnames=("n", "threshold", "n_iters"))
def _cluster_encoded_labels(sig, keys, mask_bytes, n: int, threshold: float,
                            n_iters: int):
    """Cluster rows that sit in lane order and return labels in ORIGINAL
    order, equal elementwise to the unencoded path's.

    ``mask_bytes`` is the encoder's 1-bit-per-row membership mask
    (little-endian); cumsums of it reconstruct both permutations, so the
    wire cost of reordering is n/8 bytes instead of 4n.  Hub election by
    original index (see bucket_representatives) keeps the verified edge
    set — and therefore the components and the min-original-index labels —
    identical to a run without the encoder.
    """
    bits = ((mask_bytes[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :])
            & 1).reshape(-1)[:n].astype(jnp.int32)  # 1 = delta lane
    n_full_dyn = n - jnp.sum(bits)
    dr = jnp.cumsum(bits) - bits          # exclusive cumsum: delta rank
    fr = jnp.cumsum(1 - bits) - (1 - bits)
    lane_of = jnp.where(bits == 1, n_full_dyn + dr, fr).astype(jnp.int32)
    orig_of = jnp.zeros(n, jnp.int32).at[lane_of].set(
        jnp.arange(n, dtype=jnp.int32))
    reps = bucket_representatives(keys, orig=orig_of, lane_of=lane_of)
    est = estimated_jaccard(sig, reps)
    self_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    valid = (est >= threshold) & (reps != self_idx)
    lab = propagate_labels(reps, valid, n_iters=n_iters)  # lane-space ids
    cmin = jnp.full(n, n, jnp.int32).at[lab].min(orig_of)
    return cmin[lab][lane_of]


def _validate_encoding(params: ClusterParams) -> None:
    get_scheme(params.scheme)
    if params.encoding not in ("auto", "delta", "pack24"):
        raise ValueError(f"unknown encoding {params.encoding!r}; "
                         "expected auto | delta | pack24")
    if params.entropy not in ("auto", "off", "force"):
        raise ValueError(f"unknown entropy mode {params.entropy!r}; "
                         "expected auto | off | force")
    if params.prefilter not in ("auto", "off", "on"):
        raise ValueError(f"unknown prefilter mode {params.prefilter!r}; "
                         "expected auto | off | on")
    if params.prefilter == "on" and params.sig_store:
        raise ValueError(
            "ClusterParams.prefilter='on' is storeless-only: the store "
            "must cache a signature for every row, and prefiltered rows "
            "never compute one. Use prefilter='auto' (which disables "
            "itself under a sig_store) or drop the store.")
    if params.prefilter == "on" and params.threshold <= 0:
        raise ValueError(
            "ClusterParams.prefilter='on' needs threshold > 0: with no "
            "signature verification every proposed edge is accepted, so "
            "bucket isolation proves nothing about labels.")


def _quant_bits(items: np.ndarray, params: ClusterParams) -> int:
    """Effective wire_quant_bits under the policy; 0 = off/no gain.

    Storeless runs additionally clamp to the degraded floor a previous
    run's RESOURCE_EXHAUSTED quant-drop persisted to the machine
    calibration (the second degradation rung, below) — the next run
    starts at a wire width the device is known to hold.  Store-enabled
    runs never clamp: the store policy key carries quant_bits, and a
    drifting policy would refuse (or worse, poison) the cache."""
    b = params.wire_quant_bits
    if b < 0 or items.size == 0:
        return 0
    if b == 0:
        if items.nbytes < _AUTO_MIN_BYTES:
            b = 0
        else:
            b = _AUTO_QUANT_BITS
    if b and width_bits(int(items.max())) <= b:
        b = 0  # already at or below the target universe
    if params.sig_store or params.wire_quant_bits < 0:
        return b
    floor = _degraded_quant_floor()
    if floor and (b == 0 or floor < b) \
            and items.size and width_bits(int(items.max())) > floor:
        return floor
    return b


def _maybe_quantize(items: np.ndarray,
                    params: ClusterParams) -> tuple[np.ndarray, int]:
    """Apply the wire_quant_bits policy; returns (items, effective bits)
    with bits == 0 when quantization is off or gains nothing."""
    b = _quant_bits(items, params)
    return (quantize_ids(items, b) if b else items), b


def _plan_wire(items: np.ndarray, params: ClusterParams,
               qbits_override: int | None = None):
    """(items, enc, qbits): the single-host wire plan.

    ``qbits_override``: the prefiltered paths pass the quantization
    decision made over the FULL row set — the kept subset must ship in
    exactly the universe the unfiltered run would have used, or label
    parity breaks through the auto thresholds re-resolving on the
    smaller input.

    Order matters: the delta sketch groups on RAW ids — a quantized
    universe collapses its (min, max) hash keys into a few hundred
    distinct values, so chance collisions flood the verifier and the
    encoder declines.  Quantization then applies elementwise to whatever
    actually ships (full/val lanes, or the plain chunks).  Because
    quantize_ids is per-value deterministic, delta decode reconstructs
    exactly ``quantize_ids(items)`` on both paths, preserving
    cross-encoding label parity."""
    from dataclasses import replace

    enc = _maybe_encode(items, params)
    qbits = (qbits_override if qbits_override is not None
             else _quant_bits(items, params))
    if qbits:
        if enc is not None:
            enc = replace(enc,
                          full_rows=quantize_ids(enc.full_rows, qbits),
                          val_flat=quantize_ids(enc.val_flat, qbits))
        else:
            items = quantize_ids(items, qbits)
    return items, enc, qbits


def _maybe_encode(items: np.ndarray, params: ClusterParams):
    """Apply the ClusterParams.encoding policy; None = ship plain lanes."""
    _validate_encoding(params)
    if params.encoding == "pack24":
        return None
    if params.encoding == "auto" and items.nbytes < _AUTO_MIN_BYTES:
        return None
    frac = _AUTO_MIN_DELTA_FRACTION if params.encoding == "auto" else 0.0
    return encode_delta(items, min_delta_fraction=frac)


# Auto-chunking threshold for H2D double-buffering: one chunk per
# _CHUNK_BYTES of items, capped at _MAX_CHUNKS.  The cap is tuned for a
# remote/tunneled PJRT link (round-4 sweep at 1M x 64: 8 chunks throttled
# the link to ~21 MB/s vs ~27 MB/s for big single puts; 4 chunks kept big-
# put bandwidth while still overlapping the ~1.8 s device compute behind
# the transfer).
_CHUNK_BYTES = 48 * 1024 * 1024
_MAX_CHUNKS = 4

# Ids at or above this value are shipped raw uint32 (the adaptive packer
# refuses to pack the chunk) — the historical pack24 kill switch, kept as
# a monkeypatchable escape hatch for the raw-wire path.
_PACK_LIMIT = 1 << 24


def should_pack24(items: np.ndarray) -> bool:
    """True when `items` ids all fit the 24-bit universe (below
    _PACK_LIMIT).  The adaptive packer (encode.pack_chunk) has superseded
    this as the single-host wire decision; it remains THE mesh-feed pack
    decision and a compat probe for external callers."""
    return bool(items.size) and bool(items.max() < _PACK_LIMIT)


def _stream_plan(items: np.ndarray, params: ClusterParams) -> int:
    """Chunk step — THE chunking policy, shared by the streamed, resumable
    and bench-probe (`wire_payloads`) paths so their chunks always align.
    step >= n means single-shot (chunking off or input too small to
    double-buffer); chunks land on block_n boundaries so the pallas path
    pads at most the final chunk.  A chunk byte size that survived a
    previous run's RESOURCE_EXHAUSTED halving (persisted to the machine
    calibration file) clamps the plan, so the next run starts at a size
    the device is known to hold."""
    n = items.shape[0]
    n_chunks = params.h2d_chunks
    if n_chunks == 0:
        n_chunks = int(min(_MAX_CHUNKS, max(1, items.nbytes // _CHUNK_BYTES)))
    if n_chunks <= 1 or n < 2 * params.block_n:
        step = max(n, 1)
    else:
        step = -(-n // n_chunks)
        step = -(-step // params.block_n) * params.block_n
    return _apply_calibrated_step(step, items, params)


def _apply_calibrated_step(step: int, items: np.ndarray,
                           params: ClusterParams) -> int:
    """Clamp the planned step to the calibrated surviving chunk size."""
    if items.size == 0:
        return step
    from ..utils.calibration import calibration_path, load_calibration

    cal_bytes = load_calibration(calibration_path())["wire"].get(
        "chunk_bytes")
    if not cal_bytes:
        return step
    row_bytes = int(items.shape[1]) * items.itemsize
    cal_step = max(1, int(cal_bytes) // max(row_bytes, 1))
    if cal_step >= step:
        return step
    if cal_step >= 2 * params.block_n:
        cal_step = (cal_step // params.block_n) * params.block_n
    return max(cal_step, 1)


# -- degradation ladder ------------------------------------------------------
#
# The streaming loop's answer to the three long-run failure classes the
# retry engine alone cannot handle:
#
# - **Memory pressure** (XLA RESOURCE_EXHAUSTED): halve the chunk step,
#   re-pack the remaining rows from the host-side buffer and resume —
#   completed chunks' device results are kept, and the surviving size is
#   persisted to the machine calibration so the next run starts there.
# - **Stalls** (a hung H2D put over the tunneled link, hung device
#   compute): the StageWatchdog cancels the attempt past an adaptive
#   budget derived from the measured link rate and retries; the fault
#   plane's `stall` kind at the `pipeline.h2d` / `pipeline.compute`
#   seats forces this in chaos tests.
# - **Device loss**: after repeated non-OOM device failures the run
#   fails over to the CPU backend mid-stream (`jax.default_device`) and
#   continues — the resumable checkpoint path picks up on the fallback.
#
# Every rung fires a degradation event (observability plane), surfaced
# in run_manifest.json and the bench `degradation_*` keys.  Labels are
# invariant under every rung: chunking only changes how rows ship, and
# MinHash is row-independent.

def _halved_step(step: int, params: ClusterParams) -> int | None:
    """The next rung down the chunk-size ladder; None when out of rungs."""
    if step <= 16:
        return None
    new = -(-step // 2)
    if new >= 2 * params.block_n:
        new = (new // params.block_n) * params.block_n
    return new if new < step else None


def _persist_chunk_bytes(step: int, items: np.ndarray) -> None:
    """Record the surviving chunk byte size so the next run's
    `_stream_plan` starts below the observed memory ceiling."""
    from ..utils.calibration import calibration_path, update_calibration

    if items.size == 0:
        return
    row_bytes = int(items.shape[1]) * items.itemsize
    update_calibration(calibration_path(),
                       wire={"chunk_bytes": int(step) * row_bytes})


# Second degradation rung, tried BEFORE chunk-halving on storeless
# streams: drop wire_quant_bits one step down the b-bit-minwise ladder
# (arXiv:1205.2958 — 8-10 bits retain clustering accuracy), re-quantize
# from the raw host buffer, and restart the stream in the smaller
# universe.  The surviving width persists to the machine calibration so
# the next run starts degraded; a later run that completes cleanly at
# the degraded width restores full fidelity (the device healed).
_QUANT_RUNGS = (10, 8)


def _next_quant_rung(bits: int) -> int | None:
    """One step down the quantization ladder; None when out of rungs.
    ``bits <= 0`` (quantization off) engages the first rung."""
    for rung in _QUANT_RUNGS:
        if bits <= 0 or rung < bits:
            return rung
    return None


def _degraded_quant_floor() -> int:
    """The persisted degraded wire width (0 = none)."""
    from ..utils.calibration import calibration_path, load_calibration

    v = load_calibration(calibration_path())["wire"].get("quant_bits")
    return int(v) if v else 0


def _persist_quant_bits(bits: int) -> None:
    from ..utils.calibration import calibration_path, update_calibration

    update_calibration(calibration_path(), wire={"quant_bits": int(bits)})


def _restore_quant_bits() -> None:
    """Device heal: clear the degraded floor so the next run ships full-
    fidelity ids again."""
    from ..utils.calibration import calibration_path, update_calibration

    update_calibration(calibration_path(), wire={"quant_bits": None})


def _make_watchdog() -> StageWatchdog:
    """The run's stage watchdog, its H2D budget seeded from the persisted
    link probe (bench_link's measured MB/s) when available."""
    from ..utils.calibration import calibration_path, load_calibration

    seed = {}
    mbps = load_calibration(calibration_path())["wire"].get("h2d_MBps")
    if mbps:
        seed["h2d"] = float(mbps) * 1e6
    return StageWatchdog(seed_rates=seed)


def _compute_budget_s() -> float:
    """Absolute deadline for one chunk's device compute wait (hung
    dispatch / dead link under a silent backend).  0 disables."""
    if not watchdog_enabled():
        return 0.0
    return float(os.environ.get("TSE1M_WATCHDOG_COMPUTE_BUDGET_S", 600.0))


class _DeviceSupervisor:
    """Per-run device-failure ledger: bounded retries, then a mid-run
    TPU->CPU failover for the remainder of the stream."""

    _FAIL_LIMIT = 2    # failures before the CPU failover engages
    _MAX_RETRIES = 5   # total failures before the run gives up

    def __init__(self) -> None:
        self.failures = 0
        self.fallback = False

    def device_ctx(self):
        """Context for device work: the CPU fallback device once engaged,
        a no-op before that (or when no CPU backend exists)."""
        if not self.fallback:
            return contextlib.nullcontext()
        try:
            cpu = jax.devices("cpu")[0]
        except RuntimeError:
            return contextlib.nullcontext()
        return jax.default_device(cpu)

    def note_failure(self, site: str, e: BaseException) -> bool:
        """Record one device failure; True = retry (possibly on the
        fallback), False = out of budget, caller re-raises."""
        self.failures += 1
        record_degradation("device_retry", site=site,
                           detail={"error": f"{type(e).__name__}: {e}"[:200],
                                   "failures": self.failures})
        if self.failures >= self._FAIL_LIMIT and not self.fallback:
            self.fallback = True
            record_degradation("device_failover", site=site,
                               detail={"to": "cpu",
                                       "failures": self.failures})
            log.warning("%s: %d device failure(s); failing over to CPU for "
                        "the remainder of this run", site, self.failures)
        return self.failures <= self._MAX_RETRIES


@partial(jax.jit, static_argnames=("n", "bits"))
def _unpack_bits(packed, n: int, bits: int, offset):
    """uint8 bit stream -> [n] uint32 on device (little-endian bit order,
    value i at stream bits [i*bits, (i+1)*bits), + offset bias).  Inverse
    of encode.pack_bits_host; oracle: encode.unpack_bits_host.
    Byte-multiple widths reshape-and-combine; sub-byte/odd widths gather
    the (at most 5) bytes each value's bit window can span — out-of-range
    tail reads are index-clamped and their bits always fall above the
    width mask (see the contribution-bit argument in the PR notes)."""
    if n == 0:
        return jnp.zeros(0, jnp.uint32)
    offset = jnp.asarray(offset, jnp.uint32)
    if bits % 8 == 0:
        k = bits // 8
        b = packed[:n * k].reshape(n, k).astype(jnp.uint32)
        out = b[:, 0]
        for j in range(1, k):
            out = out | (b[:, j] << jnp.uint32(8 * j))
        return out + offset
    start = jnp.arange(n, dtype=jnp.int32) * bits
    byte0 = start >> 3
    shift = (start & 7).astype(jnp.uint32)
    idx = byte0[:, None] + jnp.arange(5, dtype=jnp.int32)[None, :]
    b = packed[jnp.clip(idx, 0, packed.shape[0] - 1)].astype(jnp.uint32)
    word0 = b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24)
    low = word0 >> shift
    hi = jnp.where(shift == 0, jnp.uint32(0),
                   b[:, 4] << ((jnp.uint32(32) - shift) & jnp.uint32(31)))
    val = (low | hi) & jnp.uint32((1 << bits) - 1)
    return val + offset


def _decode_wire(payload_d, wire: ChunkWire, use_pallas: str = "auto"):
    """Device payload + header -> decoded uint32 array of wire.shape.

    Wire-v3 entropy chunks route through the fused rANS decoders
    (cluster/kernels/rans.py); bit-packed chunks through _unpack_bits.
    The offset ships as an EXPLICIT scalar conversion: handed to the jit
    as a raw np.uint32 it would be staged implicitly per call — the
    regression class lint/runtime.no_implicit_transfers exists to catch.
    """
    if wire.ent is not None:
        from .kernels.rans import decode_lane_device

        flat = decode_lane_device(wire.ent, payload_d,
                                  use_pallas=use_pallas)
        if wire.offset:
            flat = flat + jax.device_put(np.uint32(wire.offset))
        return flat.reshape(wire.shape)
    flat = _unpack_bits(payload_d, wire.n_values, wire.bits,
                        jax.device_put(np.uint32(wire.offset)))
    return flat.reshape(wire.shape)


def _produce_chunk(chunk: np.ndarray, rec: StageRecorder,
                   wd: StageWatchdog | None = None,
                   sup: "_DeviceSupervisor | None" = None,
                   entropy: str = "off"):
    """Host half of one chunk: adaptive pack (encode stage) + device_put
    with a completion wait (h2d stage).  Runs on the producer thread when
    overlap is on, so both stages hide behind the main thread's compute.
    The wait doubles as backpressure — at most one chunk is being staged
    beyond the one in flight.  (Over a tunneled PJRT link
    block_until_ready can return before the wire drains; the h2d wall
    then underreports and the surplus shows up in compute — documented in
    PARITY.md.)

    With a watchdog, the put runs under the adaptive H2D deadline: a
    stalled transfer (the `pipeline.h2d` fault seat's `stall` kind, or a
    real hung link) is cancelled and retried; the h2d wall+bytes record
    exactly once per committed chunk, so stall recovery cannot skew the
    wire-accounting drift guard."""
    t0 = time.perf_counter()
    stats: dict = {}
    wire = pack_chunk(chunk, _PACK_LIMIT, entropy=entropy, stats=stats)
    if wire.ent is not None:
        # CRC frame check right before the arrays ship (store-shard
        # semantics for the wire: corruption between the producer
        # thread's encode and the put must refuse, not decode garbage).
        from .entropy import verify_frame

        verify_frame(wire.ent)
    rec.add("encode", time.perf_counter() - t0, wire.nbytes)
    if stats.get("entropy_s"):
        # The `entropy` stage's bytes column counts bytes SAVED vs the
        # bit-packed alternative (stage_entropy_mb in the bench JSON).
        rec.add("entropy", stats["entropy_s"],
                stats.get("entropy_saved_bytes", 0))

    def put():
        fault_point("pipeline.h2d")
        with (sup.device_ctx() if sup is not None
              else contextlib.nullcontext()):
            d = jax.device_put(wire.device_payload())
            jax.block_until_ready(d)
        return d

    t0 = time.perf_counter()
    payload_d = (wd.guarded_call("h2d", put, nbytes=wire.nbytes,
                                 site="pipeline.h2d")
                 if wd is not None else put())
    rec.add("h2d", time.perf_counter() - t0, wire.nbytes)
    return payload_d, wire


def _iter_streamed(chunks: list, rec: StageRecorder, overlap: bool,
                   wd: StageWatchdog | None = None,
                   sup: "_DeviceSupervisor | None" = None,
                   entropy: str = "off"):
    """Yield (device payload, ChunkWire) per chunk, double-buffered: with
    overlap on (and >1 chunk), chunk k+1's pack + device_put run on a
    single producer thread while the caller computes on chunk k.  JAX
    transfers and dispatch are async, so transfer k+1 is on the wire
    during compute k even on backends whose device_put returns early."""
    if not overlap or len(chunks) <= 1:
        for c in chunks:
            yield _produce_chunk(c, rec, wd, sup, entropy)
        return
    from concurrent.futures import ThreadPoolExecutor

    ex = ThreadPoolExecutor(max_workers=1, thread_name_prefix="tse1m-h2d")
    try:
        fut = ex.submit(_produce_chunk, chunks[0], rec, wd, sup, entropy)
        for k in range(len(chunks)):
            cur = fut.result()
            if k + 1 < len(chunks):
                fut = ex.submit(_produce_chunk, chunks[k + 1], rec, wd,
                                sup, entropy)
            yield cur
    finally:
        ex.shutdown(wait=False, cancel_futures=True)


def _chunk_minhash(payload_d, wire: ChunkWire, hp, params: ClusterParams,
                   rec: StageRecorder, want_decoded: bool,
                   sup: "_DeviceSupervisor | None" = None):
    """One chunk's device half: decode + fused signature/band keys per
    the run's scheme (compute stage).  Byte-width chunks take the
    fused-unpack path when the scheme has one (decoded bytes never
    round-trip HBM); ``want_decoded`` forces a materialized decode (the
    encoded path needs the full-lane rows resident for the delta
    scatter).  The completion wait runs under an absolute watchdog
    deadline (`pipeline.compute` seat): a hung device surfaces as a
    cancellable StallError instead of wedging the run forever."""
    kw = dict(use_pallas=params.use_pallas, block_n=params.block_n)
    with rec.stage("compute"), (sup.device_ctx() if sup is not None
                                else contextlib.nullcontext()):
        decoded = None
        if wire.ent is not None or want_decoded or wire.bits % 8 != 0:
            decoded = _decode_wire(payload_d, wire, params.use_pallas)
            sig, keys = scheme_sig_and_keys(decoded, hp, params.n_bands,
                                            **kw)
        else:
            sig, keys = scheme_sig_and_keys_packed(
                payload_d, wire.shape, wire.bits // 8,
                jax.device_put(np.uint32(wire.offset)), hp, params.n_bands,
                **kw)

        def wait():
            fault_point("pipeline.compute")
            jax.block_until_ready(keys)

        run_with_deadline(wait, _compute_budget_s(), "pipeline.compute")
    return sig, keys, decoded


def _stream_minhash_degraded(rows: np.ndarray, hp, params: ClusterParams,
                             rec: StageRecorder, want_decoded: bool,
                             sup: "_DeviceSupervisor | None" = None,
                             wd: StageWatchdog | None = None,
                             initial_step: int | None = None,
                             quant_ctx: dict | None = None):
    """The degradation-aware chunk driver every streaming path feeds
    through: stream `rows` chunk-by-chunk (double-buffered when
    params.overlap), surviving OOM by chunk halving, stalls by watchdog
    cancel+retry, and device loss by CPU failover — completed chunks are
    never recomputed.  ``quant_ctx`` (``{"raw": pre-quantization items,
    "bits": current effective width}``, storeless callers only) arms the
    quant-drop rung: the FIRST answer to RESOURCE_EXHAUSTED is one step
    down the b-bit ladder — re-quantize from the raw buffer and restart
    the stream in the smaller universe (all chunks must share one
    universe, so completed chunks are discarded) — and only past the
    last rung does chunk halving engage.  Returns (parts [(sig, keys)
    per chunk], decoded chunk list when want_decoded else None,
    per-chunk wire bits)."""
    n = rows.shape[0]
    step = initial_step or _stream_plan(rows, params)
    wd = wd or _make_watchdog()
    sup = sup or _DeviceSupervisor()
    parts: list = []
    decoded: list = []
    wire_bits: list = []
    pos = 0
    while True:
        chunks = _row_chunks(rows[pos:], step)
        done = 0
        try:
            for payload_d, wire in _iter_streamed(chunks, rec,
                                                  params.overlap, wd, sup,
                                                  params.entropy):
                sig, keys, cd = _chunk_minhash(payload_d, wire, hp, params,
                                               rec, want_decoded=want_decoded,
                                               sup=sup)
                parts.append((sig, keys))
                wire_bits.append(wire.bits)
                if want_decoded:
                    decoded.append(cd)
                done += 1
        except Exception as e:
            # Completed chunks are all full-step (only the final chunk is
            # short, and if it completed the loop completed).
            pos += done * step
            if is_resource_exhausted(e) and quant_ctx is not None:
                nxt = _next_quant_rung(int(quant_ctx.get("bits", 0)))
                raw = quant_ctx.get("raw")
                if (nxt is not None and raw is not None and raw.size
                        and width_bits(int(raw.max())) > nxt):
                    record_degradation(
                        "quant_drop", site="pipeline.stream",
                        detail={"from_bits": int(quant_ctx.get("bits", 0)),
                                "to_bits": int(nxt),
                                "error": f"{type(e).__name__}: {e}"[:200]})
                    log.warning(
                        "pipeline.stream: RESOURCE_EXHAUSTED; dropping "
                        "wire_quant_bits %s -> %d and restarting the "
                        "stream (b-bit rung before chunk halving)",
                        quant_ctx.get("bits", 0) or "off", nxt)
                    quant_ctx["bits"] = int(nxt)
                    rows = quantize_ids(raw, nxt)
                    last_run_info["wire_quant_bits"] = int(nxt)
                    last_run_info["quant_drops"] = (
                        last_run_info.get("quant_drops", 0) + 1)
                    _persist_quant_bits(nxt)
                    parts.clear()
                    decoded.clear()
                    wire_bits.clear()
                    pos = 0
                    continue
            if is_resource_exhausted(e):
                new_step = _halved_step(step, params)
                if new_step is None:
                    raise
                record_degradation(
                    "chunk_halving", site="pipeline.stream",
                    detail={"from_rows": int(step),
                            "to_rows": int(new_step),
                            "error": f"{type(e).__name__}: {e}"[:200]})
                last_run_info["chunk_halvings"] = (
                    last_run_info.get("chunk_halvings", 0) + 1)
                log.warning("pipeline.stream: RESOURCE_EXHAUSTED; halving "
                            "chunk step %d -> %d rows and resuming from "
                            "row %d", step, new_step, pos)
                step = new_step
                _persist_chunk_bytes(step, rows)
                continue
            if is_device_loss(e) and sup.note_failure("pipeline.stream", e):
                continue
            raise
        break
    return parts, (decoded if want_decoded else None), wire_bits


def _row_chunks(rows: np.ndarray, step: int) -> list:
    return [rows[i:i + step] for i in range(0, max(rows.shape[0], 1), step)]


def _checkpointed_chunks(pending: list, hp, params: ClusterParams,
                         rec: StageRecorder, ckpt, parts: dict,
                         want_decoded: bool = False,
                         chunks_d: list | None = None) -> None:
    """Run the pending checkpoint chunks under the degradation ladder.

    Stalls retry under the watchdog, device loss fails over to CPU (the
    resumable path continues on the fallback — `_DeviceSupervisor`), and
    a chunk that hits RESOURCE_EXHAUSTED recomputes in halved sub-chunks
    whose results concatenate into the SAME shard, so the checkpoint
    layout (manifest step/chunk count) never changes mid-run and a later
    resume still lines up.  Each completed chunk's (sig, keys) lands on
    host (D2H for durability: the persisted shard IS the resume state)
    and saves before the next chunk commits."""
    wd = _make_watchdog()
    sup = _DeviceSupervisor()
    remaining = list(pending)
    while remaining:
        done = 0
        try:
            stream = _iter_streamed([c for _, c in remaining], rec,
                                    params.overlap, wd, sup,
                                    params.entropy)
            for (idx, _), (payload_d, wire) in zip(remaining, stream):
                sig, keys, cd = _chunk_minhash(
                    payload_d, wire, hp, params, rec,
                    want_decoded=want_decoded, sup=sup)
                if chunks_d is not None:
                    chunks_d[idx] = cd
                with rec.stage("d2h"):
                    sig_h, keys_h = np.asarray(sig), np.asarray(keys)
                ckpt.save_chunk(idx, sig_h, keys_h)
                parts[idx] = (sig, keys)
                done += 1
        except Exception as e:
            remaining = remaining[done:]
            idx, chunk = remaining[0]
            if is_resource_exhausted(e):
                half = _halved_step(chunk.shape[0], params)
                if half is None:
                    raise
                record_degradation("chunk_halving",
                                   site="pipeline.resumable",
                                   detail={"chunk": int(idx),
                                           "to_rows": int(half)})
                last_run_info["chunk_halvings"] = (
                    last_run_info.get("chunk_halvings", 0) + 1)
                _persist_chunk_bytes(half, chunk)
                sub_parts, sub_dec, _ = _stream_minhash_degraded(
                    chunk, hp, params, rec, want_decoded=want_decoded,
                    sup=sup, wd=wd, initial_step=half)
                sig = jnp.concatenate([p[0] for p in sub_parts])
                keys = jnp.concatenate([p[1] for p in sub_parts])
                if chunks_d is not None:
                    chunks_d[idx] = (sub_dec[0] if len(sub_dec) == 1
                                     else jnp.concatenate(sub_dec))
                with rec.stage("d2h"):
                    sig_h, keys_h = np.asarray(sig), np.asarray(keys)
                ckpt.save_chunk(idx, sig_h, keys_h)
                parts[idx] = (sig, keys)
                remaining = remaining[1:]
                continue
            if is_device_loss(e) and sup.note_failure("pipeline.resumable",
                                                      e):
                continue
            raise
        break


def _put_delta_meta(enc, rec: StageRecorder, entropy: str = "off"):
    """Pack the delta lanes (encode stage) and ship mask + rep + counts +
    pos + val as ONE pytree device_put (h2d stage) — one dispatch instead
    of the five sequential puts the previous layout paid (each put costs a
    link round-trip over tunneled PJRT).  The mask bits count toward the
    h2d bytes: they ride this put, and the recorded wire must equal the
    `wire_payloads` inventory exactly (bench.py's drift guard) — under
    wire v3 that inventory includes each rANS-coded lane's word stream,
    frequency table and initial states."""
    t0 = time.perf_counter()
    stats: dict = {}
    meta = pack_delta_meta(enc, entropy=entropy, stats=stats)
    for lane in meta.lanes():
        if lane.ent is not None:
            from .entropy import verify_frame

            verify_frame(lane.ent)
    if meta.val.ent is not None:
        from .entropy import verify_frame

        verify_frame(meta.val.ent)
    nbytes = meta.nbytes + enc.mask_bits.nbytes
    rec.add("encode", time.perf_counter() - t0, nbytes)
    if stats.get("entropy_s"):
        rec.add("entropy", stats["entropy_s"],
                stats.get("entropy_saved_bytes", 0))
    t0 = time.perf_counter()
    mask_d, rep_d, counts_d, pos_d, val_d = jax.device_put(
        (enc.mask_bits, meta.rep.device_payload(),
         meta.counts.device_payload(), meta.pos.device_payload(),
         meta.val.device_payload()))
    jax.block_until_ready((mask_d, rep_d, counts_d, pos_d, val_d))
    rec.add("h2d", time.perf_counter() - t0, nbytes)
    return meta, mask_d, rep_d, counts_d, pos_d, val_d


def _decode_lane(lane, lane_d, use_pallas: str):
    """One metadata lane's device decode: rANS frame or bit stream."""
    if lane.ent is not None:
        from .kernels.rans import decode_lane_device

        return decode_lane_device(lane.ent, lane_d, use_pallas=use_pallas)
    return _unpack_bits(lane_d, lane.n, lane.bits,
                        jax.device_put(np.uint32(0)))


def _decode_delta_meta(meta, enc, full_d, rep_d, counts_d, pos_d, val_d,
                       use_pallas: str = "auto"):
    """Unpack the delta lanes on device (bit streams via _unpack_bits,
    entropy-coded lanes via the fused rANS decoders) and scatter-decode
    the delta rows against the resident full lane.  Offsets convert
    explicitly (see _decode_wire) so the hot loop stays implicit-
    transfer-free under the runtime sanitizer."""
    rep = _decode_lane(meta.rep, rep_d, use_pallas)
    counts = _decode_lane(meta.counts, counts_d, use_pallas)
    pos = _decode_lane(meta.pos, pos_d, use_pallas)
    vals = _decode_wire(val_d, meta.val, use_pallas).reshape(-1)
    return _decode_delta_raw(full_d, rep, counts, pos, vals)


def _cluster_encoded(items: np.ndarray, enc, hp, params: ClusterParams,
                     rec: StageRecorder) -> np.ndarray:
    """Single-host encoded path: stream the full lane chunked + double-
    buffered (retaining the decoded device rows), decode the delta lane
    against it, MinHash both, cluster with original-order labels."""
    n = items.shape[0]
    parts, chunks_d, wire_bits = _stream_minhash_degraded(
        enc.full_rows, hp, params, rec, want_decoded=True)
    full_d = chunks_d[0] if len(chunks_d) == 1 else jnp.concatenate(chunks_d)
    meta, mask_d, rep_d, counts_d, pos_d, val_d = _put_delta_meta(
        enc, rec, params.entropy)
    with rec.stage("compute"):
        delta_items = _decode_delta_meta(meta, enc, full_d, rep_d, counts_d,
                                         pos_d, val_d, params.use_pallas)
        dsig, dkeys = scheme_sig_and_keys(delta_items, hp, params.n_bands,
                                          use_pallas=params.use_pallas,
                                          block_n=params.block_n)
        sig = jnp.concatenate([p[0] for p in parts] + [dsig])
        keys = jnp.concatenate([p[1] for p in parts] + [dkeys])
        labels = _cluster_encoded_labels(sig, keys, mask_d, n,
                                         params.threshold, params.n_iters)
        jax.block_until_ready(labels)
    last_run_info["chunk_bits"] = wire_bits
    with rec.stage("d2h", nbytes=labels.size * 4):
        out = np.asarray(labels)
    return out


def _wire_mb(rec: StageRecorder) -> float:
    return round(rec.nbytes.get("h2d", 0) / 2**20, 2)


def _record_wire(rec: StageRecorder) -> None:
    """Publish the run's exact H2D byte count alongside the rounded MB —
    bench.py asserts the transfer probe's inventory equals this, so
    `transfer_mb` can never drift from what the pipeline actually
    shipped."""
    last_run_info["wire_mb"] = _wire_mb(rec)
    last_run_info["wire_bytes"] = int(rec.nbytes.get("h2d", 0))


def _finish_run(rec: StageRecorder, t0: float) -> None:
    rec.set_total(time.perf_counter() - t0)
    stages = rec.as_dict()
    last_run_info["stages"] = stages
    # Degradation-ladder telemetry is part of the run contract: 0 when
    # the run never degraded, so bench/CI can assert the key exists.
    last_run_info.setdefault("chunk_halvings", 0)
    record_last_stages(stages)


def cluster_sessions(items, params: ClusterParams | None = None,
                     mesh: jax.sharding.Mesh | None = None,
                     axis: str = "data") -> np.ndarray:
    """Cluster [N, S] uint32 session feature sets -> [N] int32 labels.

    With a mesh, `items` is placed sharded along its first axis; the jitted
    pipeline keeps the MinHash stage sharded and lets XLA gather for the
    bucket-sort stage.
    """
    params = params or ClusterParams()
    _validate_encoding(params)
    if params.prefilter == "on" and mesh is not None:
        raise ValueError(
            "ClusterParams.prefilter='on' is a single-host wire lever: "
            "the mesh feed has no per-host keep mask to apply. Drop "
            "prefilter (auto disables itself under a mesh) or run "
            "single-host.")
    if params.sig_store and mesh is not None:
        # Refuse loudly rather than silently dropping the store (the
        # pre-pod behavior): this entry point has no per-host row
        # ownership to shard the probe by.  The pod path carries the
        # store under a mesh.
        raise ValueError(
            "--sig-store (ClusterParams.sig_store) is not supported on "
            "cluster_sessions under a mesh: the signature store shards "
            "per host by digest range. Feed each process's host-resident "
            "local rows through cluster_sessions_pod (cli cluster routes "
            "there automatically under a mesh), or drop sig_store for a "
            "cold mesh run.")
    if params.sig_store and mesh is None:
        # Warm path (cluster/store.py + cluster/incremental.py): probe the
        # persistent signature cache, ship only the novel tail.  A
        # pod-sharded store root routes to the pod path instead (see
        # _cluster_with_store).
        return _cluster_with_store(
            np.ascontiguousarray(items, dtype=np.uint32), params)
    hp = make_params(params.scheme, params.n_hashes, params.seed).device()

    if mesh is not None:
        # The base-delta + adaptive-width wire encoding is a single-host
        # H2D optimisation; mesh feeding ships raw shards or the 24-bit
        # byte pack (unpacked inside the shard_map kernel) — but a typo'd
        # encoding value must still fail here, not only in local testing.
        _validate_encoding(params)
        rec = StageRecorder()
        t_all = time.perf_counter()
        last_run_info.clear()
        from ..parallel.mesh import pad_to_devices

        if isinstance(items, jax.Array):
            # Pre-sharded global array (the multi-host feeding path:
            # parallel/multihost.put_process_local — no single host holds
            # all rows, so there is nothing to pad, pack or device_put
            # here).
            if items.shape[0] % mesh.devices.size:
                raise ValueError(
                    "pre-sharded items must be padded to a multiple of the "
                    "mesh size — feed through parallel/multihost."
                    "put_process_local_padded and slice the labels back to "
                    "the logical row count")
            n = items.shape[0]
            items_d = items
            packed = False
            last_run_info.update(encoding="mesh-presharded")
        else:
            items = np.ascontiguousarray(items, dtype=np.uint32)
            if params.wire_quant_bits > 0:  # explicit only: mesh links are
                #                             local/ICI, auto stays off
                items, qb = _maybe_quantize(items, params)
                last_run_info.update(wire_quant_bits=qb)
            n = items.shape[0]
            items, _ = pad_to_devices(items, mesh)
            packed = should_pack24(items)
            with rec.stage("encode"):
                payload = _pack24_host(items) if packed else items
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(
                    axis, *([None] * (payload.ndim - 1))))
            with rec.stage("h2d", nbytes=payload.nbytes):
                items_d = jax.device_put(payload, sharding)
                items_d.block_until_ready()
            last_run_info.update(
                encoding="mesh-pack24" if packed else "mesh-raw",
                wire_mb=round(payload.nbytes / 2**20, 2))
        from .sharded import _sharded_cluster_kernel

        # Band-sharded tail (cluster/sharded.py): distributes the
        # bucket/verify/propagate stages, not just MinHash.
        kernel = _sharded_cluster_kernel(mesh, axis, params.n_bands,
                                         params.threshold, params.n_iters,
                                         packed=packed,
                                         scheme=params.scheme)
        with rec.stage("compute"):
            labels = kernel(items_d, *hp.arrays)
            jax.block_until_ready(labels)
        if jax.process_count() > 1:
            # Multi-host: shards live on non-addressable devices, so a
            # plain np.asarray would fail — allgather across processes
            # (rides DCN; every host gets the full label vector).
            from jax.experimental import multihost_utils

            with rec.stage("d2h"):
                out = np.asarray(
                    multihost_utils.process_allgather(labels,
                                                      tiled=True))[:n]
            _finish_run(rec, t_all)
            return out
        with rec.stage("d2h"):
            out = np.asarray(labels)[:n]
        _finish_run(rec, t_all)
        return out

    items = np.ascontiguousarray(items, dtype=np.uint32)
    rec = StageRecorder()
    t_all = time.perf_counter()
    last_run_info.clear()
    # Wire v3, lever 1: the host prefilter runs over the RAW ids before
    # anything is planned; the quantization decision is made over the
    # FULL row set and passed down so the kept subset ships in exactly
    # the universe the unfiltered run would have used.
    qbits_full = _quant_bits(items, params)
    keep = _prefilter_keep(items, params, rec)
    work = items if keep is None else items[keep]
    out = _cluster_single_host(work, hp, params, rec, qbits_full)
    if keep is not None:
        out = _scatter_prefiltered(items.shape[0], keep, out)
    _record_wire(rec)
    _record_wire_v3(items, params, qbits_full, keep, rec)
    _finish_run(rec, t_all)
    return out


def _scatter_prefiltered(full_n: int, keep: np.ndarray,
                         out: np.ndarray) -> np.ndarray:
    """Map subset labels back to the full row set: dropped rows label
    themselves (no verified edge can reach them), kept components'
    minimum index maps back through the (sorted, order-preserving)
    kept-index table — so the result equals the unfiltered run's
    min-original-index labels elementwise."""
    keep_idx = np.flatnonzero(keep)
    full = np.arange(full_n, dtype=np.int32)
    full[keep_idx] = keep_idx[out].astype(np.int32)
    return full


def _prefilter_mask(items: np.ndarray,
                    params: ClusterParams) -> np.ndarray | None:
    """THE prefilter engagement decision + mask, shared by the pipeline
    and the bench probe (`wire_payloads`) so the two can never disagree
    about what ships.  None = filter off (mode, store, threshold, or
    auto size gate); else the boolean keep mask over the RAW rows.
    Modes: 'off' never; 'auto' on large storeless runs with a verifying
    threshold; 'on' forces (invalid combinations refused by
    _validate_encoding)."""
    if (params.prefilter == "off" or params.sig_store
            or params.threshold <= 0):
        return None
    if params.prefilter == "auto" and items.nbytes < _AUTO_MIN_BYTES:
        return None
    from .prefilter import collide_mask

    return collide_mask(items, params.seed, scheme=params.scheme)


def _prefilter_keep(items: np.ndarray, params: ClusterParams,
                    rec: StageRecorder) -> np.ndarray | None:
    """`_prefilter_mask` + telemetry: a keep mask when the filter
    engaged AND dropped something, else None.  Telemetry lands in
    last_run_info either way so the bench keys always exist."""
    last_run_info.update(prefilter_hit_rate=0.0, prefilter_rows_dropped=0)
    t0 = time.perf_counter()
    keep = _prefilter_mask(items, params)
    if keep is None:
        return None
    from .prefilter import N_BANDS

    rec.add("prefilter", time.perf_counter() - t0)
    n = items.shape[0]
    dropped = int(n - keep.sum())
    last_run_info.update(
        prefilter_hit_rate=round(dropped / max(n, 1), 4),
        prefilter_rows_dropped=dropped, prefilter_bands=N_BANDS)
    if dropped == 0:
        return None
    return keep


def _record_wire_v3(items: np.ndarray, params: ClusterParams, qbits: int,
                    keep: np.ndarray | None, rec: StageRecorder) -> None:
    """Wire-v3 savings telemetry (`wire_v3_saved_mb` bench key): the
    entropy column is measured (codec bytes vs the bit-packed
    alternative, accrued on the `entropy` stage); the prefilter column
    is an estimate — dropped rows costed at the run's packed width, the
    lane they would most likely have shipped in."""
    ent_saved = int(rec.nbytes.get("entropy", 0))
    pf_saved = 0
    if keep is not None and items.size:
        w = qbits or chunk_wire_bits(items, _PACK_LIMIT)[0]
        dropped = int(items.shape[0] - keep.sum())
        pf_saved = dropped * int(items.shape[1]) * w // 8
    last_run_info.update(
        wire_version=3,
        entropy_saved_mb=round(ent_saved / 2**20, 3),
        prefilter_saved_mb=round(pf_saved / 2**20, 3),
        wire_v3_saved_mb=round((ent_saved + pf_saved) / 2**20, 3))


def _cluster_single_host(items: np.ndarray, hp, params: ClusterParams,
                         rec: StageRecorder,
                         qbits_override: int | None = None) -> np.ndarray:
    """The storeless single-host pipeline over (possibly prefiltered)
    rows: plan the wire, stream + MinHash + cluster, return labels in
    row order.  Wire/stage accounting accrues into ``rec``; the caller
    owns _record_wire/_finish_run."""
    raw_items = items  # pre-quantization buffer (the quant-drop rung
    #                    re-quantizes from here; _plan_wire never mutates)
    t0 = time.perf_counter()
    items, enc, qbits = _plan_wire(items, params, qbits_override)
    rec.add("encode", time.perf_counter() - t0)
    last_run_info.update(wire_quant_bits=qbits)
    clamped = (params.sig_store is None and params.wire_quant_bits == 0
               and qbits and qbits == _degraded_quant_floor())
    if enc is not None:
        last_run_info.update(
            encoding="delta", encode_s=round(time.perf_counter() - t0, 4),
            n_full=enc.n_full, n_delta=enc.n_delta)
        return _cluster_encoded(items, enc, hp, params, rec)

    last_run_info.update(encoding="plain")
    # The quant-drop rung is storeless-only (a store's policy key pins
    # quant_bits — a mid-run drop would poison every cached signature)
    # and respects an explicit wire_quant_bits=-1 ("never quantize").
    quant_ctx = ({"raw": raw_items, "bits": qbits}
                 if params.sig_store is None
                 and params.wire_quant_bits >= 0 else None)
    sig, keys = _minhash_streamed(items, hp, params, rec,
                                  quant_ctx=quant_ctx)
    with rec.stage("compute"):
        labels = _cluster_from_sig_jit(sig, keys, params.threshold,
                                       params.n_iters)
        jax.block_until_ready(labels)
    with rec.stage("d2h", nbytes=labels.size * 4):
        out = np.asarray(labels)
    if (clamped and not last_run_info.get("quant_drops")
            and not last_run_info.get("chunk_halvings")):
        # Device heal: a full run held the degraded width with zero
        # pressure — restore full fidelity for the next run.
        record_degradation("quant_restore", site="pipeline.stream",
                           detail={"from_bits": int(qbits)})
        _restore_quant_bits()
    return out


@jax.jit
def _unpack24(packed):
    """[n, S, 3] uint8 little-endian -> [n, S] uint32 (on device)."""
    p = packed.astype(jnp.uint32)
    return p[..., 0] | (p[..., 1] << 8) | (p[..., 2] << 16)


def _pack24_host(chunk: np.ndarray) -> np.ndarray:
    """[n, S] uint32 (< 2^24) -> contiguous [n, S, 3] uint8 byte view
    (the mesh feed's pack; single-host chunks use encode.pack_chunk)."""
    if chunk.dtype.byteorder == ">":  # big-endian hosts: normalize first
        chunk = chunk.astype("<u4")
    return np.ascontiguousarray(
        chunk[..., None].view(np.uint8)[..., :3])


def cluster_sessions_resumable(items, params: ClusterParams | None = None,
                               checkpoint_dir: str | None = None,
                               cleanup: bool = True) -> np.ndarray:
    """`cluster_sessions` with per-chunk checkpoint/resume (SURVEY §5 A4).

    Each streamed chunk's (signatures, band keys) shard persists under
    ``checkpoint_dir`` as it completes (`cluster/checkpoint.py`); a killed
    run re-invoked with the same directory recomputes only unfinished
    chunks, then proceeds to label propagation.  Pending chunks stream
    through the same double-buffered producer as the non-checkpointed
    path — the shard save (the kill window the chaos tests aim at) stays
    on the main thread, strictly after that chunk's compute.  ``cleanup``
    removes the shards after a successful run.  With no directory this is
    exactly `cluster_sessions`.  Single-host form; a pod job gives each
    process its own directory for its local row range.
    """
    params = params or ClusterParams()
    _validate_encoding(params)
    if checkpoint_dir is None:
        return cluster_sessions(items, params)
    from .checkpoint import ClusterCheckpoint

    items = np.ascontiguousarray(items, dtype=np.uint32)
    n = items.shape[0]
    if n == 0:
        return np.empty(0, np.int32)
    if params.wire_quant_bits == 0 and params.sig_store is None:
        # Clamp an auto-policy resume to the SURVIVING wire policy: the
        # shards hold signatures of the universe the previous attempt
        # actually used (possibly a degraded quant width from the
        # RESOURCE_EXHAUSTED rung, possibly none), and an auto re-plan
        # that resolves differently would refuse the resume.  Explicit
        # widths still refuse on mismatch — that contract is the guard
        # against genuinely changed policy.
        prior_meta = ClusterCheckpoint.peek_meta(checkpoint_dir)
        if prior_meta is not None:
            from dataclasses import replace

            prior_bits = int(prior_meta.get("wire_quant_bits", 0) or 0)
            params = replace(params,
                             wire_quant_bits=prior_bits if prior_bits
                             else -1)
    digests = None
    if params.sig_store:
        # Warm-merge runs touch the device only for the novel tail and
        # commit atomically — per-chunk checkpointing adds nothing there.
        # A run the store cannot merge falls through to the chunked cold
        # pipeline below and populates the store once it completes.
        out = _cluster_with_store(items, params, merge_only=True)
        if out is not None:
            return out
        from .store import row_digests

        digests = row_digests(items)  # of the RAW ids, before quantization
    hp = make_params(params.scheme, params.n_hashes, params.seed).device()
    rec = StageRecorder()
    t_all = time.perf_counter()
    last_run_info.clear()
    # Wire v3 prefilter (storeless only — the store caches a signature
    # per row): deterministic over (items, params), so a resume
    # recomputes the same keep mask; the checkpoint fingerprints the
    # SUBSET and carries the kept count, so a resume under a changed
    # prefilter policy refuses instead of mixing shards.
    full_items = items
    qbits_full = _quant_bits(items, params)
    keep = None
    if digests is None:
        keep = _prefilter_keep(items, params, rec)
    if keep is not None:
        items = items[keep]
        n = items.shape[0]
    t0 = time.perf_counter()
    # Shards hold signatures of the QUANTIZED universe, so a resume under
    # a different quantization policy must read as a different run and
    # refuse — the manifest meta carries the effective bits.
    items, enc, qbits = _plan_wire(items, params, qbits_full)
    rec.add("encode", time.perf_counter() - t0)
    last_run_info.update(wire_quant_bits=qbits)

    if enc is None:
        last_run_info.update(encoding="plain")
        step = _stream_plan(items, params)  # same chunks as streamed
        # The quant key appears only when quantization engaged: shard
        # contents are unchanged otherwise, and the symmetric manifest
        # comparison already refuses a quantized<->unquantized resume.
        extra = {}
        if qbits:
            extra["wire_quant_bits"] = qbits
        if keep is not None:
            extra["prefilter_kept"] = int(n)
        ckpt = ClusterCheckpoint(checkpoint_dir, items, params, step,
                                 extra=extra or None)
        parts: dict = {}
        pending = []
        for idx, i in enumerate(range(0, n, step)):
            # A shard that exists but is torn (truncated npz) reads as
            # not-done and the chunk recomputes — resume must produce the
            # same labels as an uninterrupted run, never crash on it.
            shard = (ckpt.load_chunk_or_none(idx)
                     if ckpt.chunk_done(idx) else None)
            if shard is not None:
                with rec.stage("h2d", nbytes=shard[0].nbytes
                               + shard[1].nbytes):
                    parts[idx] = (jax.device_put(shard[0]),
                                  jax.device_put(shard[1]))
                continue
            pending.append((idx, items[i:i + step]))
        _checkpointed_chunks(pending, hp, params, rec, ckpt, parts)
        with rec.stage("compute"):
            sig = jnp.concatenate([parts[i][0] for i in sorted(parts)])
            keys = jnp.concatenate([parts[i][1] for i in sorted(parts)])
            labels = _cluster_from_sig_jit(sig, keys, params.threshold,
                                           params.n_iters)
            jax.block_until_ready(labels)
        with rec.stage("d2h", nbytes=labels.size * 4):
            out = np.asarray(labels)
        if digests is not None:
            _store_populate_from_run(params, qbits, digests, sig, keys, out,
                                     None, rec)
        if cleanup:
            ckpt.cleanup()
        if keep is not None:
            out = _scatter_prefiltered(full_items.shape[0], keep, out)
        _record_wire(rec)
        _record_wire_v3(full_items, params, qbits_full, keep, rec)
        _finish_run(rec, t_all)
        return out

    # Encoded layout: one shard per full-lane chunk + one delta-lane shard.
    # The lane split is part of the manifest (it decides what each shard
    # holds); a resume whose encoder drew different lanes — e.g. the native
    # grouping pass available on one machine but not the other — refuses
    # instead of concatenating mismatched shards.
    import hashlib

    last_run_info.update(encoding="delta", n_full=enc.n_full,
                         n_delta=enc.n_delta)
    full = enc.full_rows
    step = _stream_plan(full, params)
    n_full_chunks = max(1, -(-full.shape[0] // step))
    lane_fp = hashlib.blake2b(
        enc.mask_bits.tobytes() + enc.counts.tobytes(),
        digest_size=16).hexdigest()
    extra = {"encoding": "delta", "lane_fingerprint": lane_fp}
    if qbits:
        extra["wire_quant_bits"] = qbits
    if keep is not None:
        extra["prefilter_kept"] = int(n)
    ckpt = ClusterCheckpoint(checkpoint_dir, items, params, step,
                             extra=extra, n_chunks=n_full_chunks + 1)
    parts = {}
    chunks_d: list = [None] * n_full_chunks
    pending = []
    for idx, i in enumerate(range(0, full.shape[0], step)):
        shard = (ckpt.load_chunk_or_none(idx)
                 if ckpt.chunk_done(idx) else None)
        if shard is not None:
            with rec.stage("h2d", nbytes=shard[0].nbytes + shard[1].nbytes):
                parts[idx] = (jax.device_put(shard[0]),
                              jax.device_put(shard[1]))
            continue
        pending.append((idx, full[i:i + step]))
    _checkpointed_chunks(pending, hp, params, rec, ckpt, parts,
                         want_decoded=True, chunks_d=chunks_d)
    didx = n_full_chunks
    dshard = ckpt.load_chunk_or_none(didx) if ckpt.chunk_done(didx) else None
    if dshard is not None:
        with rec.stage("h2d", nbytes=dshard[0].nbytes + dshard[1].nbytes):
            dpart = (jax.device_put(dshard[0]), jax.device_put(dshard[1]))
    else:
        # Delta decode needs the full lane device-resident; chunks whose
        # shards were loaded from disk never shipped their rows this run,
        # so put them now (raw rows only — their signatures are done).
        for idx, i in enumerate(range(0, full.shape[0], step)):
            if chunks_d[idx] is None:
                payload_d, wire = _produce_chunk(full[i:i + step], rec,
                                                 entropy=params.entropy)
                with rec.stage("compute"):
                    chunks_d[idx] = _decode_wire(payload_d, wire,
                                                 params.use_pallas)
        full_d = (chunks_d[0] if len(chunks_d) == 1
                  else jnp.concatenate(chunks_d))
        meta, mask_d, rep_d, counts_d, pos_d, val_d = _put_delta_meta(
            enc, rec, params.entropy)
        with rec.stage("compute"):
            delta_items = _decode_delta_meta(meta, enc, full_d, rep_d,
                                             counts_d, pos_d, val_d,
                                             params.use_pallas)
            dsig, dkeys = scheme_sig_and_keys(
                delta_items, hp, params.n_bands,
                use_pallas=params.use_pallas, block_n=params.block_n)
        with rec.stage("d2h"):
            dsig_h, dkeys_h = np.asarray(dsig), np.asarray(dkeys)
        ckpt.save_chunk(didx, dsig_h, dkeys_h)
        dpart = (dsig, dkeys)
    with rec.stage("compute"):
        sig = jnp.concatenate([parts[i][0] for i in sorted(parts)]
                              + [dpart[0]])
        keys = jnp.concatenate([parts[i][1] for i in sorted(parts)]
                               + [dpart[1]])
        labels = _cluster_encoded_labels(
            sig, keys, jax.device_put(enc.mask_bits), n, params.threshold,
            params.n_iters)
        jax.block_until_ready(labels)
    with rec.stage("d2h", nbytes=labels.size * 4):
        out = np.asarray(labels)
    if digests is not None:
        _store_populate_from_run(params, qbits, digests, sig, keys, out,
                                 enc, rec)
    if cleanup:
        ckpt.cleanup()
    if keep is not None:
        out = _scatter_prefiltered(full_items.shape[0], keep, out)
    _record_wire(rec)
    _record_wire_v3(full_items, params, qbits_full, keep, rec)
    _finish_run(rec, t_all)
    return out


def _minhash_streamed(items: np.ndarray, hp, params: ClusterParams,
                      rec: StageRecorder, quant_ctx: dict | None = None):
    """items -> (signatures, band keys), overlapping encode + H2D with
    compute.

    The ~N*S-byte items transfer is the dominant wall-time cost on a
    remote/tunneled PJRT backend, while MinHash itself is cheap.  Chunks
    are equal-sized (the last may be short), so at most two kernel shapes
    are compiled.  Results are concatenated on device; labels are
    unchanged vs the unchunked path because MinHash is row-independent —
    which is also why the degradation ladder (OOM halving, stall retry,
    CPU failover) is label-invariant here.
    """
    parts, _, wire_bits = _stream_minhash_degraded(items, hp, params, rec,
                                                   want_decoded=False,
                                                   quant_ctx=quant_ctx)
    last_run_info["chunk_bits"] = wire_bits
    if len(parts) == 1:
        return parts[0]
    sig = jnp.concatenate([p[0] for p in parts])
    keys = jnp.concatenate([p[1] for p in parts])
    return sig, keys


def wire_payloads(items, params: ClusterParams | None = None):
    """(payloads, info): the EXACT host->device payload arrays the single-
    host pipeline would ship for `items` under `params` — quantization,
    delta lanes and adaptive bit-packing included.  bench.py's transfer
    probe times these, so the probe cannot drift from the shipped format.
    """
    params = params or ClusterParams()
    _validate_encoding(params)
    items = np.ascontiguousarray(items, dtype=np.uint32)
    # Mirror the pipeline's wire-v3 plan exactly: full-set quantization
    # decision, prefilter keep mask, then the per-chunk/per-lane codec
    # choice — so the probe's byte inventory equals the StageRecorder
    # h2d bytes (bench's wire_drift_bytes == 0 guard).
    full_n = items.shape[0]
    qbits_full = _quant_bits(items, params)
    keep = _prefilter_mask(items, params)
    if keep is not None and keep.all():
        keep = None
    if keep is not None:
        items = items[keep]
    items, enc, qbits = _plan_wire(items, params, qbits_full)
    payloads, chunk_bits = [], []
    if enc is None:
        step = _stream_plan(items, params)
        for chunk in _row_chunks(items, step):
            wire = pack_chunk(chunk, _PACK_LIMIT, entropy=params.entropy)
            payloads += wire.wire_arrays()
            chunk_bits.append(wire.bits)
        info = dict(encoding="plain")
    else:
        step = _stream_plan(enc.full_rows, params)
        for chunk in _row_chunks(enc.full_rows, step):
            wire = pack_chunk(chunk, _PACK_LIMIT, entropy=params.entropy)
            payloads += wire.wire_arrays()
            chunk_bits.append(wire.bits)
        meta = pack_delta_meta(enc, entropy=params.entropy)
        payloads += [enc.mask_bits] + meta.wire_arrays()
        info = dict(encoding="delta", n_full=enc.n_full, n_delta=enc.n_delta)
    info.update(wire_quant_bits=qbits, chunk_bits=chunk_bits,
                wire_version=3,
                prefilter_rows_dropped=(0 if keep is None
                                        else int(full_n - keep.sum())),
                wire_mb=round(sum(p.nbytes for p in payloads) / 2**20, 2))
    return payloads, info


# ---------------------------------------------------------------------------
# Persistent-store warm path (cluster/store.py + cluster/incremental.py).
#
# Continuous fuzzing re-clusters a corpus that is overwhelmingly yesterday's
# corpus; the content-addressed signature store turns that into wire and
# compute savings: hash every row, bulk-probe the store, and run the
# encode→stream→minhash pipeline only on rows whose signature is not
# cached.  Two warm shapes:
#
# - "merge": the input is the previous run's rows plus an appended tail of
#   at most ClusterParams.merge_max_novel of the input.  Only the
#   content-novel tail rows touch the device at all; candidate edges come
#   from the persisted per-band bucket tables and a host union-find merges
#   labels.  Labels are elementwise-identical to a cold batch run (see
#   incremental.py for the hub-election argument); wire is the novel rows.
# - "union": any other store-enabled run (first population, reordered
#   input, large novelty).  Cached signatures ship instead of their rows,
#   fresh rows stream through the existing pipeline, and the device runs
#   banded LSH + propagation over the union; the completed run's state is
#   committed for future merges.
#
# All device transfers stay in this module (the blessed wire layer);
# store.py and incremental.py are host-only.


def _store_policy(params: ClusterParams, qbits: int) -> dict:
    return {"n_hashes": params.n_hashes, "seed": params.seed,
            "quant_bits": qbits, "scheme": params.scheme}


def minhash_novel_rows(rows: np.ndarray, params: ClusterParams,
                       qbits: int, rec: StageRecorder | None = None,
                       wd: StageWatchdog | None = None,
                       pad_pow2: bool = True) -> np.ndarray:
    """Host [K, S] raw rows -> host [K, H] uint32 MinHash signatures via
    the degraded streaming pipeline — the serve plane's ingest miss path.

    Rows are quantized to the store policy's universe, streamed through
    `_stream_minhash_degraded` (OOM halving / stall retry / CPU failover
    — the same ladder every batch path rides), and the signatures
    fetched back to host.  ``pad_pow2`` pads the row count to the next
    power of two with copies of row 0 (MinHash is row-independent, the
    pad is sliced off) so a long-lived daemon ingesting arbitrary batch
    sizes compiles O(log max-batch) kernel shapes, not one per size."""
    rec = rec or StageRecorder()
    k = int(rows.shape[0])
    if k == 0:
        return np.empty((0, params.n_hashes), np.uint32)
    sub = quantize_ids(rows, qbits) if qbits else rows
    if pad_pow2:
        padded = 1 << (k - 1).bit_length()
        if padded > k:
            sub = np.concatenate(
                [sub, np.broadcast_to(sub[:1], (padded - k, sub.shape[1]))])
    hp = make_params(params.scheme, params.n_hashes, params.seed).device()
    parts, _, _ = _stream_minhash_degraded(sub, hp, params, rec,
                                           want_decoded=False, wd=wd)
    sig_d = (parts[0][0] if len(parts) == 1
             else jnp.concatenate([p[0] for p in parts]))
    with rec.stage("d2h", nbytes=int(sig_d.size) * 4):
        sig = np.asarray(sig_d)
    return np.ascontiguousarray(sig[:k], np.uint32)


def _cluster_with_store(items: np.ndarray, params: ClusterParams,
                        merge_only: bool = False):
    """Store-enabled clustering; returns [N] int32 labels.

    ``merge_only=True`` (the resumable caller): return None instead of
    running the union path, so the caller can fall back to its chunk-
    checkpointed cold pipeline and populate the store afterwards."""
    from .store import ShardedSignatureStore, SignatureStore, row_digests

    if ShardedSignatureStore.is_sharded_root(params.sig_store):
        # A pod-sharded store probed by a plain single-process run (the
        # resumed-after-host-loss shape): route through the pod path over
        # the local device mesh — this process inherits every digest
        # range, reassignments fire as degradation events, and the lost
        # hosts' un-appended rows probe as misses and recompute.
        return cluster_sessions_pod(items, items.shape[0], params,
                                    solo=jax.process_count() > 1)

    rec = StageRecorder()
    t_all = time.perf_counter()
    last_run_info.clear()
    n = items.shape[0]
    if n == 0:
        return np.empty(0, np.int32)
    qbits = _quant_bits(items, params)
    store = SignatureStore(params.sig_store, _store_policy(params, qbits))
    with rec.stage("probe"):
        digests = row_digests(items)
        hit, shard, row = store.bulk_probe(digests)
    state = store.load_state(params.n_bands, params.threshold)
    hit_rate = float(hit.mean())
    last_run_info.update(encoding="store", wire_quant_bits=qbits,
                         cache_hit_rate=round(hit_rate, 4),
                         cache_store_rows=store.n_rows)
    merge_ok = (state is not None and state.n_rows <= n
                and (n - state.n_rows) <= params.merge_max_novel * n
                and state.matches_prefix(digests))
    if merge_ok:
        labels = _store_warm_merge(items, digests, hit, shard, row, state,
                                   store, params, qbits, rec)
        last_run_info["cache_mode"] = "merge"
    elif merge_only:
        return None
    else:
        labels = _store_union(items, digests, hit, shard, row, store,
                              params, qbits, rec)
        last_run_info["cache_mode"] = "union"
    _record_wire(rec)
    _finish_run(rec, t_all)
    return labels


def _store_warm_merge(items, digests, hit, shard, row, state, store,
                      params: ClusterParams, qbits: int,
                      rec: StageRecorder) -> np.ndarray:
    """The accreted-tail warm path: device MinHash only for content-novel
    tail rows, stored signatures for the rest, host union-find merge."""
    from . import incremental as inc
    from .host import host_band_keys

    n = items.shape[0]
    n_old = state.n_rows
    k_new = n - n_old
    if k_new == 0:
        last_run_info["cache_novel_rows"] = 0
        return state.labels.astype(np.int32, copy=True)
    h = params.n_hashes
    tail_hit = hit[n_old:]
    miss = ~tail_hit
    new_sig = np.empty((k_new, h), np.uint32)
    if tail_hit.any():
        with rec.stage("load", nbytes=int(tail_hit.sum()) * h * 4):
            new_sig[tail_hit] = store.load_signatures(
                shard[n_old:][tail_hit], row[n_old:][tail_hit])
    if miss.any():
        sub = items[n_old:][miss]
        if qbits:
            sub = quantize_ids(sub, qbits)
        hp = make_params(params.scheme, params.n_hashes,
                         params.seed).device()
        sig_d, _ = _minhash_streamed(sub, hp, params, rec)
        with rec.stage("d2h", nbytes=int(sig_d.size) * 4):
            new_sig[miss] = np.asarray(sig_d)
    with rec.stage("compute"):
        # Band keys for the short tail on host — bit-identical to the
        # device fold (tests/test_cluster.py) and free of a link RTT.
        new_keys = host_band_keys(new_sig, params.n_bands)

        def gather_old(uniq: np.ndarray) -> np.ndarray:
            loc = state.locator[uniq]
            out = store.load_signatures(loc[:, 0], loc[:, 1])
            rec.add("load", 0.0, out.nbytes)
            return out

        # The batch warm merge is a CLIENT of the serving plane's live
        # index (cluster/incremental.LiveClusterIndex): one absorb
        # implementation — candidate edges from the stored tables,
        # exact signature verification, union-by-min label merge,
        # extend-never-rebuild tables — shared with tse1m_tpu/serve.
        index = inc.LiveClusterIndex.from_state(state)
        index = index.absorb(new_keys, new_sig, gather_old, h,
                             params.threshold)
        labels = index.labels
    # Commit: append the novel signatures, extend (never rebuild) the band
    # tables, advance the state to cover all n rows.
    if miss.any():
        store.append(digests[n_old:][miss], new_sig[miss])
    hit2, sh2, rw2 = store.bulk_probe(digests[n_old:])
    locator = np.concatenate(
        [state.locator, np.stack([sh2, rw2], axis=1)])
    store.save_state(labels, locator, index.band_tables(), digests,
                     params.n_bands, params.threshold)
    last_run_info["cache_novel_rows"] = int(miss.sum())
    return labels


def _store_union(items, digests, hit, shard, row, store,
                 params: ClusterParams, qbits: int,
                 rec: StageRecorder) -> np.ndarray:
    """Store-enabled full run: cached signatures ship instead of their
    rows; fresh rows stream through the existing pipeline; banded LSH +
    propagation run on device over the union.  Rows sit in
    [hit..., miss...] lane order and the encoded-path label kernel maps
    them back — hub election by original index keeps labels identical to
    a storeless run."""
    from . import incremental as inc

    n = items.shape[0]
    hp = make_params(params.scheme, params.n_hashes, params.seed).device()
    miss = ~hit
    hit_idx = np.flatnonzero(hit)
    miss_idx = np.flatnonzero(miss)
    sig_parts, key_parts = [], []
    if hit_idx.size:
        with rec.stage("load", nbytes=int(hit_idx.size) * params.n_hashes
                       * 4):
            sig_hit = store.load_signatures(shard[hit], row[hit])
        with rec.stage("h2d", nbytes=sig_hit.nbytes):
            sig_hit_d = jax.device_put(sig_hit)
            sig_hit_d.block_until_ready()
        with rec.stage("compute"):
            sig_parts.append(sig_hit_d)
            key_parts.append(band_keys(sig_hit_d, params.n_bands))
    if miss_idx.size:
        sub = items[miss_idx]
        if qbits:
            sub = quantize_ids(sub, qbits)
        sig_miss_d, keys_miss_d = _minhash_streamed(sub, hp, params, rec)
        sig_parts.append(sig_miss_d)
        key_parts.append(keys_miss_d)
    mask_bits = np.packbits(miss, bitorder="little")
    with rec.stage("h2d", nbytes=mask_bits.nbytes):
        mask_d = jax.device_put(mask_bits)
        mask_d.block_until_ready()
    with rec.stage("compute"):
        sig = sig_parts[0] if len(sig_parts) == 1 else jnp.concatenate(
            sig_parts)
        keys = key_parts[0] if len(key_parts) == 1 else jnp.concatenate(
            key_parts)
        labels_d = _cluster_encoded_labels(sig, keys, mask_d, n,
                                           params.threshold, params.n_iters)
        jax.block_until_ready(labels_d)
    with rec.stage("d2h", nbytes=n * 4):
        labels = np.asarray(labels_d)
    with rec.stage("d2h", nbytes=int(sig.size + keys.size) * 4):
        sig_lane = np.asarray(sig)
        keys_lane = np.asarray(keys)
    orig_of = np.concatenate([hit_idx, miss_idx])
    sig_orig = np.empty_like(sig_lane)
    sig_orig[orig_of] = sig_lane
    keys_orig = np.empty_like(keys_lane)
    keys_orig[orig_of] = keys_lane
    _store_commit(store, digests, miss, sig_orig, keys_orig, labels,
                  params, rec)
    last_run_info["cache_novel_rows"] = int(miss_idx.size)
    return labels


def _store_commit(store, digests, miss_mask, sig_orig, keys_orig, labels,
                  params: ClusterParams, rec: StageRecorder) -> None:
    """Append novel signatures and commit the full LSH state (labels,
    band tables, locator) so the next accreted run can warm-merge."""
    from . import incremental as inc

    store.append(digests[miss_mask], sig_orig[miss_mask])
    _, sh2, rw2 = store.bulk_probe(digests)
    locator = np.stack([sh2, rw2], axis=1)
    with rec.stage("compute"):
        tables = inc.build_band_tables(keys_orig)
    store.save_state(labels, locator, tables, digests,
                     params.n_bands, params.threshold)


def _store_populate_from_run(params: ClusterParams, qbits: int,
                             digests, sig_d, keys_d, labels, enc,
                             rec: StageRecorder) -> None:
    """Populate the store from a completed cold run's device arrays (the
    resumable path): fetch signatures/keys, undo the encoder's lane
    order, append misses and commit state."""
    from .store import SignatureStore

    store = SignatureStore(params.sig_store, _store_policy(params, qbits))
    with rec.stage("probe"):
        hit, _, _ = store.bulk_probe(digests)
    with rec.stage("d2h", nbytes=int(sig_d.size + keys_d.size) * 4):
        sig_lane = np.asarray(sig_d)
        keys_lane = np.asarray(keys_d)
    if enc is not None:
        is_delta = np.unpackbits(
            enc.mask_bits, bitorder="little")[:digests.shape[0]].astype(bool)
        orig_of = np.concatenate(
            [np.flatnonzero(~is_delta), np.flatnonzero(is_delta)])
        sig_orig = np.empty_like(sig_lane)
        sig_orig[orig_of] = sig_lane
        keys_orig = np.empty_like(keys_lane)
        keys_orig[orig_of] = keys_lane
    else:
        sig_orig, keys_orig = sig_lane, keys_lane
    _store_commit(store, digests, ~hit, sig_orig, keys_orig, labels,
                  params, rec)
    last_run_info.update(cache_hit_rate=round(float(hit.mean()), 4),
                         cache_mode="populate",
                         cache_novel_rows=int((~hit).sum()))


# ---------------------------------------------------------------------------
# Pod warm path (cluster/store.ShardedSignatureStore +
# resilience/coordinator.py): `--sig-store` under a mesh.
#
# Each process probes ONLY its local row range (bounding host MinHash
# work at N/nproc) against the digest-range-sharded store — every range
# is readable by every host, writable by exactly its owner — then
# device-MinHashes only its local novel tail through the existing
# degraded streaming pipeline.  The cross-host data plane is the SHARED
# STORE ROOT, not a device collective: the sharded store already
# requires a shared filesystem, novel (digest, signature) tails exchange
# as atomic per-run files (parallel/multihost.fs_exchange) so each owner
# appends its digest range's rows, and each host assembles the full
# signature matrix (its own slice + peers' novel tails + peers' cached
# rows gathered straight from the store) and runs the band-sharded tail
# kernel (cluster/sharded.py, minus the MinHash stage) on its LOCAL
# device mesh.  The tail is replicated per host — it is the cheap stage,
# MinHash over novel rows is the partitioned one — which buys two things:
# no cross-process XLA executable (the CPU backend cannot run one at
# all), and no collective that can hang forever on a dead peer; every
# cross-host wait polls the heartbeat monitor instead.  Labels are
# bit-identical to a cold run over the same rows.
#
# ``solo=True`` runs the same path with the exchange skipped: the
# coordinator's failover shape — a survivor re-executing the whole
# partition after peers were declared lost.  Elastic membership lives in
# resilience/coordinator.MembershipLedger: the survivor advances the
# epoch, the lost hosts' digest ranges re-deal to it under fresh epoch
# leases (`shard_range_reassigned` events, superseded leases fencing any
# zombie that later wakes), and their un-appended rows probe as misses
# and recompute — the exact semantics torn/corrupt shards already have,
# which is why failover labels equal an uninterrupted run's elementwise.


def cluster_sessions_pod(local_items, n_rows: int,
                         params: ClusterParams | None = None,
                         mesh: jax.sharding.Mesh | None = None,
                         axis: str = "data", supervisor=None,
                         exchange_dir: str | None = None,
                         solo: bool = False,
                         membership: dict | None = None,
                         n_processes: int | None = None,
                         process_id: int | None = None) -> np.ndarray:
    """Store-enabled clustering across pod processes.

    ``local_items``: this process's host-resident LOGICAL rows — the
    ``multihost.pod_row_range(n_rows, nproc, pid)`` slice (all rows when
    single-process or ``solo``).  ``mesh`` must be a LOCAL device mesh
    (defaults to one over ``jax.local_devices()``).  ``supervisor``
    (resilience.PodSupervisor) makes every cross-host wait raise
    HostLostError on a dead peer instead of hanging; ``exchange_dir`` is
    this run's negotiated exchange directory
    (resilience/coordinator.exchange_dir — required for multi-process
    runs).  ``membership`` is this run's epoch record
    (resilience/coordinator.MembershipLedger): it decides range
    ownership and arms the lease fence — a writer whose range was
    re-dealt raises LeaseSupersededError at its first append instead of
    double-writing.  A local-only call without one self-bootstraps a
    single-member ledger under the store's pod dir (advancing the epoch
    when the previous run had more members — the resumed-after-loss
    shape).  ``n_processes``/``process_id`` carry explicit pod identity
    (multihost.pod_process_env) so the pod plane never has to touch
    jax.distributed; they default to the jax identity for mesh callers.
    Returns the full [n_rows] label vector on every process."""
    from ..parallel import multihost
    from ..parallel.mesh import shard_along
    from .sharded import _sharded_label_kernel_from_sig
    from .store import ShardedSignatureStore, row_digests

    params = params or ClusterParams()
    _validate_encoding(params)
    if not params.sig_store:
        raise ValueError("cluster_sessions_pod requires params.sig_store "
                         "(the pod path IS the store path; use "
                         "cluster_sessions for cold runs)")
    if mesh is None:
        mesh = jax.sharding.Mesh(np.array(jax.local_devices()), (axis,))
    nproc = (int(n_processes) if n_processes is not None
             else (1 if solo else jax.process_count()))
    pid = (int(process_id) if process_id is not None
           else (0 if solo else jax.process_index()))
    local_only = solo or nproc == 1
    if not local_only and exchange_dir is None:
        raise ValueError("multi-process cluster_sessions_pod needs the "
                         "run's exchange_dir (negotiate it via "
                         "resilience.coordinator — cli.run_pod_cluster "
                         "does)")
    if membership is None and local_only:
        # Self-bootstrap a single-member epoch: a resumed/solo run
        # against an existing pod root advances the ledger (the lost
        # hosts' ranges re-deal to this process and their old-epoch
        # leases supersede), and a fresh root starts at epoch 0.
        from ..resilience.coordinator import MembershipLedger

        ledger = MembershipLedger(
            os.path.join(params.sig_store, "pod"),
            ShardedSignatureStore.root_n_ranges(params.sig_store,
                                                default=max(nproc, 1)))
        membership = ledger.bootstrap([pid], os.urandom(8).hex())
    if membership is None:
        raise ValueError("multi-process cluster_sessions_pod needs the "
                         "run's membership record (the epoch deal from "
                         "resilience.coordinator.MembershipLedger — "
                         "cli.run_pod_cluster negotiates it)")
    monitor = supervisor.monitor if supervisor is not None else None

    rec = StageRecorder()
    t_all = time.perf_counter()
    last_run_info.clear()
    local_items = np.ascontiguousarray(local_items, dtype=np.uint32)
    lo, hi = ((0, n_rows) if local_only
              else multihost.pod_row_range(n_rows, nproc, pid))
    k_local = hi - lo
    if local_items.shape[0] != k_local:
        raise ValueError(
            f"process {pid} must feed rows [{lo}, {hi}) of the logical "
            f"array ({k_local} rows), got {local_items.shape[0]}")
    # Auto wire quantization stays off under the pod path (it keys off a
    # GLOBAL byte/max inventory no single host holds); explicit bits
    # apply — and land in the store policy, which refuses mismatches.
    qbits = params.wire_quant_bits if params.wire_quant_bits > 0 else 0
    h = params.n_hashes
    with rec.stage("probe"):
        digests = row_digests(local_items)  # RAW ids, pre-quantization
        store = ShardedSignatureStore(params.sig_store,
                                      _store_policy(params, qbits),
                                      n_processes=1 if local_only else nproc,
                                      process_id=pid,
                                      membership=membership)
        hit, loc = store.probe(digests)
    sig_local = np.zeros((k_local, h), np.uint32)
    if hit.any():
        with rec.stage("load", nbytes=int(hit.sum()) * h * 4):
            sig_local[hit] = store.load_signatures(loc[hit])
    miss = ~hit
    if miss.any():
        # Per-host novel tail: only this process's content-novel rows
        # touch the device, through the existing degradation-aware
        # streaming pipeline (OOM halving / stall retry / CPU failover).
        sub = local_items[miss]
        if qbits:
            sub = quantize_ids(sub, qbits)
        hp = make_params(params.scheme, params.n_hashes,
                         params.seed).device()
        sig_d, _ = _minhash_streamed(sub, hp, params, rec)
        with rec.stage("d2h", nbytes=int(sig_d.size) * 4):
            sig_local[miss] = np.asarray(sig_d)
    if local_only:
        payloads = [{"digests": digests, "miss": miss,
                     "novel_sigs": sig_local[miss]}]
    else:
        # Novel-tail exchange over the shared store root (doubles as the
        # barrier between per-host MinHash and the replicated tail); the
        # wait polls the heartbeat monitor — a dead peer raises
        # HostLostError here, never a hang.
        payloads = multihost.fs_exchange(
            exchange_dir, "novel", {"digests": digests, "miss": miss,
                                    "novel_sigs": sig_local[miss]},
            monitor=monitor, n_processes=nproc, process_id=pid)
    # Each digest range's OWNER appends its rows (single-writer per
    # range); duplicate content MinHashed by two hosts dedups in append.
    all_nd = np.concatenate([p["digests"][p["miss"].astype(bool)]
                             for p in payloads])
    all_ns = np.concatenate([p["novel_sigs"] for p in payloads])
    mine = store.owned_mask(all_nd)
    appended = store.append(all_nd[mine], all_ns[mine])
    total_rows = sum(int(p["digests"].shape[0]) for p in payloads)
    total_hits = sum(int((~p["miss"].astype(bool)).sum())
                     for p in payloads)
    # Full signature matrix, pid order == logical row order
    # (pod_row_range deals contiguous slices): peers' novel tails came
    # over the exchange; peers' cached rows gather straight from the
    # store (readable by every host — committed before this run, so the
    # read cannot race this run's appends).
    parts: list[np.ndarray] = []
    my_slot = 0 if local_only else pid  # payload list index of this host
    with rec.stage("load", nbytes=(total_rows - k_local) * h * 4):
        for p, pay in enumerate(payloads):
            if p == my_slot:
                parts.append(sig_local)
                continue
            pmiss = pay["miss"].astype(bool)
            psig = np.zeros((pay["digests"].shape[0], h), np.uint32)
            psig[pmiss] = pay["novel_sigs"]
            if (~pmiss).any():
                chit, cloc = store.probe(pay["digests"][~pmiss])
                if not chit.all():
                    raise RuntimeError(
                        f"pod: {int((~chit).sum())} row(s) process {p} "
                        "reported cached are no longer in the store "
                        "(eviction or quarantine raced the run); rerun — "
                        "the rows will probe as misses and recompute")
                psig[~pmiss] = store.load_signatures(cloc)
            parts.append(psig)
    sig_full = parts[0] if len(parts) == 1 else np.concatenate(parts)
    hit_rate = float(total_hits) / max(total_rows, 1)
    last_run_info.update(
        encoding="pod-store", cache_mode="pod",
        cache_hit_rate=round(hit_rate, 4),
        cache_novel_rows=int(total_rows - total_hits),
        cache_store_rows=int(store.n_rows), wire_quant_bits=qbits,
        pod_processes=nproc, pod_n_ranges=store.n_ranges,
        pod_owned_ranges=list(store.owned),
        pod_reassigned_ranges=list(store.reassigned_ranges),
        pod_appended_rows=int(appended),
        pod_epoch=(int(membership["epoch"]) if membership else None),
        pod_members=list(membership.get("members", []))
        if membership else None)
    # Replicated tail on the LOCAL mesh: row-sharded signatures in,
    # replicated labels out — the sharded kernel family minus its MinHash
    # stage.  Pad rows carry zero signatures: they sit past every real
    # index (hub election by min original index can never elect them over
    # a real row) and are sliced off the label vector.
    n_dev = mesh.devices.size
    pad_rows = (-n_rows) % n_dev
    sig_feed = (np.concatenate(
        [sig_full, np.zeros((pad_rows, h), np.uint32)])
        if pad_rows else sig_full)
    with rec.stage("h2d", nbytes=sig_feed.nbytes):
        sig_arr = jax.device_put(sig_feed,
                                 shard_along(mesh, axis=axis, rank=2))
        jax.block_until_ready(sig_arr)
    kernel = _sharded_label_kernel_from_sig(mesh, axis, params.n_bands,
                                            params.threshold,
                                            params.n_iters)
    with rec.stage("compute"):
        labels_d = kernel(sig_arr)
        jax.block_until_ready(labels_d)
    with rec.stage("d2h", nbytes=n_rows * 4):
        labels = np.asarray(labels_d)[:n_rows]
    _record_wire(rec)
    _finish_run(rec, t_all)
    return labels
