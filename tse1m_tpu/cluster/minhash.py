"""MinHash signatures + banded LSH keys (jax reference path).

Hash family: multiply-add over uint32 with natural wraparound —
``h_i(x) = a_i * x + b_i (mod 2^32)`` with odd ``a_i``.  Multiply-shift
universal hashing is integer-only, so everything rides the VPU; no
float precision traps, bit-exact across CPU/TPU and vs the numpy host
oracle (host.py), which shares the same parameters.

The signature kernel is deliberately a `fori_loop` over the (small, static)
set dimension accumulating an elementwise min of `[N, H]` blocks: peak
memory stays O(N*H) instead of the O(N*S*H) a broadcast formulation would
materialise, and XLA fuses the multiply-add-min chain into one pass.
A fused pallas VMEM-blocked variant lives in minhash_pallas.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

UMAX = np.uint32(0xFFFFFFFF)
# FNV-1a-style mixing constants for band keys.
_FNV_PRIME = np.uint32(16777619)
_FNV_OFFSET = np.uint32(2166136261)


def make_hash_params(n_hashes: int, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic (a, b) uint32 hash parameters, a forced odd.

    Generated host-side with numpy so the device path and the numpy oracle
    share bit-identical signatures (determinism requirement, SURVEY.md §7.3).
    """
    rng = np.random.default_rng(seed)
    a = rng.integers(1, 1 << 32, size=n_hashes, dtype=np.uint32) | np.uint32(1)
    b = rng.integers(0, 1 << 32, size=n_hashes, dtype=np.uint32)
    return a, b


@partial(jax.jit, static_argnames=())
def minhash_signatures(items: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """[N, S] uint32 feature sets -> [N, H] uint32 MinHash signatures.

    sig[n, h] = min_s (a[h] * items[n, s] + b[h]) mod 2^32.
    """
    items = items.astype(jnp.uint32)
    a = a.astype(jnp.uint32)
    b = b.astype(jnp.uint32)
    n, s = items.shape

    def body(i, acc):
        col = jax.lax.dynamic_slice_in_dim(items, i, 1, axis=1)  # [N, 1]
        h = col * a[None, :] + b[None, :]  # [N, H], wraps mod 2^32
        return jnp.minimum(acc, h)

    init = jnp.full((n, a.shape[0]), UMAX, dtype=jnp.uint32)
    return jax.lax.fori_loop(0, s, body, init)


@partial(jax.jit, static_argnames=("n_bands",))
def band_keys(sig: jax.Array, n_bands: int) -> jax.Array:
    """[N, H] signatures -> [N, B] uint32 LSH band keys.

    Jitted (n_bands static) so the FNV constants embed as compile-time
    constants instead of staging eagerly per call — the runtime sanitizer
    (lint/runtime.py) runs the hot loop under a transfer guard that
    rejects exactly that implicit per-call staging.

    Each band folds its H/B signature rows with an FNV-1a-style mix, salted
    by the band index so identical row-chunks in different bands can't
    collide by construction.  32-bit keys do admit birthday collisions
    (~N^2/2^33 spurious bucket merges per band at N=1M) — downstream
    signature verification (pipeline.py) rejects those edges, so we avoid
    the cost of 64-bit lexicographic sorting on a 32-bit-native device.

    Bands are *interleaved*: band k folds signature rows {k, k+B, k+2B, ...}.
    Hash rows are iid so this is statistically identical to contiguous
    banding, and it makes "row j of every band" a contiguous [N, B] slice —
    the layout the fused pallas kernel can lower (Mosaic has no strided
    vector extract).
    """
    sig = sig.astype(jnp.uint32)
    n, h = sig.shape
    assert h % n_bands == 0, f"n_hashes {h} not divisible by n_bands {n_bands}"
    r = h // n_bands
    chunks = sig.reshape(n, r, n_bands)  # [:, j, k] = sig[:, j*B + k]

    def fold(carry, x):
        return (carry ^ x) * _FNV_PRIME, None

    salt = _FNV_OFFSET + jnp.arange(n_bands, dtype=jnp.uint32)[None, :]
    keys, _ = jax.lax.scan(fold, jnp.broadcast_to(salt, (n, n_bands)),
                           jnp.moveaxis(chunks, 1, 0))
    return keys
