"""MinHash signature kernels + banded LSH keys (jax reference paths).

Two signature kernels live here; the per-run choice is the ``scheme``
policy field (cluster/schemes.py owns the registry and every dispatch):

- **kminhash** — K independent multiply-add hashes over uint32 with
  natural wraparound: ``h_i(x) = a_i * x + b_i (mod 2^32)`` with odd
  ``a_i``.  Multiply-shift universal hashing is integer-only, so
  everything rides the VPU; no float precision traps, bit-exact across
  CPU/TPU and vs the numpy host oracle (host.py), which shares the same
  parameters.  The kernel is deliberately a `fori_loop` over the (small,
  static) set dimension accumulating an elementwise min of `[N, H]`
  blocks: peak memory stays O(N*H) instead of the O(N*S*H) a broadcast
  formulation would materialise, and XLA fuses the multiply-add-min
  chain into one pass.

- **cminhash** — one-permutation hashing with circulant-shift repair
  (C-MinHash, arXiv:2109.03337/2109.04595) and bounded optimal-style
  densification (arXiv:1703.04664).  ONE multiply-add pass permutes the
  elements (the only per-element hash evaluations — ~H× fewer than
  kminhash at equal ``n_hashes``); each permuted value lands in bin
  ``u % H`` and the bin keeps its minimum.  Empty bins (sparse rows)
  densify deterministically: a fixed schedule of donor maps borrows
  from non-empty bins (chained rounds — the empty fraction squares per
  round), and any bin still empty after the schedule takes the
  C-MinHash circulant value ``rowmin(u) + off[k]`` so no bin ever
  carries the UMAX sentinel into a band key.  All three implementations
  (this fori_loop reference, host.py's numpy mirror, and the pallas
  VMEM-blocked variant in minhash_pallas.py) are bit-identical —
  CI-asserted, because the store/prefilter/serve planes compare
  signatures across them.

Band keys are computed from signatures by the same FNV fold for every
scheme — banding consumes signature rows, not hash internals.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

UMAX = np.uint32(0xFFFFFFFF)
# FNV-1a-style mixing constants for band keys.
_FNV_PRIME = np.uint32(16777619)
_FNV_OFFSET = np.uint32(2166136261)


def make_hash_params(n_hashes: int, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic (a, b) uint32 hash parameters, a forced odd.

    Generated host-side with numpy so the device path and the numpy oracle
    share bit-identical signatures (determinism requirement, SURVEY.md §7.3).
    """
    rng = np.random.default_rng(seed)
    a = rng.integers(1, 1 << 32, size=n_hashes, dtype=np.uint32) | np.uint32(1)
    b = rng.integers(0, 1 << 32, size=n_hashes, dtype=np.uint32)
    return a, b


@partial(jax.jit, static_argnames=())
def minhash_signatures(items: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """[N, S] uint32 feature sets -> [N, H] uint32 MinHash signatures.

    sig[n, h] = min_s (a[h] * items[n, s] + b[h]) mod 2^32.
    """
    items = items.astype(jnp.uint32)
    a = a.astype(jnp.uint32)
    b = b.astype(jnp.uint32)
    n, s = items.shape

    def body(i, acc):
        col = jax.lax.dynamic_slice_in_dim(items, i, 1, axis=1)  # [N, 1]
        h = col * a[None, :] + b[None, :]  # [N, H], wraps mod 2^32
        return jnp.minimum(acc, h)

    init = jnp.full((n, a.shape[0]), UMAX, dtype=jnp.uint32)
    return jax.lax.fori_loop(0, s, body, init)


@partial(jax.jit, static_argnames=())
def _cminhash_densify(v: jax.Array, rowmin: jax.Array, jmap: jax.Array,
                      offs: jax.Array) -> jax.Array:
    """Densification + circulant fallback over the [N, H] bin-min block
    (UMAX = empty).  O(N*H) — bandwidth-trivial next to the O(N*S)
    permutation pass, so it runs as plain jnp even when that pass runs
    in pallas: ONE implementation serves both, which is half of the
    bit-parity argument."""

    def densify(t, v):
        jm = jax.lax.dynamic_index_in_dim(jmap, t, 0, keepdims=False)
        cand = jnp.take(v, jm, axis=1)
        return jnp.where((v == UMAX) & (cand != UMAX), cand, v)

    v = jax.lax.fori_loop(0, jmap.shape[0], densify, v)
    # Circulant-shift fallback (the C-MinHash construction, degenerate
    # to the row minimum): only bins still empty after the densification
    # schedule — for |S| within ~2x of H this is statistically never.
    fb = rowmin[:, None] + offs[None, :]
    return jnp.where(v == UMAX, fb, v)


@partial(jax.jit, static_argnames=())
def cminhash_signatures(items: jax.Array, a0: jax.Array, b0: jax.Array,
                        jmap: jax.Array, offs: jax.Array) -> jax.Array:
    """[N, S] uint32 feature sets -> [N, H] uint32 one-permutation
    signatures (C-MinHash + bounded densification; module docstring).

    ``a0``/``b0``: the single permutation's multiply-add constants
    (scalar uint32, a0 odd).  ``jmap``: [T, H] int32 donor maps for the
    densification rounds.  ``offs``: [H] uint32 circulant fallback
    offsets.  All are host-derived from the scheme seed
    (schemes.make_params) so host/device share them bit-identically.
    """
    items = items.astype(jnp.uint32)
    n, s = items.shape
    h = offs.shape[0]
    u = items * a0 + b0                       # the one permutation pass
    bins = (u % jnp.uint32(h)).astype(jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)

    def seg_min(i, acc):
        uc = jax.lax.dynamic_slice_in_dim(u, i, 1, axis=1)[:, 0]
        bc = jax.lax.dynamic_slice_in_dim(bins, i, 1, axis=1)[:, 0]
        return acc.at[rows, bc].min(uc)

    v = jax.lax.fori_loop(0, s, seg_min,
                          jnp.full((n, h), UMAX, dtype=jnp.uint32))
    return _cminhash_densify(v, u.min(axis=1), jmap, offs)


@partial(jax.jit, static_argnames=("n_bands",))
def band_keys(sig: jax.Array, n_bands: int) -> jax.Array:
    """[N, H] signatures -> [N, B] uint32 LSH band keys.

    Jitted (n_bands static) so the FNV constants embed as compile-time
    constants instead of staging eagerly per call — the runtime sanitizer
    (lint/runtime.py) runs the hot loop under a transfer guard that
    rejects exactly that implicit per-call staging.

    Each band folds its H/B signature rows with an FNV-1a-style mix, salted
    by the band index so identical row-chunks in different bands can't
    collide by construction.  32-bit keys do admit birthday collisions
    (~N^2/2^33 spurious bucket merges per band at N=1M) — downstream
    signature verification (pipeline.py) rejects those edges, so we avoid
    the cost of 64-bit lexicographic sorting on a 32-bit-native device.

    Bands are *interleaved*: band k folds signature rows {k, k+B, k+2B, ...}.
    Hash rows are iid so this is statistically identical to contiguous
    banding, and it makes "row j of every band" a contiguous [N, B] slice —
    the layout the fused pallas kernel can lower (Mosaic has no strided
    vector extract).
    """
    sig = sig.astype(jnp.uint32)
    n, h = sig.shape
    assert h % n_bands == 0, f"n_hashes {h} not divisible by n_bands {n_bands}"
    r = h // n_bands
    chunks = sig.reshape(n, r, n_bands)  # [:, j, k] = sig[:, j*B + k]

    def fold(carry, x):
        return (carry ^ x) * _FNV_PRIME, None

    salt = _FNV_OFFSET + jnp.arange(n_bands, dtype=jnp.uint32)[None, :]
    keys, _ = jax.lax.scan(fold, jnp.broadcast_to(salt, (n, n_bands)),
                           jnp.moveaxis(chunks, 1, 0))
    return keys
