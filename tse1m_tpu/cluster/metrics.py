"""Clustering quality metrics (host-side, numpy).

ARI is the north-star acceptance gate (BASELINE.json: ARI >= 0.98 vs the
host baseline).  Implemented directly from the pair-counting contingency
form so there is no sklearn dependency; sparse via unique pair codes —
O(N log N), fine for 1M labels.
"""

from __future__ import annotations

import numpy as np


def _comb2(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.float64)
    return x * (x - 1.0) / 2.0


def adjusted_rand_index(labels_a, labels_b) -> float:
    a = np.asarray(labels_a).ravel()
    b = np.asarray(labels_b).ravel()
    if a.shape != b.shape:
        raise ValueError(f"label shapes differ: {a.shape} vs {b.shape}")
    n = a.size
    if n < 2:
        return 1.0
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    nb = int(bi.max()) + 1
    codes = ai.astype(np.int64) * nb + bi
    _, pair_counts = np.unique(codes, return_counts=True)
    _, a_counts = np.unique(ai, return_counts=True)
    _, b_counts = np.unique(bi, return_counts=True)

    sum_pairs = _comb2(pair_counts).sum()
    sum_a = _comb2(a_counts).sum()
    sum_b = _comb2(b_counts).sum()
    total = _comb2(np.array([n]))[0]

    expected = sum_a * sum_b / total
    max_index = (sum_a + sum_b) / 2.0
    if max_index == expected:
        return 1.0
    return float((sum_pairs - expected) / (max_index - expected))
