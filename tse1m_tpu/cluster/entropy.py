"""Static-table interleaved-rANS entropy codec for the wire's lanes.

After PR 2's adaptive bit-packing the wire ships every lane at the
minimal *fixed* width its value range needs — but the delta lanes are
highly *skewed* within that range (counts concentrate around the planted
mutation rate, base references and positions are far from uniform), so a
fixed width still leaves the gap between ``bits`` and the lane's actual
order-0 entropy on the table.  This module closes it with a classic
static-table range coder (rANS, the table-driven duality of arithmetic
coding): per-lane frequency tables are measured on host, normalized to a
2^12 grid, and shipped in the header; symbols stream through
``N_STREAMS`` interleaved rANS states so the device can decode
data-parallel (one vector lane per stream — the SIMD-rANS layout), and
the whole frame is CRC-checked like a store shard before it is allowed
onto the wire.

The codec is *honest*: :func:`encode_lane` first estimates the coded
size from the measured entropy and returns ``None`` unless the table +
payload beat the bit-packed form by a real margin (then re-checks the
measured size post-encode) — uniform lanes (e.g. quantized ids, whose
universe is a hash image) fall back to the plain pack, so wire v3 never
regresses v2.  Decoders: :func:`decode_lane_host` is the numpy oracle;
`cluster/kernels/rans.py` holds the on-device decoders (jnp `fori_loop`
+ a pallas variant) fused into the pipeline's packed-unpack path.

rANS invariants (32-bit state, 16-bit renormalization, 12-bit
frequencies): state ``x`` lives in ``[2^16, 2^32)``; encoding symbol
``s`` with frequency ``f`` requires ``x < ((L >> 12) << 16) * f`` so at
most ONE 16-bit word is emitted per symbol, and decode consumes at most
one — which is what makes the per-step word-consumption count a cheap
cumsum on device instead of a data-dependent loop.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# Same polynomial-availability dance as cluster/store.py's shard frames:
# hardware CRC32C when the wheel is present, zlib CRC-32 otherwise (equal
# burst-detection power; only the polynomial differs, and the frame never
# leaves this process so cross-algo portability is moot).
try:  # pragma: no cover - environment-dependent
    from crc32c import crc32c as _crc_update
except ImportError:  # pragma: no cover
    from zlib import crc32 as _crc_update

PROB_BITS = 12                 # frequency grid: tables normalize to 2^12
_M = 1 << PROB_BITS
RANS_L = 1 << 16               # state lower bound; words are 16-bit
N_STREAMS = 32                 # interleaved states = device vector lanes
# Direct symbol coding up to this width (table = 2^bits entries); wider
# values split into 8-bit byte planes, each its own 256-symbol stream.
_DIRECT_BITS_MAX = 12
# Measured-win margin: the coded frame (payload + tables + states) must
# beat the bit-packed lane by at least this many bytes, or the caller
# ships plain pack — the "selectable per chunk" fallback of wire v3.
WIN_MIN_SAVE_BYTES = 64


class EntropyFrameError(ValueError):
    """A coded lane's CRC frame does not match its arrays (memory
    corruption between encode and device_put)."""


@dataclass(frozen=True)
class PlaneCode:
    """One symbol stream's coded form — exactly the arrays that cross
    the wire for this plane (everything else is static header)."""

    words: np.ndarray   # [W] uint16 — interleaved renormalization words
    x0: np.ndarray      # [N_STREAMS] uint32 — initial decoder states
    freqs: np.ndarray   # [alphabet] uint16 — normalized frequency table

    @property
    def nbytes(self) -> int:
        return int(self.words.nbytes + self.x0.nbytes + self.freqs.nbytes)


@dataclass(frozen=True)
class EntropyLane:
    """A lane's complete coded frame: per-plane streams + CRC.

    ``bits`` is the logical value width (the same number the bit-packed
    alternative would use); values are reconstructed as the little-endian
    combination of the planes.  ``n`` is the value count.
    """

    n: int
    bits: int
    planes: tuple          # tuple[PlaneCode, ...]
    crc: int

    @property
    def nbytes(self) -> int:
        return int(sum(p.nbytes for p in self.planes))

    def wire_arrays(self) -> list:
        """The device_put inventory, in a fixed order the decoders (and
        bench's transfer probe) share: (words, x0, freqs) per plane."""
        out: list = []
        for p in self.planes:
            out += [p.words, p.x0, p.freqs]
        return out

    def plane_alphabet(self, p: int) -> int:
        return int(self.planes[p].freqs.shape[0])


def packed_nbytes(n: int, bits: int) -> int:
    """Size of the bit-packed alternative (encode.pack_bits_host)."""
    return (n * bits + 7) // 8


def _lane_crc(n: int, bits: int, planes: tuple) -> int:
    crc = _crc_update(np.asarray([n, bits], np.int64).tobytes(), 0)
    for p in planes:
        crc = _crc_update(np.ascontiguousarray(p.words).tobytes(), crc)
        crc = _crc_update(np.ascontiguousarray(p.x0).tobytes(), crc)
        crc = _crc_update(np.ascontiguousarray(p.freqs).tobytes(), crc)
    return int(crc) & 0xFFFFFFFF


def verify_frame(lane: EntropyLane) -> None:
    """Re-check the frame right before the arrays ship (the producer
    thread packs into buffers the main thread later puts; a flipped byte
    between the two must refuse, mirroring store-shard semantics)."""
    have = _lane_crc(lane.n, lane.bits, lane.planes)
    if have != lane.crc:
        raise EntropyFrameError(
            f"entropy lane frame mismatch: crc {have:#010x} != recorded "
            f"{lane.crc:#010x} (n={lane.n}, bits={lane.bits}) — buffer "
            "corrupted between encode and ship")


def normalize_freqs(counts: np.ndarray) -> np.ndarray:
    """Scale integer symbol counts to a table summing exactly to 2^12,
    every present symbol >= 1 (rANS requires nonzero frequency for every
    codable symbol).  Deterministic."""
    counts = np.asarray(counts, np.int64)
    total = int(counts.sum())
    if total <= 0:
        raise ValueError("normalize_freqs needs at least one symbol")
    f = (counts * _M // total).astype(np.int64)
    f[(counts > 0) & (f == 0)] = 1
    err = int(f.sum()) - _M
    if err != 0:
        # Settle the rounding debt against the largest entries (never
        # below 1): ≤ alphabet iterations, bounded and deterministic.
        order = np.argsort(-f, kind="stable")
        i = 0
        while err != 0:
            j = order[i % order.size]
            if err > 0 and f[j] > 1:
                f[j] -= 1
                err -= 1
            elif err < 0 and f[j] > 0:
                f[j] += 1
                err += 1
            i += 1
    return f.astype(np.uint16)


def _cumcount(a: np.ndarray, k: int) -> np.ndarray:
    """For each element, how many earlier elements share its value."""
    order = np.argsort(a, kind="stable")
    counts = np.bincount(a, minlength=k)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    ranks = np.empty(a.size, np.int64)
    ranks[order] = np.arange(a.size) - np.repeat(starts, counts)
    return ranks


def rans_encode(sym: np.ndarray, freqs: np.ndarray,
                ) -> tuple[np.ndarray, np.ndarray]:
    """Encode ``sym`` (uint32, < alphabet) -> (words uint16, x0 uint32).

    Symbols deal round-robin into ``N_STREAMS`` states (symbol i belongs
    to stream i % K at step i // K); each stream encodes its symbols in
    reverse, and the emitted words interleave into ONE flat array in the
    exact order the forward-running decoder consumes them — so the
    decoder needs a single shared pointer, no per-stream offsets."""
    k = N_STREAMS
    n = int(sym.size)
    if n == 0:
        return (np.zeros(0, np.uint16),
                np.full(k, RANS_L, np.uint32))
    steps = -(-n // k)
    cum = np.zeros(freqs.shape[0] + 1, np.uint64)
    cum[1:] = np.cumsum(freqs.astype(np.uint64))
    f64 = freqs.astype(np.uint64)
    sym = np.ascontiguousarray(sym, np.uint32)
    x = np.full(k, RANS_L, np.uint64)
    flags = np.zeros((steps, k), bool)
    buf = np.zeros((k, steps + 1), np.uint16)
    wc = np.zeros(k, np.int64)
    ks = np.arange(k)
    for t in range(steps - 1, -1, -1):
        idx = t * k + ks
        act = idx < n
        s = sym[np.minimum(idx, n - 1)]
        f = f64[s]
        xmax = np.uint64((RANS_L >> PROB_BITS) << 16) * f
        emit = act & (x >= xmax)
        if emit.any():
            rows = ks[emit]
            buf[rows, wc[rows]] = (x[emit] & np.uint64(0xFFFF)).astype(
                np.uint16)
            wc[rows] += 1
            x[emit] >>= np.uint64(16)
            flags[t, emit] = True
        with np.errstate(divide="ignore"):
            xn = ((x // np.maximum(f, 1)) << np.uint64(PROB_BITS)) \
                + (x % np.maximum(f, 1)) + cum[s]
        x = np.where(act, xn, x)
    # Interleave: decode consumes at step t for stream k1 iff
    # flags[t, k1]; each stream's words in consumption order are its
    # emitted words reversed (encode ran t backwards).
    pos = np.flatnonzero(flags.ravel())          # ascending (t, stream)
    stream = (pos % k).astype(np.int64)
    occ = _cumcount(stream, k)                   # consumption rank
    cnt = np.bincount(stream, minlength=k)
    words = buf[stream, cnt[stream] - 1 - occ]
    return np.ascontiguousarray(words, np.uint16), x.astype(np.uint32)


def rans_decode_host(words: np.ndarray, x0: np.ndarray, freqs: np.ndarray,
                     n: int) -> np.ndarray:
    """Numpy oracle for the device decoders; inverse of rans_encode."""
    k = N_STREAMS
    if n == 0:
        return np.zeros(0, np.uint32)
    steps = -(-n // k)
    cumi = np.cumsum(freqs.astype(np.uint64))
    cume = np.concatenate([[np.uint64(0)], cumi[:-1]])
    slot_sym = np.searchsorted(cumi, np.arange(_M), side="right").astype(
        np.int64)
    f64 = freqs.astype(np.uint64)
    x = x0.astype(np.uint64).copy()
    ks = np.arange(k)
    out = np.empty((steps, k), np.uint32)
    ptr = 0
    words = np.asarray(words, np.uint64)
    for t in range(steps):
        act = (t * k + ks) < n
        slot = x & np.uint64(_M - 1)
        s = slot_sym[slot.astype(np.int64)]
        out[t] = s
        xn = f64[s] * (x >> np.uint64(PROB_BITS)) + slot - cume[s]
        x = np.where(act, xn, x)
        need = act & (x < RANS_L)
        rows = np.flatnonzero(need)
        if rows.size:
            w = words[ptr:ptr + rows.size]
            x[rows] = (x[rows] << np.uint64(16)) | w
            ptr += rows.size
    return out.reshape(-1)[:n]


def _plane_symbols(vals: np.ndarray, bits: int) -> list[tuple[np.ndarray,
                                                              int]]:
    """Split values into per-plane symbol streams: direct symbols up to
    _DIRECT_BITS_MAX, little-endian byte planes above."""
    v = np.ascontiguousarray(vals, np.uint32).reshape(-1)
    if bits <= _DIRECT_BITS_MAX:
        return [(v, 1 << bits)]
    nb = (bits + 7) // 8
    return [(((v >> np.uint32(8 * p)) & np.uint32(0xFF)), 256)
            for p in range(nb)]


def _entropy_bits(counts: np.ndarray) -> float:
    """Order-0 entropy (bits/symbol) of a count vector."""
    total = counts.sum()
    if total == 0:
        return 0.0
    p = counts[counts > 0] / total
    return float(-(p * np.log2(p)).sum())


def encode_lane(vals: np.ndarray, bits: int,
                force: bool = False) -> EntropyLane | None:
    """Entropy-code a lane of ``bits``-wide values, or None when the
    frame would not beat the bit-packed form (the per-chunk win
    threshold; ``force`` encodes regardless, for tests/CI).

    Two gates: a cheap entropy *estimate* skips the encoder entirely for
    near-uniform lanes, then the *measured* frame size is re-checked —
    the estimate is a lower bound, never the authority."""
    v = np.ascontiguousarray(vals, np.uint32).reshape(-1)
    n = int(v.size)
    if bits < 1 or bits > 32:
        raise ValueError(f"lane width must be in [1, 32], got {bits}")
    if n == 0:
        if not force:
            return None
        planes = []
        for _, alphabet in _plane_symbols(v, bits):
            freqs = np.zeros(alphabet, np.uint16)
            freqs[0] = _M
            planes.append(PlaneCode(words=np.zeros(0, np.uint16),
                                    x0=np.full(N_STREAMS, RANS_L,
                                               np.uint32),
                                    freqs=freqs))
        planes = tuple(planes)
        return EntropyLane(n=0, bits=bits, planes=planes,
                           crc=_lane_crc(0, bits, planes))
    packed = packed_nbytes(n, bits)
    specs = _plane_symbols(v, bits)
    counts = [np.bincount(s, minlength=a) for s, a in specs]
    if not force:
        est = sum(n * _entropy_bits(c) / 8 for c in counts)
        header = sum(2 * a + 4 * N_STREAMS for _, a in specs)
        if est + header + WIN_MIN_SAVE_BYTES >= packed:
            return None
    planes = []
    for (s, _alphabet), c in zip(specs, counts):
        freqs = normalize_freqs(c)
        words, x0 = rans_encode(s, freqs)
        planes.append(PlaneCode(words=words, x0=x0, freqs=freqs))
    planes = tuple(planes)
    lane = EntropyLane(n=n, bits=bits, planes=planes,
                       crc=_lane_crc(n, bits, planes))
    if not force and lane.nbytes + WIN_MIN_SAVE_BYTES >= packed:
        return None  # the estimate lied (pathological table overhead)
    return lane


def decode_lane_host(lane: EntropyLane) -> np.ndarray:
    """Reference decoder — the device decoders' numpy oracle."""
    verify_frame(lane)
    out = np.zeros(lane.n, np.uint32)
    for p, pc in enumerate(lane.planes):
        plane = rans_decode_host(pc.words, pc.x0, pc.freqs, lane.n)
        out |= plane << np.uint32(8 * p if lane.bits > _DIRECT_BITS_MAX
                                  else 0)
    return out
