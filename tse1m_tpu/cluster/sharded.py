"""Band-sharded multi-device clustering: distribute the post-MinHash tail.

Round 4 sharded only the MinHash stage; the bucket/verify/propagate tail
ran fully replicated — every device argsorted the full [N] key vector for
every band, so per-device work grew O(N_total * B) with device count and
the weak-scaling curve collapsed (619k -> 60k rows/s from 1 -> 8 devices,
MULTICHIP_r04).  This module shards the tail BY BAND with `shard_map`:

- MinHash + band keys: row-sharded, collective-free (as before);
- `all_to_all` re-shards keys [N/d, B] -> [N, B/d]: each device owns all
  rows of B/d bands and sorts only those — per-device sort work is
  O((B/d) * N log N), restoring weak scaling;
- hub election stays by GLOBAL row id (segment-min over global indices),
  so the verified edge set — and therefore the labels — is bit-identical
  to the single-device path (asserted in tests/test_cluster.py);
- one `all_gather` replicates the signatures for edge verification (the
  only O(N*H) term; 512 MB at 1M x 128 — within a v5e's 16 GB to ~20M
  rows, and the traffic rides ICI on a pod);
- label propagation keeps labels replicated ([N] int32) and reduces each
  pull/push step across devices with `pmin` over the band axis — the
  per-iteration gathers, the dominant tail cost, shrink to B/d bands per
  device.

Reference seat: the north-star "MinHash + banded LSH under pjit over the
TPU mesh" (BASELINE.json; SURVEY.md §2.4 — the reference itself has no
parallelism to mirror, SURVEY §2.4's explicit statement).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils.compat import shard_map

from .lsh import bucket_representatives, estimated_jaccard, propagate_labels
from .minhash import band_keys
from .schemes import scheme_signatures_traced


def _band_sharded_tail(sig_loc, keys_loc, axis: str, pad_bands: int,
                       threshold: float, n_iters: int):
    """The shared bucket/verify/propagate tail, from this device's row
    shard of (signatures, band keys) to replicated labels.  Called from
    inside a shard_map body by both the item-fed and the signature-fed
    kernels — one implementation is what keeps their labels (and the
    single-device path's) bit-identical."""
    if pad_bands:
        nl = keys_loc.shape[0]
        gid = (jax.lax.axis_index(axis).astype(jnp.uint32) * nl
               + jnp.arange(nl, dtype=jnp.uint32))
        keys_loc = jnp.concatenate(
            [keys_loc,
             jnp.broadcast_to(gid[:, None], (nl, pad_bands))], axis=1)
    # Re-shard: each device gets ALL rows of its B/d bands.  Global row
    # ids are recoverable because all_to_all concatenates source shards
    # in axis order, matching the contiguous row sharding.
    kt = jax.lax.all_to_all(keys_loc, axis, split_axis=1, concat_axis=0,
                            tiled=True)                # [N, B/d]
    sig = jax.lax.all_gather(sig_loc, axis, axis=0, tiled=True)  # [N, H]
    n = sig.shape[0]

    # Same election + verification as the single-device path, applied
    # to this device's owned bands — one shared implementation is what
    # keeps the mesh labels bit-identical (lsh.band_hub_election).
    reps_t = bucket_representatives(kt)                # [N, B/d]
    est_t = estimated_jaccard(sig, reps_t)
    self_idx = jnp.arange(n, dtype=jnp.int32)[:, None]
    valid_t = (est_t >= threshold) & (reps_t != self_idx)
    return propagate_labels(reps_t, valid_t, n_iters=n_iters,
                            axis_name=axis)


@lru_cache(maxsize=32)
def _sharded_cluster_kernel(mesh, axis: str, n_bands: int, threshold: float,
                            n_iters: int, packed: bool = False,
                            scheme: str = "kminhash"):
    # lru_cache'd factory (parallel/rq_mesh.py pattern): a jit wrapper
    # built per call would discard its compile cache every time.
    n_dev = mesh.shape[axis]
    # all_to_all needs the band axis divisible by the mesh; pad with dummy
    # bands keyed by global row id — every dummy bucket is a singleton, so
    # its rep is itself and it contributes no edges (label-neutral).
    pad_bands = (-n_bands) % n_dev

    # ``packed``: the feed ships [N, S, 3] uint8 (pipeline._pack24_host)
    # instead of raw uint32 — a 25% cut of the mesh H2D placement — and
    # each device unpacks only its own row shard here, inside the
    # shard_map body, so decoded bytes never cross the host link.  The
    # combine is plain jnp (not pallas): it fuses into the row-local
    # MinHash chain under jit.
    items_spec = P(axis, None, None) if packed else P(axis, None)
    # The scheme's hash constants ride as replicated positional arrays —
    # (a[H], b[H]) for kminhash; (a0[1], b0[1], jmap[T, H], offs[H]) for
    # the one-permutation schemes; specs must match each rank.  The
    # kernel dispatches through the scheme registry so the mesh path can
    # never drift from the single-device family (graftlint scheme-parity).
    const_specs = ((P(None), P(None)) if scheme == "kminhash"
                   else (P(None), P(None), P(None, None), P(None)))

    # check_vma off: the shared row-local kernels (scheme signature
    # kernels, band_keys) build fori_loop carries with jnp.full/iota —
    # replicated in the varying-manifest type system — while their bodies
    # mix in varying shards, which the 0.9 vma checker rejects even
    # though the program is sound.  Replication of the output is
    # guaranteed by construction: both propagation reductions cross the
    # mesh through `pmin`.
    @jax.jit
    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(items_spec,) + const_specs,
             out_specs=P(None))
    def kernel(items_loc, *consts):
        if packed:
            p = items_loc.astype(jnp.uint32)               # [N/d, S, 3]
            items_loc = p[..., 0] | (p[..., 1] << 8) | (p[..., 2] << 16)
        sig_loc = scheme_signatures_traced(items_loc, scheme,
                                           consts)         # [N/d, H]
        keys_loc = band_keys(sig_loc, n_bands)             # [N/d, B]
        return _band_sharded_tail(sig_loc, keys_loc, axis, pad_bands,
                                  threshold, n_iters)

    return kernel


@lru_cache(maxsize=32)
def _sharded_label_kernel_from_sig(mesh, axis: str, n_bands: int,
                                   threshold: float, n_iters: int):
    """The pod warm path's tail kernel: row-sharded PRECOMPUTED MinHash
    signatures in (each host feeds cached store gathers + its novel
    tail's fresh signatures), replicated labels out.  Skips the MinHash
    stage entirely — the signatures either came out of the per-host
    signature store or were device-computed over the novel rows only —
    and runs the exact `_band_sharded_tail` the item-fed kernel runs, so
    labels are bit-identical to a cold run over the same rows."""
    n_dev = mesh.shape[axis]
    pad_bands = (-n_bands) % n_dev

    @jax.jit
    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P(axis, None),), out_specs=P(None))
    def kernel(sig_loc):
        keys_loc = band_keys(sig_loc, n_bands)             # [N/d, B]
        return _band_sharded_tail(sig_loc, keys_loc, axis, pad_bands,
                                  threshold, n_iters)

    return kernel
