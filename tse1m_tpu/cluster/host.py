"""Numpy host oracle for the clustering pipeline.

Shares the exact hash parameters with the device path (minhash.py
``make_hash_params``) so signatures are bit-identical, then resolves
components with a classic union-find instead of device label propagation.
This is the "CPU/pandas baseline" the north star measures ARI and speedup
against (BASELINE.json); it is also the semantics oracle in tests.
"""

from __future__ import annotations

import numpy as np

from .minhash import _FNV_OFFSET, _FNV_PRIME

_UMAX = np.uint32(0xFFFFFFFF)


def host_signatures(items: np.ndarray, a: np.ndarray, b: np.ndarray,
                    chunk: int = 65536) -> np.ndarray:
    """[N, S] uint32 -> [N, H] uint32, identical to the device kernel."""
    items = np.ascontiguousarray(items, dtype=np.uint32)
    n, s = items.shape
    h = a.shape[0]
    sig = np.empty((n, h), dtype=np.uint32)
    with np.errstate(over="ignore"):
        for lo in range(0, n, chunk):
            blk = items[lo:lo + chunk]  # [bn, S]
            hashed = blk[:, :, None] * a[None, None, :] + b[None, None, :]
            sig[lo:lo + chunk] = hashed.min(axis=1)
    return sig


def host_cminhash_signatures(items: np.ndarray, a0, b0, jmap: np.ndarray,
                             offs: np.ndarray,
                             chunk: int = 65536) -> np.ndarray:
    """[N, S] uint32 -> [N, H] uint32, identical to
    minhash.cminhash_signatures: one permutation pass, bin-by-modulo
    segment min, the same densification schedule, the same circulant
    fallback.  Every operation is uint32 with natural wraparound, so
    host and device agree bit-for-bit."""
    items = np.ascontiguousarray(items, dtype=np.uint32)
    n, s = items.shape
    h = int(offs.shape[0])
    t_rounds = int(jmap.shape[0])
    out = np.empty((n, h), dtype=np.uint32)
    with np.errstate(over="ignore"):
        for lo in range(0, n, chunk):
            blk = items[lo:lo + chunk]
            bn = blk.shape[0]
            u = blk * a0 + b0
            bins = (u % np.uint32(h)).astype(np.intp)
            v = np.full((bn, h), _UMAX, dtype=np.uint32)
            rows = np.repeat(np.arange(bn, dtype=np.intp), blk.shape[1])
            np.minimum.at(v, (rows, bins.ravel()), u.ravel())
            for t in range(t_rounds):
                cand = v[:, jmap[t]]
                v = np.where((v == _UMAX) & (cand != _UMAX), cand, v)
            fb = u.min(axis=1)[:, None] + offs[None, :]
            out[lo:lo + chunk] = np.where(v == _UMAX, fb, v)
    return out


def host_band_keys(sig: np.ndarray, n_bands: int) -> np.ndarray:
    n, h = sig.shape
    r = h // n_bands
    # Interleaved banding, matching minhash.band_keys: band k folds rows
    # {k, k+B, k+2B, ...}.
    chunks = sig.reshape(n, r, n_bands)
    keys = np.broadcast_to(
        _FNV_OFFSET + np.arange(n_bands, dtype=np.uint32)[None, :],
        (n, n_bands)).copy()
    with np.errstate(over="ignore"):
        for j in range(r):
            keys = (keys ^ chunks[:, j, :]) * _FNV_PRIME
    return keys


class _UnionFind:
    def __init__(self, n: int):
        self.parent = np.arange(n, dtype=np.int64)

    def find(self, x: int) -> int:
        p = self.parent
        root = x
        while p[root] != root:
            root = p[root]
        while p[x] != root:
            p[x], x = root, p[x]
        return root

    def union(self, x: int, y: int) -> None:
        rx, ry = self.find(x), self.find(y)
        if rx != ry:
            if rx < ry:
                self.parent[ry] = rx
            else:
                self.parent[rx] = ry


def host_cluster(items: np.ndarray, n_hashes: int = 128, n_bands: int = 16,
                 threshold: float = 0.5, seed: int = 0,
                 scheme: str = "kminhash") -> np.ndarray:
    """End-to-end host clustering; returns [N] int64 min-index labels.

    ``scheme`` picks the signature kernel family (cluster/schemes.py);
    for ``weighted`` the caller feeds already-expanded replica rows."""
    from .schemes import make_params, scheme_host_signatures

    sig = scheme_host_signatures(items, make_params(scheme, n_hashes, seed))
    keys = host_band_keys(sig, n_bands)
    n = items.shape[0]
    uf = _UnionFind(n)
    min_agree = threshold * n_hashes
    for band in range(n_bands):
        order = np.argsort(keys[:, band], kind="stable")
        ks = keys[order, band]
        boundaries = np.flatnonzero(np.concatenate(
            [[True], ks[1:] != ks[:-1], [True]]))
        for i in range(len(boundaries) - 1):
            lo, hi = boundaries[i], boundaries[i + 1]
            if hi - lo < 2:
                continue
            members = order[lo:hi]
            rep = members.min()
            for m in members:
                if m != rep and (sig[m] == sig[rep]).sum() >= min_agree:
                    uf.union(int(m), int(rep))
    return np.array([uf.find(i) for i in range(n)], dtype=np.int64)
