"""Canonical schema for the five study tables.

The reference ships no DDL — its DB arrives pre-built from a gitignored
``backup_clean.sql`` (reference ``README.md:50-56``, ``.gitignore:6-7``); the
schema below is the one inferred from every query call site (SURVEY.md §2.2):

- ``issues``          reference producers ``5_get_issue_reports.py``; consumed
                      by ``queries1.py:71-80,104-118,280-314``
- ``buildlog_data``   producer ``4_get_buildlog_analysis.py:29-42``; consumed
                      by ``queries1.py:15-69,82-102,267-278``
- ``total_coverage``  producer ``3_get_coverage_data.py:132``; consumed by
                      ``queries1.py:120-129``
- ``project_info``    producer ``1_get_projects_infos.py:108-117``
- ``projects``        count-only usage ``queries1.py:6-11``

Array-valued columns (``modules``, ``revisions``, ``regressed_build``) are
Postgres arrays in the reference; the sqlite dialect stores them as JSON text
and the artifact writers re-emit the Postgres literal form (``{a,b}``) so
output CSVs stay byte-compatible (see golden
``data/result_data/rq3/change_analysis/*.csv``).

The ``result`` enum is canonicalised to {Finish, Halfway, Error, Unknown}:
the reference's analyzer emits {Success, Error, Unknown}
(``4_get_buildlog_analysis.py:230-237``) while its queries filter
('Finish','Halfway') (``queries1.py:4``) — ingest maps Success->Finish.
"""

from __future__ import annotations

SCHEMA_TABLES = ("projects", "project_info", "buildlog_data", "total_coverage", "issues")

_SQLITE_DDL = """
CREATE TABLE IF NOT EXISTS projects (
    project_name TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS project_info (
    project TEXT PRIMARY KEY,
    first_commit_datetime TEXT,
    language TEXT,
    homepage TEXT,
    main_repo TEXT,
    primary_contact TEXT,
    yaml_json TEXT
);
CREATE TABLE IF NOT EXISTS buildlog_data (
    name TEXT PRIMARY KEY,
    project TEXT NOT NULL,
    timecreated TEXT NOT NULL,
    build_type TEXT NOT NULL,
    result TEXT NOT NULL,
    modules TEXT,
    revisions TEXT
);
CREATE INDEX IF NOT EXISTS idx_buildlog_project_time
    ON buildlog_data(project, build_type, timecreated);
CREATE TABLE IF NOT EXISTS total_coverage (
    project TEXT NOT NULL,
    date TEXT NOT NULL,
    coverage REAL,
    covered_line REAL,
    total_line REAL,
    PRIMARY KEY (project, date)
);
CREATE TABLE IF NOT EXISTS issues (
    project TEXT NOT NULL,
    number TEXT NOT NULL,
    rts TEXT NOT NULL,
    status TEXT,
    crash_type TEXT,
    severity TEXT,
    type TEXT,
    regressed_build TEXT,
    new_id TEXT,
    PRIMARY KEY (project, number)
);
CREATE INDEX IF NOT EXISTS idx_issues_project_rts ON issues(project, rts);
"""

_POSTGRES_DDL = """
CREATE TABLE IF NOT EXISTS projects (
    project_name TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS project_info (
    project TEXT PRIMARY KEY,
    first_commit_datetime TIMESTAMPTZ,
    language TEXT,
    homepage TEXT,
    main_repo TEXT,
    primary_contact TEXT,
    yaml_json TEXT
);
CREATE TABLE IF NOT EXISTS buildlog_data (
    name TEXT PRIMARY KEY,
    project TEXT NOT NULL,
    timecreated TIMESTAMPTZ NOT NULL,
    build_type TEXT NOT NULL,
    result TEXT NOT NULL,
    modules TEXT[],
    revisions TEXT[]
);
CREATE INDEX IF NOT EXISTS idx_buildlog_project_time
    ON buildlog_data(project, build_type, timecreated);
CREATE TABLE IF NOT EXISTS total_coverage (
    project TEXT NOT NULL,
    date DATE NOT NULL,
    coverage DOUBLE PRECISION,
    covered_line DOUBLE PRECISION,
    total_line DOUBLE PRECISION,
    PRIMARY KEY (project, date)
);
CREATE TABLE IF NOT EXISTS issues (
    project TEXT NOT NULL,
    number TEXT NOT NULL,
    rts TIMESTAMPTZ NOT NULL,
    status TEXT,
    crash_type TEXT,
    severity TEXT,
    type TEXT,
    regressed_build TEXT[],
    new_id TEXT,
    PRIMARY KEY (project, number)
);
CREATE INDEX IF NOT EXISTS idx_issues_project_rts ON issues(project, rts);
"""


def ddl(dialect: str) -> str:
    if dialect == "sqlite":
        return _SQLITE_DDL
    if dialect == "postgres":
        return _POSTGRES_DDL
    raise ValueError(f"unknown dialect {dialect!r}")


def create_schema(db) -> None:
    """Create all study tables on an open tse1m_tpu.db.DB connection.

    Runs as one retried transaction unit (db/connection.run_transaction):
    every statement is IF NOT EXISTS, so replaying the whole batch after
    a transient failure is idempotent."""
    statements = [s.strip() for s in ddl(db.dialect).split(";") if s.strip()]

    def _create(dbx) -> None:
        for stmt in statements:
            dbx.execute(stmt)

    db.run_transaction(_create, site="db.create_schema")
