from .connection import DB
from .schema import create_schema, SCHEMA_TABLES

__all__ = ["DB", "create_schema", "SCHEMA_TABLES"]
