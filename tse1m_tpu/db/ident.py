"""SQL identifier validation/quoting — the single blessed seat for
interpolating a table or column NAME into SQL text.

Values are always bound as parameters (db/connection.py qmark style);
identifiers can't be bound, so everywhere the schema is dynamic (the
ingest upsert builder, the dump restorer's COPY header, the CLI's table
inventory) previously interpolated raw strings.  Those came from our own
CSVs/dumps today, but a hostile dump header like
``COPY t ("name); DROP TABLE issues; --") FROM stdin`` would have walked
straight into an f-string.  graftlint's ``sql-interp`` rule recognises
exactly the helpers below (plus ``int()``) as safe interpolations.
"""

from __future__ import annotations

import re
from typing import Sequence

# Conservative unquoted-identifier grammar, valid on sqlite AND Postgres:
# leading letter/underscore, then word chars, within Postgres's NAMEDATALEN
# limit.  Anything outside it is rejected rather than quoted-through —
# every identifier this codebase generates is schema-controlled, so an
# exotic name is an attack or a bug, not a use case.
_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_MAX_LEN = 63


class InvalidIdentifier(ValueError):
    """An identifier failed validation (injection attempt or schema bug)."""


def validate_ident(name: str) -> str:
    """Return ``name`` unchanged iff it is a safe bare SQL identifier."""
    if not isinstance(name, str) or not name or len(name) > _MAX_LEN \
            or not _IDENT_RE.match(name):
        raise InvalidIdentifier(f"unsafe SQL identifier: {name!r}")
    return name


def quote_ident(name: str) -> str:
    """Validate and return the identifier ready for interpolation.

    Validation already restricts to the bare-identifier grammar, so no
    quoting characters are ever needed — returning the bare name keeps
    generated SQL byte-identical to the pre-ident.py output (golden
    artifacts, dump round-trips)."""
    return validate_ident(name)


def col_list(names: Sequence[str]) -> str:
    """``"a, b, c"`` with every element validated — the column-list form
    the upsert/restore builders interpolate."""
    return ", ".join(validate_ident(n) for n in names)


__all__ = ["InvalidIdentifier", "col_list", "quote_ident", "validate_ident"]
