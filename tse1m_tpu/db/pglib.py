"""Minimal Postgres driver over ``libpq`` via ctypes.

The reference hard-requires psycopg2 (dbFile.py:1); environments that
ship ``libpq.so.5`` but no psycopg2 wheel (this image, minimal CI boxes)
would otherwise silently fall back to sqlite.  This module implements the
slice of DB-API the framework's connection layer actually uses —
``connect`` -> connection with ``cursor()``/``commit()``/``close()``,
cursors with ``execute(sql, params)`` (``%s`` placeholders),
``executemany``, ``fetchall``/``fetchone``, ``rowcount`` — against libpq
directly, so ``engine = postgres`` works wherever the C library exists.

Fidelity notes (mirroring psycopg2 where the framework depends on it):
- parameters go out of band via ``PQexecParams`` (no string interpolation;
  the security property the rebuild's parameterized queries exist for);
- results convert by column OID: ints, floats/numeric, bool, text,
  date/timestamp(tz) -> ``datetime``, ``text[]`` -> ``list[str]`` (the
  shape test_postgres_live.py's round-trip asserts);
- transactions are explicit: a lazy ``BEGIN`` before the first statement,
  ``commit()`` sends ``COMMIT`` — psycopg2's default behavior.

The pure pieces (placeholder rewrite, parameter adaption, OID
conversion, array literal parse/compose) are unit-tested offline
(tests/test_pglib.py); the transport needs a live server and is covered
by test_postgres_live.py wherever one exists.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import datetime as _dt
import re
from typing import Any, Iterable, Sequence

from ..utils.logging import get_logger

log = get_logger("db.pglib")

# -- libpq binding -----------------------------------------------------------

_CONNECTION_OK = 0
_PGRES_COMMAND_OK = 1
_PGRES_TUPLES_OK = 2

_lib = None
_lib_tried = False


def _libpq():
    """Load libpq lazily; None when absent (callers fall back)."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    name = (ctypes.util.find_library("pq") or "libpq.so.5")
    try:
        lib = ctypes.CDLL(name)
    except OSError as e:
        log.info("libpq unavailable (%s)", e)
        return None
    c_char_p, c_int, c_void_p = ctypes.c_char_p, ctypes.c_int, ctypes.c_void_p
    protos = {
        "PQconnectdb": ([c_char_p], c_void_p),
        "PQstatus": ([c_void_p], c_int),
        "PQerrorMessage": ([c_void_p], c_char_p),
        "PQfinish": ([c_void_p], None),
        "PQexec": ([c_void_p, c_char_p], c_void_p),
        "PQexecParams": ([c_void_p, c_char_p, c_int, c_void_p,
                          ctypes.POINTER(c_char_p), ctypes.POINTER(c_int),
                          ctypes.POINTER(c_int), c_int], c_void_p),
        "PQresultStatus": ([c_void_p], c_int),
        "PQresultErrorMessage": ([c_void_p], c_char_p),
        "PQntuples": ([c_void_p], c_int),
        "PQnfields": ([c_void_p], c_int),
        "PQftype": ([c_void_p, c_int], ctypes.c_uint),
        "PQgetisnull": ([c_void_p, c_int, c_int], c_int),
        "PQgetvalue": ([c_void_p, c_int, c_int], c_char_p),
        "PQcmdTuples": ([c_void_p], c_char_p),
        "PQclear": ([c_void_p], None),
    }
    for fn, (argtypes, restype) in protos.items():
        f = getattr(lib, fn)
        f.argtypes = argtypes
        f.restype = restype
    _lib = lib
    return _lib


def available() -> bool:
    return _libpq() is not None


# -- SQL placeholder rewrite -------------------------------------------------

def format_to_dollar(sql: str) -> str:
    """``%s`` placeholders -> ``$1..$n`` (PQexecParams style), skipping
    string literals and SQL comments; ``%%`` unescapes to a literal %."""
    out = []
    n = 0
    i = 0
    ln = len(sql)
    while i < ln:
        ch = sql[i]
        if ch == "'":  # string literal: copy until closing quote ('' stays)
            j = i + 1
            while j < ln:
                if sql[j] == "'":
                    if j + 1 < ln and sql[j + 1] == "'":
                        j += 2
                        continue
                    break
                j += 1
            out.append(sql[i:j + 1])
            i = j + 1
        elif ch == "-" and sql[i:i + 2] == "--":
            j = sql.find("\n", i)
            j = ln if j < 0 else j
            out.append(sql[i:j])
            i = j
        elif ch == "%" and sql[i:i + 2] == "%s":
            n += 1
            out.append(f"${n}")
            i += 2
        elif ch == "%" and sql[i:i + 2] == "%%":
            out.append("%")
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


# -- parameter / result conversion -------------------------------------------

def adapt_param(v: Any) -> bytes | None:
    """Python value -> libpq text-format parameter (None = SQL NULL)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return b"t" if v else b"f"
    if isinstance(v, bytes):
        return v
    if isinstance(v, (_dt.datetime, _dt.date)):
        return v.isoformat().encode()
    if isinstance(v, (list, tuple)):
        return compose_array(v).encode()
    return str(v).encode()


def compose_array(items: Iterable[Any]) -> str:
    """Python list -> Postgres array literal with full quoting."""
    parts = []
    for it in items:
        if it is None:
            parts.append("NULL")
            continue
        s = str(it)
        s = s.replace("\\", "\\\\").replace('"', '\\"')
        parts.append(f'"{s}"')
    return "{" + ",".join(parts) + "}"


def parse_text_array(lit: str) -> list:
    """Postgres ``text[]`` literal -> list[str|None] (psycopg2's shape)."""
    from .ingest import _split_pg_array

    body = lit.strip()
    if body.startswith("{") and body.endswith("}"):
        body = body[1:-1]
    if not body:
        return []
    out = []
    for tok in _split_pg_array(body):
        out.append(None if tok == "NULL" else tok)
    return out


_TS_RE = re.compile(
    r"^(\d{4})-(\d{2})-(\d{2})[ T](\d{2}):(\d{2}):(\d{2})(\.\d+)?"
    r"(?:([+-])(\d{2})(?::?(\d{2}))?)?$")


def _parse_timestamp(text: str) -> Any:
    m = _TS_RE.match(text)
    if not m:
        return text  # infinity / BC dates — out of study scope, keep raw
    y, mo, d, h, mi, s = (int(m.group(k)) for k in range(1, 7))
    frac = m.group(7)
    us = int(float(frac) * 1e6) if frac else 0
    tz = None
    if m.group(8):
        sign = 1 if m.group(8) == "+" else -1
        off = _dt.timedelta(hours=int(m.group(9)),
                            minutes=int(m.group(10) or 0))
        tz = _dt.timezone(sign * off)
    return _dt.datetime(y, mo, d, h, mi, s, us, tzinfo=tz)


def convert_cell(oid: int, text: str) -> Any:
    """libpq text-format cell -> Python value by column OID (the psycopg2
    conversions the framework's consumers rely on)."""
    if oid in (20, 21, 23, 26):          # int8/int2/int4/oid
        return int(text)
    if oid in (700, 701, 1700):          # float4/float8/numeric
        return float(text)
    if oid == 16:                        # bool
        return text == "t"
    if oid in (1114, 1184):              # timestamp / timestamptz
        return _parse_timestamp(text)
    if oid == 1082:                      # date
        return _dt.date.fromisoformat(text)
    if oid in (1009, 1015):              # text[] / varchar[]
        return parse_text_array(text)
    return text


# -- DB-API slice ------------------------------------------------------------

class Error(Exception):
    pass


class OperationalError(Error):
    """Connection-level failure (server gone, network drop) — the
    reconnect-class error ``db/connection.py``'s retry engine looks for
    (psycopg2 raises its own OperationalError for the same states)."""


# libpq error strings that mean the connection itself died.
_CONN_DEAD_MARKERS = (
    "server closed the connection", "terminating connection",
    "connection to server", "no connection to the server",
    "could not receive data", "could not send data", "connection reset",
    "ssl connection has been closed",
)


def _classify(message: str) -> type[Error]:
    low = message.lower()
    if any(m in low for m in _CONN_DEAD_MARKERS):
        return OperationalError
    return Error


class Cursor:
    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._rows: list = []
        self._pos = 0
        self.rowcount = -1

    def execute(self, sql: str, params: Sequence[Any] | None = None):
        from ..resilience import fault_point

        fault_point("pglib.exec")
        self._conn._check_alive()
        self._conn._begin()
        res = self._conn._exec_params(sql, params or ())
        lib = _libpq()
        try:
            status = lib.PQresultStatus(res)
            if status == _PGRES_TUPLES_OK:
                nt, nf = lib.PQntuples(res), lib.PQnfields(res)
                oids = [lib.PQftype(res, j) for j in range(nf)]
                rows = []
                for i in range(nt):
                    row = []
                    for j in range(nf):
                        if lib.PQgetisnull(res, i, j):
                            row.append(None)
                        else:
                            row.append(convert_cell(
                                oids[j],
                                lib.PQgetvalue(res, i, j).decode()))
                    rows.append(tuple(row))
                self._rows, self._pos = rows, 0
                self.rowcount = nt
            elif status == _PGRES_COMMAND_OK:
                self._rows, self._pos = [], 0
                t = lib.PQcmdTuples(res)
                self.rowcount = int(t) if t else -1
            else:
                msg = lib.PQresultErrorMessage(res).decode().strip()
                raise _classify(msg)(msg)
        finally:
            lib.PQclear(res)
        return self

    def executemany(self, sql: str, seq: Iterable[Sequence[Any]]):
        total = 0
        for params in seq:
            self.execute(sql, params)
            total += max(self.rowcount, 0)
        self.rowcount = total
        return self

    def fetchall(self) -> list[tuple]:
        rows = self._rows[self._pos:]
        self._pos = len(self._rows)
        return rows

    def fetchone(self):
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def close(self) -> None:
        self._rows = []


class Connection:
    def __init__(self, pgconn):
        self._pg = pgconn
        self._in_txn = False

    @property
    def closed(self) -> bool:
        """True when the underlying libpq connection is gone or in a bad
        state (PQstatus != CONNECTION_OK) — psycopg2's ``closed`` shape."""
        if self._pg is None:
            return True
        return _libpq().PQstatus(self._pg) != _CONNECTION_OK

    def _check_alive(self) -> None:
        if self._pg is None:
            raise OperationalError("connection already closed")
        if _libpq().PQstatus(self._pg) != _CONNECTION_OK:
            raise OperationalError("no connection to the server")

    def _begin(self) -> None:
        if not self._in_txn:
            self._command("BEGIN")
            self._in_txn = True

    def _command(self, sql: str) -> None:
        lib = _libpq()
        res = lib.PQexec(self._pg, sql.encode())
        if not res:  # libpq returns NULL when the connection dropped
            raise OperationalError(
                lib.PQerrorMessage(self._pg).decode().strip()
                or "no connection to the server")
        try:
            if lib.PQresultStatus(res) not in (_PGRES_COMMAND_OK,
                                               _PGRES_TUPLES_OK):
                msg = lib.PQresultErrorMessage(res).decode().strip()
                raise _classify(msg)(msg)
        finally:
            lib.PQclear(res)

    def _exec_params(self, sql: str, params: Sequence[Any]):
        lib = _libpq()
        adapted = [adapt_param(p) for p in params]
        n = len(adapted)
        values = (ctypes.c_char_p * n)(*adapted) if n else None
        res = lib.PQexecParams(self._pg, format_to_dollar(sql).encode(),
                               n, None, values, None, None, 0)
        if not res:
            msg = lib.PQerrorMessage(self._pg).decode().strip()
            raise (_classify(msg) if msg else OperationalError)(
                msg or "no connection to the server")
        return res

    def cursor(self) -> Cursor:
        return Cursor(self)

    def commit(self) -> None:
        if self._in_txn:
            self._command("COMMIT")
            self._in_txn = False

    def rollback(self) -> None:
        if self._in_txn:
            self._command("ROLLBACK")
            self._in_txn = False

    def close(self) -> None:
        if self._pg is not None:
            _libpq().PQfinish(self._pg)
            self._pg = None


def conninfo(database: str, user: str, password: str, host: str,
             port: int | str, connect_timeout: int = 10) -> str:
    def esc(v) -> str:
        s = str(v).replace("\\", "\\\\").replace("'", "\\'")
        return f"'{s}'"
    return (f"dbname={esc(database)} user={esc(user)} "
            f"password={esc(password)} host={esc(host)} port={esc(port)} "
            f"connect_timeout={int(connect_timeout)}")


def connect(database: str, user: str, password: str, host: str,
            port: int | str) -> Connection:
    lib = _libpq()
    if lib is None:
        raise Error("libpq is not available on this system")
    pg = lib.PQconnectdb(conninfo(database, user, password, host,
                                  port).encode())
    if lib.PQstatus(pg) != _CONNECTION_OK:
        msg = lib.PQerrorMessage(pg).decode().strip()
        lib.PQfinish(pg)
        raise Error(msg or "connection failed")
    return Connection(pg)
