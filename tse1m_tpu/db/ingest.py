"""CSV -> DB ingestion — the reference's missing link.

The reference's collectors emit CSVs (``1_get_projects_infos.py:76``,
``2_get_buildlog_metadata.py:95``, ``3_get_coverage_data.py:43``,
``4_get_buildlog_analysis.py:11``, ``5_get_issue_reports.py:296-309``) but no
script loads them into Postgres; the DB ships pre-built as
``backup_clean.sql`` (SURVEY.md §1, "gap in the reference").  This module is
that loader, plus enum canonicalisation and array-literal handling.

Array columns accept either Postgres literal form (``{a,b}``) or JSON
(``["a","b"]``) on input; storage is engine-native (TEXT[] on Postgres, JSON
text on sqlite).  ``pg_array_literal`` re-emits the Postgres form for
artifact writers so output CSVs match the reference's golden files
(e.g. ``data/result_data/rq3/change_analysis/zstd.csv``).
"""

from __future__ import annotations

import csv
import json
import os
from typing import Iterable, Sequence

from .connection import DB
from .ident import col_list, quote_ident
from .schema import create_schema
from ..utils.logging import get_logger

log = get_logger("ingest")

# The reference's analyzer emits {Success, Error, Unknown}
# (4_get_buildlog_analysis.py:230-237) while the shipped DB and all queries
# use {Finish, Halfway, Error} (queries1.py:4) — canonicalise at the door.
_RESULT_CANON = {"Success": "Finish", "success": "Finish"}


def canon_result(value: str | None) -> str:
    if value is None:
        return "Unknown"
    return _RESULT_CANON.get(value, value)


def _split_pg_array(body: str) -> list[str]:
    """Tokenise the body of a Postgres array literal, honouring double-quoted
    items containing commas/braces and backslash escapes.

    Quoted items are preserved verbatim — including empty strings and
    leading/trailing whitespace (`{""}` is a one-element array in Postgres);
    unquoted tokens are stripped and dropped when empty, matching how the
    reference's loosely-formatted CSV arrays behave.  Round-trip with
    `pg_array_literal` is property-tested (tests/test_properties.py)."""
    items: list[tuple[str, bool]] = []
    buf: list[str] = []
    in_quotes = False
    was_quoted = False
    i = 0
    while i < len(body):
        c = body[i]
        if in_quotes:
            if c == "\\" and i + 1 < len(body):
                buf.append(body[i + 1])
                i += 2
                continue
            if c == '"':
                in_quotes = False
            else:
                buf.append(c)
        elif c == '"':
            in_quotes = True
            was_quoted = True
        elif c == ",":
            items.append(("".join(buf), was_quoted))
            buf = []
            was_quoted = False
        else:
            buf.append(c)
        i += 1
    if buf or was_quoted or items:
        items.append(("".join(buf), was_quoted))
    out: list[str] = []
    for text, quoted in items:
        if quoted:
            out.append(text)
        else:
            text = text.strip()
            if text:
                out.append(text)
    return out


def parse_array(value) -> list[str]:
    """Accept '{a,b}' (with optional quoted items), '["a","b"]', a Python
    list, '' or None."""
    if value is None or (isinstance(value, float) and value != value):
        return []
    if isinstance(value, (list, tuple)):
        return [str(v) for v in value]
    s = str(value).strip()
    if not s or s in ("{}", "[]"):
        return []
    if s.startswith("{") and s.endswith("}"):
        return _split_pg_array(s[1:-1])
    if s.startswith("["):
        return [str(v) for v in json.loads(s)]
    return [s]


def pg_array_literal(items: Sequence[str]) -> str:
    """Emit the Postgres literal form, quoting items that contain
    delimiters so parse_array/Postgres round-trip losslessly."""
    out = []
    for item in items:
        s = str(item)
        # Quote anything the unquoted grammar could mangle: delimiters,
        # backslashes, items with leading/trailing (or any) whitespace —
        # unquoted tokens are stripped on parse — and empty strings.
        if s == "" or s != s.strip() or any(
                c in s for c in ',{}" \\') or not s.isprintable():
            s = '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'
        out.append(s)
    return "{" + ",".join(out) + "}"


def store_array(db: DB, items: Sequence[str]):
    if db.dialect == "postgres":
        return list(items)
    return json.dumps(list(items))


def _read_csv(path: str) -> Iterable[dict]:
    with open(path, newline="", encoding="utf-8") as f:
        yield from csv.DictReader(f)


def _upsert_sql(db: DB, table: str, cols: Sequence[str], conflict: Sequence[str]) -> str:
    """Dialect-consistent upsert: re-ingesting a corrected CSV updates the
    row on both engines (last-write-wins).  Table/column names pass the
    identifier validator — they come from our own loader tables today,
    but this seat is the template every future loader copies."""
    qs = ",".join("?" * len(cols))
    if db.dialect == "sqlite":
        return (f"INSERT OR REPLACE INTO {quote_ident(table)} "
                f"({col_list(cols)}) VALUES ({qs})")
    updates = ", ".join(f"{quote_ident(c)} = EXCLUDED.{quote_ident(c)}"
                        for c in cols if c not in conflict)
    return (f"INSERT INTO {quote_ident(table)} ({col_list(cols)}) "
            f"VALUES ({qs}) "
            f"ON CONFLICT ({col_list(conflict)}) DO UPDATE SET {updates}")


def load_project_info(db: DB, rows: Iterable[dict]) -> int:
    n = 0
    batch = []
    for r in rows:
        yaml_keys = {k: v for k, v in r.items()
                     if k not in ("project", "first_commit_datetime", "language",
                                  "homepage", "main_repo", "primary_contact")}
        batch.append((r["project"], r.get("first_commit_datetime"), r.get("language"),
                      r.get("homepage"), r.get("main_repo"), r.get("primary_contact"),
                      json.dumps(yaml_keys) if yaml_keys else None))
        n += 1
    db.executeMany(
        _upsert_sql(db, "project_info",
                    ("project", "first_commit_datetime", "language", "homepage",
                     "main_repo", "primary_contact", "yaml_json"),
                    ("project",)),
        batch,
    )
    return n


def load_buildlog_data(db: DB, rows: Iterable[dict]) -> int:
    batch = []
    for r in rows:
        batch.append((
            r["name"], r["project"], r["timecreated"], r["build_type"],
            canon_result(r.get("result")),
            store_array(db, parse_array(r.get("modules"))),
            store_array(db, parse_array(r.get("revisions"))),
        ))
    db.executeMany(
        _upsert_sql(db, "buildlog_data",
                    ("name", "project", "timecreated", "build_type", "result",
                     "modules", "revisions"),
                    ("name",)),
        batch,
    )
    return len(batch)


def load_total_coverage(db: DB, rows: Iterable[dict]) -> int:
    batch = []
    for r in rows:
        def _f(key):
            v = r.get(key)
            return float(v) if v not in (None, "") else None
        batch.append((r["project"], r["date"], _f("coverage"),
                      _f("covered_line"), _f("total_line")))
    db.executeMany(
        _upsert_sql(db, "total_coverage",
                    ("project", "date", "coverage", "covered_line", "total_line"),
                    ("project", "date")),
        batch,
    )
    return len(batch)


def load_issues(db: DB, rows: Iterable[dict]) -> int:
    batch = []
    for r in rows:
        batch.append((
            r["project"], str(r["number"]), r["rts"], r.get("status"),
            r.get("crash_type"), r.get("severity"), r.get("type"),
            store_array(db, parse_array(r.get("regressed_build"))),
            r.get("new_id"),
        ))
    db.executeMany(
        _upsert_sql(db, "issues",
                    ("project", "number", "rts", "status", "crash_type", "severity",
                     "type", "regressed_build", "new_id"),
                    ("project", "number")),
        batch,
    )
    return len(batch)


_LOADERS = {
    "project_info": load_project_info,
    "buildlog_data": load_buildlog_data,
    "total_coverage": load_total_coverage,
    "issues": load_issues,
}


def ingest_csv_dir(db: DB, csv_dir: str) -> dict[str, int]:
    """Load every recognised CSV in ``csv_dir`` (named <table>.csv) into an
    initialised schema.  Returns per-table row counts."""
    create_schema(db)
    counts: dict[str, int] = {}
    for table, loader in _LOADERS.items():
        path = os.path.join(csv_dir, f"{table}.csv")
        if os.path.exists(path):
            counts[table] = loader(db, _read_csv(path))
            log.info("loaded %-16s %8d rows from %s", table, counts[table], path)
    derive_projects(db)
    return counts


def derive_projects(db: DB) -> None:
    """Rebuild the count-only ``projects`` table (queries1.py:6-11) from
    buildlog rows.  There is no projects.csv in the collection pipeline; the
    table is always derived.

    The DELETE+INSERT rebuild is one retried transaction unit: a transient
    failure between the two statements must rerun *both*, otherwise a
    per-statement retry would roll back the DELETE, replay only the INSERT,
    and the commit would persist stale rows alongside the new ones."""

    def _rebuild(dbx: DB) -> None:
        dbx.execute("DELETE FROM projects")
        dbx.execute("INSERT INTO projects (project_name) "
                    "SELECT project FROM buildlog_data")

    db.run_transaction(_rebuild, site="db.derive_projects")
