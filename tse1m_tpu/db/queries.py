"""Parameterized query builders.

Functional superset of the reference's SQL-string module
(``program/__module/queries1.py``), with three deliberate changes:

1. Every builder returns ``(sql, params)`` — no f-string value interpolation
   (the reference quotes values ad hoc, ``queries1.py:43,65`` — SURVEY.md
   §2.3 flags this as injection-prone).
2. ``DATE(col) < :limit`` comparisons are expressed as plain
   ``col < :limit`` (equivalent for date literals, works identically on
   sqlite and Postgres, and keeps the column indexable).
3. One *bulk* variant per hot loop: the reference issues one query per
   project inside Python loops (the N+1 pattern, e.g.
   ``rq1_detection_rate.py:192-201``); the bulk builders fetch the whole
   study ordered by (project, time) so the columnar layer can build CSR
   segments in one pass.

The reference's ``GET_VALID_ISSUES`` filters ``status IN
('Finish','Halfway')`` (``queries1.py:76``) — a build-result enum applied to
an issue-status column, i.e. a latent bug that always matches zero rows.  We
do not replicate it; issue selection uses the fixed statuses used everywhere
else (``queries1.py:40``).
"""

from __future__ import annotations

from typing import Sequence

from ..config import DEFAULT_LIMIT_DATE, FIXED_STATUSES, RESULT_OK
from .ident import validate_ident

Query = tuple[str, tuple]

# Column whitelist for the export_type knob of total-coverage extraction
# (reference interpolates the column name raw, queries1.py:125-126).
_COVERAGE_COLUMNS = frozenset({"coverage", "covered_line", "total_line"})


def _in(values: Sequence[str]) -> str:
    # `IN ()` is a Postgres syntax error (sqlite tolerates it); emit a
    # never-matching one-element list so empty target sets are portable.
    if not values:
        return "(NULL)"
    return "(" + ",".join("?" * len(values)) + ")"


def count_projects() -> Query:
    # queries1.py:6-11
    return (
        "SELECT project_name, COUNT(*) AS frequency FROM projects "
        "GROUP BY project_name ORDER BY frequency DESC",
        (),
    )


def eligible_projects(min_days: int = 365, limit_date: str = DEFAULT_LIMIT_DATE) -> Query:
    """Projects with >= min_days non-zero coverage days before limit_date —
    the study-wide eligibility predicate (rq1_detection_rate.py:144-151,
    duplicated across six reference scripts; SURVEY.md §2.3)."""
    return (
        "SELECT project FROM total_coverage "
        "WHERE coverage IS NOT NULL AND coverage > 0 AND date < ? "
        "GROUP BY project HAVING COUNT(*) >= ? "
        "ORDER BY project",
        (limit_date, min_days),
    )


def all_fuzzing_build(project: str) -> Query:
    # queries1.py:267-278 (ALL_FUZZING_BUILD — result unfiltered)
    return (
        "SELECT name, timecreated FROM buildlog_data "
        "WHERE project = ? AND build_type = 'Fuzzing' ORDER BY timecreated",
        (project,),
    )


def successful_fuzzing_build(project: str) -> Query:
    # queries1.py:61-69
    return (
        "SELECT name, timecreated FROM buildlog_data "
        f"WHERE project = ? AND build_type = 'Fuzzing' AND result IN {_in(RESULT_OK)} "
        "ORDER BY timecreated",
        (project, *RESULT_OK),
    )


def all_fuzzing_builds_bulk(targets: Sequence[str]) -> Query:
    """Bulk replacement for the Phase-1/Phase-2 per-project loops
    (rq1_detection_rate.py:192-201,219-223)."""
    return (
        "SELECT project, name, timecreated, result, modules, revisions "
        "FROM buildlog_data "
        f"WHERE build_type = 'Fuzzing' AND project IN {_in(targets)} "
        "ORDER BY project, timecreated",
        tuple(targets),
    )


def coverage_builds(project: str) -> Query:
    # queries1.py:94-102 (the live, non-shadowed GET_COVERAGE_BUILDS)
    return (
        "SELECT name, project, timecreated, build_type, result, modules, revisions "
        "FROM buildlog_data "
        "WHERE project = ? AND build_type = 'Coverage' AND result = 'Finish' "
        "ORDER BY timecreated",
        (project,),
    )


def coverage_builds_bulk(targets: Sequence[str]) -> Query:
    """ALL Coverage builds with their result column (no result filter).

    RQ3 walks the full sequence and requires the *first* build after an
    issue to be successful (rq3_diff_coverage_at_detection.py:273-274), so
    OK-filtering at fetch time would change which build is "first".
    Downstream paths mask by result instead (RQ2 change-points keep
    RESULT_OK rows — note the reference's 'HalfWay' spelling in
    rq2_coverage_and_added.py:65 / rq3:261 silently matched only 'Finish'
    against the DB's 'Halfway' vocabulary; we use the canonical enum).

    ``name`` is deliberately NOT selected: no RQ consumes coverage-build
    names, and decoding 713k near-unique strings cost ~0.25 s of the
    1M-build extraction wall."""
    return (
        "SELECT project, timecreated, modules, revisions, result "
        "FROM buildlog_data "
        f"WHERE build_type = 'Coverage' AND project IN {_in(targets)} "
        "ORDER BY project, timecreated",
        tuple(targets),
    )


def same_date_build_issue(targets: Sequence[str], limit_date: str = DEFAULT_LIMIT_DATE) -> Query:
    """For each fixed issue, the latest successful Fuzzing build strictly
    before its report time (window-function join, queries1.py:15-58)."""
    sql = (
        "WITH matched_buildlogs AS (\n"
        "  SELECT i.number, i.project, i.rts,\n"
        "         bd.timecreated AS buildlog_timecreated, bd.build_type, bd.result,\n"
        "         bd.name AS buildlog_name, bd.modules, bd.revisions,\n"
        "         ROW_NUMBER() OVER (PARTITION BY i.project, i.number\n"
        "                            ORDER BY bd.timecreated DESC) AS rn\n"
        "  FROM issues i\n"
        "  JOIN buildlog_data bd\n"
        "    ON i.project = bd.project AND i.rts > bd.timecreated\n"
        "   AND bd.build_type = 'Fuzzing'\n"
        f"   AND bd.result IN {_in(RESULT_OK)}\n"
        "   AND bd.timecreated < ?\n"
        f"  WHERE i.status IN {_in(FIXED_STATUSES)}\n"
        f"    AND i.project IN {_in(targets)}\n"
        ")\n"
        "SELECT number, project, rts, buildlog_timecreated, build_type, result,\n"
        "       buildlog_name, modules, revisions\n"
        "FROM matched_buildlogs WHERE rn = 1\n"
        "ORDER BY project ASC, rts ASC"
    )
    return sql, (*RESULT_OK, limit_date, *FIXED_STATUSES, *targets)


def issues_without_matching_build(targets: Sequence[str],
                                  limit_date: str = DEFAULT_LIMIT_DATE) -> Query:
    # queries1.py:280-314
    sql = (
        "SELECT i.project, i.number, i.rts, p.first_commit_datetime, i.new_id\n"
        "FROM issues i JOIN project_info p ON i.project = p.project\n"
        f"WHERE i.status IN {_in(FIXED_STATUSES)}\n"
        f"  AND i.project IN {_in(targets)}\n"
        "  AND NOT EXISTS (\n"
        "    SELECT 1 FROM buildlog_data bd\n"
        "    WHERE bd.project = i.project AND i.rts > bd.timecreated\n"
        "      AND bd.build_type = 'Fuzzing'\n"
        f"      AND bd.result IN {_in(RESULT_OK)}\n"
        "      AND bd.timecreated < ?\n"
        "  )\n"
        "ORDER BY i.project ASC, i.rts ASC"
    )
    return sql, (*FIXED_STATUSES, *targets, *RESULT_OK, limit_date)


def severity_issues(severity: str, targets: Sequence[str], dialect: str,
                    limit_date: str = DEFAULT_LIMIT_DATE) -> Query:
    """Issues of a severity that have at least one non-null regressed build
    (queries1.py:104-118; uses unnest on Postgres, json_each on sqlite)."""
    if dialect == "postgres":
        exists = ("EXISTS (SELECT 1 FROM unnest(regressed_build) AS b "
                  "WHERE b IS NOT NULL)")
    else:
        exists = ("regressed_build IS NOT NULL AND EXISTS ("
                  "SELECT 1 FROM json_each(regressed_build) "
                  "WHERE json_each.value IS NOT NULL)")
    return (
        "SELECT project, rts, regressed_build, severity FROM issues "
        f"WHERE project IN {_in(targets)} AND rts < ? AND severity = ? AND {exists} "
        "ORDER BY project, rts, number",
        (*targets, limit_date, severity),
    )


def total_coverage_each_project(project: str, export_type: str,
                                limit_date: str = DEFAULT_LIMIT_DATE) -> Query:
    # queries1.py:120-129; export_type is a column name -> whitelisted,
    # and validated as an identifier (db/ident.py) for defense in depth.
    if export_type not in _COVERAGE_COLUMNS:
        raise ValueError(f"export_type must be one of {sorted(_COVERAGE_COLUMNS)}")
    return (
        "SELECT covered_line, total_line FROM total_coverage "
        f"WHERE project = ? AND {validate_ident(export_type)} IS NOT NULL "
        f"AND {validate_ident(export_type)} != 0 "
        "AND date < ? ORDER BY date",
        (project, limit_date),
    )


def total_coverage_bulk(targets: Sequence[str],
                        limit_date: str = DEFAULT_LIMIT_DATE) -> Query:
    """All coverage rows before ``limit_date``, unfiltered: RQ2's
    change-point date join reads rows regardless of coverage value
    (rq2_coverage_and_added.py:30-47) while the trend/eligibility paths
    apply their own coverage != 0 masks downstream.  Callers pass
    ``limit_date + 1 day`` when they need the boundary day RQ3 reads
    (``DATE(date) < '2025-01-09'``, rq3_diff_coverage_at_detection.py:263)
    and mask back down to the study cutoff elsewhere."""
    return (
        "SELECT project, date, coverage, covered_line, total_line FROM total_coverage "
        f"WHERE project IN {_in(targets)} AND date < ? "
        "ORDER BY project, date",
        (*targets, limit_date),
    )


def issues_bulk(targets: Sequence[str], limit_date: str = DEFAULT_LIMIT_DATE,
                fixed_only: bool = True) -> Query:
    statuses = FIXED_STATUSES
    sql = (
        "SELECT project, number, rts, status, crash_type, severity FROM issues "
        f"WHERE project IN {_in(targets)} AND rts < ? "
    )
    params: tuple = (*targets, limit_date)
    if fixed_only:
        sql += f"AND status IN {_in(statuses)} "
        params += statuses
    sql += "ORDER BY project, rts, number"
    return sql, params
