"""Database connection layer.

API-compatible superset of the reference's psycopg2 wrapper
(``program/__module/dbFile.py:16-38`` — ``connect``, ``executeQuery``,
``executeMany``, ``executeValues``, ``closeConnection``) with two upgrades:

1. Dual engine: embedded sqlite (default in this environment, where
   psycopg2/Postgres are unavailable) and Postgres when psycopg2 is present.
2. Parameterized queries throughout.  The reference interpolates values with
   f-strings (``queries1.py:43``, ``rq4a_bug.py:131``) — injection-prone and
   unplannable; here every query takes a params tuple.  Queries are written
   with the ``?`` qmark style and rewritten to ``%s`` for Postgres.
"""

from __future__ import annotations

import os
import re
import sqlite3
from typing import Any, Iterable, Sequence

from ..config import Config, load_config
from ..utils.logging import get_logger

log = get_logger("db")

_QMARK_RE = re.compile(r"\?")


class DB:
    """Connection wrapper.

    ``DB(config=...)`` picks the engine from config; the legacy keyword form
    ``DB(database=, user=, password=, host=, port=)`` (dbFile.py's signature)
    is accepted and implies Postgres when psycopg2 is importable, otherwise
    falls back to sqlite at the configured path.
    """

    def __init__(
        self,
        database: str | None = None,
        user: str | None = None,
        password: str | None = None,
        host: str | None = None,
        port: int | str | None = None,
        config: Config | None = None,
        sqlite_path: str | None = None,
    ) -> None:
        self.config = config or load_config()
        self._legacy_pg = database is not None
        if self._legacy_pg:
            self.config.postgres.database = database
            if user:
                self.config.postgres.user = user
            if password:
                self.config.postgres.password = password
            if host:
                self.config.postgres.host = host
            if port:
                self.config.postgres.port = int(port)
        if sqlite_path:
            self.config.sqlite_path = sqlite_path
        self.dialect = self._resolve_dialect()
        self.connection = None
        self.cursor = None

    def _resolve_dialect(self) -> str:
        self._pg_driver = None
        if self.config.engine == "postgres" or self._legacy_pg:
            try:
                import psycopg2  # noqa: F401

                self._pg_driver = "psycopg2"
                return "postgres"
            except ImportError:
                pass
            # psycopg2 missing: drive libpq directly (db/pglib.py) so
            # `engine = postgres` works wherever the C library exists.
            from . import pglib

            if pglib.available():
                self._pg_driver = "pglib"
                log.info("psycopg2 unavailable; using the ctypes libpq "
                         "driver (db/pglib.py)")
                return "postgres"
            log.warning("psycopg2 and libpq unavailable; falling back to "
                        "sqlite at %s", self.config.sqlite_path)
        return "sqlite"

    # -- lifecycle ---------------------------------------------------------

    def connect(self):
        if self.dialect == "postgres":
            pg = self.config.postgres
            if self._pg_driver == "pglib":
                from . import pglib

                self.connection = pglib.connect(
                    database=pg.database, user=pg.user,
                    password=pg.password, host=pg.host, port=pg.port)
            else:
                import psycopg2

                self.connection = psycopg2.connect(
                    database=pg.database, user=pg.user, password=pg.password,
                    host=pg.host, port=pg.port,
                )
        else:
            path = self.config.sqlite_path
            if path != ":memory:":
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self.connection = sqlite3.connect(path)
            self.connection.execute("PRAGMA journal_mode=WAL")
            self.connection.execute("PRAGMA synchronous=NORMAL")
        self.cursor = self.connection.cursor()
        return self

    def closeConnection(self) -> None:
        if self.cursor is not None:
            self.cursor.close()
        if self.connection is not None:
            self.connection.close()
        self.cursor = self.connection = None

    close = closeConnection

    def __enter__(self):
        return self.connect()

    def __exit__(self, *exc) -> None:
        self.closeConnection()

    # -- query helpers -----------------------------------------------------

    def _adapt(self, sql: str) -> str:
        if self.dialect == "postgres":
            return _QMARK_RE.sub("%s", sql)
        return sql

    def execute(self, sql: str, params: Sequence[Any] = ()) -> None:
        self.cursor.execute(self._adapt(sql), tuple(params))

    def execute_raw(self, sql: str) -> int:
        """Execute one complete statement verbatim — no qmark adaptation,
        no parameter interpolation.  The restore path needs this: dump
        statements may carry ``?`` or ``%`` inside string literals, which
        ``_adapt`` + driver interpolation would corrupt or crash on.
        Returns the driver-reported affected-row count (0 when unknown)."""
        self.cursor.execute(sql)
        n = self.cursor.rowcount
        return int(n) if n and n > 0 else 0

    def query(self, sql: str, params: Sequence[Any] = ()) -> list[tuple]:
        self.cursor.execute(self._adapt(sql), tuple(params))
        return self.cursor.fetchall()

    def count(self, sql: str, params: Sequence[Any] = ()) -> int:
        """Row count of an arbitrary query without shipping its rows —
        diagnostics at the 1.19M-row scale only need the number."""
        (n,) = self.query(f"SELECT COUNT(*) FROM ({sql}) AS t", params)[0]
        return int(n)

    def require_study_tables(self) -> None:
        """Fail with actionable guidance when the study schema is absent
        (shared by StudyContext.open and the CLI)."""
        try:
            self.query("SELECT 1 FROM issues LIMIT 1")
        except Exception as e:
            raise SystemExit(
                f"study database not initialised ({e}). Populate it first: "
                "`python -m tse1m_tpu.cli synth` for a synthetic study or "
                "`python -m tse1m_tpu.cli ingest --csv-dir ...` for "
                "collector CSVs."
            ) from e

    def commit(self) -> None:
        self.connection.commit()

    # -- reference-compatible surface (dbFile.py:16-38) --------------------

    def executeQuery(self, type: str, sql: str, params: Sequence[Any] = ()):
        """``type`` is 'select' (returns rows) or anything else (DML+commit),
        mirroring dbFile.py's select/insert/update switch."""
        self.cursor.execute(self._adapt(sql), tuple(params))
        if type == "select":
            return self.cursor.fetchall()
        self.connection.commit()
        return None

    def executeMany(self, sql: str, rows: Iterable[Sequence[Any]]) -> None:
        self.cursor.executemany(self._adapt(sql), [tuple(r) for r in rows])
        self.connection.commit()

    def executeValues(self, sql: str, rows: Iterable[Sequence[Any]], page_size: int = 1000) -> None:
        """Bulk insert.  Postgres uses psycopg2.extras.execute_values
        (dbFile.py:37's mechanism); sqlite uses executemany, which is the
        equivalent fast path there.  ``sql`` must be of the form
        ``INSERT INTO t (cols) VALUES ?`` with a single placeholder."""
        rows = [tuple(r) for r in rows]
        if not rows:
            return
        if self.dialect == "postgres" and self._pg_driver == "pglib":
            # execute_values equivalent: one multi-VALUES statement per
            # page, parameters still out of band.
            width = len(rows[0])
            for i in range(0, len(rows), page_size):
                page = rows[i:i + page_size]
                tuples = ",".join(
                    "(" + ",".join("%s" for _ in range(width)) + ")"
                    for _ in page)
                flat = [v for r in page for v in r]
                self.cursor.execute(
                    self._adapt(sql).replace("VALUES %s",
                                             f"VALUES {tuples}"), flat)
        elif self.dialect == "postgres":
            from psycopg2.extras import execute_values

            execute_values(self.cursor, self._adapt(sql), rows, page_size=page_size)
        else:
            width = len(rows[0])
            placeholders = "(" + ",".join("?" * width) + ")"
            self.cursor.executemany(sql.replace("VALUES ?", f"VALUES {placeholders}"), rows)
        self.connection.commit()
