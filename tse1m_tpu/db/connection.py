"""Database connection layer.

API-compatible superset of the reference's psycopg2 wrapper
(``program/__module/dbFile.py:16-38`` — ``connect``, ``executeQuery``,
``executeMany``, ``executeValues``, ``closeConnection``) with two upgrades:

1. Dual engine: embedded sqlite (default in this environment, where
   psycopg2/Postgres are unavailable) and Postgres when psycopg2 is present.
2. Parameterized queries throughout.  The reference interpolates values with
   f-strings (``queries1.py:43``, ``rq4a_bug.py:131``) — injection-prone and
   unplannable; here every query takes a params tuple.  Queries are written
   with the ``?`` qmark style and rewritten to ``%s`` for Postgres.
"""

from __future__ import annotations

import os
import re
import sqlite3
from typing import Any, Callable, Iterable, Sequence

from ..config import Config, load_config
from ..resilience import (deadline_guard, fault_point, io_retry_policy,
                          retry_call)
from ..utils.logging import get_logger

log = get_logger("db")

_QMARK_RE = re.compile(r"\?")

# Message markers that mean "the server connection is gone" across
# psycopg2, libpq (db/pglib.py), and sqlite — reconnect-class failures.
_DISCONNECT_MARKERS = (
    "server closed the connection", "connection already closed",
    "terminating connection", "connection reset", "could not connect",
    "connection refused", "connection timed out", "broken pipe",
    "ssl connection has been closed", "no connection to the server",
)

# sqlite-side transient failures: retry on the SAME connection.
_SQLITE_TRANSIENT_MARKERS = ("database is locked", "disk i/o error",
                             "database table is locked")


def is_disconnect(e: BaseException) -> bool:
    """True when the exception means the connection itself died (the next
    attempt needs a fresh connection, not just a re-execute)."""
    if isinstance(e, ConnectionError):  # incl. InjectedConnectionDrop
        return True
    from . import pglib

    if isinstance(e, pglib.OperationalError):
        return True
    mod = type(e).__module__ or ""
    if mod.startswith("psycopg2") and type(e).__name__ in (
            "OperationalError", "InterfaceError"):
        return True
    if isinstance(e, sqlite3.ProgrammingError):
        return "closed" in str(e).lower()
    return any(m in str(e).lower() for m in _DISCONNECT_MARKERS)


def is_transient(e: BaseException) -> bool:
    """The retry allowlist for DB statements: dropped connections,
    lock/busy contention, and injected faults.  SQL/programming errors
    (syntax, missing table, constraint) surface immediately."""
    from ..resilience import InjectedFault

    if is_disconnect(e) or isinstance(e, InjectedFault):
        return True
    if isinstance(e, (sqlite3.OperationalError, sqlite3.DatabaseError)):
        return any(m in str(e).lower() for m in _SQLITE_TRANSIENT_MARKERS)
    return False


class DB:
    """Connection wrapper.

    ``DB(config=...)`` picks the engine from config; the legacy keyword form
    ``DB(database=, user=, password=, host=, port=)`` (dbFile.py's signature)
    is accepted and implies Postgres when psycopg2 is importable, otherwise
    falls back to sqlite at the configured path.
    """

    def __init__(
        self,
        database: str | None = None,
        user: str | None = None,
        password: str | None = None,
        host: str | None = None,
        port: int | str | None = None,
        config: Config | None = None,
        sqlite_path: str | None = None,
    ) -> None:
        self.config = config or load_config()
        self._legacy_pg = database is not None
        if self._legacy_pg:
            self.config.postgres.database = database
            if user:
                self.config.postgres.user = user
            if password:
                self.config.postgres.password = password
            if host:
                self.config.postgres.host = host
            if port:
                self.config.postgres.port = int(port)
        if sqlite_path:
            self.config.sqlite_path = sqlite_path
        self.dialect = self._resolve_dialect()
        self.connection = None
        self.cursor = None
        # Uncommitted writes issued through non-committing ops since the
        # last commit/rollback/connect — i.e. a caller-managed transaction
        # is open and per-statement retry is no longer safe.
        self._dirty = False
        # > 0 while inside run_transaction: the unit owns retry there.
        self._txn_depth = 0
        c = self.config
        self._retry_policy = io_retry_policy(
            max_attempts=max(1, c.db_retry_attempts),
            base_delay=c.db_retry_base_delay,
            max_delay=c.db_retry_max_delay)

    def _resolve_dialect(self) -> str:
        self._pg_driver = None
        if self.config.engine == "postgres" or self._legacy_pg:
            try:
                import psycopg2  # noqa: F401

                self._pg_driver = "psycopg2"
                return "postgres"
            except ImportError:
                pass
            # psycopg2 missing: drive libpq directly (db/pglib.py) so
            # `engine = postgres` works wherever the C library exists.
            from . import pglib

            if pglib.available():
                self._pg_driver = "pglib"
                log.info("psycopg2 unavailable; using the ctypes libpq "
                         "driver (db/pglib.py)")
                return "postgres"
            log.warning("psycopg2 and libpq unavailable; falling back to "
                        "sqlite at %s", self.config.sqlite_path)
        return "sqlite"

    # -- lifecycle ---------------------------------------------------------

    def connect(self):
        retry_call(self._connect_once, policy=self._retry_policy,
                   site="db.connect", should_retry=is_transient)
        return self

    def _connect_once(self) -> None:
        fault_point("db.connect")
        self._dirty = False  # a fresh connection has no open transaction
        timeout_ms = self.config.db_statement_timeout_ms
        if self.dialect == "postgres":
            pg = self.config.postgres
            if self._pg_driver == "pglib":
                from . import pglib

                self.connection = pglib.connect(
                    database=pg.database, user=pg.user,
                    password=pg.password, host=pg.host, port=pg.port)
            else:
                import psycopg2

                self.connection = psycopg2.connect(
                    database=pg.database, user=pg.user, password=pg.password,
                    host=pg.host, port=pg.port,
                )
            self.cursor = self.connection.cursor()
            if timeout_ms > 0:
                # A hung statement must fail (and be retried/surfaced),
                # not stall a collector for hours.  SET is transactional
                # in Postgres and both drivers run it inside a BEGIN
                # (implicit for psycopg2, lazy for pglib): commit it
                # immediately so the first rollback — including the one
                # the retry engine's own recovery issues — cannot
                # silently revert the timeout for the rest of the
                # session.
                self.cursor.execute(
                    f"SET statement_timeout = {int(timeout_ms)}")
                self.connection.commit()
        else:
            path = self.config.sqlite_path
            if path != ":memory:":
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self.connection = sqlite3.connect(
                path, timeout=(timeout_ms / 1000.0) if timeout_ms > 0
                else 5.0)
            self.connection.execute("PRAGMA journal_mode=WAL")
            self.connection.execute("PRAGMA synchronous=NORMAL")
            if timeout_ms > 0:
                self.connection.execute(
                    f"PRAGMA busy_timeout={int(timeout_ms)}")
            self.cursor = self.connection.cursor()

    def _reconnect(self) -> None:
        """Drop the (possibly dead) connection and open a fresh one —
        the recovery hook the retry engine runs after a disconnect."""
        log.warning("db: reconnecting after dropped connection")
        try:
            self.closeConnection()
        except Exception:  # graftlint: disable=broad-except -- best-effort teardown of a connection already known dead
            self.cursor = self.connection = None
        self._connect_once()

    # A wedged sqlite statement (runaway cross join, scan over a corrupt
    # page) is interrupted at this multiple of db_statement_timeout_ms —
    # above the busy_timeout so lock waits get their full budget first.
    # Postgres needs no guard: SET statement_timeout is server-side.
    _STMT_DEADLINE_MULT = 4

    def _with_statement_deadline(self, op: Callable, site: str):
        """Run one statement under the watchdog's absolute deadline
        (sqlite only, and only when a statement timeout is configured):
        past the budget, ``Connection.interrupt`` cancels the statement
        cooperatively and it fails in-thread as OperationalError —
        classified transient, so the bounded retry path owns recovery.
        A hung statement was previously the failure that never raises."""
        timeout_ms = self.config.db_statement_timeout_ms
        if self.dialect != "sqlite" or timeout_ms <= 0:
            return op()
        budget_s = timeout_ms * self._STMT_DEADLINE_MULT / 1000.0
        with deadline_guard(budget_s, self.connection.interrupt, site=site):
            return op()

    def _statement(self, op: Callable, site: str = "db.execute",
                   commits: bool = False, writes: bool = False):
        """Run ``op()`` (a closure over ``self.cursor``) under the shared
        retry engine.  Transient faults re-execute on the same connection;
        disconnect-class failures reconnect first.

        Retry is only safe when the op is its own unit of work, so:

        - ops that commit internally (``commits=True``: executeMany,
          executeValues, DML executeQuery, ``execute_raw(commit=True)``)
          always retry — rollback/reconnect discards nothing committed
          and the whole op re-applies;
        - non-committing ops retry only while no caller-managed
          transaction is open (``self._dirty`` unset).  Once a caller
          has issued an uncommitted write, the recovery rollback would
          silently drop the *earlier* statements of that transaction and
          the caller's eventual ``commit()`` would persist a
          half-applied unit — so the failure surfaces instead.  Use
          :meth:`run_transaction` to make a multi-statement unit
          retryable as a whole.
        - inside :meth:`run_transaction` the unit owns retry; statements
          execute exactly once per unit attempt.

        The standard at-least-once caveat stands: a retried committing
        op can double-apply when the server committed *and* dropped
        before replying.
        """

        def attempt():
            fault_point(site)
            if self.connection is None or self.cursor is None:
                self._connect_once()
            result = self._with_statement_deadline(op, site)
            if commits:
                self._dirty = False
            elif writes:
                self._dirty = True
            return result

        if self._txn_depth:
            return attempt()  # the enclosing run_transaction retries
        if self._dirty and not commits:
            return attempt()  # open caller transaction: surface, not retry

        def recover(exc: BaseException, _attempt: int) -> None:
            if is_disconnect(exc):
                self._reconnect()
            else:
                try:  # clear any aborted-transaction state before re-trying
                    self.connection.rollback()
                except Exception:  # graftlint: disable=broad-except -- best-effort rollback; the retried statement surfaces real failures
                    pass

        return retry_call(attempt, policy=self._retry_policy, site=site,
                          should_retry=is_transient, on_retry=recover)

    def run_transaction(self, fn: Callable[["DB"], Any],
                        site: str = "db.txn"):
        """Execute ``fn(self)`` as one retried, atomic unit.

        Statements issued inside run once per attempt (no per-statement
        retry); on a transient failure the whole unit rolls back —
        reconnecting when the connection died — and re-runs from the
        top, and the commit happens here after a fully successful
        attempt.  ``fn`` must therefore be idempotent *as a whole*, e.g.
        the DELETE+INSERT rebuild in ``db/ingest.derive_projects`` or
        the IF-NOT-EXISTS DDL in ``db/schema.create_schema``.  Ops that
        commit internally (executeMany/executeValues/...) escape the
        unit's atomicity — avoid them inside ``fn``.
        """

        def attempt():
            if self.connection is None or self.cursor is None:
                self._connect_once()
            self._txn_depth += 1
            try:
                result = fn(self)
            finally:
                self._txn_depth -= 1
            self.connection.commit()
            self._dirty = False
            return result

        def recover(exc: BaseException, _attempt: int) -> None:
            self._dirty = False
            if is_disconnect(exc):
                self._reconnect()
            else:
                try:
                    self.connection.rollback()
                except Exception:  # graftlint: disable=broad-except -- best-effort rollback; the retried unit surfaces real failures
                    pass

        return retry_call(attempt, policy=self._retry_policy, site=site,
                          should_retry=is_transient, on_retry=recover)

    def closeConnection(self) -> None:
        if self.cursor is not None:
            self.cursor.close()
        if self.connection is not None:
            self.connection.close()
        self.cursor = self.connection = None
        self._dirty = False

    close = closeConnection

    def __enter__(self):
        return self.connect()

    def __exit__(self, *exc) -> None:
        self.closeConnection()

    # -- query helpers -----------------------------------------------------

    def _adapt(self, sql: str) -> str:
        if self.dialect == "postgres":
            return _QMARK_RE.sub("%s", sql)
        return sql

    def execute(self, sql: str, params: Sequence[Any] = ()) -> None:
        self._statement(
            lambda: self.cursor.execute(self._adapt(sql), tuple(params)),
            writes=True)

    def execute_raw(self, sql: str, commit: bool = False) -> int:
        """Execute one complete statement verbatim — no qmark adaptation,
        no parameter interpolation.  The restore path needs this: dump
        statements may carry ``?`` or ``%`` inside string literals, which
        ``_adapt`` + driver interpolation would corrupt or crash on.
        ``commit=True`` commits the statement as its own unit of work,
        which keeps it retryable under the shared engine (the restore
        path streams thousands of independent INSERTs and must not hold
        them all in one fragile uncommitted transaction).
        Returns the driver-reported affected-row count (0 when unknown)."""

        def op() -> int:
            self.cursor.execute(sql)
            n = self.cursor.rowcount
            if commit:
                self.connection.commit()
            return int(n) if n and n > 0 else 0

        return self._statement(op, commits=commit, writes=True)

    def query(self, sql: str, params: Sequence[Any] = ()) -> list[tuple]:
        def op() -> list[tuple]:
            self.cursor.execute(self._adapt(sql), tuple(params))
            return self.cursor.fetchall()

        return self._statement(op)

    def count(self, sql: str, params: Sequence[Any] = ()) -> int:
        """Row count of an arbitrary query without shipping its rows —
        diagnostics at the 1.19M-row scale only need the number."""
        # graftlint: disable=sql-interp -- wraps an already-parameterized query; no identifier reaches the text
        (n,) = self.query(f"SELECT COUNT(*) FROM ({sql}) AS t", params)[0]
        return int(n)

    def require_study_tables(self) -> None:
        """Fail with actionable guidance when the study schema is absent
        (shared by StudyContext.open and the CLI)."""
        try:
            self.query("SELECT 1 FROM issues LIMIT 1")
        except Exception as e:
            raise SystemExit(
                f"study database not initialised ({e}). Populate it first: "
                "`python -m tse1m_tpu.cli synth` for a synthetic study or "
                "`python -m tse1m_tpu.cli ingest --csv-dir ...` for "
                "collector CSVs."
            ) from e

    def commit(self) -> None:
        self.connection.commit()
        self._dirty = False

    def rollback(self) -> None:
        self.connection.rollback()
        self._dirty = False

    # -- reference-compatible surface (dbFile.py:16-38) --------------------

    def executeQuery(self, type: str, sql: str, params: Sequence[Any] = ()):
        """``type`` is 'select' (returns rows) or anything else (DML+commit),
        mirroring dbFile.py's select/insert/update switch."""

        def op():
            self.cursor.execute(self._adapt(sql), tuple(params))
            if type == "select":
                return self.cursor.fetchall()
            self.connection.commit()
            return None

        return self._statement(op, commits=(type != "select"))

    def executeMany(self, sql: str, rows: Iterable[Sequence[Any]]) -> None:
        rows = [tuple(r) for r in rows]

        def op() -> None:
            self.cursor.executemany(self._adapt(sql), rows)
            self.connection.commit()

        self._statement(op, commits=True)

    def executeValues(self, sql: str, rows: Iterable[Sequence[Any]], page_size: int = 1000) -> None:
        """Bulk insert.  Postgres uses psycopg2.extras.execute_values
        (dbFile.py:37's mechanism); sqlite uses executemany, which is the
        equivalent fast path there.  ``sql`` must be of the form
        ``INSERT INTO t (cols) VALUES ?`` with a single placeholder."""
        rows = [tuple(r) for r in rows]
        if not rows:
            return

        def op() -> None:
            # The whole page set is one commit scope, so a retried attempt
            # (after rollback/reconnect) re-inserts from the start instead
            # of double-applying a committed prefix.
            if self.dialect == "postgres" and self._pg_driver == "pglib":
                # execute_values equivalent: one multi-VALUES statement per
                # page, parameters still out of band.
                width = len(rows[0])
                for i in range(0, len(rows), page_size):
                    page = rows[i:i + page_size]
                    tuples = ",".join(
                        "(" + ",".join("%s" for _ in range(width)) + ")"
                        for _ in page)
                    flat = [v for r in page for v in r]
                    self.cursor.execute(
                        self._adapt(sql).replace("VALUES %s",
                                                 f"VALUES {tuples}"), flat)
            elif self.dialect == "postgres":
                from psycopg2.extras import execute_values

                execute_values(self.cursor, self._adapt(sql), rows,
                               page_size=page_size)
            else:
                width = len(rows[0])
                placeholders = "(" + ",".join("?" * width) + ")"
                self.cursor.executemany(
                    sql.replace("VALUES ?", f"VALUES {placeholders}"), rows)
            self.connection.commit()

        self._statement(op, commits=True)
