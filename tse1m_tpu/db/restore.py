"""Restore a SQL dump of the study database.

The reference's canonical DB bootstrap is a pg_dump restored with
``psql -U user -d dbname < backup_clean.sql`` (reference README.md:55);
the dump itself is gitignored there (.gitignore:7) and absent from the
snapshot.  This module gives holders of the real dump a first-class path
into EITHER engine:

- pg_dump's default format carries data as COPY blocks::

      COPY public.buildlog_data (name, project, ...) FROM stdin;
      <tab-separated rows, \\N for NULL>
      \\.

  The restorer applies OUR canonical DDL (db/schema.py — the five-table
  schema with the Success/Finish enum unified, SURVEY §2.2) and streams
  each known table's COPY rows in as parameterized inserts.  pg_dump's
  DDL/SET/ALTER/sequence noise is skipped, so the same dump restores
  into sqlite and Postgres alike.
- ``INSERT INTO <study table> ...`` statements (pg_dump --inserts, or a
  hand-written fixture) execute as-is.

Array columns (modules/revisions/regressed_build) keep their Postgres
text literal form (``{a,b}``) — exactly what the columnar extraction
layer parses (data/columnar.py).
"""

from __future__ import annotations

import re

from ..utils.logging import get_logger
from .ident import col_list, quote_ident
from .schema import SCHEMA_TABLES, create_schema

log = get_logger("db.restore")

_COPY_RE = re.compile(
    r"^COPY\s+(?:[\w\"]+\.)?(\w+)\s*\(([^)]*)\)\s+FROM\s+stdin;\s*$",
    re.IGNORECASE)
_INSERT_RE = re.compile(r"^INSERT\s+INTO\s+(?:[\w\"]+\.)?(\w+)",
                        re.IGNORECASE)

# COPY text-format escapes (https://www.postgresql.org/docs/current/
# sql-copy.html#id-1.9.3.55.9.2) — the ones pg_dump emits.
_UNESCAPE = {"\\\\": "\\", "\\b": "\b", "\\f": "\f", "\\n": "\n",
             "\\r": "\r", "\\t": "\t", "\\v": "\v"}
_ESC_RE = re.compile(r"\\[\\bfnrtv]")


def _copy_cell(cell: str):
    if cell == "\\N":
        return None
    if "\\" in cell:
        cell = _ESC_RE.sub(lambda m: _UNESCAPE[m.group(0)], cell)
    return cell


def _scan_quotes(text: str, in_string: bool) -> bool:
    """Track single-quote string state across a statement fragment so a
    ``;`` at a line end inside a text literal (pg_dump emits embedded
    newlines verbatim) doesn't terminate the statement early.  The SQL
    ``''`` escape toggles twice — a no-op, as required."""
    for ch in text:
        if ch == "'":
            in_string = not in_string
    return in_string


def restore_sql_dump(db, path: str, create: bool = True,
                     batch: int = 5000) -> dict:
    """Load ``path`` (pg_dump or INSERT-style SQL) into ``db``.

    Returns per-table inserted row counts.  Unknown tables and non-data
    statements are skipped (counted under ``"skipped_statements"``); the
    ``projects`` table is re-derived from buildlog rows when the dump
    doesn't carry it (db/ingest.derive_projects — it is derived data).
    """
    if create:
        create_schema(db)
    counts: dict = {t: 0 for t in SCHEMA_TABLES}
    skipped = 0

    with open(path, encoding="utf-8") as f:
        in_copy = None  # (table, insert sql, pending rows)
        stmt_parts: list = []
        in_string = False
        for raw in f:
            line = raw.rstrip("\n")
            if in_copy is not None:
                table, sql, rows = in_copy
                if line == "\\.":
                    if rows:
                        db.executeMany(sql, rows)
                        counts[table] += len(rows)
                    in_copy = None
                    continue
                if sql is None:
                    continue  # data of an unknown table — skipped
                rows.append([_copy_cell(c) for c in line.split("\t")])
                if len(rows) >= batch:
                    db.executeMany(sql, rows)
                    counts[table] += len(rows)
                    rows.clear()
                continue

            m = _COPY_RE.match(line)
            if m:
                table = m.group(1).lower()
                cols = [c.strip().strip('"') for c in m.group(2).split(",")]
                if table in counts:
                    # The COPY header is attacker-controlled text in a
                    # hostile dump; identifiers must validate before they
                    # touch SQL (db/ident.py).
                    ph = ", ".join("?" * len(cols))
                    sql = (f"INSERT INTO {quote_ident(table)} "
                           f"({col_list(cols)}) VALUES ({ph})")
                    in_copy = (table, sql, [])
                else:
                    log.info("restore: skipping COPY into unknown table %s",
                             table)
                    in_copy = ("__skip__", None, None)
                    counts.setdefault("__skip__", 0)
                continue

            # Accumulate ;-terminated statements (quote-aware: a ';' at a
            # line end inside a string literal doesn't end the statement);
            # execute only the study tables' INSERTs verbatim, drop
            # everything else (SET/CREATE/ALTER/...).
            stmt_parts.append(line)
            in_string = _scan_quotes(line, in_string)
            if not in_string and line.rstrip().endswith(";"):
                stmt = "\n".join(stmt_parts).strip()
                stmt_parts = []
                m = _INSERT_RE.match(stmt)
                if m and m.group(1).lower() in counts:
                    table = m.group(1).lower()
                    # rowcount, not statement count: pg_dump --inserts can
                    # pack many rows per VALUES list.  commit=True: each
                    # dump INSERT is its own retryable unit — holding the
                    # whole stream in one transaction would mean a single
                    # mid-stream disconnect silently drops every prior
                    # uncommitted row.
                    counts[table] += db.execute_raw(
                        stmt.rstrip(";").replace(f"public.{table}", table),
                        commit=True)
                elif stmt and not stmt.startswith("--"):
                    skipped += 1
    # A COPY block for a skipped table collects under "__skip__": drop it.
    counts.pop("__skip__", None)
    # Canonicalise the result enum at the door (db/ingest._RESULT_CANON):
    # a dump produced by the reference's analyzer carries 'Success' where
    # every analysis query filters ('Finish','Halfway') — left unmapped,
    # those sessions would silently vanish from every RQ.
    if counts.get("buildlog_data", 0):
        from .ingest import _RESULT_CANON

        def _canon(dbx) -> None:
            # One retried transaction unit: the UPDATEs are idempotent
            # as a batch, so a transient mid-batch failure replays all.
            for src, dst in _RESULT_CANON.items():
                dbx.execute("UPDATE buildlog_data SET result = ? "
                            "WHERE result = ?", (dst, src))

        db.run_transaction(_canon, site="db.restore.canon")
    if counts.get("projects", 0) == 0 and counts.get("buildlog_data", 0):
        from .ingest import derive_projects

        derive_projects(db)
        counts["projects"] = db.count("SELECT * FROM projects", ())
    db.commit()
    counts["skipped_statements"] = skipped
    log.info("restore: %s", counts)
    return counts
