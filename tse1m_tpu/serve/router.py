"""Stateless fan-out router for the sharded serve plane.

One router process fronts N shard daemons — each a single-writer
`ServeDaemon` over its ``range_NNNN/`` slice of a pod store root, owning
its digest range through an epoch lease
(`resilience.coordinator.RangeLeaseGuard`).  The router speaks the same
JSON-over-TCP verbs as a single daemon, so `ServeClient` and
``cli serve-client`` work unchanged against either topology:

- **ingest** splits the batch by digest range (`cluster.store
  .digest_range_ids` — the same deal the pod batch plane uses), forwards
  each slice to its owner with a per-shard idempotent request id, and
  acks only after EVERY owner's manifest commit.  On a shard-daemon
  death mid-window the forward retries against the epoch-advanced
  replacement writer with the SAME request id: a slice that already
  committed replays its original ack from the shard's manifest journal
  (zero rows double-absorbed), a slice that never committed ingests
  fresh (zero acked rows lost).  Lease fencing makes the replay safe —
  the superseded writer can no longer append.
- **query** broadcasts to every shard (an LSH near-duplicate can live in
  any range — only exact duplicates co-shard by digest) and min-merges:
  membership comes from the digest owner, the label is the smallest
  mapped global id any shard proposes.
- The router holds NO durable state.  Its only soft state is the
  per-shard local-row -> global-row map, rebuilt purely from ack
  ``rows`` fields (``setdefault`` — min global id wins), which is why a
  replayed ack composes: digest-lookup rows map onto already-assigned
  global ids.  Routed shard daemons should run ``state_commit_every=1``
  so a writer restart preserves local row identity for every batch that
  was acked before the crash (the one in-flight batch per shard is
  retried idempotently).

The router never opens a store directory and never writes a store file
(graftlint ``serve-write-plane``): durability lives entirely at the
shard writers.
"""

from __future__ import annotations

import socket
import socketserver
import threading

import numpy as np

from ..cluster.store import digest_range_ids, row_digests
from ..observability import metrics as obs_metrics
from ..observability.export import flat_metrics, prometheus_text
from ..observability.latency import LatencyRecorder
from ..observability.tracing import (continue_trace, recent_spans, span,
                                     spans_recorded)
from ..resilience import RetryPolicy, fault_point, reraise_if_fault, retry_call
from ..resilience.watchdog import request_budget_s
from ..trace import sync as tsync
from ..trace.hooks import shared_access, trace_point
from ..utils.logging import get_logger
from .daemon import IngestRejected
from .server import (_Handler, decode_vectors, encode_vectors, read_msg,
                     write_msg)

log = get_logger("serve.router")

# graftspec binding: fault seats here must be modeled by these specs.
SPEC_MODELS = ("ingest_ack",)

_CONNECT_TIMEOUT_S = 5.0

# Synthetic label space for cluster representatives the router never
# acked (rows pre-loaded into a shard store outside this router): each
# (shard, local row) still gets ONE deterministic global label, kept
# below -1 so it can never collide with a routed global row id.
_FOREIGN_BASE = -2


class TcpTransport:
    """One pinned connection to one shard daemon; reconnects lazily and
    re-resolves the port file on every reconnect — a replacement writer
    under a fresh port publishes itself by rewriting the same file."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 port_file: str | None = None) -> None:
        self.host = host
        self.port = int(port)
        self.port_file = port_file
        self._sock: socket.socket | None = None

    def _resolve_port(self) -> int:
        if self.port_file:
            with open(self.port_file, encoding="utf-8") as f:
                return int(f.read().strip())
        return self.port

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self._resolve_port()),
                                         timeout=_CONNECT_TIMEOUT_S)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __call__(self, msg: dict, timeout_s: float | None = None) -> dict:
        sock = self._connect()
        sock.settimeout(timeout_s or _CONNECT_TIMEOUT_S)
        try:
            write_msg(sock, msg)
            return read_msg(sock)
        except (ConnectionError, socket.timeout, OSError):
            self.close()
            raise


class LocalTransport:
    """In-process transport over a `ServeDaemon` (or `ServeReplica`):
    the graftrace schedule explorer and the unit tests drive the real
    router logic without sockets.  Speaks the same message dicts the
    TCP servers dispatch."""

    def __init__(self, daemon) -> None:
        self.daemon = daemon

    def __call__(self, msg: dict, timeout_s: float | None = None) -> dict:
        op = str(msg.get("op", ""))
        if op == "ingest":
            rid = msg.get("request_id")
            return self.daemon.ingest(decode_vectors(msg),
                                      request_id=str(rid) if rid else None)
        if op == "query":
            res = self.daemon.query(decode_vectors(msg))
            return {"ok": True,
                    "labels": res["labels"].astype(int).tolist(),
                    "known": res["known"].astype(bool).tolist(),
                    "generation": int(res["generation"])}
        if op == "topk":
            return self.daemon.topk(
                decode_vectors(msg), k=int(msg.get("k", 10)),
                mode=str(msg.get("mode", "candidates")))
        if op == "ping":
            idx = self.daemon._index
            return {"ok": True, "op": "ping",
                    "generation": idx.generation, "rows": idx.n_rows}
        if op == "status":
            return {"ok": True, **self.daemon.status()}
        if op == "quiesce":
            return self.daemon.quiesce()
        return {"ok": False, "error": f"unknown op {op!r}"}


class ShardRouter:
    """Fan `query`/`ingest` over the shard owners; min-merge the
    answers.  Thread-safe: the per-shard row map and the request
    counter live under one lock; forwards happen outside it."""

    def __init__(self, transports: dict[int, object],
                 monitor=None,
                 retry: RetryPolicy | None = None) -> None:
        if not transports:
            raise ValueError("router needs at least one shard transport")
        self.transports = dict(transports)
        self.n_shards = len(self.transports)
        if sorted(self.transports) != list(range(self.n_shards)):
            raise ValueError(
                f"shard transports must cover ranges 0..{self.n_shards - 1} "
                f"densely, got {sorted(self.transports)}")
        # Optional resilience.coordinator.PeerMonitor over the shard
        # daemons' heartbeat files (peers = range ids): `status` reports
        # which writers are currently lost without waiting on a forward
        # timeout to discover it.
        self.monitor = monitor
        # Failover window: enough attempts to cover a replacement
        # writer's respawn + recovery behind the same port file.
        self.retry = retry or RetryPolicy(max_attempts=8, base_delay=0.1,
                                          max_delay=2.0)
        self._lock = tsync.Lock("ShardRouter")
        # shard id -> {local index row -> global row id}; global ids are
        # assigned in submission order, so min-global == first ingest.
        self._gmap: dict[int, dict[int, int]] = {
            sid: {} for sid in self.transports}
        self._next_row = 0
        self._seq = 0
        self._replayed = 0
        self.lat_forward = LatencyRecorder("serve_router_forward")

    # -- forwarding ----------------------------------------------------------

    def _forward(self, sid: int, msg: dict,
                 timeout_s: float | None = None) -> dict:
        """One shard exchange under the shared retry engine: connection
        failures (a dying or restarting writer) re-send the SAME message
        — same request id — so the replacement's journal replay, not a
        second absorb, answers a retried committed slice."""

        def attempt() -> dict:
            with span("serve.router.forward", shard=int(sid),
                      op=str(msg.get("op", ""))):
                with self.lat_forward.time():
                    resp = self.transports[sid](msg, timeout_s=timeout_s)
            # The lost-ack window: the shard has committed and answered,
            # this process has not yet passed the answer up.  An
            # injected drop here is exactly "writer died after commit,
            # before the ack reached the client".
            fault_point("serve.router.forward")
            return resp

        resp = retry_call(attempt, policy=self.retry,
                          site="serve.router.forward")
        if not resp.get("ok", False):
            if resp.get("error") == "backpressure":
                raise IngestRejected(int(resp.get("depth", 0)),
                                     float(resp.get("retry_after_s", 0.1)))
            raise RuntimeError(
                f"shard {sid} refused {msg.get('op')}: {resp.get('error')}")
        return resp

    def _map_label(self, sid: int, local: int) -> int:
        """Shard-local label (an index row id) -> global label, under
        the caller's lock.  Unrouted representatives get a stable
        synthetic id below -1 (never a routed global row)."""
        g = self._gmap[sid].get(int(local))
        if g is not None:
            return g
        return _FOREIGN_BASE - (int(local) * self.n_shards + int(sid))

    # -- verbs ---------------------------------------------------------------

    def ingest(self, vectors: np.ndarray, timeout: float | None = None,
               request_id: str | None = None) -> dict:
        vectors = np.ascontiguousarray(vectors, np.uint32)
        k = int(vectors.shape[0])
        rid_in = str(request_id) if request_id else None
        with self._lock:
            shared_access(self, "gmap", write=True)
            self._seq += 1
            rid = rid_in or f"r{self._seq:08d}"
            g0 = self._next_row
            self._next_row += k
        if k == 0:
            return {"ok": True, "acked": 0, "novel": 0, "generation": 0,
                    "labels": [], "rows": [], "shards": {}}
        rows_sid = digest_range_ids(row_digests(vectors), self.n_shards)
        trace_point("serve.router.split")
        per_shard: dict[int, np.ndarray] = {}
        for sid in np.unique(rows_sid):
            per_shard[int(sid)] = np.flatnonzero(rows_sid == sid)
        acked = novel = 0
        replayed = False
        gens: dict[int, int] = {}
        glabels = np.empty(k, np.int64)
        # In-flight window: ONE slice outstanding per shard, forwarded
        # in range order — deterministic under the schedule explorer.
        resps: dict[int, dict] = {}
        for sid in sorted(per_shard):
            sel = per_shard[sid]
            msg = {"op": "ingest", "request_id": f"{rid}/{sid}",
                   **encode_vectors(vectors[sel])}
            resps[sid] = self._forward(sid, msg, timeout_s=timeout)
        with self._lock:
            shared_access(self, "gmap", write=True)
            for sid in sorted(per_shard):
                sel = per_shard[sid]
                resp = resps[sid]
                acked += int(resp.get("acked", 0))
                novel += int(resp.get("novel", 0))
                gens[sid] = int(resp.get("generation", 0))
                if resp.get("replayed"):
                    replayed = True
                    self._replayed += 1
                gmap = self._gmap[sid]
                # Map THIS slice's rows first (min-global wins), then
                # translate its labels — a cluster representative may be
                # in the slice itself.
                for i, local in zip(sel.tolist(), resp["rows"]):
                    # A replayed ack can carry -1 for a row whose store
                    # copy was since evicted; never map a sentinel.
                    if int(local) >= 0:
                        gmap.setdefault(int(local), g0 + int(i))
                for i, local in zip(sel.tolist(), resp["labels"]):
                    glabels[i] = (self._map_label(sid, int(local))
                                  if int(local) >= 0 else -1)
        out = {"ok": True, "acked": acked, "novel": novel,
               "generation": max(gens.values()),
               "labels": glabels.tolist(),
               "rows": (g0 + np.arange(k, dtype=np.int64)).tolist(),
               "shards": {str(s): g for s, g in sorted(gens.items())}}
        if replayed:
            out["replayed"] = True
        return out

    def query(self, vectors: np.ndarray) -> dict:
        """Broadcast membership: `known` comes from the digest owner,
        the label is the min routed global id across every shard that
        proposes one (direct cross-shard agreement; transitive merges
        across three or more shards settle at the daily batch
        recluster)."""
        vectors = np.ascontiguousarray(vectors, np.uint32)
        n = int(vectors.shape[0])
        owner = digest_range_ids(row_digests(vectors), self.n_shards)
        msg_payload = encode_vectors(vectors)
        resps: dict[int, dict] = {}
        for sid in sorted(self.transports):
            resps[sid] = self._forward(sid, {"op": "query", **msg_payload})
        known = np.zeros(n, bool)
        out = np.full(n, -1, np.int64)
        gens = {sid: int(r.get("generation", 0))
                for sid, r in resps.items()}
        with self._lock:
            shared_access(self, "gmap", write=False)
            for i in range(n):
                known[i] = bool(resps[int(owner[i])]["known"][i])
                best = None
                foreign = None
                for sid, resp in resps.items():
                    local = int(resp["labels"][i])
                    if local < 0:
                        continue
                    g = self._map_label(sid, local)
                    if g >= 0:
                        best = g if best is None else min(best, g)
                    else:
                        foreign = g if foreign is None else min(foreign, g)
                if best is not None:
                    out[i] = best
                elif foreign is not None:
                    out[i] = foreign
        return {"labels": out, "known": known,
                "generation": max(gens.values()),
                "shard_generations": gens}

    def topk(self, vectors: np.ndarray, k: int = 10,
             mode: str = "candidates") -> dict:
        """Broadcast top-k: every shard ranks its own rows, the router
        merges the per-shard answers under the shards' own wire order
        (-agreement count, digest hex ascending) and keeps the global
        k.  Digests co-shard exactly (no row lives in two ranges), so
        in scan mode the merged list is elementwise what ONE unsharded
        daemon over the union of the rows answers; candidate mode
        inherits each shard's hub recall.  Shard-local labels map to
        routed global ids the same way ``query`` maps them."""
        vectors = np.ascontiguousarray(vectors, np.uint32)
        n = int(vectors.shape[0])
        k = int(k)
        payload = encode_vectors(vectors)
        resps: dict[int, dict] = {}
        for sid in sorted(self.transports):
            resps[sid] = self._forward(
                sid, {"op": "topk", "k": k, "mode": str(mode), **payload})
        gens = {sid: int(r.get("generation", 0))
                for sid, r in resps.items()}
        out_s = np.full((n, k), -1, np.int64)
        out_l = np.full((n, k), -1, np.int64)
        out_i = [[""] * k for _ in range(n)]
        with self._lock:
            shared_access(self, "gmap", write=False)
            for i in range(n):
                cand = []
                for sid, resp in resps.items():
                    sc = resp["scores"][i]
                    ids = resp["ids"][i]
                    lb = resp["labels"][i]
                    for j in range(len(sc)):
                        if int(sc[j]) < 0:
                            continue
                        lab = int(lb[j])
                        cand.append((int(sc[j]), str(ids[j]),
                                     self._map_label(sid, lab)
                                     if lab >= 0 else -1))
                cand.sort(key=lambda t: (-t[0], t[1]))
                for t, (sc, hx, g) in enumerate(cand[:k]):
                    out_s[i, t] = sc
                    out_i[i][t] = hx
                    out_l[i, t] = g
        return {"ok": True, "k": k, "mode": str(mode),
                "generation": max(gens.values()),
                "shard_generations": gens,
                "scores": out_s.tolist(), "ids": out_i,
                "labels": out_l.tolist()}

    def ping(self) -> dict:
        resps = {sid: self._forward(sid, {"op": "ping"})
                 for sid in sorted(self.transports)}
        return {"ok": True, "op": "ping",
                "rows": sum(int(r.get("rows", 0)) for r in resps.values()),
                "generation": max(int(r.get("generation", 0))
                                  for r in resps.values()),
                "shards": self.n_shards}

    def quiesce(self, timeout: float | None = None) -> dict:
        resps = {sid: self._forward(sid, {"op": "quiesce"},
                                    timeout_s=timeout)
                 for sid in sorted(self.transports)}
        return {"ok": True,
                "generation": max(int(r.get("generation", 0))
                                  for r in resps.values()),
                "shards": {str(s): int(r.get("generation", 0))
                           for s, r in sorted(resps.items())}}

    def status(self) -> dict:
        shard_status: dict[str, dict] = {}
        for sid in sorted(self.transports):
            try:
                shard_status[str(sid)] = self._forward(
                    sid, {"op": "status"})
            except (ConnectionError, OSError, RuntimeError) as e:
                shard_status[str(sid)] = {"ok": False,
                                          "error": f"{type(e).__name__}: {e}"}
        lost = self.monitor.poll() if self.monitor is not None else []
        with self._lock:
            shared_access(self, "gmap", write=False)
            mapped = sum(len(m) for m in self._gmap.values())
            stats = {"router_rows": self._next_row,
                     "router_requests": self._seq,
                     "router_replayed_acks": self._replayed,
                     "router_mapped_rows": mapped}
        obs_metrics.gauge("serve_router_rows").set(stats["router_rows"])
        return {"ok": all(s.get("ok", False)
                          for s in shard_status.values()),
                "topology": "sharded",
                "shards": self.n_shards,
                "shards_lost": [int(p) for p in lost],
                **stats,
                **self.lat_forward.summary(),
                "shard_status": shard_status}


class RouterServer(socketserver.ThreadingTCPServer):
    """The router's JSON-over-TCP face: same framing, same verbs, same
    error envelope as `ServeServer` — a `ServeClient` cannot tell the
    difference (the point: clients and the CLI work unchanged over the
    sharded topology)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, router: ShardRouter,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__((host, port), _Handler)
        self.router = router
        self._shutdown_requested = threading.Event()

    @property
    def port(self) -> int:
        return int(self.server_address[1])

    def dispatch(self, msg: dict) -> dict:
        op = str(msg.get("op", ""))
        ctx = msg.pop("trace", None)
        try:
            with continue_trace(ctx):
                with span(f"serve.router.{op}"):
                    resp = self._dispatch_op(op, msg)
        except IngestRejected as e:
            resp = {"ok": False, "error": "backpressure",
                    "retry_after_s": round(e.retry_after_s, 3),
                    "depth": e.depth}
        except Exception as e:
            reraise_if_fault(e)
            log.error("router: %s request failed (%s: %s)", op,
                      type(e).__name__, e)
            resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        if ctx and isinstance(ctx, dict) and ctx.get("t"):
            resp.setdefault("trace", str(ctx["t"]))
        return resp

    def _dispatch_op(self, op: str, msg: dict) -> dict:
        if op == "ping":
            return self.router.ping()
        if op == "status":
            return self.router.status()
        if op == "query":
            res = self.router.query(decode_vectors(msg))
            return {"ok": True,
                    "labels": res["labels"].astype(int).tolist(),
                    "known": res["known"].astype(bool).tolist(),
                    "generation": int(res["generation"])}
        if op == "topk":
            return self.router.topk(
                decode_vectors(msg), k=int(msg.get("k", 10)),
                mode=str(msg.get("mode", "candidates")))
        if op == "ingest":
            rid = msg.get("request_id")
            return self.router.ingest(
                decode_vectors(msg),
                timeout=request_budget_s("ingest") or None,
                request_id=str(rid) if rid else None)
        if op == "quiesce":
            return self.router.quiesce(
                timeout=request_budget_s("ingest") or None)
        if op == "metrics":
            return {"ok": True, "prometheus": prometheus_text(),
                    "metrics": flat_metrics()}
        if op == "trace":
            n = msg.get("n")
            return {"ok": True,
                    "spans": recent_spans(int(n) if n else None),
                    "spans_recorded": spans_recorded()}
        if op == "shutdown":
            self._shutdown_requested.set()
            threading.Thread(target=self.shutdown, daemon=True).start()
            return {"ok": True, "op": "shutdown"}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def serve_until_shutdown(self, port_file: str | None = None) -> None:
        if port_file:
            from ..utils.atomic import atomic_write

            with atomic_write(port_file) as f:
                f.write(str(self.port))
        log.info("router: listening on %s:%d (%d shard(s))",
                 self.server_address[0], self.port, self.router.n_shards)
        self.serve_forever(poll_interval=0.1)


__all__ = ["LocalTransport", "RouterServer", "ShardRouter", "TcpTransport"]
