"""Online near-duplicate serving plane.

Turns the batch warm path (content-addressed signature store + extend-
never-rebuild band tables) into a long-lived service: a single-writer
ingest daemon (`daemon.ServeDaemon`), a lock-free query path over
atomically swapped `cluster.incremental.LiveClusterIndex` snapshots, an
SLO/admission layer (`slo`), and a tiny JSON-over-TCP transport
(`server`/`client`).  `cli serve` runs it; batch `cli cluster` shares
the same index code — one merge implementation for both shapes.

Fleet scale: `router.ShardRouter` fans the same verbs over N digest-
range shard daemons (each a single-writer `ServeDaemon` over one
``range_NNNN/`` slice, fenced by an epoch lease) with durable-once
ingest acks, and `replicate.ServeReplica` serves stale-bounded reads
from a streamed store copy — `ServeClient` works unchanged against
any of the three topologies.
"""

from .client import Backpressure, ServeClient, ServeError
from .daemon import IngestRejected, ServeDaemon
from .replicate import (ReplicationPuller, ServeReplica, replica_staleness,
                        stream_shards)
from .router import LocalTransport, RouterServer, ShardRouter, TcpTransport
from .server import ServeServer
from .slo import AdmissionController, SloPolicy, SloTracker

__all__ = ["AdmissionController", "Backpressure", "IngestRejected",
           "LocalTransport", "ReplicationPuller", "RouterServer",
           "ServeClient", "ServeDaemon", "ServeError", "ServeReplica",
           "ServeServer", "ShardRouter", "SloPolicy", "SloTracker",
           "TcpTransport", "replica_staleness", "stream_shards"]
