"""Online near-duplicate serving plane.

Turns the batch warm path (content-addressed signature store + extend-
never-rebuild band tables) into a long-lived service: a single-writer
ingest daemon (`daemon.ServeDaemon`), a lock-free query path over
atomically swapped `cluster.incremental.LiveClusterIndex` snapshots, an
SLO/admission layer (`slo`), and a tiny JSON-over-TCP transport
(`server`/`client`).  `cli serve` runs it; batch `cli cluster` shares
the same index code — one merge implementation for both shapes.
"""

from .client import Backpressure, ServeClient, ServeError
from .daemon import IngestRejected, ServeDaemon
from .server import ServeServer
from .slo import AdmissionController, SloPolicy, SloTracker

__all__ = ["AdmissionController", "Backpressure", "IngestRejected",
           "ServeClient", "ServeDaemon", "ServeError", "ServeServer",
           "SloPolicy", "SloTracker"]
