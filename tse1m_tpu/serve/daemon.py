"""Long-lived near-duplicate serving daemon (ingest loop + query path).

The batch pipeline answers "cluster these N sessions" once a day; this
daemon answers "which cluster does THIS coverage vector belong to?"
continuously, over the same persistent machinery:

- **Ingest** (single writer): batches of coverage vectors are digested,
  deduplicated against the live index, probed against the signature
  store, and only the content-novel tail is device-MinHashed — through
  the existing degraded streaming pipeline
  (`cluster.pipeline.minhash_novel_rows`: OOM halving, stall retry, CPU
  failover), padded to power-of-two batch shapes so a long-lived process
  compiles O(log max-batch) kernel shapes.  Novel signatures append to
  the store under the single-writer discipline; a batch is ACKNOWLEDGED
  only after the store manifest commit, so an acknowledged row survives
  SIGKILL (the chaos contract: restart loses zero acked rows).
- **Query** (lock-free readers): each ingest generation publishes a new
  immutable `cluster.incremental.LiveClusterIndex` snapshot by swapping
  ONE reference — queries grab the reference once and never observe a
  half-updated band table.  Old-signature gathers go through a
  read-only mmap store handle (`SignatureStore(read_only=True)`)
  refreshed per generation via the store's generation counter.  The
  query path is host-only (digest lookup, or host MinHash + band-table
  probe + exact signature verification for novel vectors): zero device
  transfers, zero compiles — sanitizer-clean by construction.
- **SLO** (`serve/slo.py`): admission control refuses ingest past the
  backlog bound BEFORE query p99 degrades; per-request-class watchdog
  budgets come from `resilience.watchdog.request_budget_s`; latency
  histograms (`observability.latency.LatencyRecorder`) and queue depth
  flow into the status endpoint and the bench ``serve_*`` keys.

Crash recovery: the daemon adopts the store's persisted LSH state as
generation 0 and then absorbs, in deterministic (shard, row) order, any
store rows the state does not cover — exactly the rows whose append
committed (and was acked) but whose state commit the crash outran.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from ..cluster.host import host_band_keys
from ..cluster.incremental import LiveClusterIndex, _delta_max_runs
from ..cluster.pipeline import (ClusterParams, _store_policy,
                                minhash_novel_rows)
from ..cluster.schemes import make_params, scheme_host_signatures
from ..cluster.encode import quantize_ids
from ..cluster.store import SignatureStore, row_digests
from ..observability import StageRecorder, record_degradation
from ..observability import metrics as obs_metrics
from ..observability import profiling
from ..observability.flight import dump_flight, get_flight_dir, set_flight_dir
from ..observability.latency import LatencyRecorder
from ..observability.tracing import continue_trace, current_trace, span
from ..resilience import (StageWatchdog, fault_point, reraise_if_fault)
from ..resilience.coordinator import LeaseSupersededError
from ..resilience.watchdog import deadline_clock
from ..trace.hooks import shared_access, trace_point
from ..utils.logging import get_logger
from .slo import AdmissionController, SloPolicy, SloTracker

log = get_logger("serve.daemon")

# graftspec binding: the lint conformance pass holds every fault seat
# in this module to an action of these protocol specs (tse1m_tpu/spec/).
SPEC_MODELS = ("ingest_ack", "lease")

_RECOVER_CHUNK = 65536
_CONTROL_COMMIT = "commit_state"


def _labels_by_locator(index, loc: np.ndarray,
                       ok: np.ndarray) -> np.ndarray:
    """Reverse-map (shard, row) store locators to index labels: the
    scan path ranks STORE rows, which may include rows appended but not
    yet absorbed into the published snapshot — those answer label -1
    (novel), never a stale label."""
    labels = np.full(loc.shape[0], -1, np.int64)
    sel = np.flatnonzero(ok)
    if sel.size == 0 or int(index.n_rows) == 0:
        return labels
    big = np.int64(2**31)
    ikey = (index.locator[:, 0].astype(np.int64) * big
            + index.locator[:, 1].astype(np.int64))
    order = np.argsort(ikey, kind="stable")
    skey = ikey[order]
    q = loc[sel, 0].astype(np.int64) * big + loc[sel, 1].astype(np.int64)
    pos = np.searchsorted(skey, q)
    inb = pos < skey.shape[0]
    hit = np.zeros(q.shape[0], bool)
    hit[inb] = skey[pos[inb]] == q[inb]
    labels[sel[hit]] = index.labels[order[pos[hit]]].astype(np.int64)
    return labels


def _topk_answer(srv, index, store, gather, vectors: np.ndarray,
                 k: int, mode: str) -> dict:
    """Shared ``topk`` verb body (daemon and read replica): host
    signatures, then either the band-candidate probe
    (`LiveClusterIndex.topk`, low-latency, recall bounded by the hub
    structure) or the exact device scan of every committed store row
    (`cluster.kernels.score.bulk_topk_store`, recall 1.0).

    Wire contract: per query exactly ``k`` slots, hits sorted by
    (-agreement count, digest hex ascending), padded with
    ``("", -1, -1)``.  The digest tiebreak makes the order
    shard-count invariant — the router merges shard answers under the
    same key and gets the unsharded daemon's answer elementwise."""
    from ..cluster.kernels.score import bulk_topk_store, store_scan_locator

    if mode not in ("candidates", "scan"):
        raise ValueError(f"unknown topk mode {mode!r}; expected "
                         "'candidates' or 'scan'")
    k = int(k)
    vectors = np.ascontiguousarray(vectors, np.uint32)
    nq = int(vectors.shape[0])
    base = {"ok": True, "generation": int(index.generation),
            "mode": mode, "k": k}
    if nq == 0 or k == 0:
        empty = [[] for _ in range(nq)]
        return {**base, "scores": [list(e) for e in empty],
                "ids": [list(e) for e in empty], "labels": empty}
    rows_in = vectors
    if srv.qbits:
        rows_in = quantize_ids(rows_in, srv.qbits)
    sigs = scheme_host_signatures(rows_in, srv._hp)
    if mode == "scan":
        counts, srows = bulk_topk_store(
            store, sigs, k, use_pallas=srv.params.use_pallas)
        flat = srows.ravel().astype(np.int64)
        ok = flat >= 0
        loc = np.full((flat.shape[0], 2), -1, np.int32)
        if ok.any():
            loc[ok] = store_scan_locator(store, flat[ok])
        labels = _labels_by_locator(index, loc, ok)
    else:
        keys = host_band_keys(sigs, srv.params.n_bands)
        counts, irows = index.topk(sigs, keys, gather, k)
        flat = irows.ravel().astype(np.int64)
        ok = flat >= 0
        loc = np.full((flat.shape[0], 2), -1, np.int32)
        labels = np.full(flat.shape[0], -1, np.int64)
        if ok.any():
            loc[ok] = index.locator[flat[ok]]
            labels[ok] = index.labels[flat[ok]].astype(np.int64)
    counts = np.ascontiguousarray(counts, np.int32).reshape(-1).copy()
    ids = np.full(flat.shape[0], "", object)
    sel = np.flatnonzero(ok)
    if sel.size:
        try:
            dg = store.load_digests(loc[sel, 0], loc[sel, 1])
        except (OSError, ValueError) as e:
            # An evicted/compacted shard raced the gather: hits degrade
            # to misses (the query path's contract), never a wrong id.
            log.warning("serve: topk digest gather degraded (%s); "
                        "dropping %d hits", e, sel.size)
            counts[sel] = -1
            labels[sel] = -1
        else:
            ids[sel] = ["%016x%016x" % (int(a), int(b)) for a, b in dg]
    counts = counts.reshape(nq, k)
    labels = labels.reshape(nq, k)
    ids = ids.reshape(nq, k)
    out_s, out_i, out_l = [], [], []
    for qi in range(nq):
        c, hx, lb = counts[qi], ids[qi], labels[qi]
        valid = sorted(np.flatnonzero(c >= 0).tolist(),
                       key=lambda j: (-int(c[j]), hx[j]))
        pad = k - len(valid)
        out_s.append([int(c[j]) for j in valid] + [-1] * pad)
        out_i.append([str(hx[j]) for j in valid] + [""] * pad)
        out_l.append([int(lb[j]) for j in valid] + [-1] * pad)
    return {**base, "scores": out_s, "ids": out_i, "labels": out_l}


class IngestRejected(RuntimeError):
    """Admission control refused the batch (backpressure)."""

    def __init__(self, depth: int, retry_after_s: float) -> None:
        super().__init__(
            f"ingest backlog at {depth} batches; retry in "
            f"~{retry_after_s:.2f}s")
        self.depth = depth
        self.retry_after_s = retry_after_s


class _Ticket:
    __slots__ = ("items", "op", "event", "result", "error", "trace",
                 "request_id")

    def __init__(self, items=None, op: str = "ingest",
                 request_id: str | None = None) -> None:
        self.items = items
        self.op = op
        self.request_id = request_id
        self.event = threading.Event()
        self.result: dict | None = None
        self.error: BaseException | None = None
        # Trace context captured at submit: the ingest thread adopts it
        # so the store append lands in the submitting client's trace.
        self.trace: dict | None = current_trace()

    def fail(self, e: BaseException) -> None:
        self.error = e
        self.event.set()

    def done(self, result: dict) -> None:
        self.result = result
        self.event.set()

    def wait(self, timeout: float | None = None) -> dict:
        if not self.event.wait(timeout):
            raise TimeoutError("ingest batch not acknowledged in time")
        if self.error is not None:
            raise self.error
        return self.result or {}


class ServeDaemon:
    """The serving plane's single-process core: one writer thread, any
    number of reader threads, one store directory.

    Thread contract: `submit`/`ingest`/`query`/`status` are safe from
    any thread; everything that WRITES (store appends, state commits,
    index swaps) happens on the one ingest thread — the same
    single-writer discipline the pod plane enforces with leases, here
    enforced by construction.

    ``signer`` picks the signature backend for content-novel rows:
    ``"device"`` (default) streams them through the degraded device
    pipeline; ``"host"`` uses the numpy mirror
    (`cluster.schemes.scheme_host_signatures` — bit-identical to the
    device kernels, CI-asserted), for device-free serving hosts and the
    graftrace schedule explorer."""

    # graftlint atomic-swap: the live index is published by ONE
    # reference swap per ingest generation; the snapshot itself is a
    # frozen dataclass (immutable-after-publish, snapshot-publish pass).
    __publish_slots__ = ("_index",)

    def __init__(self, store_dir: str,
                 params: ClusterParams | None = None,
                 slo: SloPolicy | None = None,
                 state_commit_every: int = 8,
                 signer: str = "device",
                 lease_guard=None) -> None:
        from ..cluster.store import ShardedSignatureStore

        if signer not in ("device", "host"):
            raise ValueError(f"unknown signer {signer!r}; expected "
                             "'device' or 'host'")

        if ShardedSignatureStore.is_sharded_root(store_dir):
            raise ValueError(
                f"{store_dir} is a pod-sharded store root; the serving "
                "daemon is single-host — serve one range directory, or "
                "run one daemon per range owner")
        self.params = params or ClusterParams()
        self.signer = signer
        self.slo = slo or SloPolicy.from_env()
        # Single-writer fencing for the sharded plane: when this daemon
        # serves one digest range of a pod root, the guard proves epoch
        # tenure at every durability point — a superseded (zombie)
        # writer self-fences with zero rows written.
        self.lease_guard = lease_guard
        self.state_commit_every = max(1, int(state_commit_every))
        if self.slo.live_delta_runs is not None:
            # The LSM delta-run bound is read by the index at absorb
            # time; the policy field is the serving-plane surface for it.
            import os

            os.environ["TSE1M_LIVE_DELTA_RUNS"] = str(
                int(self.slo.live_delta_runs))
        policy = self._resolve_policy(store_dir)
        self.qbits = int(policy["quant_bits"])
        # The store's scheme WINS (serving must answer in the kernel
        # family the cached signatures were computed under — a legacy
        # manifest with no scheme key is kminhash by definition), and
        # the ingest pipeline must MinHash novel rows under the same
        # scheme, so the params adopt it.
        scheme = str(policy.get("scheme", self.params.scheme))
        if scheme != self.params.scheme:
            from dataclasses import replace

            self.params = replace(self.params, scheme=scheme)
        self.store = SignatureStore(store_dir, policy)
        self.reader = SignatureStore(store_dir, policy, read_only=True)
        self._hp = make_params(self.params.scheme, self.params.n_hashes,
                               self.params.seed)
        self.rec = StageRecorder()
        self.watchdog = StageWatchdog()
        self.admission = AdmissionController(self.slo)
        self.tracker = SloTracker(self.slo)
        self.lat_query = LatencyRecorder("serve_query")
        self.lat_topk = LatencyRecorder("serve_topk")
        self.lat_ingest = LatencyRecorder("serve_ingest")
        self.last_scrub: dict = {
            "store_scrub_shards": len(self.store.shards),
            "store_scrub_corrupt": len(self.store.quarantined_at_open)}
        self._digest_parts: list[np.ndarray] = []
        self._index = LiveClusterIndex.empty(self.params.n_bands)
        self._recover()
        self._q: queue.Queue[_Ticket] = queue.Queue()
        self._stop = threading.Event()
        self._busy = False
        # In-flight absorb state for slow-request attribution: the
        # ingest thread overwrites the whole dict at each phase (one
        # GIL-atomic reference store), a slow query copies it — the
        # capture names the site (batch vs index swap) and size of the
        # work it queued behind.
        self._inflight: dict = {}
        self._last_committed_gen = self._index.generation
        self._ingest_error: BaseException | None = None
        self._thread: threading.Thread | None = None
        # The store directory is the daemon's manifest-equivalent: crash
        # dumps land next to the data they describe (an explicit
        # set_flight_dir / TSE1M_FLIGHT_DIR still wins).
        if get_flight_dir() is None:
            set_flight_dir(store_dir)

    # -- lifecycle -----------------------------------------------------------

    def _resolve_policy(self, store_dir: str) -> dict:
        """An existing store's manifest policy wins (serving must answer
        in the universe the cached signatures were computed in); a fresh
        directory takes the policy from params."""
        import json
        import os

        path = os.path.join(store_dir, "store_manifest.json")
        if os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as f:
                    return dict(json.load(f)["policy"])
            except (OSError, ValueError, KeyError) as e:
                log.warning("unreadable store manifest (%s); opening "
                            "fresh", e)
        qb = self.params.wire_quant_bits
        return _store_policy(self.params, qb if qb and qb > 0 else 0)

    def start(self) -> "ServeDaemon":
        self._thread = threading.Thread(target=self._ingest_loop,
                                        name="tse1m-serve-ingest",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, commit: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None
        if commit and self._ingest_error is None:
            # The ingest thread is dead; committing from here keeps the
            # single-writer invariant (exactly one live writer).
            self._commit_state()

    # -- recovery ------------------------------------------------------------

    def _recover(self) -> None:
        state = self.store.load_state(self.params.n_bands,
                                      self.params.threshold)
        if state is not None:
            digests = np.empty((state.n_rows, 2), np.uint64)
            loc = state.locator
            for sid in np.unique(loc[:, 0]):
                sel = np.flatnonzero(loc[:, 0] == sid)
                digests[sel] = np.asarray(
                    self.store._key_mmap(int(sid))[loc[sel, 1]])
            self._index = LiveClusterIndex.from_state(state, digests)
            self._digest_parts = [digests]
        # Absorb acked-but-uncommitted rows (append outran the state
        # commit): every store row the index does not know, in
        # deterministic (shard id, row) order.
        absorbed = 0
        for entry in sorted(self.store.shards, key=lambda e: int(e["id"])):
            sid = int(entry["id"])
            keys = np.asarray(self.store._key_mmap(sid))
            for lo in range(0, keys.shape[0], _RECOVER_CHUNK):
                d = keys[lo:lo + _RECOVER_CHUNK]
                hit, _ = self._index.lookup_digests(d)
                fresh = np.flatnonzero(~hit)
                if fresh.size == 0:
                    continue
                sigs = np.asarray(
                    self.store._sig_mmap(sid)[lo + fresh])
                locator = np.stack(
                    [np.full(fresh.size, sid, np.int32),
                     (lo + fresh).astype(np.int32)], axis=1)
                self._absorb(d[fresh], sigs, locator)
                absorbed += int(fresh.size)
        if absorbed:
            log.warning("serve: recovered %d acked row(s) the persisted "
                        "state did not cover (crash between append and "
                        "state commit)", absorbed)
        self._inflight = {}

    # -- index mutation (ingest thread only) ---------------------------------

    def _gather_writer_sigs(self, index: LiveClusterIndex,
                            uniq: np.ndarray) -> np.ndarray:
        loc = index.locator[uniq]
        try:
            return self.store.load_signatures(loc[:, 0], loc[:, 1])
        except (OSError, ValueError):
            # LRU eviction raced an old locator: degrade per shard — a
            # hub whose signature is gone gets a sentinel that can never
            # reach the agreement threshold, so the candidate edge drops
            # and the new row recomputes its own cluster (exactly the
            # miss-and-recompute semantics eviction already means).
            h = self.params.n_hashes
            out = np.full((int(uniq.size), h), 0xFFFFFFFF, np.uint32)
            lost = 0
            for sid in np.unique(loc[:, 0]):
                sel = np.flatnonzero(loc[:, 0] == sid)
                try:
                    out[sel] = self.store.load_signatures(loc[sel, 0],
                                                          loc[sel, 1])
                except (OSError, ValueError):
                    lost += int(sel.size)
            record_degradation(
                "serve_evicted_gather", site="serve.ingest",
                detail={"rows": lost})
            log.warning("serve: %d hub signature(s) evicted from the "
                        "store; their candidate edges drop and the new "
                        "rows recompute", lost)
            return out

    def _absorb(self, digests: np.ndarray, sigs: np.ndarray,
                locator: np.ndarray) -> None:
        self._inflight = {"site": "serve.index.swap",
                          "rows": int(digests.shape[0]),
                          "since_s": round(deadline_clock(), 3)}
        index = self._index
        keys = host_band_keys(sigs, self.params.n_bands)
        new_index = index.absorb(
            keys, sigs, lambda u: self._gather_writer_sigs(index, u),
            self.params.n_hashes, self.params.threshold,
            new_locator=locator, new_digests=digests)
        self._digest_parts.append(
            np.ascontiguousarray(digests, np.uint64))
        # THE publication point: one reference swap; concurrent queries
        # keep whichever snapshot they already grabbed.
        trace_point("serve.index.swap")
        shared_access(self, "_index", write=True, atomic=True)
        self._index = new_index
        obs_metrics.gauge("serve_store_generation").set(
            self.store.generation)
        obs_metrics.gauge("serve_store_rows").set(self.store.n_rows)

    def _all_digests(self) -> np.ndarray:
        if len(self._digest_parts) > 1:
            self._digest_parts = [np.concatenate(self._digest_parts)]
        return (self._digest_parts[0] if self._digest_parts
                else np.empty((0, 2), np.uint64))

    def _commit_state(self) -> None:
        trace_point("serve.state.commit")
        index = self._index
        if index.n_rows == 0:
            return
        if self.lease_guard is not None:
            self.lease_guard.verify()
        self.store.save_state(
            index.labels, index.locator,
            index.band_tables(),
            self._all_digests(), self.params.n_bands,
            self.params.threshold)
        self._last_committed_gen = index.generation

    # -- ingest --------------------------------------------------------------

    def submit(self, items: np.ndarray,
               request_id: str | None = None) -> _Ticket:
        """Admission-checked enqueue; raises IngestRejected under
        backpressure.  The returned ticket's ``wait()`` blocks until the
        batch is durably acknowledged (store append committed).

        ``request_id`` makes the batch idempotent: a retry carrying the
        id of an ingest that already committed replays the original ack
        (journal consult in ``_ingest_batch``) instead of re-absorbing."""
        if self._ingest_error is not None:
            raise RuntimeError("serve ingest loop is down") \
                from self._ingest_error
        depth = self._q.qsize()
        obs_metrics.gauge("serve_queue_depth").set(depth)
        admitted, retry_after = self.admission.try_admit(depth)
        if not admitted:
            raise IngestRejected(depth, retry_after)
        t = _Ticket(np.ascontiguousarray(items, np.uint32),
                    request_id=request_id)
        trace_point("serve.queue.put")
        self._q.put(t)
        return t

    def ingest(self, items: np.ndarray,
               timeout: float | None = None,
               request_id: str | None = None) -> dict:
        return self.submit(items, request_id=request_id).wait(timeout)

    def _ingest_loop(self) -> None:
        while not self._stop.is_set():
            try:
                trace_point("serve.queue.get")
                t = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            self._busy = True
            try:
                if t.op == _CONTROL_COMMIT:
                    self._commit_state()
                    t.done({"ok": True,
                            "generation": self._index.generation})
                else:
                    with continue_trace(t.trace):
                        with span("serve.ingest.batch",
                                  rows=int(t.items.shape[0])):
                            ti = deadline_clock()
                            with self.lat_ingest.time():
                                t.done(self._ingest_batch(
                                    t.items, request_id=t.request_id))
                            wall_i = deadline_clock() - ti
                            if wall_i > self.slo.ingest_budget_s > 0:
                                profiling.capture_slow_request(
                                    "ingest", wall_i,
                                    self.slo.ingest_budget_s * 1e3,
                                    t0=ti, absorb=self._inflight,
                                    rows=int(t.items.shape[0]))
                    gen = self._index.generation
                    if (gen - self._last_committed_gen
                            >= self.state_commit_every):
                        self._commit_state()
            except BaseException as e:  # noqa: BLE001 — fail the ticket, then fault-transparent re-raise below
                t.fail(e)
                try:
                    reraise_if_fault(e)
                except BaseException:
                    self._ingest_error = e
                    dump_flight("serve.ingest_crash", site="serve.ingest",
                                extra={"error": f"{type(e).__name__}: {e}"})
                    raise
                if isinstance(e, LeaseSupersededError):
                    # Self-fence: this writer's digest range was re-dealt
                    # (the verify fired BEFORE the append, so zero rows
                    # were written).  Latch the error — further submits
                    # are refused — but keep the thread alive so the
                    # read-only query path drains gracefully.
                    self._ingest_error = e
                    log.error("serve: shard writer fenced (%s); ingest "
                              "refused from here on", e)
                    continue
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    self._ingest_error = e
                    dump_flight("serve.ingest_exit", site="serve.ingest",
                                extra={"error": type(e).__name__})
                    raise
                log.error("serve: ingest batch failed (%s: %s); daemon "
                          "continues", type(e).__name__, e)
            finally:
                self._busy = False
                self._inflight = {}

    def _replay_ack(self, request_id: str, items: np.ndarray) -> dict:
        """The idempotent-retry answer: this request id already committed
        (its journal entry rode the append's manifest write), so the
        rows are in the index — answer from there instead of absorbing a
        second copy.  Row ids come from the digest map (for a batch that
        crossed a writer restart they are the surviving first-occurrence
        rows, which min-merge correctly router-side)."""
        entry = self.store.serve_journal[request_id]
        index = self._index
        digests = row_digests(items)
        hit, row = index.lookup_digests(digests)
        labels = np.full(int(items.shape[0]), -1, np.int64)
        labels[hit] = index.labels[row[hit]].astype(np.int64)
        record_degradation(
            "serve_ingest_replayed", site="serve.ingest",
            detail={"request_id": request_id,
                    "acked": int(entry.get("acked", 0))})
        return {"ok": True, "acked": int(entry.get("acked", 0)),
                "novel": int(entry.get("novel", 0)),
                "generation": index.generation,
                "labels": labels.astype(int).tolist(),
                "rows": row.astype(int).tolist(),
                "replayed": True}

    def _ingest_batch(self, items: np.ndarray,
                      request_id: str | None = None) -> dict:
        """One acknowledged batch: EVERY row becomes a new index row (the
        batch pipeline's label space keeps content-duplicate sessions as
        distinct rows, and post-quiesce parity is elementwise against
        it), while the STORE stays content-addressed — cached contents
        gather their signature, only the content-novel tail touches the
        device."""
        k = int(items.shape[0])
        self._inflight = {"site": "serve.ingest.batch", "rows": k,
                          "since_s": round(deadline_clock(), 3)}
        if request_id is not None and request_id in self.store.serve_journal:
            return self._replay_ack(request_id, items)
        index = self._index
        n_old = index.n_rows
        if k == 0:
            return {"ok": True, "acked": 0, "novel": 0,
                    "generation": index.generation,
                    "labels": [], "rows": []}
        digests = row_digests(items)
        h = self.params.n_hashes
        sigs = np.empty((k, h), np.uint32)
        s_hit, sh, rw = self.store.bulk_probe(digests)
        if s_hit.any():
            sigs[s_hit] = self.store.load_signatures(sh[s_hit], rw[s_hit])
        miss = ~s_hit
        novel = int(miss.sum())
        if novel:
            sigs[miss] = self._sign_novel(items[miss])
        # Durability point: the ack below is only sent once this commit
        # (tmp+rename shard + manifest) has happened — a SIGKILL anywhere
        # after it loses zero acknowledged rows.
        fault_point("serve.ingest.commit")
        if self.lease_guard is not None:
            # Fence point: tenure is proven AFTER the durability seat and
            # BEFORE the append — a superseded writer raises here with
            # zero rows written to the re-dealt range.
            self.lease_guard.verify()
        if request_id is not None:
            # Staged under the id so the append's manifest write commits
            # the ack atomically with the rows it acknowledges.
            self.store.journal_record(request_id,
                                      {"acked": k, "novel": novel})
        self.store.append(digests[miss], sigs[miss])
        _, sh2, rw2 = self.store.bulk_probe(digests)
        locator = np.stack([sh2, rw2], axis=1).astype(np.int32)
        # Refresh the query-side reader BEFORE publishing the new index
        # generation, so no published locator ever outruns the reader's
        # view of the store.
        self.reader.refresh()
        self._absorb(digests, sigs, locator)
        new_index = self._index
        gr = n_old + np.arange(k, dtype=np.int64)
        return {"ok": True, "acked": k, "novel": novel,
                "generation": new_index.generation,
                "labels": new_index.labels[gr].astype(int).tolist(),
                "rows": gr.tolist()}

    def _sign_novel(self, rows: np.ndarray) -> np.ndarray:
        """[K, S] raw rows -> [K, H] uint32 signatures under the store
        policy, via the configured backend (see class docstring)."""
        if self.signer == "host":
            sub = quantize_ids(rows, self.qbits) if self.qbits else rows
            return scheme_host_signatures(sub, self._hp)
        return minhash_novel_rows(rows, self.params, self.qbits,
                                  rec=self.rec, wd=self.watchdog)

    # -- queries (any thread) ------------------------------------------------

    def _gather_reader_sigs(self, index: LiveClusterIndex,
                            uniq: np.ndarray) -> np.ndarray | None:
        loc = index.locator[uniq]
        try:
            return self.reader.load_signatures(loc[:, 0], loc[:, 1])
        except (OSError, ValueError) as e:
            # An evicted/compacted shard raced this gather: candidates
            # degrade to misses (the vector reads as novel), never a
            # wrong label.
            log.warning("serve: query gather degraded (%s); treating "
                        "candidates as misses", e)
            return None

    def query(self, vectors: np.ndarray) -> dict:
        """Cluster membership for [K, S] uint32 coverage vectors.

        Host-only hot path: known vectors (content digest already
        ingested) answer straight from the snapshot's label array; novel
        vectors are MinHashed on host (bit-identical to the device
        kernel), probed against the snapshot's band tables and verified
        with the exact signature-agreement rule.  Label -1 means "a new
        singleton cluster"."""
        t0 = deadline_clock()
        vectors = np.ascontiguousarray(vectors, np.uint32)
        shared_access(self, "_index", write=False, atomic=True)
        index = self._index  # ONE snapshot reference for the whole query
        n = int(vectors.shape[0])
        digests = row_digests(vectors)
        hit, row = index.lookup_digests(digests)
        out = np.full(n, -1, np.int64)
        if hit.any():
            out[hit] = index.labels[row[hit]].astype(np.int64)
        miss = np.flatnonzero(~hit)
        if miss.size:
            rows = vectors[miss]
            if self.qbits:
                rows = quantize_ids(rows, self.qbits)
            sigs = scheme_host_signatures(rows, self._hp)
            keys = host_band_keys(sigs, self.params.n_bands)
            out[miss] = index.query_labels(
                sigs, keys, lambda u: self._gather_reader_sigs(index, u),
                self.params.n_hashes, self.params.threshold)
        wall = deadline_clock() - t0
        self.lat_query.add(wall)
        self.tracker.observe_query(wall)
        if wall * 1e3 > self.slo.query_p99_target_ms:
            # SLO violation: freeze the evidence while it is still warm
            # — the ingest thread's in-flight absorb state (copied: it
            # may finish mid-capture), this thread's recent lock waits
            # and the sampler window all point at the convoy.
            profiling.capture_slow_request(
                "query", wall, self.slo.query_p99_target_ms, t0=t0,
                absorb=self._inflight if self._busy else None,
                rows=n, generation=int(index.generation))
        return {"labels": out, "known": hit,
                "generation": index.generation}

    def topk(self, vectors: np.ndarray, k: int = 10,
             mode: str = "candidates") -> dict:
        """The k nearest stored sessions per [K, S] coverage vector, by
        exact signature agreement.  ``mode="candidates"`` probes the
        snapshot's band tables (low latency; recall bounded by the hub
        structure), ``mode="scan"`` device-scans every committed store
        row (exact, recall 1.0 — the backfill/re-label path).  Answers
        in content digests + cluster labels; see `_topk_answer` for the
        wire contract."""
        t0 = deadline_clock()
        vectors = np.ascontiguousarray(vectors, np.uint32)
        shared_access(self, "_index", write=False, atomic=True)
        index = self._index  # ONE snapshot reference for the whole call
        res = _topk_answer(self, index, self.reader,
                           lambda u: self._gather_reader_sigs(index, u),
                           vectors, k, mode)
        wall = deadline_clock() - t0
        self.lat_topk.add(wall)
        if mode == "candidates" and (wall * 1e3
                                     > self.slo.query_p99_target_ms):
            # Scan mode is a bulk job — only the interactive candidate
            # path is held to the query SLO budget.
            profiling.capture_slow_request(
                "topk", wall, self.slo.query_p99_target_ms, t0=t0,
                absorb=self._inflight if self._busy else None,
                rows=int(vectors.shape[0]),
                generation=int(index.generation))
        return res

    # -- control -------------------------------------------------------------

    def quiesce(self, timeout: float | None = None) -> dict:
        """Drain the ingest queue and commit the LSH state; returns the
        commit acknowledgement.  After quiesce, a cold batch run over
        the same session set reproduces the index labels elementwise."""
        t = _Ticket(op=_CONTROL_COMMIT)
        self._q.put(t)
        return t.wait(timeout)

    def status(self) -> dict:
        index = self._index
        return {
            "ok": self._ingest_error is None,
            "rows": int(index.n_rows),
            "generation": int(index.generation),
            "store_generation": int(self.store.generation),
            "store_rows": int(self.store.n_rows),
            "queue_depth": int(self._q.qsize()),
            # Registry-backed history, not a point-in-time read: a
            # backpressure episode that drained before this status call
            # still shows in the high-water mark and rejection counter.
            "queue_depth_hwm": int(obs_metrics.gauge(
                "serve_ingest_backlog_max").value),
            "ingest_rejected_total": int(obs_metrics.counter(
                "serve_ingest_rejected_total").value),
            "uncommitted_generations": int(index.generation
                                           - self._last_committed_gen),
            # graftprof: slow-request tally + the three worst lock-wait
            # sites (empty until the lock-wait recorder is enabled).
            "slow_requests_total": profiling.slow_requests_total(),
            "lock_wait_top": profiling.lock_wait_summary(top=3),
            "last_scrub": dict(self.last_scrub),
            "policy": dict(self.store.policy),
            # The LSM consolidation bound actually in effect (SloPolicy
            # live_delta_runs / TSE1M_LIVE_DELTA_RUNS): the p99 tuning
            # knob the pre-split measurement round surfaces.
            "live_delta_runs": _delta_max_runs(),
            **self.admission.stats(),
            **self.tracker.stats(),
            **self.lat_query.summary(),
            **self.lat_topk.summary(),
            **self.lat_ingest.summary(),
            # Per-verb breakdown (query vs topk vs ingest): one blended
            # histogram hides a slow verb behind a fast one.
            "latency_by_verb": {
                "query": self.lat_query.snapshot(),
                "topk": self.lat_topk.snapshot(),
                "ingest": self.lat_ingest.snapshot(),
            },
        }


__all__ = ["IngestRejected", "ServeDaemon"]
