"""Shard-streaming replication: read replicas for the serve plane.

A replica is a host that answers `query` from a streamed copy of one
shard writer's store directory and never joins the write plane: its
store handle is ``read_only=True`` (the same exclusion the pod plane
uses for non-owned digest ranges), so a replica cannot append, commit
state, or stamp manifests — graftlint's lease-fence/serve-write-plane
passes hold that by construction.

The protocol is a file copy over artifacts that are already safe to
copy: committed shards are immutable and CRC-framed, the LSH state npz
carries its own frame, and the manifest names exactly which files a
generation consists of.  One pull (:func:`stream_shards`):

1. read the writer's committed manifest,
2. copy every shard file the replica does not already hold, verifying
   each against the manifest's CRC (a torn copy — or the writer
   evicting mid-read — fails the frame and the pull retries),
3. copy the current LSH state blob + pointer the same way,
4. commit the manifest LAST, atomically — the replica's
   ``refresh()`` adopts the new generation only once every file it
   references is in place.

Staleness is bounded and observable: the replica serves the writer's
generation as of its last completed pull, and
:func:`replica_staleness` reports the generation gap (writer manifest
generation minus replica generation) — the number the bench's
``serve_replica_qps`` round asserts against.
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np

from ..cluster.host import host_band_keys
from ..cluster.incremental import LiveClusterIndex
from ..cluster.pipeline import ClusterParams, _store_policy
from ..cluster.schemes import make_params, scheme_host_signatures
from ..cluster.encode import quantize_ids
from ..cluster.store import SignatureStore, file_crc, row_digests
from ..observability import metrics as obs_metrics
from ..observability.latency import LatencyRecorder
from ..resilience import fault_point
from ..resilience.watchdog import deadline_clock
from ..trace.hooks import shared_access, trace_point
from ..utils.atomic import atomic_write
from ..utils.logging import get_logger

log = get_logger("serve.replicate")

# graftspec binding: fault seats here must be modeled by these specs.
SPEC_MODELS = ("replica",)

_MANIFEST = "store_manifest.json"
_STATE = "state.json"
_RECOVER_CHUNK = 65536


def _copy_framed(src_path: str, dst_path: str,
                 want_crc: int | None) -> int:
    """Copy one committed artifact, verifying the copy against the
    frame its manifest promises.  Returns bytes copied (0 = the replica
    already holds a frame-identical file)."""
    if want_crc is not None and os.path.exists(dst_path):
        try:
            if int(file_crc(dst_path)) == int(want_crc):
                return 0  # immutable once committed: nothing to re-pull
        except OSError:
            pass
    tmp = dst_path + ".tmp.npy"
    shutil.copyfile(src_path, tmp)
    if want_crc is not None and int(file_crc(tmp)) != int(want_crc):
        os.remove(tmp)
        raise OSError(
            f"streamed copy of {os.path.basename(src_path)} failed its "
            "CRC frame (torn read under the writer)")
    os.replace(tmp, dst_path)
    return os.path.getsize(dst_path)


def _stream_once(src: str, dst: str) -> dict:
    manifest = None
    mpath = os.path.join(src, _MANIFEST)
    try:
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return {"generation": 0, "shards_copied": 0, "state_copied": False,
                "bytes_copied": 0}
    shards_copied = 0
    bytes_copied = 0
    for entry in manifest.get("shards", []):
        sid = int(entry["id"])
        copied = 0
        for crc_key, name in (("sig_crc", f"sig_{sid:05d}.npy"),
                              ("key_crc", f"key_{sid:05d}.npy")):
            copied += _copy_framed(os.path.join(src, name),
                                   os.path.join(dst, name),
                                   entry.get(crc_key))
        if copied:
            shards_copied += 1
            bytes_copied += copied
    state_copied = False
    smeta = None
    try:
        with open(os.path.join(src, _STATE), encoding="utf-8") as f:
            smeta = json.load(f)
    except (OSError, ValueError):
        smeta = None
    if smeta and smeta.get("file"):
        bytes_copied += _copy_framed(
            os.path.join(src, str(smeta["file"])),
            os.path.join(dst, str(smeta["file"])), smeta.get("crc"))
        with atomic_write(os.path.join(dst, _STATE)) as f:
            json.dump(smeta, f)
        state_copied = True
    # The adoption point: every file the manifest references is in
    # place; committing it publishes the generation to the replica's
    # refresh().  A kill before this line leaves the replica serving
    # the previous generation with some pre-staged (orphan) files the
    # next pull CRC-skips — never a torn view.
    manifest.pop("serve_journal", None)  # write-plane state stays behind
    fault_point("serve.replica.stream", path=os.path.join(dst, _MANIFEST))
    with atomic_write(os.path.join(dst, _MANIFEST)) as f:
        json.dump(manifest, f)
    return {"generation": int(manifest.get("generation", 0)),
            "shards_copied": shards_copied, "state_copied": state_copied,
            "bytes_copied": bytes_copied}


def stream_shards(src: str, dst: str, max_attempts: int = 3) -> dict:
    """One replication pull from a writer's store directory into the
    replica's (see module docstring).  Retries a bounded number of
    times when the writer's eviction/compaction races the copy — the
    same vanished-file idiom the store's own ``refresh()`` uses."""
    os.makedirs(dst, exist_ok=True)
    trace_point("serve.replica.stream")
    for attempt in range(max_attempts):
        try:
            out = _stream_once(src, dst)
            obs_metrics.counter("serve_replica_pulls_total").inc()
            return out
        except OSError as e:
            if attempt == max_attempts - 1:
                raise
            log.warning("replica pull raced the writer (%s); retrying "
                        "from the manifest", e)
    raise AssertionError("unreachable")  # pragma: no cover


def replica_staleness(src: str, replica: "ServeReplica") -> int:
    """Writer generations the replica has not adopted yet (0 = fresh).
    Reads the writer's committed manifest; absent/torn reads as the
    replica's own generation (staleness unknown -> 0, never negative)."""
    try:
        with open(os.path.join(src, _MANIFEST), encoding="utf-8") as f:
            gen = int(json.load(f).get("generation", 0))
    except (OSError, ValueError):
        return 0
    return max(0, gen - int(replica.store.generation))


class ServeReplica:
    """Read-only query plane over a streamed store copy.

    Duck-typed to the verbs `ServeServer` dispatches — ``query``,
    ``topk``, ``status``, ``ping`` state via ``_index`` — so a replica serves the
    same TCP protocol as a writer daemon; the write-plane verbs
    (``ingest``/``quiesce``) refuse with a structured error.  The index
    is rebuilt from the streamed LSH state + store rows at each
    ``refresh`` adoption and published by ONE reference swap, exactly
    the writer daemon's snapshot discipline."""

    # graftlint atomic-swap / snapshot-publish: one reference swap per
    # adopted generation.
    __publish_slots__ = ("_index",)

    def __init__(self, directory: str,
                 params: ClusterParams | None = None) -> None:
        self.params = params or ClusterParams()
        self.directory = directory
        policy = self._resolve_policy(directory)
        self.qbits = int(policy["quant_bits"])
        # The streamed store's policy WINS wholesale (scheme, hash
        # count, seed): a replica must answer in the signature universe
        # the writer's cached rows were computed under.
        adopt = {"scheme": str(policy.get("scheme", self.params.scheme)),
                 "n_hashes": int(policy.get("n_hashes",
                                            self.params.n_hashes)),
                 "seed": int(policy.get("seed", self.params.seed))}
        if any(getattr(self.params, f) != v for f, v in adopt.items()):
            from dataclasses import replace

            self.params = replace(self.params, **adopt)
        self.store = SignatureStore(directory, policy, read_only=True)
        self._hp = make_params(self.params.scheme, self.params.n_hashes,
                               self.params.seed)
        self.read_only = True
        self.lat_query = LatencyRecorder("serve_replica_query")
        self.lat_topk = LatencyRecorder("serve_replica_topk")
        self._index = LiveClusterIndex.empty(self.params.n_bands)
        self._generation_adopted = -1
        self._rebuild()

    def _resolve_policy(self, directory: str) -> dict:
        path = os.path.join(directory, _MANIFEST)
        try:
            with open(path, encoding="utf-8") as f:
                return dict(json.load(f)["policy"])
        except (OSError, ValueError, KeyError):
            qb = self.params.wire_quant_bits
            return _store_policy(self.params, qb if qb and qb > 0 else 0)

    # -- adoption ------------------------------------------------------------

    def _rebuild(self) -> None:
        """Adopt the store's current generation: streamed LSH state
        first (row identity matches the writer exactly for every state-
        covered row), then absorb any store rows the state does not
        cover, in deterministic (shard, row) order — the writer
        daemon's own recovery discipline."""
        index = LiveClusterIndex.empty(self.params.n_bands)
        state = self.store.load_state(self.params.n_bands,
                                      self.params.threshold)
        if state is not None:
            digests = np.empty((state.n_rows, 2), np.uint64)
            loc = state.locator
            for sid in np.unique(loc[:, 0]):
                sel = np.flatnonzero(loc[:, 0] == sid)
                digests[sel] = np.asarray(
                    self.store._key_mmap(int(sid))[loc[sel, 1]])
            index = LiveClusterIndex.from_state(state, digests)
        for entry in sorted(self.store.shards, key=lambda e: int(e["id"])):
            sid = int(entry["id"])
            keys = np.asarray(self.store._key_mmap(sid))
            for lo in range(0, keys.shape[0], _RECOVER_CHUNK):
                d = keys[lo:lo + _RECOVER_CHUNK]
                hit, _ = index.lookup_digests(d)
                fresh = np.flatnonzero(~hit)
                if fresh.size == 0:
                    continue
                sigs = np.asarray(self.store._sig_mmap(sid)[lo + fresh])
                keys_b = host_band_keys(sigs, self.params.n_bands)
                locator = np.stack(
                    [np.full(fresh.size, sid, np.int32),
                     (lo + fresh).astype(np.int32)], axis=1)
                index = index.absorb(
                    keys_b, sigs,
                    lambda u, _ix=index: self._gather(_ix, u),
                    self.params.n_hashes, self.params.threshold,
                    new_locator=locator, new_digests=d[fresh])
        # THE publication point (one swap; concurrent queries keep the
        # snapshot they already grabbed).
        trace_point("serve.replica.adopt")
        shared_access(self, "_index", write=True, atomic=True)
        self._index = index
        self._generation_adopted = int(self.store.generation)
        obs_metrics.gauge("serve_replica_generation").set(
            self.store.generation)

    def refresh(self) -> bool:
        """Adopt a newer streamed generation (the ONLY way replica
        state advances — graftlint serve-write-plane).  Returns True
        when the served view changed."""
        trace_point("serve.replica.refresh")
        changed = self.store.refresh()
        if changed or int(self.store.generation) != self._generation_adopted:
            self._rebuild()
            return True
        return False

    # -- queries (any thread) ------------------------------------------------

    def _gather(self, index: LiveClusterIndex,
                uniq: np.ndarray) -> np.ndarray | None:
        loc = index.locator[uniq]
        try:
            return self.store.load_signatures(loc[:, 0], loc[:, 1])
        except (OSError, ValueError) as e:
            log.warning("replica: gather degraded (%s); candidates read "
                        "as misses", e)
            return None

    def query(self, vectors: np.ndarray) -> dict:
        """Same contract as `ServeDaemon.query`, over the last adopted
        generation (stale-bounded: at most the pull interval behind the
        writer)."""
        t0 = deadline_clock()
        vectors = np.ascontiguousarray(vectors, np.uint32)
        shared_access(self, "_index", write=False, atomic=True)
        index = self._index
        n = int(vectors.shape[0])
        digests = row_digests(vectors)
        hit, row = index.lookup_digests(digests)
        out = np.full(n, -1, np.int64)
        if hit.any():
            out[hit] = index.labels[row[hit]].astype(np.int64)
        miss = np.flatnonzero(~hit)
        if miss.size:
            rows = vectors[miss]
            if self.qbits:
                rows = quantize_ids(rows, self.qbits)
            sigs = scheme_host_signatures(rows, self._hp)
            keys = host_band_keys(sigs, self.params.n_bands)
            out[miss] = index.query_labels(
                sigs, keys, lambda u: self._gather(index, u),
                self.params.n_hashes, self.params.threshold)
        self.lat_query.add(deadline_clock() - t0)
        return {"labels": out, "known": hit,
                "generation": index.generation}

    def topk(self, vectors: np.ndarray, k: int = 10,
             mode: str = "candidates") -> dict:
        """Same contract as `ServeDaemon.topk` (read plane: both the
        candidate probe and the exact scan are reads over the adopted
        snapshot + streamed store copy)."""
        from .daemon import _topk_answer

        t0 = deadline_clock()
        vectors = np.ascontiguousarray(vectors, np.uint32)
        shared_access(self, "_index", write=False, atomic=True)
        index = self._index
        res = _topk_answer(self, index, self.store,
                           lambda u: self._gather(index, u),
                           vectors, k, mode)
        self.lat_topk.add(deadline_clock() - t0)
        return res

    # -- write-plane verbs refuse --------------------------------------------

    def ingest(self, items, timeout=None, request_id=None) -> dict:
        raise RuntimeError(
            "this host is a read replica (read_only=True); ingest "
            "belongs to the range's single writer")

    def quiesce(self, timeout=None) -> dict:
        raise RuntimeError("read replica: no write-plane state to commit")

    def status(self) -> dict:
        index = self._index
        return {"ok": True, "read_only": True,
                "rows": int(index.n_rows),
                "generation": int(index.generation),
                "store_generation": int(self.store.generation),
                "store_rows": int(self.store.n_rows),
                "generation_adopted": int(self._generation_adopted),
                **self.lat_query.summary(),
                **self.lat_topk.summary(),
                "latency_by_verb": {
                    "query": self.lat_query.snapshot(),
                    "topk": self.lat_topk.snapshot(),
                }}


class ReplicationPuller:
    """Periodic pull + adopt from a daemon thread: the replica-side
    driver that keeps staleness bounded by ``interval_s``."""

    def __init__(self, src: str, replica: ServeReplica,
                 interval_s: float = 1.0) -> None:
        import threading

        self.src = src
        self.replica = replica
        self.interval_s = float(interval_s)
        self.pulls = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def pull_once(self) -> bool:
        stream_shards(self.src, self.replica.store.directory)
        changed = self.replica.refresh()
        self.pulls += 1
        return changed

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.pull_once()
            except OSError as e:
                log.warning("replica pull failed (%s); retrying next "
                            "interval", e)
            self._stop.wait(self.interval_s)

    def start(self) -> "ReplicationPuller":
        import threading

        if self._thread is None:
            t = threading.Thread(target=self._run, daemon=True,
                                 name="tse1m-serve-replica-pull")
            self._thread = t
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None


__all__ = ["ReplicationPuller", "ServeReplica", "replica_staleness",
           "stream_shards"]
