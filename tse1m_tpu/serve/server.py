"""JSON-over-TCP front end for the serving daemon.

Deliberately tiny: a 4-byte big-endian length prefix followed by one
UTF-8 JSON object per direction, stdlib only (this container has no web
framework, and the protocol is trivially testable).  Vectors travel
either as JSON lists (interactive/CLI use) or as base64 raw
little-endian uint32 with an explicit shape (``vectors_b64``/``shape``
— the bulk path bench and chaos drivers use).

Request classes map to watchdog budgets
(`resilience.watchdog.request_budget_s`): ingest and control requests
run under `run_with_deadline` (a reaper thread cancels a wedged batch
and the client gets a structured error instead of a hang); the query
class is latency-bounded client-side (socket timeout = the query
budget) and SLO-tracked server-side — a per-query reaper thread would
cost more than the 50 ms p99 it protects.

Request handlers are fault-transparent (graftlint ``broad-except``):
errors become structured ``{"ok": false, "error": ...}`` responses, but
an injected fault (`resilience.InjectedFault`) re-raises through the
handler so chaos runs see the real failure mode, never a cosmetic
error string.
"""

from __future__ import annotations

import base64
import json
import socket
import socketserver
import struct
import threading

import numpy as np

from ..observability import profiling
from ..observability.export import flat_metrics, prometheus_text
from ..observability.tracing import (continue_trace, recent_spans, span,
                                     spans_recorded)
from ..resilience import reraise_if_fault
from ..resilience.watchdog import request_budget_s, run_with_deadline
from ..utils.logging import get_logger
from .daemon import IngestRejected, ServeDaemon

log = get_logger("serve.server")

_LEN = struct.Struct(">I")
_MAX_MSG = 1 << 30


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


def read_msg(sock: socket.socket) -> dict:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_MSG:
        raise ValueError(f"message of {n} bytes exceeds the 1 GiB bound")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


def write_msg(sock: socket.socket, obj: dict) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def decode_vectors(msg: dict) -> np.ndarray:
    if "vectors_b64" in msg:
        k, s = (int(x) for x in msg["shape"])
        raw = base64.b64decode(msg["vectors_b64"])
        if len(raw) != k * s * 4:
            raise ValueError(f"vectors_b64 carries {len(raw)} bytes; "
                             f"shape {(k, s)} needs {k * s * 4}")
        return np.frombuffer(raw, dtype="<u4").reshape(k, s)
    return np.asarray(msg.get("vectors", []), dtype=np.uint32)


def encode_vectors(vectors: np.ndarray) -> dict:
    v = np.ascontiguousarray(vectors, dtype="<u4")
    return {"vectors_b64": base64.b64encode(v.tobytes()).decode("ascii"),
            "shape": list(v.shape)}


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: ServeServer = self.server  # type: ignore[assignment]
        try:
            while True:
                try:
                    msg = read_msg(self.request)
                except (ConnectionError, struct.error):
                    return  # client went away between requests
                resp = server.dispatch(msg)
                write_msg(self.request, resp)
                if msg.get("op") == "shutdown":
                    return
        except Exception as e:
            reraise_if_fault(e)
            log.warning("serve: connection handler failed (%s: %s)",
                        type(e).__name__, e)


class ServeServer(socketserver.ThreadingTCPServer):
    """One daemon, many concurrent client connections (thread per
    connection; requests on one connection are processed in order)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, daemon: ServeDaemon,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__((host, port), _Handler)
        self.daemon = daemon
        self._shutdown_requested = threading.Event()

    @property
    def port(self) -> int:
        return int(self.server_address[1])

    def dispatch(self, msg: dict) -> dict:
        """Route one request.  The envelope's ``trace`` key (stamped by
        ``ServeClient``) is adopted before the per-op span opens, so the
        daemon-side work lands in the caller's trace; the trace id is
        echoed on the response so the client can correlate without a
        collector."""
        op = str(msg.get("op", ""))
        ctx = msg.pop("trace", None)
        try:
            with continue_trace(ctx):
                with span(f"serve.{op}"):
                    resp = self._dispatch_op(op, msg)
        except IngestRejected as e:
            resp = {"ok": False, "error": "backpressure",
                    "retry_after_s": round(e.retry_after_s, 3),
                    "depth": e.depth}
        except Exception as e:
            reraise_if_fault(e)
            log.error("serve: %s request failed (%s: %s)", op,
                      type(e).__name__, e)
            resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        if ctx and isinstance(ctx, dict) and ctx.get("t"):
            resp.setdefault("trace", str(ctx["t"]))
        return resp

    def _dispatch_op(self, op: str, msg: dict) -> dict:
        if op == "ping":
            return {"ok": True, "op": "ping",
                    "generation": self.daemon._index.generation,
                    "rows": self.daemon._index.n_rows}
        if op == "status":
            return {"ok": True, **self._guarded(
                "status", self.daemon.status)}
        if op == "query":
            vectors = decode_vectors(msg)
            res = self.daemon.query(vectors)
            return {"ok": True,
                    "labels": res["labels"].astype(int).tolist(),
                    "known": res["known"].astype(bool).tolist(),
                    "generation": int(res["generation"])}
        if op == "topk":
            vectors = decode_vectors(msg)
            return self.daemon.topk(vectors,
                                    k=int(msg.get("k", 10)),
                                    mode=str(msg.get("mode",
                                                     "candidates")))
        if op == "ingest":
            vectors = decode_vectors(msg)
            rid = msg.get("request_id")
            return self._guarded(
                "ingest", lambda: self.daemon.ingest(
                    vectors, timeout=request_budget_s("ingest") or None,
                    request_id=str(rid) if rid else None))
        if op == "quiesce":
            return self._guarded(
                "ingest", lambda: self.daemon.quiesce(
                    timeout=request_budget_s("ingest") or None))
        if op == "metrics":
            # Live registry pull (the Prometheus shape plus the flat
            # bench-JSON aggregation) — the telemetry-plane analogue of
            # `status`, queryable mid-run without touching the daemon.
            return {"ok": True, "prometheus": prometheus_text(),
                    "metrics": flat_metrics()}
        if op == "trace":
            n = msg.get("n")
            return {"ok": True,
                    "spans": recent_spans(int(n) if n else None),
                    "spans_recorded": spans_recorded()}
        if op == "slowlog":
            # SLO-violation captures (span chain + sampler window +
            # in-flight absorb state), newest last.
            n = msg.get("n")
            return {"ok": True,
                    "slow_requests": profiling.recent_slow_requests(
                        int(n) if n else None),
                    "slow_requests_total":
                        profiling.slow_requests_total()}
        if op == "profile":
            # Live profiler summary; ``dump: true`` additionally writes
            # the atomic profile_NNN.json next to the flight files.
            resp = {"ok": True, **profiling.profile_status()}
            if msg.get("dump"):
                resp["profile_path"] = profiling.dump_profile()
            return resp
        if op == "shutdown":
            self._shutdown_requested.set()
            threading.Thread(target=self.shutdown,
                             daemon=True).start()
            return {"ok": True, "op": "shutdown"}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _guarded(self, request_class: str, fn):
        """Control-plane requests under the per-class watchdog budget: a
        wedged batch is cancelled (StallError -> structured error), not
        an open-ended hang holding the client's socket."""
        return run_with_deadline(fn, request_budget_s(request_class),
                                 f"serve.{request_class}")

    def serve_until_shutdown(self, port_file: str | None = None) -> None:
        if port_file:
            from ..utils.atomic import atomic_write

            with atomic_write(port_file) as f:
                f.write(str(self.port))
        log.info("serve: listening on %s:%d (store rows=%d gen=%d)",
                 self.server_address[0], self.port,
                 self.daemon._index.n_rows,
                 self.daemon._index.generation)
        self.serve_forever(poll_interval=0.1)


__all__ = ["ServeServer", "decode_vectors", "encode_vectors", "read_msg",
           "write_msg"]
