"""Client for the serving daemon (CLI `serve-client`, bench, tests).

One TCP connection, requests pipelined in order; reconnects lazily.
Timeouts come from the per-request-class watchdog budgets
(`resilience.watchdog.request_budget_s`) — the QUERY class is enforced
here at the socket (the server keeps its query hot path reaper-free),
while ingest/control classes are additionally reaper-guarded
server-side.  Connection-level failures route through the shared retry
engine (`resilience.retry_call`): a daemon mid-restart answers a ping
after a reconnect instead of failing the caller's first attempt.
"""

from __future__ import annotations

import os
import socket

import numpy as np

from ..observability.tracing import current_trace, span
from ..resilience import RetryPolicy, retry_call
from ..resilience.watchdog import request_budget_s
from .server import decode_vectors, encode_vectors, read_msg, write_msg

_CONNECT_TIMEOUT_S = 5.0


class ServeError(RuntimeError):
    """The daemon answered with a structured error."""

    def __init__(self, resp: dict) -> None:
        super().__init__(str(resp.get("error", "serve request failed")))
        self.resp = resp


class Backpressure(ServeError):
    """Ingest admission refused the batch; retry after ``retry_after_s``."""

    def __init__(self, resp: dict) -> None:
        super().__init__(resp)
        self.retry_after_s = float(resp.get("retry_after_s", 0.1))


class ServeClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 retry: RetryPolicy | None = None) -> None:
        self.host = host
        self.port = int(port)
        self._sock: socket.socket | None = None
        self._retry = retry or RetryPolicy(max_attempts=3, base_delay=0.05,
                                           max_delay=1.0)

    # -- transport -----------------------------------------------------------

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=_CONNECT_TIMEOUT_S)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, et, ev, tb) -> None:
        self.close()

    def request(self, op: str, timeout_s: float | None = None,
                **payload) -> dict:
        """One request/response on the pinned connection; connection
        failures drop the socket and retry through the shared engine.

        The whole exchange runs inside a ``client.<op>`` span whose
        trace context rides the envelope, so the daemon-side spans for
        this request land in the same trace as the client-perceived
        wall (retries included)."""

        with span(f"client.{op}") as sp:
            msg = {"op": op, **payload}
            ctx = current_trace()
            if ctx:
                msg["trace"] = ctx

            def attempt() -> dict:
                sock = self._connect()
                sock.settimeout(timeout_s or _CONNECT_TIMEOUT_S)
                try:
                    write_msg(sock, msg)
                    return read_msg(sock)
                except (ConnectionError, socket.timeout, OSError):
                    self.close()
                    raise

            resp = retry_call(attempt, policy=self._retry,
                              site=f"serve.client.{op}")
            sp.set_tag("ok", bool(resp.get("ok", False)))
        if not resp.get("ok", False):
            if resp.get("error") == "backpressure":
                raise Backpressure(resp)
            raise ServeError(resp)
        return resp

    # -- API -----------------------------------------------------------------

    def ping(self) -> dict:
        return self.request("ping", timeout_s=request_budget_s("status")
                            or None)

    def status(self) -> dict:
        return self.request("status", timeout_s=request_budget_s("status")
                            or None)

    def query(self, vectors: np.ndarray,
              timeout_s: float | None = None) -> dict:
        resp = self.request(
            "query",
            timeout_s=timeout_s or request_budget_s("query") or None,
            **encode_vectors(vectors))
        resp["labels"] = np.asarray(resp["labels"], np.int64)
        resp["known"] = np.asarray(resp["known"], bool)
        return resp

    def topk(self, vectors: np.ndarray, k: int = 10,
             mode: str = "candidates",
             timeout_s: float | None = None) -> dict:
        """The k nearest stored sessions per vector, by exact signature
        agreement.  ``scores``/``labels`` come back as [Q, k] int arrays
        (-1 padded); ``ids`` stays a [Q][k] list of digest hex strings
        ("" padding).  ``mode="scan"`` is the exact full-store path —
        budgeted as an ingest-class (bulk) request, not a query."""
        cls = "query" if mode == "candidates" else "ingest"
        resp = self.request(
            "topk",
            timeout_s=timeout_s or request_budget_s(cls) or None,
            k=int(k), mode=str(mode), **encode_vectors(vectors))
        resp["scores"] = np.asarray(resp["scores"], np.int64)
        resp["labels"] = np.asarray(resp["labels"], np.int64)
        return resp

    def ingest(self, vectors: np.ndarray,
               timeout_s: float | None = None,
               request_id: str | None = None) -> dict:
        """Durable ingest: the response means every row is committed to
        the store (SIGKILL after this returns loses nothing).  Raises
        :class:`Backpressure` under admission control — the caller owns
        the backoff (it knows whether the batch is droppable).

        Idempotent end to end: ONE request id is minted per logical
        call and rides every retry of it, so a reconnect after the
        server committed-but-did-not-answer replays the original ack
        server-side instead of re-absorbing the batch (the pre-fix
        failure mode: the pinned connection's in-flight ingest was
        re-sent as a NEW request after a server restart)."""
        return self.request(
            "ingest",
            timeout_s=timeout_s or request_budget_s("ingest") or None,
            request_id=request_id or os.urandom(8).hex(),
            **encode_vectors(vectors))

    def metrics(self) -> dict:
        """Live registry pull: ``prometheus`` (text exposition format)
        plus the flat ``metrics_*`` aggregation."""
        return self.request("metrics", timeout_s=request_budget_s("status")
                            or None)

    def trace(self, n: int | None = None) -> dict:
        """Recent completed spans from the daemon's ring buffer."""
        payload = {"n": int(n)} if n else {}
        return self.request("trace", timeout_s=request_budget_s("status")
                            or None, **payload)

    def slowlog(self, n: int | None = None) -> dict:
        """Recent slow-request captures (graftprof): span chain,
        sampler stacks, lock waits, in-flight absorb state per entry."""
        payload = {"n": int(n)} if n else {}
        return self.request("slowlog",
                            timeout_s=request_budget_s("status") or None,
                            **payload)

    def profile(self, dump: bool = False) -> dict:
        """Live profiler summary (sampler aggregate, top lock-wait
        sites); ``dump=True`` also writes profile_NNN.json daemon-side
        and returns its path."""
        payload = {"dump": True} if dump else {}
        return self.request("profile",
                            timeout_s=request_budget_s("status") or None,
                            **payload)

    def quiesce(self, timeout_s: float | None = None) -> dict:
        return self.request(
            "quiesce",
            timeout_s=timeout_s or request_budget_s("ingest") or None)

    def shutdown(self) -> dict:
        return self.request("shutdown", timeout_s=5.0)


__all__ = ["Backpressure", "ServeClient", "ServeError", "decode_vectors",
           "encode_vectors"]
