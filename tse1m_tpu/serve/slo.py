"""SLO layer for the online serving plane: admission control first.

The serving daemon's contract is a QUERY p99, and the only lever that
protects it under load is refusing work early: ingest is the elastic
class (a fuzzing session landing a few seconds later is free; a wedged
interactive query is not), so when the ingest backlog grows past the
policy bound, new ingest batches are rejected with a retry hint —
BEFORE query latency degrades — and every refusal is visible as a
``serve_backpressure`` degradation event plus queue-depth telemetry.

This is the load face of the PR 5 degradation ladder: the ingest path
itself already rides the watchdog/OOM/failover rungs inside the
pipeline; this module adds the request-class rung on top, with budgets
from ``resilience.watchdog.request_budget_s`` (one monotonic clock, the
``watchdog-clock`` lint plane).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..observability import record_degradation
from ..observability import metrics as obs_metrics
from ..resilience.watchdog import request_budget_s
from ..trace import sync as tsync
from ..trace.hooks import shared_access


@dataclass(frozen=True)
class SloPolicy:
    """Serving-plane targets and admission bounds.

    ``max_backlog_batches`` bounds the ingest queue: past it, submit is
    refused (backpressure) instead of queued — queue time is latency the
    acknowledging client cannot see, and an unbounded queue turns a load
    spike into an availability hole.  ``query_p99_target_ms`` is the SLO
    the plane reports against (violations are counted, not enforced per
    request — the per-request guard is the watchdog budget, which is a
    wedge detector, not an SLO)."""

    max_backlog_batches: int = 64
    query_p99_target_ms: float = 50.0
    query_budget_s: float = field(
        default_factory=lambda: request_budget_s("query"))
    ingest_budget_s: float = field(
        default_factory=lambda: request_budget_s("ingest"))
    # LSM delta-run consolidation bound for the live index (None =
    # leave TSE1M_LIVE_DELTA_RUNS / the built-in default alone).  The
    # pre-split measurement round tunes this when the lock-wait fat
    # tail is ``serve.index.swap``: fewer runs = cheaper probes per
    # query but more consolidation stalls on the ingest thread.
    live_delta_runs: int | None = None

    @classmethod
    def from_env(cls) -> "SloPolicy":
        runs = os.environ.get("TSE1M_LIVE_DELTA_RUNS")
        return cls(
            max_backlog_batches=int(
                os.environ.get("TSE1M_SERVE_MAX_BACKLOG", 64)),
            query_p99_target_ms=float(
                os.environ.get("TSE1M_SERVE_P99_TARGET_MS", 50.0)),
            live_delta_runs=int(runs) if runs else None)


class AdmissionController:
    """Ingest admission + queue-depth accounting (thread-safe).

    ``try_admit`` is called with the current queue depth before an
    ingest batch may enqueue; a refusal returns the retry hint the
    transport layer sends back.  Only the refused->admitted *transition*
    fires a degradation event (a sustained overload is one incident, not
    ten thousand), while every refusal increments the counter."""

    def __init__(self, policy: SloPolicy) -> None:
        self.policy = policy
        self._lock = tsync.Lock("AdmissionController")
        self._rejected = 0
        self._in_backpressure = False
        self._backlog_max = 0

    def note_depth(self, depth: int) -> None:
        with self._lock:
            shared_access(self, "backlog", write=True)
            if depth > self._backlog_max:
                self._backlog_max = depth

    def try_admit(self, depth: int) -> tuple[bool, float]:
        """(admitted, retry_after_s).  Depth counts batches queued ahead
        of this one.

        ONE critical section for the whole decision (graftrace audit):
        the old shape took the lock three times — max-depth update,
        admit reset, reject transition — so two admitting threads
        straddling a rejector could clear ``_in_backpressure`` between
        its counter bump and its transition read and double-fire the
        ``serve_backpressure`` degradation for one sustained incident
        (regression schedule: tests/test_trace.py)."""
        with self._lock:
            shared_access(self, "backlog", write=True)
            if depth > self._backlog_max:
                self._backlog_max = depth
            admitted = depth < self.policy.max_backlog_batches
            if admitted:
                self._in_backpressure = False
            else:
                self._rejected += 1
                fresh = not self._in_backpressure
                self._in_backpressure = True
        # Registry mirror (outside the admission lock — the metric
        # types bring their own): the backlog high-water mark and the
        # rejection counter survive into `serve --status`, the
        # Prometheus text, and the merged manifest.
        obs_metrics.gauge("serve_ingest_backlog_max").set_max(depth)
        if admitted:
            return True, 0.0
        obs_metrics.counter("serve_ingest_rejected_total").inc()
        if fresh:
            record_degradation(
                "serve_backpressure", site="serve.ingest",
                detail={"depth": int(depth),
                        "max_backlog": self.policy.max_backlog_batches})
        # Hint: roughly one queued batch's worth of drain time; the
        # client owns the actual backoff (shared retry engine).
        return False, max(0.05, self.policy.ingest_budget_s
                          / max(1, self.policy.max_backlog_batches))

    def stats(self) -> dict:
        with self._lock:
            shared_access(self, "backlog", write=False)
            return {"ingest_rejected": self._rejected,
                    "ingest_backlog_max": self._backlog_max,
                    "in_backpressure": self._in_backpressure}


class SloTracker:
    """Counts query-budget violations against the p99 target.

    The per-request watchdog budget catches wedges; this tracker makes
    slow-but-completing queries visible: each query wall past the p99
    target counts, and the first violation in a run fires a
    ``serve_slo_violation`` degradation event so the run manifest shows
    the plane ran hot even when nothing timed out."""

    def __init__(self, policy: SloPolicy) -> None:
        self.policy = policy
        self._lock = tsync.Lock("SloTracker")
        self._violations = 0

    def observe_query(self, wall_s: float) -> None:
        if wall_s * 1e3 <= self.policy.query_p99_target_ms:
            return
        with self._lock:
            shared_access(self, "violations", write=True)
            self._violations += 1
            first = self._violations == 1
        if first:
            record_degradation(
                "serve_slo_violation", site="serve.query",
                detail={"wall_ms": round(wall_s * 1e3, 3),
                        "target_ms": self.policy.query_p99_target_ms})

    def stats(self) -> dict:
        with self._lock:
            shared_access(self, "violations", write=False)
            return {"query_slo_violations": self._violations,
                    "query_p99_target_ms": self.policy.query_p99_target_ms}


__all__ = ["AdmissionController", "SloPolicy", "SloTracker"]
