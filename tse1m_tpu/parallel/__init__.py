from . import rq_mesh
from .mesh import detection_hist_sharded, make_mesh, shard_along
from .rq_mesh import auto_mesh

__all__ = ["make_mesh", "shard_along", "detection_hist_sharded",
           "auto_mesh", "rq_mesh"]
