from .mesh import detection_hist_sharded, make_mesh, shard_along

__all__ = ["make_mesh", "shard_along", "detection_hist_sharded"]
