"""Device-mesh plumbing: mesh construction, sharded placement, and the
collective reductions that take NCCL's architectural seat (SURVEY.md §2.4).

The study's parallel axis is *data* (sessions/issues/projects) — there is
no model to tensor/pipeline-shard — so the mesh is 1-D and collectives are
`psum` over ICI: each device reduces its shard of events into a dense
per-iteration histogram and one all-reduce merges them.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.compat import shard_map


def make_mesh(n_devices: int | None = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def shard_along(mesh: Mesh, axis: str = "data", rank: int = 1) -> NamedSharding:
    """NamedSharding splitting dim 0 over `axis`, replicating the rest."""
    spec = P(axis, *([None] * (rank - 1)))
    return NamedSharding(mesh, spec)


def pad_to_devices(x: np.ndarray, mesh: Mesh, fill=0) -> tuple[np.ndarray, int]:
    n_dev = mesh.devices.size
    pad = (-x.shape[0]) % n_dev
    if pad:
        fill_block = np.full((pad,) + x.shape[1:], fill, dtype=x.dtype)
        x = np.concatenate([x, fill_block], axis=0)
    return x, pad


@lru_cache(maxsize=64)
def _hist_kernel(mesh: Mesh, max_iter: int, axis: str):
    # jit'd + cached by (mesh, max_iter, axis): a wrapper built inside the
    # public function would discard its compile cache on every call (see
    # rq_mesh.py's factory note).
    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P())
    def hist(shard):
        # Out-of-range iterations route to the discarded 0 bin — same
        # semantics as ops.segment.unique_pairs_count_per_iteration.
        in_range = (shard >= 1) & (shard <= max_iter)
        local = jnp.bincount(jnp.where(in_range, shard, 0),
                             length=max_iter + 1)
        return jax.lax.psum(local[1:], axis_name=axis)

    return hist


def detection_hist_sharded(iterations, max_iter: int, mesh: Mesh,
                           axis: str = "data"):
    """Per-iteration event histogram as a mesh collective.

    iterations: [Q] int32 1-based iteration index per event (0 = unlinked,
    ignored), sharded along `axis`.  Each device bincounts its shard and a
    `psum` over ICI merges the partials — the rebuild's analogue of the
    reference's per-issue counting loop (rq1_detection_rate.py:215-230).
    Returns a replicated [max_iter] int32 histogram.
    """
    return _hist_kernel(mesh, max_iter, axis)(
        jnp.asarray(iterations, dtype=jnp.int32))
