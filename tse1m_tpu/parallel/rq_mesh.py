"""Mesh-native RQ reductions: the north star's "RQ aggregations as
psum/pmean mesh collectives" (BASELINE.json; SURVEY.md §2.4).

Each helper shards the *data* axis of an RQ kernel over a 1-D device mesh
with `shard_map` and merges per-device partials with `psum` over ICI — the
architectural seat NCCL holds in the reference's GPU-world peers.  Sharding
axes are chosen so every float reduction stays *within* one device and only
integer merges cross devices, which makes the mesh path bit-identical to the
single-device path (asserted by tests/test_mesh_rq.py):

- RQ1 (rq1_detection_rate.py:189-268): the issue/event axis is sharded;
  per-device boolean (project, iteration) detection grids merge with an
  integer `psum` — set-union is exact under addition+threshold.
- RQ2 trends (rq2_coverage_count.py:330-435): per-session percentiles/means
  shard the *session* axis (each column reduces on one device, bit-exact);
  per-session project counts shard the *project* axis and `psum` int32
  partial counts; per-project Spearman shards the *project* axis.
- RQ4b (rq4b_coverage.py:910-1015): per-session group percentiles run the
  device sort + order-statistic selection in float64 (x64 context) sharded
  by session; the final two-point interpolation happens on host with
  numpy's own `_lerp` formula so results are bit-identical to
  `np.nanpercentile` (the advisor-mandated float64 parity contract).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.compat import enable_x64, shard_map

from ..ops.segment import masked_mean, masked_spearman, segment_searchsorted
from .mesh import make_mesh

AXIS = "data"

# Every kernel below is built by an lru_cache'd factory keyed on (mesh,
# static closure params): a jit wrapper created inside the public function
# body would be a fresh function object per call, so its compile cache
# would be discarded every time and each mesh RQ call would re-trace and
# re-compile (caught in round 4: the multichip scaling curve was
# compile-dominated for exactly this reason).

_F64_EXACT: dict = {}


def _device_f64_exact(device) -> bool:
    """True iff a float64 host->device->host roundtrip is lossless on
    `device` (true on CPU; false on TPU, which has no native f64)."""
    key = getattr(device, "platform", str(device))
    if key not in _F64_EXACT:
        canary = np.array([1.0 + 2.0 ** -50, np.pi, 1e300], dtype=np.float64)
        with enable_x64(True):
            # graftlint: disable=wire-layer -- 4-byte mesh-liveness canary, not a data transfer
            back = np.asarray(jax.device_get(jax.device_put(canary, device)))
        _F64_EXACT[key] = bool(np.array_equal(canary, back))
    return _F64_EXACT[key]


def auto_mesh() -> Mesh | None:
    """A 1-D data mesh over all visible devices, or None on one device."""
    return make_mesh() if jax.device_count() > 1 else None


def _pad_rows(x: np.ndarray, n_dev: int, fill) -> np.ndarray:
    pad = (-x.shape[0]) % n_dev
    if not pad:
        return x
    block = np.full((pad,) + x.shape[1:], fill, dtype=x.dtype)
    return np.concatenate([x, block], axis=0)


def _placed(mesh: Mesh, x, spec: P):
    """Host array -> device array laid out per ``spec`` for ``mesh``.

    Single-process this is a plain `jnp.asarray` (jit moves it; behavior
    identical to the original kernels).  Multi-process — where the mesh
    spans non-addressable devices and a host array cannot be device_put
    globally — every process passes the IDENTICAL full array and this hands
    `jax.make_array_from_process_local_data` only the process-local block
    of the (at most one) mesh-sharded dim.  Dims are pre-padded to the
    device count, which the per-process device counts divide evenly.
    """
    x = np.asarray(x)
    if jax.process_count() == 1:
        return jnp.asarray(x)
    sharding = NamedSharding(mesh, spec)
    dims = [i for i, s in enumerate(spec) if s == AXIS]
    if not dims:
        return jax.make_array_from_process_local_data(sharding, x, x.shape)
    d = dims[0]
    per = x.shape[d] // jax.process_count()
    sl = [slice(None)] * x.ndim
    sl[d] = slice(jax.process_index() * per, (jax.process_index() + 1) * per)
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(x[tuple(sl)]), x.shape)


def _fetch(out) -> np.ndarray:
    """Kernel output -> host numpy.  Multi-process, sharded outputs live
    partly on non-addressable devices, so gather across processes first
    (rides DCN); fully-replicated outputs and all single-process outputs
    materialise directly."""
    if jax.process_count() > 1 and not out.is_fully_replicated:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(out, tiled=True))
    return np.asarray(out)


# ---------------------------------------------------------------------------
# RQ1: sharded issue axis + psum'd detection grid
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _rq1_mesh_kernel(mesh: Mesh, n_projects: int, max_iter: int,
                     have_ok: bool):
    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                       P(), P(), P(), P(), P(), P(), P()),
             out_specs=(P(AXIS), P(AXIS), P()))
    def kernel(is_, ins, seg, ok_mask, fs, fns, f_off, oks, okns, ok_off,
               ok_idx):
        it = segment_searchsorted(fs, f_off, is_, seg, side="left",
                                  values_lo=fns, queries_lo=ins)
        pos = segment_searchsorted(oks, ok_off, is_, seg, side="left",
                                   values_lo=okns, queries_lo=ins)
        has_link = pos > 0
        if have_ok:
            gather = jnp.clip(ok_off[seg] + pos - 1, 0, ok_idx.shape[0] - 1)
            link = jnp.where(has_link, ok_idx[gather], -1)
        else:
            link = jnp.full(seg.shape, -1, dtype=jnp.int32)
        det_iter = jnp.where(has_link & ok_mask, it, 0)
        in_range = det_iter <= max_iter
        col = jnp.where(in_range, det_iter, 0)
        grid = jnp.zeros((n_projects, max_iter + 1), dtype=jnp.bool_)
        grid = grid.at[seg, col].set(True, mode="drop")
        merged = jax.lax.psum(grid.astype(jnp.int32), AXIS)
        detected = (merged[:, 1:] > 0).sum(axis=0, dtype=jnp.int32)
        return it, link, detected

    return kernel


def rq1_kernel_mesh(mesh: Mesh, fuzz_s, fuzz_ns, fuzz_offsets,
                    ok_s, ok_ns, ok_offsets, ok_orig_idx,
                    issue_s, issue_ns, issue_seg,
                    n_projects: int, max_iter: int):
    """Sharded twin of `jax_backend._rq1_kernel`: issues are split over the
    mesh, build arrays ride replicated, and the unique-detected-projects
    grid merges with a `psum` (integer, hence bit-exact vs single device).
    Returns host arrays (iteration_of_issue, link_idx, detected)."""
    n_dev = mesh.devices.size
    q = int(np.asarray(issue_s).shape[0])
    issue_s = _pad_rows(np.asarray(issue_s), n_dev, 0)
    issue_ns = _pad_rows(np.asarray(issue_ns), n_dev, 0)
    issue_seg = _pad_rows(np.asarray(issue_seg, dtype=np.int32), n_dev, 0)
    valid = _pad_rows(np.ones(q, dtype=bool), n_dev, False)
    have_ok = int(np.asarray(ok_orig_idx).shape[0]) > 0

    kernel = _rq1_mesh_kernel(mesh, n_projects, max_iter, have_ok)
    it, link, detected = kernel(
        _placed(mesh, issue_s, P(AXIS)), _placed(mesh, issue_ns, P(AXIS)),
        _placed(mesh, issue_seg, P(AXIS)), _placed(mesh, valid, P(AXIS)),
        _placed(mesh, fuzz_s, P()), _placed(mesh, fuzz_ns, P()),
        _placed(mesh, np.asarray(fuzz_offsets, dtype=np.int32), P()),
        _placed(mesh, ok_s, P()), _placed(mesh, ok_ns, P()),
        _placed(mesh, np.asarray(ok_offsets, dtype=np.int32), P()),
        _placed(mesh, np.asarray(ok_orig_idx, dtype=np.int32), P()))
    return (_fetch(it)[:q], _fetch(link)[:q], _fetch(detected))


# ---------------------------------------------------------------------------
# RQ2 trends: session-sharded percentiles/means, project-psum counts
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _percentile_mesh_kernel(mesh: Mesh):
    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(P(AXIS, None), P(AXIS, None), P(None, AXIS),
                       P(None, AXIS)),
             out_specs=(P(None, AXIS), P(None, AXIS)))
    def kernel(x, m, lo_, hi_):
        big = jnp.finfo(jnp.float32).max
        srt = jnp.sort(jnp.where(m, x, big), axis=-1)  # valid entries first
        vlo = jnp.take_along_axis(srt, lo_.T, axis=-1).T
        vhi = jnp.take_along_axis(srt, hi_.T, axis=-1).T
        return vlo, vhi

    return kernel


def percentile_by_session_mesh(cols, colmask, q, mesh: Mesh):
    """masked_percentile over [S, P] with the session axis sharded.

    Bit-parity note: the single-device path runs `masked_percentile`
    *eagerly* — every float32 op IEEE-rounded separately — while a fused
    `jit(shard_map(...))` kernel lets XLA contract the final interpolation
    into an fma, drifting 1-2 ulps.  So the device does only the
    rounding-free work (the per-session sort and the two order-statistic
    gathers, sharded over the mesh) and the host replays the eager kernel's
    float32 index/lerp sequence op-for-op, which makes this bit-identical
    to `masked_percentile` (asserted by tests/test_mesh_rq.py)."""
    n_dev = mesh.devices.size
    s = cols.shape[0]
    cols = _pad_rows(np.asarray(cols, dtype=np.float32), n_dev, 0.0)
    colmask = _pad_rows(np.asarray(colmask, dtype=bool), n_dev, False)
    qv = np.atleast_1d(np.asarray(q, dtype=np.float32))
    width = cols.shape[1]
    if width == 0:
        return np.full((qv.shape[0], s), np.nan)
    # Host-side float32 index math, same op order as masked_percentile.
    n_valid = colmask.sum(axis=1).astype(np.int32)                # [S']
    pos = (n_valid.astype(np.float32) - np.float32(1.0)) \
        * qv[:, None] / np.float32(100.0)                         # [K, S']
    lo = np.clip(np.floor(pos).astype(np.int32), 0, width - 1)
    hi = np.clip(lo + 1, 0, width - 1)
    frac = pos - lo.astype(np.float32)

    vlo, vhi = _percentile_mesh_kernel(mesh)(
        _placed(mesh, cols, P(AXIS, None)),
                      _placed(mesh, colmask, P(AXIS, None)),
                      _placed(mesh, lo, P(None, AXIS)),
                      _placed(mesh, hi, P(None, AXIS)))
    vlo = _fetch(vlo).astype(np.float32)
    vhi = _fetch(vhi).astype(np.float32)
    hi_valid = (lo + 1) <= (n_valid[None, :] - 1)
    out = vlo + np.where(hi_valid, frac * (vhi - vlo), np.float32(0.0))
    out = np.where(n_valid[None, :] > 0, out, np.float32(np.nan))
    return out.astype(np.float64)[:, :s]


@lru_cache(maxsize=64)
def _mean_mesh_kernel(mesh: Mesh):
    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS, None), P(AXIS, None)),
             out_specs=P(AXIS))
    def kernel(x, m):
        return masked_mean(x, m)

    return kernel


def mean_by_session_mesh(cols, colmask, mesh: Mesh):
    """masked_mean over [S, P] sharded on the session axis (bit-exact)."""
    n_dev = mesh.devices.size
    s = cols.shape[0]
    cols = _pad_rows(np.asarray(cols, dtype=np.float32), n_dev, 0.0)
    colmask = _pad_rows(np.asarray(colmask, dtype=bool), n_dev, False)
    return _fetch(_mean_mesh_kernel(mesh)(
        _placed(mesh, cols, P(AXIS, None)),
        _placed(mesh, colmask, P(AXIS, None)))).astype(np.float64)[:s]


def counts_by_project_psum(mask, mesh: Mesh) -> np.ndarray:
    """Per-session valid-project counts of a [P, S] mask as a `psum` over a
    project-sharded mesh — the pmean/psum seat of the reference's per-session
    `len(valid_projects)` loop (rq2_coverage_count.py:390-398).  Integer, so
    exact."""
    n_dev = mesh.devices.size
    mask = _pad_rows(np.asarray(mask, dtype=bool), n_dev, False)
    return _fetch(_counts_mesh_kernel(mesh)(
        _placed(mesh, mask, P(AXIS, None)))).astype(np.int64)


@lru_cache(maxsize=64)
def _counts_mesh_kernel(mesh: Mesh):
    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS, None),),
             out_specs=P())
    def kernel(m):
        return jax.lax.psum(m.sum(axis=0, dtype=jnp.int32), AXIS)

    return kernel


def spearman_by_project_mesh(matrix, mask, mesh: Mesh):
    """masked_spearman over [P, S] with the project axis sharded (each row
    reduces on one device; bit-identical to the single-device path)."""
    n_dev = mesh.devices.size
    p = matrix.shape[0]
    matrix = _pad_rows(np.asarray(matrix, dtype=np.float32), n_dev, 0.0)
    mask = _pad_rows(np.asarray(mask, dtype=bool), n_dev, False)
    return _fetch(_spearman_mesh_kernel(mesh)(
        _placed(mesh, matrix, P(AXIS, None)),
        _placed(mesh, mask, P(AXIS, None)))).astype(np.float64)[:p]


@lru_cache(maxsize=64)
def _spearman_mesh_kernel(mesh: Mesh):
    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS, None), P(AXIS, None)),
             out_specs=P(AXIS))
    def kernel(x, m):
        return masked_spearman(x, m)

    return kernel


# ---------------------------------------------------------------------------
# RQ4b: float64 per-session group percentiles, session-sharded
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _nanpercentile_mesh_kernel(mesh: Mesh, qf_key: tuple, g: int):
    qf_arr = np.asarray(qf_key, dtype=np.float64)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS, None),),
             out_specs=(P(None, AXIS), P(None, AXIS), P(AXIS)))
    def kernel(x):
        m = ~jnp.isnan(x)
        n = m.sum(axis=-1)                       # [s_shard]
        filled = jnp.where(m, x, jnp.inf)
        srt = jnp.sort(filled, axis=-1)          # valid first
        # virtual index per numpy's linear method: (n-1) * (q/100)
        pos = (n - 1).astype(jnp.float64) * jnp.asarray(qf_arr)[:, None]
        lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0,
                      max(g - 1, 0))
        hi = jnp.minimum(lo + 1,
                         jnp.maximum(n - 1, 0).astype(jnp.int32)[None, :])
        vlo = jnp.take_along_axis(srt, lo.T, axis=-1).T
        vhi = jnp.take_along_axis(srt, hi.T, axis=-1).T
        return vlo, vhi, n

    return kernel


def nanpercentile_by_session_mesh(sub: np.ndarray, q, mesh: Mesh) -> np.ndarray:
    """Bit-exact `np.nanpercentile(sub, q, axis=0)` with the heavy work — the
    per-session float64 sort and order-statistic selection — sharded over the
    mesh (x64 context; sessions split across devices).

    The device returns, per (percentile, session), the two bracketing order
    statistics; the host applies numpy's `_lerp` formula (including its
    `gamma >= 0.5` re-association fixup) in float64, so the result is
    bit-identical to the host `np.nanpercentile` the advisor-parity contract
    requires.  `sub` is [G, S] float64 with NaN = missing.  Inputs holding
    +inf, or meshes on devices without lossless float64 (TPU), are computed
    on host instead — same values, no device sharding (see guard below)."""
    g, s = sub.shape
    qf = np.atleast_1d(np.asarray(q, dtype=np.float64)) / 100.0
    if g == 0 or s == 0:
        return np.full((qf.shape[0], s), np.nan)
    # Two cases where the device kernel cannot honor the bit-parity
    # contract: (a) +inf input collides with the sort fill and breaks the
    # lerp (inf - inf = nan where numpy yields inf); (b) platforms without
    # native float64 (TPU) drop low-order bits on a mere device roundtrip.
    # Percentiles over [G, S] are cheap vs the study kernels, so both route
    # to host np.nanpercentile, which keeps mesh/non-mesh behavior and
    # values identical.
    if np.isposinf(sub).any() or not _device_f64_exact(mesh.devices.flat[0]):
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            return np.nanpercentile(sub, np.atleast_1d(q), axis=0)
    n_dev = mesh.devices.size
    cols = _pad_rows(np.ascontiguousarray(sub.T), n_dev, np.nan)  # [S', G]

    with enable_x64(True):
        kernel = _nanpercentile_mesh_kernel(mesh, tuple(qf.tolist()), g)
        vlo, vhi, n = kernel(_placed(mesh, cols.astype(np.float64),
                                     P(AXIS, None)))

        vlo = _fetch(vlo).astype(np.float64)[:, :s]
        vhi = _fetch(vhi).astype(np.float64)[:, :s]
        n = _fetch(n).astype(np.int64)[:s]
    pos = (n - 1).astype(np.float64) * qf[:, None]
    gamma = pos - np.floor(pos)
    with np.errstate(invalid="ignore"):
        diff = vhi - vlo
        out = vlo + diff * gamma
        fix = gamma >= 0.5
        out[fix] = (vhi - diff * (1.0 - gamma))[fix]
    out[:, n == 0] = np.nan
    return out


# ---------------------------------------------------------------------------
# RQ3/RQ4a: per-segment searchsorted with the query axis sharded
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _searchsorted_mesh_kernel(mesh: Mesh, side: str):
    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(P(AXIS), P(AXIS), P(AXIS), P(), P(), P()),
             out_specs=P(AXIS))
    def kernel(queries, queries_lo_, seg_, vals, vals_lo, off):
        return segment_searchsorted(vals, off, queries, seg_, side=side,
                                    values_lo=vals_lo, queries_lo=queries_lo_)

    return kernel


def segment_searchsorted_mesh(mesh: Mesh, values_s, offsets, queries_s,
                              query_seg, side: str,
                              values_lo, queries_lo) -> np.ndarray:
    """Sharded twin of `ops.segment.segment_searchsorted` (two-lane form).

    Queries — the issue axis in RQ3's three per-issue scans
    (rq3_diff_coverage_at_detection.py:269-293) and RQ4a's iteration mapping
    (rq4a_bug.py:344-346) — split over the mesh; the CSR build/coverage
    arrays ride replicated.  Every query's binary search is independent, so
    no collective is needed and the result is trivially bit-identical to
    the single-device op (asserted in tests/test_mesh_rq.py).
    """
    q = int(np.asarray(queries_s).shape[0])
    if q == 0 or int(np.asarray(values_s).shape[0]) == 0:
        return np.zeros(q, dtype=np.int32)
    n_dev = mesh.devices.size
    qs = _pad_rows(np.asarray(queries_s), n_dev, 0)
    qlo = _pad_rows(np.asarray(queries_lo), n_dev, 0)
    seg = _pad_rows(np.asarray(query_seg, dtype=np.int32), n_dev, 0)

    kernel = _searchsorted_mesh_kernel(mesh, side)
    out = kernel(_placed(mesh, qs, P(AXIS)), _placed(mesh, qlo, P(AXIS)),
                 _placed(mesh, seg, P(AXIS)),
                 _placed(mesh, values_s, P()), _placed(mesh, values_lo, P()),
                 _placed(mesh, np.asarray(offsets, dtype=np.int32), P()))
    return _fetch(out)[:q]
