"""Multi-host distribution: the DCN half of the communication backend.

SURVEY §5 places `jax.distributed` + the mesh collectives in the
architectural seat NCCL/MPI hold in GPU frameworks: intra-slice reductions
ride ICI (see `mesh.py` / `rq_mesh.py`), and *this* module supplies the
cross-host layer — process bring-up, a global mesh spanning every host's
devices, and process-local data feeding so each host loads only its slice
of the ~1M-session study (the reference's closest analogue is one process
per Chrome instance with disjoint output dirs, 5_get_issue_reports.py:486-497;
it has no device-compute distribution at all).

Design rules (scaling-book recipe):
- One global 1-D ``data`` mesh over *all* processes' devices; shardings are
  declared, XLA inserts the collectives, and a `psum` crossing host
  boundaries rides DCN automatically — kernels in `rq_mesh.py` and
  `cluster/pipeline.py` need no changes to scale out.
- Data is fed process-locally: each host materialises only
  ``local_row_range(n)`` of the global array and
  ``put_process_local`` assembles the global jax.Array from those shards
  (`jax.make_array_from_process_local_data`), so no host ever holds the
  full 1M x S items matrix.

Everything degrades to a no-op in the (tested) single-process case, which
is also how the driver's virtual-device dryrun exercises the code path.
"""

from __future__ import annotations

import os

import jax
import numpy as np

from ..utils.logging import get_logger
from .mesh import make_mesh, shard_along

log = get_logger("multihost")

_ENV_COORD = "TSE1M_COORDINATOR"      # host:port of process 0
_ENV_NPROC = "TSE1M_NUM_PROCESSES"
_ENV_PID = "TSE1M_PROCESS_ID"


def initialize_from_env() -> bool:
    """Bring up `jax.distributed` when multi-host env vars are present.

    Reads ``TSE1M_COORDINATOR`` / ``TSE1M_NUM_PROCESSES`` /
    ``TSE1M_PROCESS_ID`` (explicit, scheduler-agnostic); with none set —
    or on TPU pod slices where JAX self-discovers via the metadata server —
    falls through to single-process or automatic initialization.  Returns
    True when a multi-process runtime is (already or newly) active.
    Idempotent: a second call is a no-op.
    """
    coord = os.environ.get(_ENV_COORD)
    nproc = os.environ.get(_ENV_NPROC)
    if not coord or not nproc or int(nproc) <= 1:
        # No env config: report the current runtime state.  (Only safe to
        # query here — jax.process_count() initialises the backend, which
        # must not happen before jax.distributed.initialize when a
        # multi-process bring-up IS requested.)
        return jax.process_count() > 1
    pid_raw = os.environ.get(_ENV_PID)
    if not pid_raw:  # unset OR empty (unsubstituted template var)
        # Silent default-to-0 would make every host that forgot the var
        # register as process 0 — the coordinator then hangs or fails with
        # an opaque duplicate-registration error.  Fail fast instead.
        raise RuntimeError(
            f"{_ENV_COORD} and {_ENV_NPROC}={nproc} are set but {_ENV_PID} "
            "is not; every process must export its unique id (0..n-1)")
    pid = int(pid_raw)
    try:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=int(nproc), process_id=pid)
    except RuntimeError:
        # Already initialised (idempotent second call) — anything else
        # (backend up before init, unreachable coordinator) re-raises.
        if jax.process_count() > 1:
            return True
        raise
    log.info("jax.distributed up: process %d/%d, %d global devices",
             jax.process_index(), jax.process_count(), jax.device_count())
    return True


def global_mesh(axis: str = "data") -> jax.sharding.Mesh:
    """1-D mesh over every device of every process (== `make_mesh` on a
    single host; after `initialize_from_env` it spans the pod/cluster)."""
    return make_mesh(axis=axis)


def local_row_range(n_rows: int) -> tuple[int, int]:
    """[start, stop) of the global row axis this process must materialise.

    Rows are dealt contiguously per process in process-index order, exactly
    matching how `put_process_local` lays shards onto the mesh; the last
    process absorbs the remainder.
    """
    nproc = jax.process_count()
    pid = jax.process_index()
    per = -(-n_rows // nproc)  # ceil division: contiguous, last may be short
    start = min(pid * per, n_rows)
    return start, min(start + per, n_rows)


def put_process_local(local_rows: np.ndarray, n_global_rows: int,
                      mesh: jax.sharding.Mesh,
                      axis: str = "data") -> jax.Array:
    """Assemble a row-sharded global jax.Array from this process's slice.

    ``local_rows`` must be exactly the ``local_row_range(n_global_rows)``
    slice.  Single-process this is an ordinary sharded device_put; multi-
    process it builds the global array without any host ever seeing
    non-local rows.
    """
    sharding = shard_along(mesh, axis=axis, rank=local_rows.ndim)
    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    global_shape = (n_global_rows,) + local_rows.shape[1:]
    return jax.make_array_from_process_local_data(sharding, local_rows,
                                                  global_shape)


def all_processes_ready(tag: str = "barrier") -> None:
    """Cross-host barrier (no-op single-process): collective phases —
    e.g. 'every host finished ingest, start the sharded RQ pass' — must
    not race ahead of slow hosts."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)
