"""Multi-host distribution: the DCN half of the communication backend.

SURVEY §5 places `jax.distributed` + the mesh collectives in the
architectural seat NCCL/MPI hold in GPU frameworks: intra-slice reductions
ride ICI (see `mesh.py` / `rq_mesh.py`), and *this* module supplies the
cross-host layer — process bring-up, a global mesh spanning every host's
devices, and process-local data feeding so each host loads only its slice
of the ~1M-session study (the reference's closest analogue is one process
per Chrome instance with disjoint output dirs, 5_get_issue_reports.py:486-497;
it has no device-compute distribution at all).

Design rules (scaling-book recipe):
- One global 1-D ``data`` mesh over *all* processes' devices; shardings are
  declared, XLA inserts the collectives, and a `psum` crossing host
  boundaries rides DCN automatically — kernels in `rq_mesh.py` and
  `cluster/pipeline.py` need no changes to scale out.
- Data is fed process-locally: each host materialises only
  ``local_row_range(n)`` of the global array and
  ``put_process_local`` assembles the global jax.Array from those shards
  (`jax.make_array_from_process_local_data`), so no host ever holds the
  full 1M x S items matrix.

Everything degrades to a no-op in the (tested) single-process case, which
is also how the driver's virtual-device dryrun exercises the code path.
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np

from ..utils.logging import get_logger
from .mesh import make_mesh, shard_along

log = get_logger("multihost")

_ENV_COORD = "TSE1M_COORDINATOR"      # host:port of process 0
_ENV_NPROC = "TSE1M_NUM_PROCESSES"
_ENV_PID = "TSE1M_PROCESS_ID"


def initialize_from_env() -> bool:
    """Bring up `jax.distributed` when multi-host env vars are present.

    Reads ``TSE1M_COORDINATOR`` / ``TSE1M_NUM_PROCESSES`` /
    ``TSE1M_PROCESS_ID`` (explicit, scheduler-agnostic); with none set —
    or on TPU pod slices where JAX self-discovers via the metadata server —
    falls through to single-process or automatic initialization.  Returns
    True when a multi-process runtime is (already or newly) active.
    Idempotent: a second call is a no-op.
    """
    coord = os.environ.get(_ENV_COORD)
    nproc = os.environ.get(_ENV_NPROC)
    if not coord or not nproc or int(nproc) <= 1:
        # No env config: report the current runtime state.  (Only safe to
        # query here — jax.process_count() initialises the backend, which
        # must not happen before jax.distributed.initialize when a
        # multi-process bring-up IS requested.)
        return jax.process_count() > 1
    pid_raw = os.environ.get(_ENV_PID)
    if not pid_raw:  # unset OR empty (unsubstituted template var)
        # Silent default-to-0 would make every host that forgot the var
        # register as process 0 — the coordinator then hangs or fails with
        # an opaque duplicate-registration error.  Fail fast instead.
        raise RuntimeError(
            f"{_ENV_COORD} and {_ENV_NPROC}={nproc} are set but {_ENV_PID} "
            "is not; every process must export its unique id (0..n-1)")
    pid = int(pid_raw)
    try:
        # global_state.initialize rather than the public wrapper: the
        # extra knobs make the XLA coordination service's OWN death
        # detection inert (default: ~100 s after a peer dies, every
        # survivor's error-poll thread LOG(FATAL)s the process — i.e. a
        # lost host EXECUTES THE SURVIVORS, the exact opposite of what
        # the pod failover plane needs).  Host loss is the file-heartbeat
        # monitor's job (resilience/coordinator.py); the service stays up
        # only for bring-up and the run-nonce KV store, and a pod run
        # that declared a loss must exit via
        # coordinator.hard_exit_if_host_lost (the Shutdown barrier can
        # never pass once a peer is dead).
        from jax._src import distributed as _dist

        _dist.global_state.initialize(
            coordinator_address=coord, num_processes=int(nproc),
            process_id=pid,
            service_heartbeat_interval_seconds=10,
            service_max_missing_heartbeats=int(os.environ.get(
                "TSE1M_DIST_MAX_MISSED_HEARTBEATS", 100_000)),
            client_heartbeat_interval_seconds=10,
            client_max_missing_heartbeats=int(os.environ.get(
                "TSE1M_DIST_MAX_MISSED_HEARTBEATS", 100_000)))
    except RuntimeError:
        # Already initialised (idempotent second call) — anything else
        # (backend up before init, unreachable coordinator) re-raises.
        if jax.process_count() > 1:
            return True
        raise
    log.info("jax.distributed up: process %d/%d, %d global devices",
             jax.process_index(), jax.process_count(), jax.device_count())
    return True


def pod_process_env() -> tuple[int, int]:
    """(n_processes, process_id) for the POD plane, WITHOUT initializing
    jax.distributed.

    The pod path's data plane is the shared store root and its control
    plane is the file-heartbeat coordinator — it needs process identity,
    not an XLA coordination service.  Reading it straight from the env
    (``TSE1M_NUM_PROCESSES`` / ``TSE1M_PROCESS_ID``) is what lets a
    survivor outlive a dead leader: there is no coordination client to
    LOG(FATAL) the process when the leader's service socket closes, so
    leader loss is just another heartbeat timeout.  Falls back to the
    already-initialized jax.distributed identity (the mesh path), else
    single-process."""
    nproc = os.environ.get(_ENV_NPROC)
    pid = os.environ.get(_ENV_PID)
    if nproc and int(nproc) > 1:
        if not pid:
            raise RuntimeError(
                f"{_ENV_NPROC}={nproc} is set but {_ENV_PID} is not; "
                "every pod process must export its unique id (0..n-1)")
        return int(nproc), int(pid)
    if jax.process_count() > 1:  # mesh bring-up already happened
        return jax.process_count(), jax.process_index()
    return 1, 0


def global_mesh(axis: str = "data") -> jax.sharding.Mesh:
    """1-D mesh over every device of every process (== `make_mesh` on a
    single host; after `initialize_from_env` it spans the pod/cluster)."""
    return make_mesh(axis=axis)


def local_row_range(n_rows: int) -> tuple[int, int]:
    """[start, stop) of the global row axis this process must materialise.

    The deal is DEVICE-aligned, not process-aligned: jax lays a 1-D
    NamedSharding out as ceil(n / n_devices) rows per device (last device
    truncated), and a process owns its local devices' contiguous block —
    so this process's range is ``local_device_count * ceil(n / n_devices)``
    rows starting at its first device's offset.  (A per-process ceil
    division disagrees with that layout whenever a process holds more than
    one device and n is not a device-count multiple — e.g. n=10 on
    2 procs x 2 devices: jax places [0,6) on process 0's devices, not
    [0,5).)  For mesh-multiple n — e.g. after `padded_row_count` — the two
    deals coincide.
    """
    n_dev = jax.device_count()
    per_dev = -(-n_rows // n_dev)  # ceil: jax's per-shard row count
    start = min(jax.process_index() * jax.local_device_count() * per_dev,
                n_rows)
    stop = min(start + jax.local_device_count() * per_dev, n_rows)
    return start, stop


def padded_row_count(n_rows: int, mesh: jax.sharding.Mesh | None = None) -> int:
    """n_rows rounded up to the mesh's device-count multiple — the global
    pad contract for pre-sharded pipelines (`cluster_sessions` requires a
    mesh-multiple row axis; a real study size never is one).  Pad rows are
    fed as zeros by the owning process and sliced off the result."""
    k = mesh.devices.size if mesh is not None else jax.device_count()
    return -(-n_rows // k) * k


def put_process_local(local_rows: np.ndarray, n_global_rows: int,
                      mesh: jax.sharding.Mesh,
                      axis: str = "data") -> jax.Array:
    """Assemble a row-sharded global jax.Array from this process's slice.

    ``local_rows`` must be exactly the ``local_row_range(n_global_rows)``
    slice.  Single-process this is an ordinary sharded device_put; multi-
    process it builds the global array without any host ever seeing
    non-local rows.
    """
    sharding = shard_along(mesh, axis=axis, rank=local_rows.ndim)
    if jax.process_count() == 1:
        # graftlint: disable=wire-layer -- the multi-host feed seat: no single host holds all rows, so the single-host wire layer cannot carry this put
        return jax.device_put(local_rows, sharding)
    global_shape = (n_global_rows,) + local_rows.shape[1:]
    return jax.make_array_from_process_local_data(sharding, local_rows,
                                                  global_shape)


def put_process_local_padded(local_rows: np.ndarray, n_logical_rows: int,
                             mesh: jax.sharding.Mesh,
                             axis: str = "data") -> tuple[jax.Array, int]:
    """`put_process_local` for an arbitrary (non-mesh-multiple) row count.

    The global row axis is padded to ``padded_row_count(n_logical_rows)``;
    ``local_rows`` must be this process's LOGICAL rows — the intersection
    of ``local_row_range(padded_row_count(n))`` with ``[0, n)`` — and the
    owner of the tail block grows it with zero rows here.  Returns
    ``(global_array, n_padded)``; consumers slice results back to
    ``[:n_logical_rows]``.
    """
    if mesh.devices.size != jax.device_count():
        # local_row_range deals by the GLOBAL device count; a sub-mesh
        # would make the pad multiple and the slice deal disagree and
        # misplace rows.  The multihost feeding contract is the global
        # mesh (`global_mesh()`).
        raise ValueError(
            f"put_process_local_padded requires the global mesh "
            f"({jax.device_count()} devices), got a {mesh.devices.size}-"
            "device sub-mesh")
    n_pad = padded_row_count(n_logical_rows, mesh)
    lo, hi = local_row_range(n_pad)
    want_logical = min(hi, n_logical_rows) - min(lo, n_logical_rows)
    if local_rows.shape[0] != want_logical:
        raise ValueError(
            f"process {jax.process_index()} must feed rows "
            f"[{lo}, {min(hi, n_logical_rows)}) of the logical array "
            f"({want_logical} rows), got {local_rows.shape[0]}")
    missing = (hi - lo) - local_rows.shape[0]
    if missing:
        block = np.zeros((missing,) + local_rows.shape[1:],
                         dtype=local_rows.dtype)
        local_rows = np.concatenate([local_rows, block], axis=0)
    return (put_process_local(local_rows, n_pad, mesh, axis), n_pad)


def all_processes_ready(tag: str = "barrier") -> None:
    """Cross-host barrier (no-op single-process): collective phases —
    e.g. 'every host finished ingest, start the sharded RQ pass' — must
    not race ahead of slow hosts."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def pod_row_range(n_rows: int, n_processes: int,
                  process_id: int) -> tuple[int, int]:
    """[start, stop) of the rows this process materialises on the pod
    warm path — a plain contiguous ceil deal over PROCESSES, not devices.

    The pod path never builds a cross-process device array (its label
    tail runs replicated on each host's local mesh), so the deal has no
    device-layout constraint to honor; what it must be is a pure function
    of (n_rows, n_processes, process_id) so a failover survivor can
    reconstruct exactly which rows a lost host owned."""
    per = -(-int(n_rows) // max(1, int(n_processes)))
    start = min(int(process_id) * per, int(n_rows))
    return start, min(start + per, int(n_rows))


def fs_exchange(xch_dir: str, tag: str, payload: dict,
                monitor=None, timeout_s: float = 600.0,
                n_processes: int | None = None,
                process_id: int | None = None) -> list:
    """All-to-all host exchange over the shared filesystem: write this
    process's arrays atomically, wait for every peer's, return the
    per-process payload list (pid order).

    This is the pod warm path's data plane — the digest-range-sharded
    signature store already requires a shared root (cluster/store.py), so
    the same root carries the novel-tail exchange; no cross-process XLA
    executable is involved, which the CPU backend cannot run at all and
    which would otherwise hang forever on a dead peer.  The wait polls
    ``monitor`` (resilience.PeerMonitor) between sleeps, so a host that
    stops heartbeating mid-exchange raises HostLostError here instead of
    stalling the pod; ``timeout_s`` is the no-monitor backstop.  The
    exchange doubles as a barrier: returning implies every process
    reached ``tag``.  ``xch_dir`` must be per-run (see
    resilience/coordinator.exchange_dir) — names carry no run identity."""
    from ..observability.tracing import pinned_trace, span
    from ..resilience.watchdog import deadline_clock

    # Explicit identity (the pod plane, which never brings up
    # jax.distributed) wins; the jax identity is the mesh-path default.
    nproc = (int(n_processes) if n_processes is not None
             else jax.process_count())
    pid = int(process_id) if process_id is not None else jax.process_index()
    os.makedirs(xch_dir, exist_ok=True)

    def _path(p: int) -> str:
        return os.path.join(xch_dir, f"{tag}.p{p:03d}.npz")

    wire = {k: np.ascontiguousarray(v) for k, v in payload.items()}
    # Trace context rides the exchange file itself: consumers index the
    # keys they asked for, so the extra array is invisible to them, but a
    # post-mortem on the npz ties it to the run's trace id.
    trace = pinned_trace()
    if trace and "__trace__" not in wire:
        wire["__trace__"] = np.frombuffer(bytes.fromhex(trace),
                                          dtype=np.uint8)
    tmp = _path(pid) + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **wire)
    os.replace(tmp, _path(pid))  # atomic: a peer never reads a torn file
    out: dict[int, dict] = {pid: {k: np.ascontiguousarray(v)
                                  for k, v in payload.items()}}
    deadline = deadline_clock() + float(timeout_s)
    pending = set(range(nproc)) - {pid}
    with span(f"pod.exchange.{tag}", peers=nproc - 1):
        while pending:
            for p in sorted(pending):
                if os.path.exists(_path(p)):
                    with np.load(_path(p)) as z:
                        out[p] = {k: z[k] for k in z.files
                                  if k != "__trace__"}
                    pending.discard(p)
            if not pending:
                break
            if monitor is not None:
                monitor.check(site=f"pod.exchange:{tag}")
            if deadline_clock() > deadline:
                raise TimeoutError(
                    f"pod exchange '{tag}': no payload from process(es) "
                    f"{sorted(pending)} within {timeout_s:.0f}s")
            time.sleep(0.1)
    return [out[p] for p in range(nproc)]
