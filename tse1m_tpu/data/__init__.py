from .columnar import StudyArrays, STUDY_EPOCH
from .synth import SynthSpec, generate_study, synth_session_sets

__all__ = ["StudyArrays", "STUDY_EPOCH", "SynthSpec", "generate_study", "synth_session_sets"]
