"""Bulk columnar extraction: DB -> CSR struct-of-arrays.

This layer kills the reference's N+1 pattern (one query per project inside
Python loops — ``rq1_detection_rate.py:192-201``, ``rq4b_coverage.py:315-326``;
SURVEY.md §2.3): each table is fetched once, ordered by (project, time), and
cut into per-project segments with offset arrays, ready for device-side
segment ops.

Timestamps are kept as int64 nanoseconds on the host (exact pandas parity)
and exposed as int32 *seconds since STUDY_EPOCH* for the device path —
second resolution is far below inter-build spacing (CI builds are hours
apart, reference transcript rq1_detection_rate.py:361 shows ~1.4k
builds/project over ~6 years) and int32 avoids x64-mode penalties on TPU.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field

import numpy as np
import pandas as pd

from ..config import Config
from ..db import queries
from ..db.connection import DB
from ..db.ingest import parse_array
from ..utils.logging import get_logger

log = get_logger("columnar")

STUDY_EPOCH = np.datetime64("2015-01-01T00:00:00", "ns")


def to_epoch_ns(values) -> np.ndarray:
    """Vectorised timestamp decode.  The ISO8601 fast path covers sqlite's
    text timestamps and synth CSVs in one C pass; `mixed` (per-element
    format inference) is only the fallback for heterogeneous or
    driver-native datetime rows (e.g. psycopg2)."""
    ser = values if isinstance(values, pd.Series) else pd.Series(
        list(values), dtype=object)
    if ser.empty:
        return np.empty(0, np.int64)
    try:
        ts = pd.to_datetime(ser, format="ISO8601")
    except (ValueError, TypeError):
        try:
            ts = pd.to_datetime(ser, format="mixed")
        except (ValueError, TypeError):
            # Mixed naive/aware rows: the study's timestamps are all UTC
            # (OSS-Fuzz GCB/issue-tracker times), so interpreting naive
            # rows as UTC is exact, not a guess.
            ts = pd.to_datetime(ser, format="mixed", utc=True)
    if not pd.api.types.is_datetime64_any_dtype(ts):
        # Older pandas returns object dtype for mixed naive/aware rows
        # (with a FutureWarning) instead of raising — same UTC coercion.
        ts = pd.to_datetime(ser, format="mixed", utc=True)
    if getattr(ts.dt, "tz", None) is not None:
        ts = ts.dt.tz_convert("UTC").dt.tz_localize(None)
    return ts.to_numpy().astype("datetime64[ns]").astype(np.int64)


def ns_to_device_s(ns: np.ndarray) -> np.ndarray:
    return ((ns - STUDY_EPOCH.astype(np.int64)) // 1_000_000_000).astype(np.int32)


def ns_to_device_pair(ns: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Split epoch-ns into (seconds-since-STUDY_EPOCH, ns-remainder) int32
    lanes for exact lexicographic time comparison on device without x64."""
    rel = ns - STUDY_EPOCH.astype(np.int64)
    return ((rel // 1_000_000_000).astype(np.int32),
            (rel % 1_000_000_000).astype(np.int32))


def rev_hash(revisions: list[str]) -> np.int64:
    """Deterministic 63-bit hash of a revision list — set equality in RQ3
    (reference compares sets, rq3_diff_coverage_at_detection.py:280) becomes
    an integer comparison.  Computed lazily over the issue-linked subset
    only (see `StudyArrays.fuzz_revhash_at` / `covb_revhash_at`); at the
    reference's 1.19M-build scale an eager per-row hash would dominate the
    extraction stage."""
    digest = hashlib.blake2b(
        ("\x1f".join(sorted(revisions))).encode(), digest_size=8
    ).digest()
    return np.int64(int.from_bytes(digest, "little") >> 1)


def _revhash_at(raw: np.ndarray, idx, memo: dict | None = None) -> np.ndarray:
    """rev_hash of `parse_array(raw[i])` for each i in idx, deduplicated
    through np.unique; `memo` (row index -> hash) persists the work across
    calls — the pandas RQ3 loop asks one row at a time, so without it the
    same coverage build would re-parse for every issue that reaches the
    revision-equality check."""
    idx = np.asarray(idx, dtype=np.int64)
    if not idx.size:
        return np.empty(0, np.int64)
    uniq, inv = np.unique(idx, return_inverse=True)
    if memo is None:
        memo = {}
    hashes = np.empty(uniq.size, dtype=np.int64)
    for k, i in enumerate(uniq):
        key = int(i)
        h = memo.get(key)
        if h is None:
            h = memo[key] = rev_hash(parse_array(raw[key]))
        hashes[k] = h
    return hashes[inv]


def _offsets_from_sorted_codes(codes: np.ndarray, n_segments: int) -> np.ndarray:
    """CSR offsets from a sorted integer code column."""
    return np.searchsorted(codes, np.arange(n_segments + 1)).astype(np.int64)


def _native_db_path(db: DB) -> str | None:
    """File path for the native sqlite decoder, or None when the fast path
    does not apply (Postgres, in-memory DBs).  The decoder opens its own
    read-only connection, so the path must be a real on-disk database."""
    if getattr(db, "dialect", None) != "sqlite":
        return None
    path = getattr(db.config, "sqlite_path", None)
    if not path or path == ":memory:" or not os.path.exists(path):
        return None
    return path


def _native_pg_conninfo(db: DB) -> str | None:
    """libpq conninfo for the native Postgres COPY-binary decoder
    (native/pg_decode.cc), or None off-Postgres.  The decoder opens its
    own connection — same pattern as the sqlite decoder's private
    read-only handle."""
    if getattr(db, "dialect", None) != "postgres":
        return None
    from ..db import pglib

    pg = db.config.postgres
    return pglib.conninfo(pg.database, pg.user, pg.password, pg.host,
                          pg.port)


def _inline_params(sql: str, params) -> str:
    """qmark SQL + params -> literal SQL.  COPY statements cannot take
    out-of-band parameters, so the native pg path inlines them; values
    are study-internal strings/numbers (project names, ISO dates) and
    strings escape by ''-doubling.  The query builders never emit a
    literal '?' in SQL text, so the split is exact."""
    parts = sql.split("?")
    if len(parts) != len(params) + 1:
        raise ValueError("placeholder/param count mismatch")
    out = [parts[0]]
    for p, nxt in zip(params, parts[1:]):
        if p is None:
            lit = "NULL"
        elif isinstance(p, (int, float)):
            lit = str(p)
        else:
            lit = "'" + str(p).replace("'", "''") + "'"
        out.append(lit)
        out.append(nxt)
    return "".join(out)


def _pg_copy_sql(sql: str, params, spec: str) -> str:
    """Wrap a bulk query in COPY ... TO STDOUT (FORMAT binary), aliasing
    the subquery columns positionally and casting text-spec'd columns
    ``::text`` so array columns arrive as their Postgres literal form
    (what parse_array consumes) instead of the binary array layout."""
    inner = _inline_params(sql, params)
    alias = ", ".join(f'"c{i}"' for i in range(len(spec)))
    sel = ", ".join(f'q."c{i}"::text' if sp in "pscubo" else f'q."c{i}"'
                    for i, sp in enumerate(spec))
    # graftlint: disable=sql-interp -- wraps our own already-parameterized bulk query; aliases are generated c0..cN
    return (f"COPY (SELECT {sel} FROM ({inner}) AS q({alias})) "
            "TO STDOUT (FORMAT binary)")


class CodedColumn:
    """Dictionary-encoded text column: int32 codes + object vocab.

    The native decoder's 'c' spec (decode.cc) and the pandas fallback's
    factorize both produce this — ZERO per-row Python objects for the
    heavy interned columns (result, covb modules/revisions), which were
    ~1 s of the 1M-build extraction as object arrays.  Supports exactly
    what consumers need: ``len``, scalar indexing -> str|None (artifact
    writers, lazy revhash), and slice/fancy indexing -> CodedColumn (the
    CSR re-sort and ``Segmented.segment``).  Code -1 = NULL."""

    __slots__ = ("codes", "vocab")

    def __init__(self, codes: np.ndarray, vocab: np.ndarray):
        self.codes = np.asarray(codes, dtype=np.int32)
        self.vocab = np.asarray(vocab, dtype=object)

    def __len__(self) -> int:
        return int(self.codes.size)

    def __getitem__(self, i):
        if isinstance(i, (int, np.integer)):
            c = int(self.codes[i])
            return None if c < 0 else self.vocab[c]
        return CodedColumn(self.codes[i], self.vocab)

    def materialize(self) -> np.ndarray:
        """Object-array form (None for NULL) — for rare full-column uses."""
        padded = np.append(self.vocab, None)  # code -1 -> last slot
        return padded[self.codes]


class BytesColumn:
    """Lazy text column: one shared uint8 arena + per-row (start, len).

    The native decoder's 'b' spec — near-unique columns (build names,
    fuzz modules/revisions) whose ~1M-per-table PyUnicode materialisations
    dominated the extraction wall, while consumers (artifact writers, the
    lazy revhash) touch only tiny subsets.  Cells decode on scalar access;
    slice/fancy indexing shares the arena.  len -1 = NULL."""

    __slots__ = ("arena", "starts", "lens")

    def __init__(self, arena: np.ndarray, starts: np.ndarray,
                 lens: np.ndarray):
        self.arena = np.asarray(arena, dtype=np.uint8)
        self.starts = np.asarray(starts, dtype=np.int64)
        self.lens = np.asarray(lens, dtype=np.int32)

    @classmethod
    def from_objects(cls, vals) -> "BytesColumn":
        """Fallback-path constructor from str|None cells (raises
        AttributeError on non-str cells, e.g. driver-native lists —
        callers keep the object array then)."""
        n = len(vals)
        starts = np.empty(n, np.int64)
        lens = np.empty(n, np.int32)
        parts = []
        pos = 0
        for i, v in enumerate(vals):
            if v is None:
                starts[i] = 0   # matches the native scan's {0, -1} NULLs
                lens[i] = -1
            else:
                b = v.encode("utf-8")
                parts.append(b)
                starts[i] = pos
                lens[i] = len(b)
                pos += len(b)
        arena = np.frombuffer(b"".join(parts), dtype=np.uint8)
        return cls(arena, starts, lens)

    def __len__(self) -> int:
        return int(self.starts.size)

    def __getitem__(self, i):
        if isinstance(i, (int, np.integer)):
            ln = int(self.lens[i])
            if ln < 0:
                return None
            s = int(self.starts[i])
            return self.arena[s:s + ln].tobytes().decode("utf-8")
        return BytesColumn(self.arena, self.starts[i], self.lens[i])

    def materialize(self) -> np.ndarray:
        """Object-array form — for rare full-column uses."""
        return np.array([self[i] for i in range(len(self))], dtype=object)


@dataclass
class Segmented:
    """One table's per-project CSR view."""

    offsets: np.ndarray  # [P+1] int64
    columns: dict[str, np.ndarray] = field(default_factory=dict)

    def segment(self, p: int) -> dict[str, np.ndarray]:
        lo, hi = self.offsets[p], self.offsets[p + 1]
        return {k: v[lo:hi] for k, v in self.columns.items()}

    def counts(self) -> np.ndarray:
        return np.diff(self.offsets)

    def __len__(self) -> int:
        return int(self.offsets[-1])


@dataclass
class StudyArrays:
    projects: list[str]
    # fuzz/covb keep modules/revisions as raw DB text — parsed and hashed
    # lazily over the small subsets that need them (fuzz_revhash_at /
    # covb_revhash_at, artifact writers).
    fuzz: Segmented       # columns: time_ns, name, result, ok,
    #                                modules_raw, revisions_raw
    covb: Segmented       # columns: time_ns, result, ok, modules_raw,
    #                                revisions_raw, grouphash (no name —
    #                                nothing consumes coverage-build names)
    issues: Segmented     # columns: time_ns, number, status, crash_type
    cov: Segmented        # columns: date_ns, coverage, covered, total

    @property
    def n_projects(self) -> int:
        return len(self.projects)

    def project_index(self) -> dict[str, int]:
        return {p: i for i, p in enumerate(self.projects)}

    # -- construction ------------------------------------------------------

    @classmethod
    def from_db(cls, db: DB, cfg: Config, projects: list[str] | None = None) -> "StudyArrays":
        if projects is None:
            sql, params = queries.eligible_projects(cfg.min_coverage_days, cfg.limit_date)
            projects = [r[0] for r in db.query(sql, params)]
        projects = sorted(projects)
        log.info("extracting %d eligible projects", len(projects))
        pidx = {p: i for i, p in enumerate(projects)}
        from ..config import RESULT_OK

        native_path = _native_db_path(db)
        native_fetches = 0

        # Table plan: (query, column names, decode spec).  The native
        # decoder's sqlite scan runs with the GIL released (decode.cc
        # phase 1); fetches run serially — a thread pool was measured NOT
        # to pay on this host.  Spec choices: near-unique fuzz text (name,
        # modules, revisions) rides 'b' (lazy bytes arena — zero per-row
        # Python objects; consumers touch only issue-linked subsets);
        # low-cardinality text (result, covb's repeated group keys) rides
        # 'c' (dictionary codes + vocab, also object-free).
        plus1 = str(np.datetime64(cfg.limit_date) + np.timedelta64(1, "D"))
        plan = {
            "fuzz": (queries.all_fuzzing_builds_bulk(projects),
                     ["project", "name", "timecreated", "result",
                      "modules", "revisions"], "pbtcbb"),
            "covb": (queries.coverage_builds_bulk(projects),
                     ["project", "timecreated", "modules",
                      "revisions", "result"], "ptccc"),
            "issues": (queries.issues_bulk(projects, cfg.limit_date,
                                           fixed_only=True),
                       ["project", "number", "rts", "status", "crash_type",
                        "severity"], "potsss"),
            "cov": (queries.total_coverage_bulk(projects, plus1),
                    ["project", "date", "coverage", "covered", "total"],
                    "ptfff"),
        }
        prefetched: dict = {}
        if native_path is not None:
            from ..native import fetch_table

            for k, ((sql, params), _cols, spec) in plan.items():
                try:
                    prefetched[k] = fetch_table(native_path, sql, params,
                                                spec, projects)
                except RuntimeError as e:
                    # Strict native parsers reject rather than guess
                    # (timezone suffixes, non-text timestamps, ...).
                    log.info("native decode fell back (%s): %s", k, e)
                    prefetched[k] = None
        elif (pg_conninfo := _native_pg_conninfo(db)) is not None:
            # Postgres: stream each bulk query as COPY binary through the
            # native decoder (pg_decode.cc) — the reference's real
            # topology (dbFile.py:26-38) gets the same object-free
            # extraction the sqlite path has.
            from ..native import fetch_table_pg

            for k, ((sql, params), _cols, spec) in plan.items():
                try:
                    prefetched[k] = fetch_table_pg(
                        pg_conninfo, _pg_copy_sql(sql, params, spec), spec,
                        projects)
                except RuntimeError as e:
                    log.info("native pg decode fell back (%s): %s", k, e)
                    prefetched[k] = None

        def fetch(table):
            """One bulk query -> {col: array} sorted by our project codes.

            Spec chars (see native/decode.cc): 'p' project->code, 't'
            ISO8601 text->int64 ns, 'f' float64, 's' interned text, 'c'
            dictionary codes+vocab (CodedColumn), 'u' text, 'b' lazy bytes
            (BytesColumn), 'o' as-stored.  The native decoder handles the
            whole row loop in C++ when available; the pandas fallback below
            produces byte-identical arrays/columns (asserted by
            tests/test_native_decode.py).
            Everything after this is column-wise — no per-row Python at the
            1.19M-build scale.

            The stable re-sort exists because SQL ORDER BY project uses the
            engine's collation, which may disagree with Python's code-point
            sort (e.g. glibc locale collations ignore '-' at primary
            weight); within-project time order from SQL is preserved by the
            stable sort."""
            nonlocal native_fetches
            (sql, params), cols, spec = plan[table]
            out = None
            raw = prefetched.get(table)
            if raw is not None:
                out = {}
                for c, sp, v in zip(cols, spec, raw):
                    if sp == "c":
                        out[c] = CodedColumn(*v)
                    elif sp == "b":
                        out[c] = BytesColumn(*v)
                    else:
                        out[c] = v
                native_fetches += 1
            if out is None:
                rows = db.query(sql, params)
                df = pd.DataFrame(rows, columns=cols, dtype=object)
                out = {}
                for c, sp in zip(cols, spec):
                    if sp == "p":
                        out[c] = (df[c].map(pidx).to_numpy(dtype=np.int64)
                                  if len(df) else np.empty(0, np.int64))
                    elif sp == "t":
                        out[c] = to_epoch_ns(df[c])
                    elif sp == "f":
                        out[c] = df[c].astype(np.float64).to_numpy()
                    elif sp == "c":
                        ser = df[c]
                        try:
                            codes, uniq = pd.factorize(ser,
                                                       use_na_sentinel=True)
                        except TypeError:
                            # Driver-native rows (psycopg2 TEXT[] -> list)
                            # are unhashable; tuples keep the original
                            # values in the vocab (parse_array accepts
                            # tuples), unlike a lossy str() projection.
                            ser = ser.map(lambda v: tuple(v)
                                          if isinstance(v, list) else v)
                            codes, uniq = pd.factorize(ser,
                                                       use_na_sentinel=True)
                        out[c] = CodedColumn(codes,
                                             np.asarray(uniq, dtype=object))
                    elif sp == "b":
                        vals = df[c].to_numpy(dtype=object)
                        try:
                            out[c] = BytesColumn.from_objects(vals)
                        except AttributeError:
                            # Driver-native rows (psycopg2 TEXT[] lists):
                            # keep the original objects — consumers index
                            # scalars and parse_array accepts lists.
                            out[c] = vals
                    else:
                        out[c] = df[c].to_numpy(dtype=object)
            codes = out.pop(cols[0]).astype(np.int64, copy=False)
            order = np.argsort(codes, kind="stable")
            return ({c: v[order] for c, v in out.items()}, codes[order])

        def ok_mask(result_col: CodedColumn) -> np.ndarray:
            # result is a 'c' fetch on both the native and fallback paths,
            # so the vocabulary test covers the whole column.
            ok_vocab = np.isin(result_col.vocab, list(RESULT_OK))
            c = result_col.codes
            good = np.zeros(c.size, dtype=bool)
            valid = c >= 0
            good[valid] = ok_vocab[c[valid]]
            return good

        # Fuzzing builds (bulk; replaces ALL_FUZZING_BUILD per-project loop).
        ftb, fcodes = fetch("fuzz")
        fuzz = Segmented(
            offsets=_offsets_from_sorted_codes(fcodes, len(projects)),
            columns={
                "time_ns": ftb["timecreated"],
                "name": ftb["name"],
                "result": ftb["result"],
                "ok": ok_mask(ftb["result"]),
                # Raw DB values; only the small issue-linked subset is ever
                # parsed/hashed (fuzz_revhash_at, artifact writers).
                "modules_raw": ftb["modules"],
                "revisions_raw": ftb["revisions"],
            },
        )

        # Coverage builds (all results).  The RQ2 group key — equality of
        # the exact (modules, revisions) string pair, the reference's
        # shift/cumsum key rq2_coverage_and_added.py:129 — is a factorize
        # per raw column with the two code columns combined into one int64:
        # code equality IS string equality per column (no hash collisions),
        # and pair-of-codes equality IS pair equality.  (Round 4: the
        # previous str.cat of the two columns allocated 713k concatenated
        # strings — ~0.5 s of the extraction wall at the 1M-build scale.)
        ctb, ccodes = fetch("covb")

        if len(ccodes):
            # The 'c' fetches already ARE the factorization; +1 folds NULL
            # (-1) into its own non-negative group.
            cm = ctb["modules"].codes.astype(np.int64) + 1
            cr = ctb["revisions"].codes.astype(np.int64) + 1
            ghash = cm * (int(cr.max()) + 1) + cr
        else:
            ghash = np.empty(0, np.int64)
        covb = Segmented(
            offsets=_offsets_from_sorted_codes(ccodes, len(projects)),
            columns={
                "time_ns": ctb["timecreated"],
                "result": ctb["result"],
                "ok": ok_mask(ctb["result"]),
                # Raw, like fuzz: RQ3 hashes only detection candidates
                # (covb_revhash_at); RQ2 artifacts parse only boundary rows.
                "modules_raw": ctb["modules"],
                "revisions_raw": ctb["revisions"],
                "grouphash": ghash,
            },
        )

        # Fixed issues before the cutoff.
        itb, icodes = fetch("issues")
        issues = Segmented(
            offsets=_offsets_from_sorted_codes(icodes, len(projects)),
            columns={
                "time_ns": itb["rts"],
                "number": itb["number"],
                "status": itb["status"],
                "crash_type": itb["crash_type"],
            },
        )

        # Daily coverage rows up to limit_date + 1 day: RQ3 reads the
        # boundary day (rq3:263 fetches DATE(date) < limit + 1); every other
        # consumer masks date_ns < limit back down to the study cutoff.
        # 'f' decode parity note: .astype/float64 (not errors="coerce") —
        # None -> NaN but a malformed value still raises, so ingest
        # corruption fails loudly instead of leaking NaNs into RQ results;
        # the native decoder types these columns REAL at the sqlite level.
        vtb, vcodes = fetch("cov")
        cov = Segmented(
            offsets=_offsets_from_sorted_codes(vcodes, len(projects)),
            columns={
                "date_ns": vtb["date"],
                "coverage": vtb["coverage"],
                "covered": vtb["covered"],
                "total": vtb["total"],
            },
        )

        log.info("columnar: %d fuzz builds, %d coverage builds, %d issues, %d coverage days",
                 len(fuzz), len(covb), len(issues), len(cov))
        arrays = cls(projects=projects, fuzz=fuzz, covb=covb, issues=issues,
                     cov=cov)
        # True only when every fetch actually went through the C++ decoder
        # — consumers (bench.py) report which path produced their timings.
        arrays.native_decode = native_fetches == 4
        return arrays

    def fuzz_revhash_at(self, idx: np.ndarray) -> np.ndarray:
        """Revision-set hashes for the given fuzz-row indices.

        Fuzz revisions are kept raw (from_db comment); RQ3 compares
        revision sets only for the handful of issue-linked builds
        (rq3_diff_coverage_at_detection.py:280), so hashing on demand over
        the gathered rows avoids a ~1M-row parse at extraction.  Results
        are memoized per row index."""
        if not hasattr(self, "_fuzz_revhash_memo"):
            self._fuzz_revhash_memo: dict = {}
        return _revhash_at(self.fuzz.columns["revisions_raw"], idx,
                           self._fuzz_revhash_memo)

    def covb_revhash_at(self, idx: np.ndarray) -> np.ndarray:
        """Revision-set hashes for the given coverage-build rows — the
        other side of RQ3's set-equality check, same lazy/memoized contract
        as `fuzz_revhash_at`."""
        if not hasattr(self, "_covb_revhash_memo"):
            self._covb_revhash_memo: dict = {}
        return _revhash_at(self.covb.columns["revisions_raw"], idx,
                           self._covb_revhash_memo)

    # -- device views ------------------------------------------------------

    def device_times(self) -> dict[str, np.ndarray]:
        """int32-seconds views for the jax backend."""
        return {
            "fuzz_times_s": ns_to_device_s(self.fuzz.columns["time_ns"]),
            "fuzz_offsets": self.fuzz.offsets,
            "issue_times_s": ns_to_device_s(self.issues.columns["time_ns"]),
            "issue_offsets": self.issues.offsets,
            "covb_times_s": ns_to_device_s(self.covb.columns["time_ns"]),
            "covb_offsets": self.covb.offsets,
        }
