"""Bulk columnar extraction: DB -> CSR struct-of-arrays.

This layer kills the reference's N+1 pattern (one query per project inside
Python loops — ``rq1_detection_rate.py:192-201``, ``rq4b_coverage.py:315-326``;
SURVEY.md §2.3): each table is fetched once, ordered by (project, time), and
cut into per-project segments with offset arrays, ready for device-side
segment ops.

Timestamps are kept as int64 nanoseconds on the host (exact pandas parity)
and exposed as int32 *seconds since STUDY_EPOCH* for the device path —
second resolution is far below inter-build spacing (CI builds are hours
apart, reference transcript rq1_detection_rate.py:361 shows ~1.4k
builds/project over ~6 years) and int32 avoids x64-mode penalties on TPU.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np
import pandas as pd

from ..config import Config
from ..db import queries
from ..db.connection import DB
from ..db.ingest import parse_array
from ..utils.logging import get_logger

log = get_logger("columnar")

STUDY_EPOCH = np.datetime64("2015-01-01T00:00:00", "ns")


def to_epoch_ns(values) -> np.ndarray:
    return pd.to_datetime(list(values), format="mixed").values.astype("datetime64[ns]").astype(np.int64)


def ns_to_device_s(ns: np.ndarray) -> np.ndarray:
    return ((ns - STUDY_EPOCH.astype(np.int64)) // 1_000_000_000).astype(np.int32)


def ns_to_device_pair(ns: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Split epoch-ns into (seconds-since-STUDY_EPOCH, ns-remainder) int32
    lanes for exact lexicographic time comparison on device without x64."""
    rel = ns - STUDY_EPOCH.astype(np.int64)
    return ((rel // 1_000_000_000).astype(np.int32),
            (rel % 1_000_000_000).astype(np.int32))


def rev_hash(revisions: list[str]) -> np.int64:
    """Deterministic 63-bit hash of a revision list — set equality in RQ3
    (reference compares sets, rq3_diff_coverage_at_detection.py:280) becomes
    an integer comparison precomputed at extraction."""
    digest = hashlib.blake2b(
        ("\x1f".join(sorted(revisions))).encode(), digest_size=8
    ).digest()
    return np.int64(int.from_bytes(digest, "little") >> 1)


def group_hash(modules_raw, revisions_raw) -> np.int64:
    """63-bit hash of the exact (modules, revisions) string combination —
    the RQ2 change-point group key (the reference concatenates the two
    column strings, rq2_coverage_and_added.py:129); consecutive-equality
    checks become integer compares."""
    digest = hashlib.blake2b(
        (str(modules_raw) + "\x1e" + str(revisions_raw)).encode(),
        digest_size=8,
    ).digest()
    return np.int64(int.from_bytes(digest, "little") >> 1)


def _offsets_from_sorted_codes(codes: np.ndarray, n_segments: int) -> np.ndarray:
    """CSR offsets from a sorted integer code column."""
    return np.searchsorted(codes, np.arange(n_segments + 1)).astype(np.int64)


@dataclass
class Segmented:
    """One table's per-project CSR view."""

    offsets: np.ndarray  # [P+1] int64
    columns: dict[str, np.ndarray] = field(default_factory=dict)

    def segment(self, p: int) -> dict[str, np.ndarray]:
        lo, hi = self.offsets[p], self.offsets[p + 1]
        return {k: v[lo:hi] for k, v in self.columns.items()}

    def counts(self) -> np.ndarray:
        return np.diff(self.offsets)

    def __len__(self) -> int:
        return int(self.offsets[-1])


@dataclass
class StudyArrays:
    projects: list[str]
    fuzz: Segmented       # columns: time_ns, name
    covb: Segmented       # columns: time_ns, revhash, name, modules, revisions
    issues: Segmented     # columns: time_ns, number, crash_type, status
    cov: Segmented        # columns: date_ns, coverage, covered, total

    @property
    def n_projects(self) -> int:
        return len(self.projects)

    def project_index(self) -> dict[str, int]:
        return {p: i for i, p in enumerate(self.projects)}

    # -- construction ------------------------------------------------------

    @classmethod
    def from_db(cls, db: DB, cfg: Config, projects: list[str] | None = None) -> "StudyArrays":
        if projects is None:
            sql, params = queries.eligible_projects(cfg.min_coverage_days, cfg.limit_date)
            projects = [r[0] for r in db.query(sql, params)]
        projects = sorted(projects)
        log.info("extracting %d eligible projects", len(projects))
        pidx = {p: i for i, p in enumerate(projects)}

        def order_rows(rows):
            """SQL ORDER BY project uses the engine's collation, which may
            disagree with Python's code-point sort (e.g. glibc locale
            collations ignore '-' at primary weight) — re-sort stably by our
            project codes so CSR offsets are always correct; within-project
            time order from SQL is preserved by the stable sort."""
            if not rows:
                return rows, np.empty(0, dtype=np.int64)
            codes = np.array([pidx[r[0]] for r in rows], dtype=np.int64)
            order = np.argsort(codes, kind="stable")
            return [rows[i] for i in order], codes[order]

        # Fuzzing builds (bulk; replaces ALL_FUZZING_BUILD per-project loop).
        sql, params = queries.all_fuzzing_builds_bulk(projects)
        rows, fcodes = order_rows(db.query(sql, params))
        from ..config import RESULT_OK

        fuzz = Segmented(
            offsets=_offsets_from_sorted_codes(fcodes, len(projects)),
            columns={
                "time_ns": to_epoch_ns([r[2] for r in rows]) if rows else np.empty(0, np.int64),
                "name": np.array([r[1] for r in rows], dtype=object),
                "result": np.array([r[3] for r in rows], dtype=object),
                "ok": np.array([r[3] in RESULT_OK for r in rows], dtype=bool),
                # Raw DB values; only the small linked subset is ever parsed
                # (at artifact-write time) — avoid eagerly parsing ~1M rows.
                "modules_raw": np.array([r[4] for r in rows], dtype=object),
                "revisions_raw": np.array([r[5] for r in rows], dtype=object),
            },
        )

        # Coverage builds (all results) with precomputed revision-set hashes.
        sql, params = queries.coverage_builds_bulk(projects)
        rows, ccodes = order_rows(db.query(sql, params))
        revs = [parse_array(r[4]) for r in rows]
        covb = Segmented(
            offsets=_offsets_from_sorted_codes(ccodes, len(projects)),
            columns={
                "time_ns": to_epoch_ns([r[2] for r in rows]) if rows else np.empty(0, np.int64),
                "name": np.array([r[1] for r in rows], dtype=object),
                "modules": np.array([parse_array(r[3]) for r in rows], dtype=object),
                "revisions": np.array(revs, dtype=object),
                "result": np.array([r[5] for r in rows], dtype=object),
                "ok": np.array([r[5] in RESULT_OK for r in rows], dtype=bool),
                "revhash": np.array([rev_hash(r) for r in revs], dtype=np.int64)
                if rows else np.empty(0, np.int64),
                "grouphash": np.array([group_hash(r[3], r[4]) for r in rows],
                                      dtype=np.int64)
                if rows else np.empty(0, np.int64),
            },
        )

        # Fixed issues before the cutoff.
        sql, params = queries.issues_bulk(projects, cfg.limit_date, fixed_only=True)
        rows, icodes = order_rows(db.query(sql, params))
        issues = Segmented(
            offsets=_offsets_from_sorted_codes(icodes, len(projects)),
            columns={
                "time_ns": to_epoch_ns([r[2] for r in rows]) if rows else np.empty(0, np.int64),
                "number": np.array([r[1] for r in rows], dtype=object),
                "status": np.array([r[3] for r in rows], dtype=object),
                "crash_type": np.array([r[4] for r in rows], dtype=object),
            },
        )

        # Daily coverage rows up to limit_date + 1 day: RQ3 reads the
        # boundary day (rq3:263 fetches DATE(date) < limit + 1); every other
        # consumer masks date_ns < limit back down to the study cutoff.
        plus1 = str(np.datetime64(cfg.limit_date) + np.timedelta64(1, "D"))
        sql, params = queries.total_coverage_bulk(projects, plus1)
        rows, vcodes = order_rows(db.query(sql, params))
        cov = Segmented(
            offsets=_offsets_from_sorted_codes(vcodes, len(projects)),
            columns={
                "date_ns": to_epoch_ns([r[1] for r in rows]) if rows else np.empty(0, np.int64),
                "coverage": np.array([r[2] if r[2] is not None else np.nan
                                      for r in rows], dtype=np.float64),
                "covered": np.array([r[3] if r[3] is not None else np.nan for r in rows],
                                    dtype=np.float64),
                "total": np.array([r[4] if r[4] is not None else np.nan for r in rows],
                                  dtype=np.float64),
            },
        )

        log.info("columnar: %d fuzz builds, %d coverage builds, %d issues, %d coverage days",
                 len(fuzz), len(covb), len(issues), len(cov))
        return cls(projects=projects, fuzz=fuzz, covb=covb, issues=issues, cov=cov)

    def fuzz_revhash_at(self, idx: np.ndarray) -> np.ndarray:
        """Revision-set hashes for the given fuzz-row indices.

        Fuzz revisions are kept raw (columnar comment above); RQ3 compares
        revision sets only for the handful of issue-linked builds
        (rq3_diff_coverage_at_detection.py:280), so hashing on demand over
        the gathered rows avoids a ~1M-row parse at extraction."""
        idx = np.asarray(idx, dtype=np.int64)
        raw = self.fuzz.columns["revisions_raw"]
        uniq, inv = np.unique(idx, return_inverse=True)
        hashes = np.array([rev_hash(parse_array(raw[i])) for i in uniq],
                          dtype=np.int64)
        return hashes[inv] if idx.size else np.empty(0, np.int64)

    # -- device views ------------------------------------------------------

    def device_times(self) -> dict[str, np.ndarray]:
        """int32-seconds views for the jax backend."""
        return {
            "fuzz_times_s": ns_to_device_s(self.fuzz.columns["time_ns"]),
            "fuzz_offsets": self.fuzz.offsets,
            "issue_times_s": ns_to_device_s(self.issues.columns["time_ns"]),
            "issue_offsets": self.issues.offsets,
            "covb_times_s": ns_to_device_s(self.covb.columns["time_ns"]),
            "covb_offsets": self.covb.offsets,
        }
