"""Synthetic study-data generator.

The reference's real dataset (~1.19M builds, 72k issues) ships as a
gitignored SQL dump absent from the snapshot (reference ``.gitignore:6-7``),
so both tests and benchmarks need statistically similar synthetic data
(SURVEY.md §7.3).  Two generators:

- :func:`generate_study` — a full relational fixture (five tables + corpus
  analysis CSV) whose shapes follow the paper: detection rate decaying from
  ~35% at session 1 toward a ~2% late-stage floor
  (rq1_detection_rate.py:373-407), saturating coverage trends, revision
  change-points every few days, corpus groups G1..G4
  (rq4a_bug.py:82-121).
- :func:`synth_session_sets` — per-session coverage feature *sets* with
  planted near-duplicate cluster structure for the MinHash/LSH north star
  (BASELINE.json configs), scalable to 1M+ sessions.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import pandas as pd

_CRASH_TYPES = [
    "Heap-buffer-overflow READ", "Heap-buffer-overflow WRITE", "Use-after-free READ",
    "Stack-buffer-overflow READ", "Null-dereference READ", "UNKNOWN READ",
    "Timeout", "Out-of-memory", "Abrt", "Integer-overflow",
]
_SEVERITIES = ["High", "Medium", "Low"]
_LANGUAGES = ["c++", "c", "python", "rust", "go", "jvm", "swift"]
_STATUS_OTHER = ["New", "Duplicate", "WontFix", "Invalid"]


@dataclass
class SynthSpec:
    n_projects: int = 24
    days: int = 450
    start: str = "2023-06-01"
    seed: int = 0
    # Mean fuzzing builds per project per day (Poisson).
    fuzz_rate: float = 1.4
    # Fraction of projects given < 365 coverage days (ineligible).
    ineligible_fraction: float = 0.15
    # Detection-rate decay: p(session) = a * session^-k, floored.
    detect_a: float = 0.35
    detect_k: float = 0.75
    detect_floor: float = 0.02
    # Revision change cadence (days) for coverage builds.
    revision_period: int = 3
    # Corpus group fractions (G1 none, G2 initial, G3 1-7d, G4 >=7d).
    corpus_fractions: tuple = (0.40, 0.30, 0.15, 0.15)


@dataclass
class SynthStudy:
    project_info: pd.DataFrame
    buildlog_data: pd.DataFrame
    total_coverage: pd.DataFrame
    issues: pd.DataFrame
    corpus_analysis: pd.DataFrame
    spec: SynthSpec = field(repr=False, default=None)

    def to_csv_dir(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        self.project_info.to_csv(f"{path}/project_info.csv", index=False)
        self.buildlog_data.to_csv(f"{path}/buildlog_data.csv", index=False)
        self.total_coverage.to_csv(f"{path}/total_coverage.csv", index=False)
        self.issues.to_csv(f"{path}/issues.csv", index=False)
        self.corpus_analysis.to_csv(f"{path}/project_corpus_analysis.csv", index=False)

    def to_db(self, db) -> None:
        from ..db.ingest import (derive_projects, load_buildlog_data, load_issues,
                                 load_project_info, load_total_coverage)
        from ..db.schema import create_schema

        create_schema(db)
        load_project_info(db, self.project_info.to_dict("records"))
        load_buildlog_data(db, self.buildlog_data.to_dict("records"))
        load_total_coverage(db, self.total_coverage.to_dict("records"))
        load_issues(db, self.issues.to_dict("records"))
        derive_projects(db)


def _fmt_ts(ts: np.ndarray) -> np.ndarray:
    return np.datetime_as_string(ts.astype("datetime64[s]"), unit="s")


def generate_study(spec: SynthSpec | None = None) -> SynthStudy:
    spec = spec or SynthSpec()
    rng = np.random.default_rng(spec.seed)
    start = np.datetime64(spec.start)

    proj_rows, build_rows, cov_rows, issue_rows, corpus_rows = [], [], [], [], []
    issue_counter = 10000
    group_labels = rng.choice(4, size=spec.n_projects, p=list(spec.corpus_fractions))

    for p in range(spec.n_projects):
        name = f"proj{p:03d}"
        ineligible = rng.random() < spec.ineligible_fraction
        n_days = int(rng.integers(60, 300)) if ineligible else spec.days
        day0 = start + np.timedelta64(int(rng.integers(0, 30)), "D")
        first_commit = day0 - np.timedelta64(int(rng.integers(200, 2000)), "D")
        proj_rows.append({
            "project": name,
            "first_commit_datetime": str(first_commit) + " 00:00:00",
            "language": rng.choice(_LANGUAGES),
            "homepage": f"https://example.org/{name}",
            "main_repo": f"https://github.com/example/{name}",
            "primary_contact": f"{name}@example.org",
        })

        # Coverage trend: saturating curve with noise; a few projects decline.
        c0 = rng.uniform(0.15, 0.45)
        c1 = rng.uniform(0.5, 0.9)
        tau = rng.uniform(60, 200)
        declining = rng.random() < 0.1
        total_lines0 = rng.integers(5_000, 80_000)

        session_idx = 0
        build_serial = 0
        rev_sha = None
        rev_serial = 0
        # G4 corpus introduced at a build index >= ~10; G3 within 1-7 days.
        group = int(group_labels[p])
        corpus_build_idx = None
        if group == 3:
            corpus_build_idx = int(rng.integers(10, 120))
        introduced_day = None

        for d in range(n_days):
            day = day0 + np.timedelta64(d, "D")
            if d % spec.revision_period == 0 or rev_sha is None:
                rev_sha = "".join(rng.choice(list("0123456789abcdef"), 40))
                # Serial advances with the source revision, so all builds in
                # one revision period share the exact revision set — the
                # property RQ2's change-point grouping and RQ3's
                # fuzz-vs-coverage revision-equality gate both key on.
                rev_serial = 350000 + d * 100

            # Fuzzing builds.
            k = rng.poisson(spec.fuzz_rate)
            if d == 0:
                k = max(k, 1)
            hours = np.sort(rng.uniform(0, 23, size=k))
            for h in hours:
                session_idx += 1
                build_serial += 1
                ts = day + np.timedelta64(int(h * 3600), "s")
                r = rng.random()
                result = "Finish" if r < 0.90 else ("Halfway" if r < 0.95 else "Error")
                build_rows.append({
                    "name": f"log-{name}-{build_serial:07d}.txt",
                    "project": name,
                    "timecreated": str(ts.astype("datetime64[s]")).replace("T", " "),
                    "build_type": "Fuzzing",
                    "result": result,
                    "modules": "{" + name + ",libfuzzer}",
                    "revisions": "{" + rev_sha + "," + str(rev_serial) + "}",
                })
                if corpus_build_idx is not None and session_idx == corpus_build_idx:
                    introduced_day = d
                # Issue detection decaying with session index.
                p_detect = max(spec.detect_a * session_idx ** -spec.detect_k,
                               spec.detect_floor)
                if rng.random() < p_detect:
                    issue_counter += 1
                    rts = ts + np.timedelta64(int(rng.uniform(1, 20) * 3600), "s")
                    fixed = rng.random() < 0.82
                    status = ("Fixed" if rng.random() < 0.5 else "Fixed (Verified)") \
                        if fixed else rng.choice(_STATUS_OTHER)
                    regressed = "{" + f"{name}-regress-{build_serial}" + "}" \
                        if rng.random() < 0.6 else ""
                    issue_rows.append({
                        "project": name,
                        "number": str(issue_counter),
                        "rts": str(rts.astype("datetime64[s]")).replace("T", " "),
                        "status": status,
                        "crash_type": rng.choice(_CRASH_TYPES),
                        "severity": rng.choice(_SEVERITIES),
                        "type": "Vulnerability" if rng.random() < 0.5 else "Bug",
                        "regressed_build": regressed,
                        "new_id": str(42000000 + issue_counter),
                    })

            # Daily coverage build (same revision set as that day's fuzz builds).
            build_serial += 1
            cov_ts = day + np.timedelta64(13 * 3600 + 11 * 60 + int(rng.integers(0, 60)), "s")
            build_rows.append({
                "name": f"log-{name}-{build_serial:07d}.txt",
                "project": name,
                "timecreated": str(cov_ts.astype("datetime64[s]")).replace("T", " "),
                "build_type": "Coverage",
                # Mix in 'Halfway' so the canonical RESULT_OK handling (vs
                # the reference's 'HalfWay' typo) is actually exercised.
                "result": ("Finish" if (cr := rng.random()) < 0.92
                           else ("Halfway" if cr < 0.97 else "Error")),
                "modules": "{" + name + ",libfuzzer}",
                "revisions": "{" + rev_sha + "," + str(rev_serial) + "}",
            })

            # Daily coverage report row.
            t = d / tau
            frac = c0 + (c1 - c0) * (1 - np.exp(-t))
            if declining:
                frac = c1 - (c1 - c0) * (1 - np.exp(-t))
            frac = float(np.clip(frac + rng.normal(0, 0.01), 0.01, 0.99))
            total_line = float(total_lines0 + d * rng.integers(0, 12))
            cov_rows.append({
                "project": name,
                "date": str(day),
                "coverage": round(frac * 100, 4),
                "covered_line": float(round(frac * total_line)),
                "total_line": total_line,
            })

        # Corpus-analysis record in C8's exact CSV schema
        # (user_corpus.py:225-233): rq4a groups on time_elapsed_seconds
        # (NaN -> G1, 0 -> G2, <7d -> G3, >=7d -> G4, rq4a_bug.py:97-100)
        # and reads corpus_commit_time for G4 (rq4a_bug.py:117).
        if group == 0:
            elapsed_s = None
        elif group == 1:
            elapsed_s = 0.0
        elif group == 2:
            elapsed_s = float(rng.uniform(1, 7)) * 86400.0
        else:
            delay_days = float(introduced_day if introduced_day is not None
                               else rng.uniform(7, 60))
            elapsed_s = max(delay_days, 7.0) * 86400.0
        commit_time = ("" if elapsed_s is None else str(
            (day0 + np.timedelta64(int(elapsed_s), "s")).astype("datetime64[s]")
        ).replace("T", " "))
        corpus_rows.append({
            "project_name": name,
            "is_Corpus": elapsed_s is not None,
            "corpus_commit_time": commit_time,
            "corpus_merged_time": "",
            "project_creation_time": str(day0) + " 00:00:00",
            "time_elapsed_seconds": elapsed_s if elapsed_s is not None else "",
            "merged_time_elapsed_seconds": "",
        })

    return SynthStudy(
        project_info=pd.DataFrame(proj_rows),
        buildlog_data=pd.DataFrame(build_rows),
        total_coverage=pd.DataFrame(cov_rows),
        issues=pd.DataFrame(issue_rows),
        corpus_analysis=pd.DataFrame(corpus_rows),
        spec=spec,
    )


def synth_session_sets(
    n_sessions: int,
    set_size: int = 64,
    universe: int = 1 << 24,
    dup_fraction: float = 0.6,
    mean_cluster_size: float = 8.0,
    mutate_prob: float = 0.05,
    seed: int = 0,
    dtype=np.uint32,
) -> tuple[np.ndarray, np.ndarray]:
    """Planted near-duplicate session coverage sets.

    Returns (items [N, set_size] uint32, labels [N] int64).  ``dup_fraction``
    of sessions belong to multi-member clusters whose members share a base
    set with ~``mutate_prob`` of items replaced (expected Jaccard ~0.9);
    the rest are singletons.  Fully vectorised — generates 1M x 64 in ~1 s.
    """
    rng = np.random.default_rng(seed)
    n_dup = int(n_sessions * dup_fraction)
    n_clusters = max(1, int(n_dup / mean_cluster_size))

    labels = np.empty(n_sessions, dtype=np.int64)
    labels[:n_dup] = rng.integers(0, n_clusters, size=n_dup)
    labels[n_dup:] = np.arange(n_clusters, n_clusters + (n_sessions - n_dup))

    base = rng.integers(0, universe, size=(n_clusters, set_size), dtype=dtype)
    items = np.empty((n_sessions, set_size), dtype=dtype)
    items[:n_dup] = base[labels[:n_dup]]
    items[n_dup:] = rng.integers(0, universe, size=(n_sessions - n_dup, set_size),
                                 dtype=dtype)

    # Mutate a small fraction of the duplicated rows' items.
    mutate_mask = rng.random((n_dup, set_size)) < mutate_prob
    n_mut = int(mutate_mask.sum())
    items[:n_dup][mutate_mask] = rng.integers(0, universe, size=n_mut, dtype=dtype)

    perm = rng.permutation(n_sessions)
    return items[perm], labels[perm]


def synth_session_hitcounts(
    items: np.ndarray,
    labels: np.ndarray,
    max_weight: int = 8,
    noise_prob: float = 0.05,
    seed: int = 0,
) -> np.ndarray:
    """Per-edge hit counts for the weighted-minwise workload
    (``--scheme weighted``): [N, S] uint32 in [1, max_weight].

    The reference paper models coverage as set membership only; real
    fuzzing coverage is a COUNT per edge, and sessions from the same
    campaign share not just which edges they hit but how hard (a hot
    parsing loop is hot in every near-duplicate run).  So members of a
    planted cluster share a per-cluster count profile, with
    ``noise_prob`` of positions re-rolled per row — planted weighted
    Jaccard stays high within a cluster and the count profile separates
    rows whose SETS collide by chance.  A count of 0 never occurs:
    membership in the row's set implies at least one hit (the weighted
    scheme clips to [1, MAX_WEIGHT] anyway — schemes.expand_weighted).
    """
    rng = np.random.default_rng(seed)
    items = np.asarray(items)
    labels = np.asarray(labels)
    uniq, inv = np.unique(labels, return_inverse=True)
    # Skewed profile (small counts common, hot edges rare) — geometric-
    # ish via integer powers, deterministic per cluster.
    base = np.minimum(
        1 + rng.geometric(0.45, size=(uniq.size, items.shape[1])) - 1,
        int(max_weight)).astype(np.uint32)
    base = np.maximum(base, np.uint32(1))
    w = base[inv].copy()
    noise = rng.random(w.shape) < noise_prob
    w[noise] = rng.integers(1, int(max_weight) + 1,
                            size=int(noise.sum())).astype(np.uint32)
    return w
