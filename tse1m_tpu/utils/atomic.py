"""Atomic file writes — the blessed tmp+rename helper.

PR 1 made the checkpointers atomic (tmp + ``os.replace``) but every
artifact writer (analysis CSVs, manifests, fault plans, issue batches)
kept opening its final path in ``"w"`` mode: a crash — or an injected
torn write — mid-write leaves a half-file that a resumed run then reads
as complete.  graftlint's ``nonatomic-write`` rule flags write-mode
``open()`` on final paths; this context manager is the fix it points at:

    with atomic_write(path, newline="") as f:
        w = csv.writer(f)
        ...

The file is written to ``path + ".tmp"`` and renamed over ``path`` only
when the block exits cleanly; on an exception the tmp file is removed
and the previous ``path`` (if any) is untouched.
"""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "w", encoding: str | None = "utf-8",
                 newline: str | None = None):
    """Open ``path + ".tmp"`` for writing; rename onto ``path`` on clean
    exit, delete the tmp on failure.  Text modes default to UTF-8;
    binary modes ("wb") pass encoding/newline through as None."""
    if "b" in mode:
        encoding = newline = None
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    f = open(tmp, mode, encoding=encoding, newline=newline)
    try:
        yield f
    except BaseException:
        f.close()
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise
    else:
        f.close()
        os.replace(tmp, path)


__all__ = ["atomic_write"]
