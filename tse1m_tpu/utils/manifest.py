"""Run manifests: a JSON record of every analysis run (config, backend,
device topology, phase timings, artifact paths, row counts) saved alongside
the artifacts.  The reference has no equivalent; its only record of a run is
a pasted console transcript (rq1_detection_rate.py:354-412)."""

from __future__ import annotations

import json
import os
import platform
import time
from dataclasses import dataclass, field
from typing import Any

from .atomic import atomic_write


@dataclass
class RunManifest:
    name: str
    backend: str
    extra: dict[str, Any] = field(default_factory=dict)
    artifacts: list[str] = field(default_factory=list)
    started_at: float = field(default_factory=time.time)

    def add_artifact(self, path: str) -> None:
        self.artifacts.append(path)

    def record(self, **kwargs: Any) -> None:
        self.extra.update(kwargs)

    def _device_info(self) -> dict[str, Any]:
        try:
            import jax

            return {
                "platform": jax.default_backend(),
                "device_count": jax.device_count(),
                "devices": [str(d) for d in jax.devices()],
            }
        except Exception:  # graftlint: disable=broad-except -- jax absent or uninitialised; manifest still valid
            return {}

    def record_backend(self, backend) -> None:
        """Record a routing backend's learned calibration (backend/auto.py
        ``calibration()``) so the manifest shows which engine each RQ ran
        on this machine and why.  No-op for plain engines."""
        cal = getattr(backend, "calibration", None)
        if callable(cal):
            self.record(router_calibration=cal())

    def save(self, out_dir: str, timings: dict[str, float] | None = None) -> str:
        os.makedirs(out_dir, exist_ok=True)
        payload = {
            "name": self.name,
            "backend": self.backend,
            "started_at": self.started_at,
            "wall_seconds": time.time() - self.started_at,
            "host": platform.node(),
            "python": platform.python_version(),
            "jax": self._device_info(),
            "timings": timings or {},
            "artifacts": self.artifacts,
            **self.extra,
        }
        path = os.path.join(out_dir, f"{self.name}_manifest.json")
        with atomic_write(path) as f:
            json.dump(payload, f, indent=2, default=str)
        return path
