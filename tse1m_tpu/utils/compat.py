"""Version compatibility shims.

The framework targets jax >= 0.9 (``jax.shard_map``, ``check_vma=``), but
minimal images ship older wheels where shard_map still lives in
``jax.experimental.shard_map`` and the replication-check kwarg is spelled
``check_rep``.  Every mesh module imports shard_map from here so the same
code runs on both — part of the resilience contract: a missing/renamed
dependency surface degrades to the equivalent API, never to 16 dead
test modules.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.9
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters
_VMA_KW = "check_vma" if "check_vma" in _PARAMS else (
    "check_rep" if "check_rep" in _PARAMS else None)


try:  # jax >= 0.9 exposes the x64 context manager at top level
    enable_x64 = __import__("jax").enable_x64
    enable_x64  # touch: the deprecation proxy raises on attribute access
except AttributeError:  # jax 0.4.x
    from jax.experimental import enable_x64


def shard_map(f=None, **kwargs):
    """`jax.shard_map` with the `check_vma` kwarg translated for older jax.

    Usable both as a decorator factory (``@partial(shard_map, mesh=...)``
    matches ``f=None`` and returns a decorator) and as a direct call.
    """
    if "check_vma" in kwargs and _VMA_KW != "check_vma":
        val = kwargs.pop("check_vma")
        if _VMA_KW is not None:
            kwargs[_VMA_KW] = val
    if f is None:
        return lambda g: _shard_map(g, **kwargs)
    return _shard_map(f, **kwargs)
