"""Version compatibility shims.

The framework targets jax >= 0.9 (``jax.shard_map``, ``check_vma=``), but
minimal images ship older wheels where shard_map still lives in
``jax.experimental.shard_map`` and the replication-check kwarg is spelled
``check_rep``.  Every mesh module imports shard_map from here so the same
code runs on both — part of the resilience contract: a missing/renamed
dependency surface degrades to the equivalent API, never to 16 dead
test modules.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.9
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters
_VMA_KW = "check_vma" if "check_vma" in _PARAMS else (
    "check_rep" if "check_rep" in _PARAMS else None)


try:  # jax >= 0.9 exposes the x64 context manager at top level
    enable_x64 = __import__("jax").enable_x64
    enable_x64  # touch: the deprecation proxy raises on attribute access
except AttributeError:  # jax 0.4.x
    from jax.experimental import enable_x64


def shard_map(f=None, **kwargs):
    """`jax.shard_map` with the `check_vma` kwarg translated for older jax.

    Usable both as a decorator factory (``@partial(shard_map, mesh=...)``
    matches ``f=None`` and returns a decorator) and as a direct call.
    """
    if "check_vma" in kwargs and _VMA_KW != "check_vma":
        val = kwargs.pop("check_vma")
        if _VMA_KW is not None:
            kwargs[_VMA_KW] = val
    if f is None:
        return lambda g: _shard_map(g, **kwargs)
    return _shard_map(f, **kwargs)


def enable_persistent_compilation_cache(path: str) -> bool:
    """Point jax's persistent XLA compilation cache at ``path``.

    Repeat runs then skip recompilation of every jitted kernel — on the
    measured remote-PJRT setup each fresh compile pays the 129 ms
    dispatch RTT several times over, and the cluster pipeline compiles a
    dozen shapes per bench round.  Thresholds drop to zero so even tiny
    kernels cache (the default 1 s floor would exclude most of the RQ
    suite).  Returns True when the cache was enabled; False (logged, not
    raised) on jax builds without the config knobs — the resilience
    contract for optional surfaces.
    """
    import jax

    from .logging import get_logger

    try:
        jax.config.update("jax_compilation_cache_dir", path)
        for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                          ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, val)
            except AttributeError:
                pass  # older jax: dir knob alone still caches big kernels
        return True
    except Exception as e:
        from ..resilience import reraise_if_fault

        reraise_if_fault(e)  # cache stays off on any real failure
        get_logger("compat").warning(
            "persistent compilation cache unavailable (%s: %s)",
            type(e).__name__, e)
        return False
