"""Phase timing + optional JAX profiler hooks.

Replaces the reference's tqdm-wall-clock-only observability
(rq1_detection_rate.py:361,367 transcripts) with structured per-phase
timings that are also written into the run manifest.
"""

from __future__ import annotations

import contextlib
import os
import time
from dataclasses import dataclass, field

from .logging import get_logger

log = get_logger("timing")


@dataclass
class PhaseTimer:
    """Collects named phase durations; optionally wraps phases in a
    jax.profiler trace when TSE1M_PROFILE_DIR is set."""

    phases: dict[str, float] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str):
        profile_dir = os.environ.get("TSE1M_PROFILE_DIR")
        trace_ctx = contextlib.nullcontext()
        if profile_dir:
            import jax

            trace_ctx = jax.profiler.trace(os.path.join(profile_dir, name))
        start = time.perf_counter()
        with trace_ctx:
            yield
        elapsed = time.perf_counter() - start
        self.phases[name] = self.phases.get(name, 0.0) + elapsed
        log.info("phase %-32s %8.3fs", name, elapsed)

    def as_dict(self) -> dict[str, float]:
        return dict(self.phases)
