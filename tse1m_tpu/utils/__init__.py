from .logging import get_logger
from .timing import PhaseTimer
from .manifest import RunManifest

__all__ = ["get_logger", "PhaseTimer", "RunManifest"]
