"""Structured logging.

The reference mixes bare ``print`` with three ad-hoc ``logging.basicConfig``
calls (SURVEY.md §5).  Here every module gets a namespaced logger with one
consistent format, configurable via TSE1M_LOG_LEVEL.
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def _configure_root() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    level = os.environ.get("TSE1M_LOG_LEVEL", "INFO").upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)-7s %(name)s: %(message)s", "%H:%M:%S")
    )
    root = logging.getLogger("tse1m")
    root.setLevel(level)
    root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    _configure_root()
    if not name.startswith("tse1m"):
        name = f"tse1m.{name}"
    return logging.getLogger(name)
