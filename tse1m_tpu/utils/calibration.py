"""Machine-local measurement calibration file (schema-versioned, TTL'd).

One JSON file shared by every layer that records a measurement on this
machine and wants the *next* process to start from it instead of a
bootstrap prior:

- the auto-router's per-(rq, engine) cost-per-row EWMAs
  (backend/auto.py — the BENCH_r05 record-and-reuse fix),
- the cluster pipeline's degradation ladder: the chunk byte size that
  survived RESOURCE_EXHAUSTED halving, so the next run starts at a size
  the device can hold (cluster/pipeline.py), and the link probe's
  measured H2D rate seeding the watchdog's adaptive stall budgets
  (bench.py -> resilience/watchdog.py).

Two properties ROADMAP called out as missing from the v1 flat file:

- **Schema version**: a file written by a different layout is ignored
  wholesale (re-measure), never half-parsed.  v1 files (no
  ``schema_version`` key) are treated as stale for the same reason —
  their entries carry no timestamps, so their age is unknowable.
- **Staleness bound**: every entry carries a wall-clock ``ts``; entries
  older than the TTL (``TSE1M_ROUTER_CAL_TTL_S``, default 6 h) are
  dropped at load.  Link RTT drifts by time of day on the tunneled
  setup, so a midnight measurement must not route the afternoon.

Writes are read-merge-write under :func:`tse1m_tpu.utils.atomic.
atomic_write`; concurrent writers last-write-win per section, which is
fine for measurements (both values were true recently).
"""

from __future__ import annotations

import json
import os
import time

from .atomic import atomic_write
from .logging import get_logger

log = get_logger("utils.calibration")

SCHEMA_VERSION = 2
_DEFAULT_TTL_S = 6 * 3600.0


def ttl_s() -> float:
    return float(os.environ.get("TSE1M_ROUTER_CAL_TTL_S", _DEFAULT_TTL_S))


def _now() -> float:
    return time.time()


def load_calibration(path: str | None) -> dict:
    """Fresh (schema-matching, within-TTL) calibration state.

    Returns ``{"cost_per_row": {key: float}, "wire": {key: value}}`` with
    stale entries already dropped; empty sections when the file is
    absent, unreadable, a different schema, or entirely stale."""
    out: dict = {"cost_per_row": {}, "wire": {}}
    if not path or not os.path.exists(path):
        return out
    try:
        with open(path, encoding="utf-8") as f:
            saved = json.load(f)
    except (OSError, ValueError) as e:
        log.warning("calibration at %s unreadable (%s); re-measuring",
                    path, e)
        return out
    version = saved.get("schema_version")
    if version != SCHEMA_VERSION:
        log.warning("calibration at %s has schema %r (want %d); ignoring "
                    "and re-measuring", path, version, SCHEMA_VERSION)
        return out
    horizon = _now() - ttl_s()
    dropped = 0
    for section in ("cost_per_row", "wire"):
        for key, entry in (saved.get(section) or {}).items():
            if not isinstance(entry, dict) or "value" not in entry:
                continue
            if float(entry.get("ts", 0.0)) < horizon:
                dropped += 1
                continue
            out[section][key] = entry["value"]
    if dropped:
        log.info("calibration at %s: dropped %d stale entr%s (TTL %.0fs)",
                 path, dropped, "y" if dropped == 1 else "ies", ttl_s())
    return out


def update_calibration(path: str | None, cost_per_row: dict | None = None,
                       wire: dict | None = None) -> None:
    """Merge new measurements into the file (stamping each with now),
    preserving other still-fresh entries.  No-op without a path."""
    if not path:
        return
    current = load_calibration(path)
    now = _now()
    payload = {"schema_version": SCHEMA_VERSION,
               "cost_per_row": {k: {"value": v, "ts": now}
                                for k, v in current["cost_per_row"].items()},
               "wire": {k: {"value": v, "ts": now}
                        for k, v in current["wire"].items()}}
    # Re-stamping preserved entries would defeat the TTL; keep their
    # original timestamps.
    try:
        with open(path, encoding="utf-8") as f:
            prior = json.load(f)
        if prior.get("schema_version") == SCHEMA_VERSION:
            for section in ("cost_per_row", "wire"):
                for k, entry in (prior.get(section) or {}).items():
                    if k in payload[section] and isinstance(entry, dict) \
                            and "ts" in entry:
                        payload[section][k]["ts"] = entry["ts"]
    except (OSError, ValueError):
        pass
    # A None value DELETES the entry (e.g. the degradation ladder
    # restoring full wire fidelity once the device heals).
    for k, v in (cost_per_row or {}).items():
        if v is None:
            payload["cost_per_row"].pop(k, None)
        else:
            payload["cost_per_row"][k] = {"value": float(v), "ts": now}
    for k, v in (wire or {}).items():
        if v is None:
            payload["wire"].pop(k, None)
        else:
            payload["wire"][k] = {"value": v, "ts": now}
    try:
        with atomic_write(path) as f:
            json.dump(payload, f, indent=2)
    except OSError as e:
        log.warning("could not persist calibration to %s (%s)", path, e)


def calibration_path() -> str | None:
    """The configured calibration file (TSE1M_ROUTER_CAL env or INI
    ``router_cal_path``); None = in-memory only."""
    env = os.environ.get("TSE1M_ROUTER_CAL")
    if env is not None:
        return env or None
    try:
        from ..config import load_config

        return load_config().router_cal_path
    except Exception:  # graftlint: disable=broad-except -- calibration is an optimization; a broken INI must not take down the pipeline
        return None


__all__ = ["SCHEMA_VERSION", "calibration_path", "load_calibration",
           "ttl_s", "update_calibration"]
