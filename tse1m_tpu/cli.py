"""Command-line interface.

The reference has no CLI beyond ``python3 <script>`` (run_all_analysis.sh);
this adds the operational commands the rebuild needs:

  python -m tse1m_tpu.cli synth   --db data/database/tse1m.sqlite [--projects N --days D]
  python -m tse1m_tpu.cli ingest  --db ... --csv-dir data/processed_data/csv
  python -m tse1m_tpu.cli rq1 [rq2a rq2b rq3 rq4a rq4b all]
  python -m tse1m_tpu.cli cluster --n 100000   (north-star session dedup)
  python -m tse1m_tpu.cli scrub data/sig_store [--repair --compact --strict]
"""

from __future__ import annotations

import argparse
import os
import sys

from .config import load_config
from .db.connection import DB
from .utils.logging import get_logger

log = get_logger("cli")


def _activate_config_fault_plan() -> None:
    """Install a FaultPlan configured via the INI (``fault_plan =`` under
    ``[FRAMEWORK]``).  ``TSE1M_FAULT_PLAN`` already activates lazily inside
    resilience.faults; this seat makes the config field equivalent for
    operator game-days, and exports the env var so chaos-test subprocesses
    spawned by this run inherit the same plan."""
    from .resilience import active_plan, install_plan
    from .resilience.faults import FaultPlan

    if active_plan() is not None:  # env plan / in-process install wins
        return
    plan_path = load_config().fault_plan
    if plan_path:
        install_plan(FaultPlan.from_json(plan_path))
        os.environ.setdefault("TSE1M_FAULT_PLAN", plan_path)
        log.warning("fault plan active from config: %s", plan_path)


def _activate_xla_cache() -> None:
    """Enable the persistent XLA compilation cache when configured
    (``xla_cache_dir`` under ``[FRAMEWORK]``, or TSE1M_XLA_CACHE_DIR) —
    repeat CLI runs then skip every kernel recompile."""
    path = load_config().xla_cache_dir
    if path:
        from .utils.compat import enable_persistent_compilation_cache

        if enable_persistent_compilation_cache(path):
            log.info("persistent XLA compilation cache: %s", path)


def _cmd_synth(args) -> int:
    from .data.synth import SynthSpec, generate_study

    cfg = load_config()
    if args.db:
        cfg.sqlite_path = args.db
    spec = SynthSpec(n_projects=args.projects, days=args.days, seed=args.seed)
    log.info("generating synthetic study: %d projects x %d days", spec.n_projects, spec.days)
    study = generate_study(spec)
    db = DB(config=cfg).connect()
    study.to_db(db)
    db.closeConnection()
    log.info("loaded into %s: %d builds, %d issues, %d coverage rows",
             cfg.sqlite_path, len(study.buildlog_data), len(study.issues),
             len(study.total_coverage))
    if args.csv_dir:
        study.to_csv_dir(args.csv_dir)
        log.info("CSV copies in %s", args.csv_dir)
    # RQ4 reads the corpus-analysis CSV from cfg.corpus_csv (rq4a_bug.py:34),
    # so a synthetic study must always materialise it there — regardless of
    # whether --csv-dir also received a copy.
    os.makedirs(os.path.dirname(cfg.corpus_csv) or ".", exist_ok=True)
    study.corpus_analysis.to_csv(cfg.corpus_csv, index=False)
    log.info("corpus analysis CSV at %s", cfg.corpus_csv)
    return 0


def _cmd_stats(args) -> int:
    """Study inventory: table sizes, the reference's project-frequency
    query (queries1.py:6-11), and the severity breakdown of regression-
    tracked issues over eligible projects (queries1.py:104-118)."""
    from .db import queries

    cfg = load_config()
    if args.db:
        cfg.sqlite_path = args.db
    db = DB(config=cfg).connect()
    try:
        db.require_study_tables()
        from .db.ident import quote_ident

        for table in ("project_info", "buildlog_data", "total_coverage",
                      "issues"):
            n = db.query(f"SELECT COUNT(*) FROM {quote_ident(table)}")[0][0]
            print(f"{table:16s} {n:>12,} rows")
        sql, params = queries.count_projects()
        freq = db.query(sql, params)
        print(f"projects         {len(freq):>12,} distinct "
              f"(top: {freq[0][0]} x{freq[0][1]})" if freq else
              "projects                    0 distinct")
        sql, params = queries.eligible_projects(cfg.min_coverage_days,
                                                cfg.limit_date)
        eligible = [r[0] for r in db.query(sql, params)]
        print(f"eligible         {len(eligible):>12,} projects "
              f"(>= {cfg.min_coverage_days} coverage days)")
        for severity in ("High", "Medium", "Low"):
            sql, params = queries.severity_issues(
                severity, eligible, db.dialect, cfg.limit_date)
            n = db.count(sql, params)
            print(f"severity {severity:7s} {n:>12,} regression-tracked issues")
    finally:
        db.closeConnection()
    return 0


def _cmd_ingest(args) -> int:
    from .db.ingest import ingest_csv_dir

    cfg = load_config()
    if args.db:
        cfg.sqlite_path = args.db
    db = DB(config=cfg).connect()
    counts = ingest_csv_dir(db, args.csv_dir)
    db.closeConnection()
    log.info("ingested: %s", counts)
    return 0


def _cmd_restore(args) -> int:
    """Restore a SQL dump (the reference's `psql ... < backup_clean.sql`
    bootstrap, README.md:55) into the configured engine — pg_dump COPY
    blocks or INSERT statements, either dialect (db/restore.py)."""
    from .db.restore import restore_sql_dump

    cfg = load_config()
    if args.db:
        cfg.sqlite_path = args.db
    db = DB(config=cfg).connect()
    counts = restore_sql_dump(db, args.dump)
    db.closeConnection()
    log.info("restored: %s", counts)
    return 0


def _cmd_rq(args) -> int:
    """Run one RQ — or, under ``all``, run every RQ to completion.

    Each step runs isolated (resilience/runner.py): one RQ blowing up no
    longer aborts the remaining five, a missing module is recorded (it
    previously vanished with exit 0), every step's status/attempts/
    traceback lands in ``<result_dir>/run_manifest.json``, and the exit
    code is nonzero iff any requested step failed or was missing."""
    cfg = load_config()
    if args.db:
        cfg.sqlite_path = args.db
    if args.backend:
        cfg.backend = args.backend
    if args.result_dir:
        cfg.result_dir = args.result_dir
    import importlib
    import os

    from .resilience import StepRunner

    specs = {
        "rq1": ("tse1m_tpu.analysis.rq1", "run_rq1"),
        "rq2a": ("tse1m_tpu.analysis.rq2_changepoints", "run_rq2_changepoints"),
        "rq2b": ("tse1m_tpu.analysis.rq2_trends", "run_rq2_trends"),
        "rq3": ("tse1m_tpu.analysis.rq3", "run_rq3"),
        "rq4a": ("tse1m_tpu.analysis.rq4a", "run_rq4a"),
        "rq4b": ("tse1m_tpu.analysis.rq4b", "run_rq4b"),
    }
    wanted = list(specs) if args.cmd == "all" else [args.cmd]
    manifest_path = os.path.join(cfg.result_dir, "run_manifest.json")
    runner = StepRunner(manifest_path)
    if args.cmd == "all":
        # Correctness plane first: the static lint pass + a runtime
        # sanitizer self-check, recorded per run in the manifest.  A
        # non-baselined finding fails THIS step (nonzero exit, full
        # summary in the record) while the RQs still run to completion.
        runner.run("graftlint", _lint_step)
        # graftspec: model-check the committed protocol specs and run
        # the mutant self-test, same ledger discipline — a violated
        # invariant or a mutant the checker misses fails this step.
        runner.run("graftspec", _spec_step)
    for name in wanted:
        mod_name, fn_name = specs[name]
        try:
            fn = getattr(importlib.import_module(mod_name), fn_name)
        except ModuleNotFoundError as e:
            if e.name == mod_name:
                log.warning("%s is not implemented yet (%s missing)",
                            name, mod_name)
                runner.record_missing(name, f"{mod_name} not importable")
                continue
            raise  # a real dependency failure inside the module — surface it
        log.info("=== %s (backend=%s) ===", name, cfg.backend)
        runner.run(name, fn, cfg)
    if runner.failed:
        log.error("run finished with failures: %s (manifest: %s)",
                  ", ".join(f"{s.name}[{s.status}]" for s in runner.failed),
                  manifest_path)
    else:
        log.info("all %d step(s) ok (manifest: %s)", len(runner.steps),
                 manifest_path)
    return runner.exit_code()


def _lint_step() -> dict:
    """The ``cli all`` correctness step: whole-repo graftlint plus the
    runtime-sanitizer self-check, returned as the step's structured
    result (resilience.StepRunner embeds dict returns — and, via
    LintError.step_result, the summary of a FAILING lint too)."""
    from .lint import run_repo_lint
    from .lint.runtime import self_check

    runtime = self_check()
    summary = run_repo_lint()  # raises LintError on non-baselined findings
    summary["runtime"] = runtime
    return summary


def _spec_step() -> dict:
    """The ``cli all`` graftspec step: exhaustively model-check every
    committed protocol spec and run the mutant self-test.  The summary
    (per-spec state counts + per-mutant catch records) lands in the
    manifest; a violated spec or an uncaught mutant fails the step."""
    from .spec import SpecError, check_all, mutant_selftest

    results = check_all()
    summary = {"specs": [r.summary() for r in results],
               "mutants": mutant_selftest()}
    bad = [r for r in results if not r.ok]
    if bad:
        raise SpecError("; ".join(
            f"{r.spec}: {r.violation.describe()}" for r in bad))
    return summary


def _cmd_spec(args) -> int:
    """graftspec commands (`tse1m spec {check,trace,mutants}`).

    ``check`` explores each spec's bounded state space and exits
    nonzero on any invariant or liveness violation; ``trace`` prints a
    violation's full counterexample plus its replayable graftrace
    schedule string (works on mutants too, which is how you LOOK at a
    protocol bug); ``mutants`` runs the committed protocol-bug mutants
    and verifies each produces a violation whose counterexample replays
    through the machine."""
    import json

    from .spec import SpecError, build_spec, check, mutant_selftest

    if args.action == "mutants":
        try:
            records = mutant_selftest(mode=args.mode)
        except SpecError as e:
            log.error("%s", e)
            return 1
        for name, rec in records.items():
            print(f"{name:24s} spec={rec['spec']:12s} caught "
                  f"{rec['kind']}:{rec['prop']} in {rec['states']} "
                  f"states, replayed: {rec['schedule']}")
        return 0

    names = list(args.names) or (["lease", "ingest_ack", "replica"]
                                 if args.action == "check" else [])
    if not names:
        raise SystemExit("spec trace needs a spec or mutant name")
    kwargs = {} if args.max_states is None \
        else {"max_states": args.max_states}
    results = []
    for name in names:
        try:
            spec = build_spec(name)
        except SpecError as e:
            log.error("%s", e)
            return 2
        results.append((name, check(spec, mode=args.mode, **kwargs)))
    bad = [(n, r) for n, r in results if not r.ok]
    if args.action == "trace":
        for name, r in results:
            if r.violation is None:
                print(f"{name}: no violation in {r.states} states "
                      f"(scope {r.scope})")
            else:
                print(f"{name}:")
                print(r.violation.describe())
                print(f"replay: {r.violation.schedule_str}")
        return 1 if bad else 0
    if args.json:
        print(json.dumps([dict(r.summary(), requested=n)
                          for n, r in results]))
    else:
        for name, r in results:
            status = ("ok" if r.ok else
                      f"VIOLATION {r.violation.kind}:{r.violation.prop}")
            print(f"{name:12s} {status}  states={r.states} "
                  f"transitions={r.transitions} depth={r.depth} "
                  f"wall={r.wall_s * 1000:.1f}ms")
        for name, r in bad:
            print(r.violation.describe())
            print(f"replay: {r.violation.schedule_str}")
    return 1 if bad else 0


def _cmd_lint(args) -> int:
    from .lint import main as lint_main

    argv = list(args.paths)
    if args.json:
        argv.append("--json")
    if args.write_baseline:
        argv.append("--write-baseline")
    if args.no_baseline:
        argv.append("--no-baseline")
    if args.rules:
        argv += ["--rules", args.rules]
    if args.changed:
        argv += ["--changed", args.changed]
    if args.why:
        argv += ["--why", args.why]
    if args.graph:
        argv.append("--graph")
    return lint_main(argv)


def _cmd_collect(args) -> int:
    """Run one offline collection step (C3-C8).  Network steps construct an
    HttpFetcher with the reference's politeness/retry policy; everything
    funnels into --data-dir in ingest-ready layouts."""
    import os
    from datetime import date, timedelta

    import pandas as pd

    from .collect.transport import FetchPolicy, HttpFetcher

    data_dir = args.data_dir
    os.makedirs(data_dir, exist_ok=True)
    if args.step == "projects":
        from .collect.projects import OSS_FUZZ_URL, run_project_info_collector

        run_project_info_collector(
            args.repo, os.path.join(data_dir, "project_info.csv"),
            clone_url=None if args.no_clone else OSS_FUZZ_URL)
    elif args.step == "gcs-metadata":
        from .collect.gcs_metadata import GcsMetadataCollector

        fetcher = HttpFetcher(FetchPolicy(retries=5, backoff_factor=1.0,
                                          politeness_delay=5.0,
                                          timeout=30.0))
        coll = GcsMetadataCollector(
            fetcher, os.path.join(data_dir, "buildlog_metadata_batches"),
            max_pages=args.max_pages)
        coll.collect(os.path.join(data_dir, "buildlog_metadata.csv"))
    elif args.step == "coverage":
        from .collect.coverage import CoverageCollector

        info = pd.read_csv(os.path.join(data_dir, "project_info.csv"))
        fetcher = HttpFetcher(FetchPolicy(politeness_delay=0.5))
        coll = CoverageCollector(
            fetcher, os.path.join(data_dir, "coverage_by_project"),
            finish_date=date.today() - timedelta(days=2))
        coll.collect_all(info, os.path.join(data_dir, "total_coverage.csv"))
    elif args.step == "buildlogs":
        from .collect.buildlogs import BuildLogAnalyzer
        from .collect.normalize import buildlog_table_rows

        meta = pd.read_csv(os.path.join(data_dir, "buildlog_metadata.csv"))
        batch_dir = os.path.join(data_dir, "buildlog_analyzed_batches")
        # Nonzero aggregate politeness delay: with workers > 1 the fetcher's
        # rate lock serializes request starts, so this bounds the *total*
        # request rate against public GCS (~10 req/s), not per-worker.
        an = BuildLogAnalyzer(HttpFetcher(FetchPolicy(politeness_delay=0.1)),
                              batch_dir, limit=args.limit,
                              workers=args.workers)
        an.analyze(meta)
        import glob

        frames = [pd.read_csv(f) for f in
                  sorted(glob.glob(os.path.join(batch_dir, "*.csv")))]
        if frames:
            buildlog_table_rows(pd.concat(frames, ignore_index=True)).to_csv(
                os.path.join(data_dir, "buildlog_data.csv"), index=False)
    elif args.step == "issues":
        from .collect.issues import (merge_window_csvs, plan_run,
                                     scrape_issues)
        from .collect.normalize import issue_table_rows

        results_dir = os.path.join(data_dir, "issue_scraping_results")
        targets = set()
        if args.ids_file and os.path.exists(args.ids_file):
            with open(args.ids_file, encoding="utf-8") as f:
                targets = {int(ln) for ln in f if ln.strip().isdigit()}
        plan = plan_run(targets, results_dir)
        if plan:
            from .collect.issues_selenium import SeleniumIssueClient

            scrape_issues(SeleniumIssueClient, plan, results_dir,
                          num_workers=args.workers)
        merged_csv = os.path.join(data_dir, "issues_merged.csv")
        if merge_window_csvs(results_dir, merged_csv):
            issue_table_rows(pd.read_csv(merged_csv, low_memory=False)).to_csv(
                os.path.join(data_dir, "issues.csv"), index=False)
    elif args.step == "corpus":
        from .collect.corpus import (GitHubMergeTimeResolver,
                                     run_corpus_collector)

        resolver = GitHubMergeTimeResolver(
            fetcher=HttpFetcher(FetchPolicy()),
            token=os.environ.get("GITHUB_TOKEN"))
        run_corpus_collector(
            args.repo,
            os.path.join(data_dir, "project_corpus_analysis.csv"), resolver)
    return 0


def run_pod_cluster(items, n: int, params):
    """Pod-supervised store-enabled clustering (the `--sig-store`-under-
    a-pod path), shared by ``cli cluster`` and the chaos/CI drivers.

    Pod identity comes from the env (multihost.pod_process_env) — the
    pod plane NEVER initializes jax.distributed, so no XLA coordination
    client exists to fatal a survivor when a peer (including the
    leader) dies.  The run opens this run's membership epoch
    (resilience/coordinator.MembershipLedger: the leader bootstraps, re-
    admitting any recovered host via the elastic range re-deal; peers
    adopt the record), starts the heartbeat writer + peer monitor, and
    feeds this process's local row slice through
    ``cluster_sessions_pod`` under epoch leases.

    Failure handling:

    - A peer whose heartbeat stops is declared lost, and the lowest-id
      survivor FAILS OVER: it advances the membership epoch (the lost
      hosts' ranges re-deal to it, their old-epoch leases supersede) and
      re-executes the whole partition solo — while every other survivor
      exits loudly.  When process 0 is among the lost, the survivor
      PROMOTES itself to leader (``leader_promoted`` event): it owns the
      next-epoch topology and merges the manifest fragments after the
      run — leader death is one more reassignment, not a pod-wide fence.
    - A zombie — this process, wedged past reassignment and then woken —
      finds its lease superseded at its first append and self-fences:
      the store demotes to read-only (``lease_superseded`` event) and
      the run aborts with LeaseSupersededError, zero rows double-
      written.

    Returns ``(labels, pod_report)``; ``pod_report`` carries the
    survivor/epoch accounting for the merged manifest."""
    import numpy as np

    from .cluster.pipeline import cluster_sessions_pod
    from .cluster.store import ShardedSignatureStore
    from .observability import record_degradation
    from .parallel import multihost
    from .resilience.coordinator import (HostLostError, LeaseSupersededError,
                                         MembershipLedger, PodSupervisor,
                                         exchange_dir, negotiate_run_nonce)

    nproc, pid = multihost.pod_process_env()
    items = np.ascontiguousarray(items, dtype=np.uint32)
    pod: dict = {"pod_process_id": pid}
    pod_dir = os.path.join(params.sig_store, "pod")
    ledger = MembershipLedger(
        pod_dir, ShardedSignatureStore.root_n_ranges(params.sig_store,
                                                     default=nproc))
    if nproc == 1:
        # Single process: leader of a one-member pod.  Bootstrapping the
        # ledger (rather than skipping it) is what re-admits this host —
        # or inherits the dead peers' ranges — at an epoch boundary when
        # the previous run had different members.
        nonce = negotiate_run_nonce(None)
        membership = ledger.bootstrap([pid], nonce)
        labels = cluster_sessions_pod(items, n, params,
                                      membership=membership,
                                      n_processes=1, process_id=pid)
        pod.update(pod_epoch=membership["epoch"])
        return labels, pod
    sup = PodSupervisor(pod_dir, nproc, pid).start()
    nonce = None  # may still be unset when the leader dies pre-publish
    try:
        try:
            nonce = negotiate_run_nonce(sup, pod_dir=pod_dir)
            if pid == 0:
                membership = ledger.bootstrap(list(range(nproc)), nonce)
            else:
                membership = ledger.wait_for(nonce, monitor=sup.monitor)
            sup.monitor.advance_epoch(membership["epoch"])
            xch = exchange_dir(pod_dir, nonce, sweep_stale=pid == 0)
            lo, hi = multihost.pod_row_range(n, nproc, pid)
            labels = cluster_sessions_pod(items[lo:hi], n, params,
                                          supervisor=sup,
                                          exchange_dir=xch,
                                          membership=membership,
                                          n_processes=nproc,
                                          process_id=pid)
            pod.update(pod_epoch=membership["epoch"])
            return labels, pod
        except LeaseSupersededError as e:
            # This process is the zombie: its range was re-dealt while it
            # was wedged.  The store already demoted itself to read-only
            # and recorded the lease_superseded event — nothing was
            # double-written; abort loudly so the fragment records it.
            log.error("pod: this process is fenced (%s); exiting without "
                      "appending", e)
            raise
        except HostLostError as e:
            survivors = sup.survivors()
            if not survivors or pid != min(survivors):
                raise  # one process fails over; the rest exit loudly
            record_degradation("pod_failover", site="cli.cluster",
                               detail={"lost": e.lost, "survivor": pid})
            promoted = 0 in e.lost and pid != 0
            if promoted:
                record_degradation("leader_promoted", site="cli.cluster",
                                   detail={"from_process": 0,
                                           "to_process": pid})
                log.warning("pod: leader (process 0) lost; process %d "
                            "promoting itself — it owns the next epoch "
                            "and merges the manifest fragments", pid)
            membership = ledger.advance([pid], nonce or os.urandom(8).hex(),
                                        reason="host_lost")
            sup.monitor.advance_epoch(membership["epoch"])
            log.warning(
                "pod: host(s) %s lost at %s; process %d failing over at "
                "epoch %d — re-executing solo with their digest ranges "
                "re-dealt (superseded leases fence any zombie)",
                e.lost, e.site, pid, membership["epoch"])
            labels = cluster_sessions_pod(items, n, params, solo=True,
                                          membership=membership,
                                          process_id=pid)
            pod.update(pod_survivor=pid, pod_lost=e.lost,
                       pod_epoch=membership["epoch"],
                       pod_promoted_leader=promoted)
            return labels, pod
    finally:
        sup.stop()


def _cmd_cluster(args) -> int:
    """North-star session dedup: MinHash+LSH clustering with an ARI report
    against the planted truth (and the host oracle on a subsample).

    ``--sig-store`` points at the persistent content-addressed signature
    store (cluster/store.py): re-runs probe cached MinHash signatures by
    row content hash and ship only the novel tail; an accreted re-run
    merges labels on host.  The store path and the run's cache stats are
    recorded in ``<result_dir>/run_manifest.json`` (the step runner also
    embeds the per-stage probe/load/h2d walls).

    Multi-host aware: under TSE1M_COORDINATOR/…_NUM_PROCESSES (see
    parallel/multihost.py) the mesh spans every host's devices; with
    ``--sig-store`` the store shards per host by digest range
    (run_pod_cluster — heartbeats, host-loss failover) and each process
    records a manifest FRAGMENT that the coordinator merges into one
    ``run_manifest.json``.  Note the synthetic items are generated in
    full on every host (the planted-truth permutation is global, so
    deterministic per-slice generation isn't possible) and only this
    process's contiguous row slice is *fed* to the devices — a real
    study would stream each host's slice from the DB
    (parallel/multihost.local_row_range).  Single-process this degrades
    to the plain local run."""
    import json

    from .observability.merge import (fragment_manifest_path,
                                      merge_run_manifests)
    from .parallel import multihost
    from .resilience import StepRunner

    cfg = load_config()
    sig_store = args.sig_store or cfg.sig_store
    from .cluster.store import ShardedSignatureStore

    # Routing decides the runtime: the POD path (a signature store under
    # a multi-process env, or an already-sharded root) carries its own
    # file-based identity and NEVER initializes jax.distributed — no XLA
    # coordination client means a dead leader cannot fatal the
    # survivors.  Only the mesh (storeless multi-host) path brings the
    # distributed runtime up, and that must precede any backend use.
    env_nproc, env_pid = multihost.pod_process_env()
    pod_route = bool(sig_store) and (
        env_nproc > 1 or ShardedSignatureStore.is_sharded_root(sig_store))
    if pod_route:
        distributed = False
        nproc, pid = env_nproc, env_pid
    else:
        distributed = multihost.initialize_from_env()
        import jax

        pid = jax.process_index() if distributed else 0
        nproc = jax.process_count() if distributed else 1
    if nproc > 1:
        manifest_path = fragment_manifest_path(cfg.result_dir, pid)
        try:  # this process's stale fragment from a previous run
            os.remove(manifest_path)
        except OSError:
            pass
    else:
        manifest_path = os.path.join(cfg.result_dir, "run_manifest.json")
    runner = StepRunner(manifest_path)
    # graftprof: --profile wraps the step in the host sampler + device
    # trace and drops profile_NNN.json next to run_manifest.json.  The
    # kill switch (TSE1M_PROFILING=0) wins over the flag.
    from .observability import profiling

    prof_on = bool(getattr(args, "profile", False)) \
        and profiling.profiling_enabled()
    if prof_on:
        profiling.install_compile_listener()
        profiling.enable_lock_wait(True)
        profiling.start_sampler()
    try:
        with profiling.device_trace(
                os.path.join(cfg.result_dir, "device_trace")
                if prof_on else None):
            rec = runner.run("cluster", _run_cluster_step, args, sig_store,
                             distributed, pod_route)
    finally:
        if prof_on:
            prof_path = profiling.dump_profile(
                extra={"step": "cluster", "n": int(args.n)},
                d=cfg.result_dir)
            profiling.stop_sampler()
            profiling.enable_lock_wait(False)
            if prof_path:
                log.info("cluster: profile -> %s", prof_path)
    if (rec.result or {}).get("pod_epoch") is not None:
        runner.set_meta(epoch=rec.result["pod_epoch"])
    if nproc > 1:
        survivor = (rec.result or {}).get("pod_survivor")
        if pid == 0 or survivor == pid:
            _await_fragments(cfg.result_dir, nproc)
            merged = merge_run_manifests(cfg.result_dir, nproc)
            log.info("pod manifest merged from %s (missing: %s) -> %s",
                     merged["pod"]["merged_from"],
                     merged["pod"]["missing"],
                     os.path.join(cfg.result_dir, "run_manifest.json"))
    if rec.result is not None:
        print(json.dumps(rec.result))
    from .resilience.coordinator import hard_exit_if_host_lost

    # A run that declared a host lost cannot tear down jax.distributed
    # (the Shutdown barrier needs the dead task); all state is on disk.
    return hard_exit_if_host_lost(runner.exit_code())


def _await_fragments(result_dir: str, nproc: int) -> None:
    """Give slower peers one heartbeat-timeout window to land their
    manifest fragments before merging — a dead peer's fragment is
    recorded as missing, never waited on forever."""
    import time as _time

    from .observability.merge import fragment_manifest_path
    from .resilience.coordinator import heartbeat_timeout_s
    from .resilience.watchdog import deadline_clock

    deadline = deadline_clock() + heartbeat_timeout_s()
    while deadline_clock() < deadline:
        if all(os.path.exists(fragment_manifest_path(result_dir, p))
               for p in range(nproc)):
            return
        _time.sleep(0.2)


def _run_cluster_step(args, sig_store: str | None,
                      distributed: bool, pod_route: bool = False) -> dict:
    from .cluster import (ClusterParams, adjusted_rand_index,
                          cluster_sessions, host_cluster)
    from .data.synth import synth_session_sets
    from .parallel import multihost

    items, truth = synth_session_sets(args.n, seed=args.seed)
    scheme = getattr(args, "scheme", "kminhash")
    params = ClusterParams(seed=args.seed, sig_store=sig_store,
                           prefilter=getattr(args, "prefilter", "auto"),
                           entropy=getattr(args, "entropy", "auto"),
                           scheme=scheme)
    if scheme == "weighted":
        # The weighted workload consumes per-edge hit counts: expand
        # (id, count) into replica ids host-side (schemes.expand_weighted)
        # and feed the replica rows through the unchanged pipeline —
        # signatures then estimate weighted Jaccard exactly.
        from .cluster.schemes import expand_weighted
        from .data.synth import synth_session_hitcounts

        weights = synth_session_hitcounts(items, truth, seed=args.seed)
        items = expand_weighted(items, weights)
    pod_report: dict = {}
    if pod_route:
        # Pod path: per-host digest-range sharded store + supervision,
        # identity from the env (jax.distributed never initialized).
        # (Single-process against a sharded root is the resumed-after-
        # host-loss shape: the membership ledger re-deals every range
        # to this process at the next epoch.)
        if args.checkpoint_dir:
            log.warning("--checkpoint-dir is ignored on the pod path: "
                        "the sharded signature store IS the durable "
                        "state (novel signatures append per chunk); "
                        "this run has no chunk checkpoints")
        labels, pod_report = run_pod_cluster(items, args.n, params)
    elif distributed:
        import numpy as np

        if args.checkpoint_dir:
            log.warning("--checkpoint-dir is ignored under multi-host: "
                        "per-chunk checkpointing is single-process only "
                        "(give each process its own directory and the "
                        "resumable API if you need it); this run is NOT "
                        "checkpointed")
        mesh = multihost.global_mesh()
        # Feed only this process's contiguous LOGICAL slice; the padded-put
        # helper grows the tail block to the mesh multiple with zero rows
        # (any study size works — a real N is never a mesh multiple).
        lo, hi = multihost.local_row_range(
            multihost.padded_row_count(args.n, mesh))
        items_d, _ = multihost.put_process_local_padded(
            np.ascontiguousarray(items[lo:min(hi, args.n)], dtype=np.uint32),
            args.n, mesh)
        labels = cluster_sessions(items_d, params, mesh=mesh)[:args.n]
        multihost.all_processes_ready("cluster-report")
    else:
        from .cluster import cluster_sessions_resumable

        labels = cluster_sessions_resumable(
            items, params, checkpoint_dir=args.checkpoint_dir)
    ari = adjusted_rand_index(labels, truth)
    k = min(args.ari_sample, args.n)
    report = {"n_sessions": args.n,
              "n_clusters": int(len(set(labels.tolist()))),
              "ari_vs_planted": round(float(ari), 5)}
    if sig_store:
        from .cluster.pipeline import last_run_info

        report["sig_store"] = sig_store
        report.update({k_: v for k_, v in last_run_info.items()
                       if k_.startswith(("cache_", "pod_"))
                       or k_ == "wire_mb"})
        report.update(pod_report)
    # Degradation-ladder telemetry (observability plane): how many times
    # the run survived by degrading.  The events themselves attach to the
    # step record (StepRunner pops them into run_manifest.json).
    from .cluster.pipeline import last_run_info as _lri
    from .observability import peek_degradation_events

    report["chunk_halvings"] = int(_lri.get("chunk_halvings", 0))
    report["degradation_events"] = len(peek_degradation_events())
    # Wire-v3 telemetry (storeless single-host runs): what the prefilter
    # and the entropy codec saved this run.
    for key in ("wire_version", "prefilter_hit_rate",
                "prefilter_rows_dropped", "wire_v3_saved_mb"):
        if key in _lri:
            report[key] = _lri[key]
    if k > 0:
        from dataclasses import replace

        host_k = host_cluster(items[:k], n_hashes=params.n_hashes,
                              n_bands=params.n_bands, seed=params.seed,
                              scheme=params.scheme)
        # The subsample re-cluster must NOT touch the store: committing
        # state for a k-row prefix would clobber the full run's state.
        dev_k = (labels if k == args.n else
                 cluster_sessions(items[:k], replace(params,
                                                     sig_store=None)))
        report["ari_vs_host_sample"] = round(
            float(adjusted_rand_index(dev_k, host_k)), 5)
        report["ari_sample_n"] = k
    return report


def _cmd_scrub(args) -> int:
    """Walk a signature store and report frame health (``tse1m scrub``).

    Opening the store already verifies every committed shard's CRC frame
    and quarantines failures (their digests will probe as misses and
    recompute); scrub makes that visible and countable — the
    ``store_scrub_*`` key namespace, recorded in run_manifest.json like
    any step.  ``--repair`` re-frames legacy (pre-CRC) shards and sweeps
    orphans; ``--compact`` folds the append shards into one.  ``--strict``
    exits nonzero when any corruption was found (CI gate).  A pod-sharded
    root (pod_topology.json present) scrubs every digest range.

    ``--verify-sigs`` goes past the CRC frame: sampled recompute of
    stored signatures from raw rows (the synthetic corpus the cluster
    command runs on; ``--verify-n/--verify-seed/--verify-set-size`` pick
    it, ``--verify-sample`` bounds the recompute).  The frame only proves
    the bytes have not rotted SINCE framing — corruption that happened
    before the frame was written was inherited as "correct", and this is
    the check that catches it (``store_scrub_verify_*`` keys; mismatching
    shards quarantine and their rows recompute)."""
    import json

    from .resilience import StepRunner

    cfg = load_config()
    directory = args.store or cfg.sig_store
    if not directory:
        log.error("no store directory: pass one, or set TSE1M_SIG_STORE / "
                  "the INI's sig_store")
        return 2
    manifest_path = os.path.join(cfg.result_dir, "run_manifest.json")
    runner = StepRunner(manifest_path)

    def scrub_step() -> dict:
        from .cluster.store import ShardedSignatureStore, SignatureStore

        if ShardedSignatureStore.is_sharded_root(directory):
            with open(os.path.join(directory, "pod_topology.json"),
                      encoding="utf-8") as f:
                policy = json.load(f)["policy"]
            store = ShardedSignatureStore(directory, policy)
        else:
            store = SignatureStore.open_existing(directory)
        report = store.scrub(repair=args.repair, compact=args.compact)
        if args.verify_sigs:
            from .data.synth import synth_session_hitcounts, \
                synth_session_sets

            items, truth = synth_session_sets(
                args.verify_n, set_size=args.verify_set_size,
                seed=args.verify_seed)
            if store.policy.get("scheme") == "weighted":
                # A weighted store caches signatures of replica-expanded
                # rows; verify must present the same expansion or every
                # probe would miss and the check would be vacuous.
                from .cluster.schemes import expand_weighted

                items = expand_weighted(
                    items, synth_session_hitcounts(items, truth,
                                                   seed=args.verify_seed))
            report.update(store.verify_signatures(
                items, sample=args.verify_sample, seed=args.verify_seed))
        report["store_scrub_dir"] = directory
        return report

    rec = runner.run("scrub", scrub_step)
    if rec.result is not None:
        print(json.dumps(rec.result))
    if rec.status != "ok":
        return 1
    corrupt = (rec.result.get("store_scrub_corrupt", 0)
               + rec.result.get("store_scrub_verify_mismatch", 0))
    if args.strict and corrupt:
        log.error("scrub found %d corrupt/mismatching row-or-shard(s) "
                  "(quarantined; rows recompute on the next warm run)",
                  corrupt)
        return 1
    return 0


def _serve_client(args):
    """Resolve the target daemon (port flag or port file) -> ServeClient."""
    from .serve import ServeClient

    port = args.port
    if not port and args.port_file and os.path.exists(args.port_file):
        with open(args.port_file, encoding="utf-8") as f:
            port = int(f.read().strip())
    if not port:
        raise SystemExit("no daemon address: pass --port or --port-file")
    return ServeClient(host=args.host, port=port)


def _cmd_serve(args) -> int:
    """Online near-duplicate serving daemon (`tse1m serve`).

    Runs the long-lived ingest daemon + query API (tse1m_tpu/serve) over
    the persistent signature store: clients stream coverage vectors in
    (`serve-client ingest`, durably acknowledged) and ask "which cluster
    does this vector belong to?" (`serve-client query`) at interactive
    latency while ingest continues.  The batch `cluster` command and
    this daemon share one index implementation
    (cluster/incremental.LiveClusterIndex), so serving answers are
    CI-asserted elementwise-consistent with a cold batch run.

    ``--status`` turns this invocation into a CLIENT ping instead: the
    daemon's index generation, row count, queue depth, SLO counters and
    last scrub result are printed AND recorded as a ``serve_status``
    step in ``<result_dir>/run_manifest.json`` via StepRunner — the same
    ledger every other operational command writes."""
    import json
    import signal

    from .resilience import StepRunner

    cfg = load_config()
    if args.status:
        manifest_path = os.path.join(cfg.result_dir, "run_manifest.json")
        runner = StepRunner(manifest_path)

        def status_step() -> dict:
            with _serve_client(args) as client:
                return client.status()

        rec = runner.run("serve_status", status_step)
        if rec.result is not None:
            print(json.dumps(rec.result))
            # Per-verb latency at a glance (query vs topk vs ingest) —
            # one blended histogram hides a slow verb behind a fast one.
            for verb, snap in sorted(
                    (rec.result.get("latency_by_verb") or {}).items()):
                log.info("serve %s: n=%d p50=%.2fms p99=%.2fms",
                         verb, int(snap.get("count", 0)),
                         float(snap.get("p50_ms", 0.0)),
                         float(snap.get("p99_ms", 0.0)))
        return 0 if rec.status == "ok" else 1

    store = args.sig_store or cfg.sig_store
    guard = None
    heartbeat = None
    state_every = args.state_every
    if getattr(args, "range", None) is not None:
        # Shard-daemon mode: single writer over ONE digest range of a
        # sharded serve root, fenced by an epoch lease (the router fans
        # requests to it by digest prefix).
        if not args.root:
            log.error("--range needs --root <sharded serve root>")
            return 2
        from .resilience.coordinator import HeartbeatWriter, RangeLeaseGuard

        store = os.path.join(args.root, f"range_{args.range:04d}")
        guard = RangeLeaseGuard.claim(args.root, args.range,
                                      owner=os.getpid())
        # The router's PeerMonitor watches heartbeats keyed by range id.
        heartbeat = HeartbeatWriter(args.root,
                                    process_id=args.range).start()
        if state_every is None:
            # Routed shard writers commit state every generation so a
            # replacement writer preserves local row identity for every
            # acked batch (serve/router.py module docstring).
            state_every = 1
        if not args.port_file:
            args.port_file = os.path.join(args.root,
                                          f"serve_{args.range:04d}.port")
    if state_every is None:
        state_every = 8
    if not store:
        log.error("no signature store: pass --sig-store, or set "
                  "TSE1M_SIG_STORE / the INI's sig_store")
        return 2
    from .cluster import ClusterParams
    from .serve import ServeDaemon, ServeServer, SloPolicy

    params = ClusterParams(seed=args.seed, use_pallas=args.use_pallas)
    daemon = ServeDaemon(store, params=params, slo=SloPolicy.from_env(),
                         state_commit_every=state_every,
                         lease_guard=guard).start()
    server = ServeServer(daemon, host=args.host, port=args.port)

    def _graceful(signum, frame):  # noqa: ARG001
        log.warning("serve: signal %d; shutting down", signum)
        from .observability.flight import dump_flight

        # Operator-initiated teardown still leaves the black box: the
        # dump distinguishes "we were told to stop" from a crash when
        # reading a dead deployment's store directory.
        dump_flight("sigterm", site="serve.shutdown",
                    extra={"signal": int(signum)})
        # shutdown() joins serve_forever, which runs in THIS thread —
        # calling it inline from the handler would deadlock.
        import threading

        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        server.serve_until_shutdown(port_file=args.port_file)
    finally:
        server.server_close()
        daemon.stop()
        if heartbeat is not None:
            heartbeat.stop()
    return 0 if daemon._ingest_error is None else 1


def _cmd_serve_router(args) -> int:
    """Fan-out router over N digest-range shard daemons (`tse1m
    serve-router`).

    Speaks the exact JSON-over-TCP verbs a single daemon does, so
    `serve-client` works unchanged against it: ingest splits by digest
    range and acks only after every owner's manifest commit (durable-
    once, idempotent request ids survive a shard writer failover);
    query broadcasts and min-merges labels.  Shard daemons are resolved
    through their ``<root>/serve_NNNN.port`` files (the default a
    ``serve --root R --range N`` daemon writes), re-read on every
    reconnect — a replacement writer publishes itself by rewriting the
    same file.  The router holds no durable state and never opens a
    store directory (graftlint serve-write-plane)."""
    import signal

    from .resilience.coordinator import PeerMonitor
    from .serve import RouterServer, ShardRouter, TcpTransport

    transports = {
        sid: TcpTransport(
            host=args.shard_host,
            port_file=os.path.join(args.root, f"serve_{sid:04d}.port"))
        for sid in range(args.shards)}
    monitor = PeerMonitor(args.root, n_processes=args.shards,
                          process_id=-1,
                          peers=list(range(args.shards)))
    router = ShardRouter(transports, monitor=monitor)
    server = RouterServer(router, host=args.host, port=args.port)

    def _graceful(signum, frame):  # noqa: ARG001
        log.warning("serve-router: signal %d; shutting down", signum)
        import threading

        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        server.serve_until_shutdown(port_file=args.port_file)
    finally:
        server.server_close()
    return 0


def _cmd_serve_replica(args) -> int:
    """Read replica over a streamed shard-store copy (`tse1m
    serve-replica`).

    Pulls the writer's committed shards + LSH state into ``--dir``
    (CRC-framed file copy, manifest committed last), adopts each new
    generation atomically, and serves ``query``/``status``/``ping``
    over the same TCP protocol — write-plane verbs refuse with a
    structured error.  Staleness is bounded by ``--interval``."""
    import signal

    from .cluster import ClusterParams
    from .serve import (ReplicationPuller, ServeReplica, ServeServer,
                        stream_shards)

    stream_shards(args.src, args.dir)  # first pull before serving
    params = ClusterParams(seed=args.seed, use_pallas="never")
    replica = ServeReplica(args.dir, params=params)
    puller = ReplicationPuller(args.src, replica,
                               interval_s=args.interval).start()
    server = ServeServer(replica, host=args.host, port=args.port)

    def _graceful(signum, frame):  # noqa: ARG001
        log.warning("serve-replica: signal %d; shutting down", signum)
        import threading

        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        server.serve_until_shutdown(port_file=args.port_file)
    finally:
        server.server_close()
        puller.stop()
    return 0


def _cmd_serve_client(args) -> int:
    """One serve-plane client request (`tse1m serve-client <op>`).

    ``query``/``ingest`` read a ``[K, S] uint32`` .npy via ``--npy``;
    every op prints the daemon's JSON response."""
    import json

    import numpy as np

    with _serve_client(args) as client:
        if args.op in ("query", "topk", "ingest"):
            if not args.npy:
                raise SystemExit(f"{args.op} needs --npy <vectors.npy>")
            vectors = np.load(args.npy)
            if args.op == "query":
                resp = client.query(vectors)
            elif args.op == "topk":
                resp = client.topk(vectors, k=args.k, mode=args.mode)
            else:
                resp = client.ingest(vectors)
            resp = {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                    for k, v in resp.items()}
        elif args.op == "slowlog":
            resp = client.slowlog(args.limit)
        elif args.op == "profile":
            resp = client.profile(dump=args.dump)
        else:
            resp = getattr(client, args.op)()
    print(json.dumps(resp))
    return 0 if resp.get("ok", False) else 1


def _cmd_backfill(args) -> int:
    """Bulk re-label via the exact scoring plane (`tse1m backfill`).

    For every query vector in ``--npy``, device-scans EVERY committed
    store row by exact signature agreement (`cluster.kernels.score
    .bulk_topk_store` — the recall-1.0 path, no band-candidate loss)
    and reports the k nearest stored sessions as (digest, agreement,
    label) triples — the re-label/backfill primitive: assign each
    unlabeled session its nearest cluster without waiting for the daily
    batch recluster.

    Two targets: ``--sig-store DIR`` scans a store directory in-process
    (read-only — safe next to a live writer), or ``--port``/
    ``--port-file`` drives a running daemon/router over TCP via the
    ``topk`` verb in scan mode."""
    import json
    import time

    import numpy as np

    vectors = np.load(args.npy)
    n = int(vectors.shape[0])
    out = {"scores": [], "ids": [], "labels": []}
    t0 = time.monotonic()
    rows_scored = 0
    if args.sig_store:
        from .cluster import ClusterParams
        from .serve import ServeReplica

        target = ServeReplica(args.sig_store,
                              params=ClusterParams(seed=args.seed))
        store_rows = int(target.store.n_rows)

        def ask(batch):
            return target.topk(batch, k=args.k, mode="scan")
    else:
        client = _serve_client(args)
        store_rows = int(client.status().get("store_rows", 0))

        def ask(batch):
            return client.topk(batch, k=args.k, mode="scan",
                               timeout_s=args.timeout)
    for lo in range(0, n, args.batch):
        resp = ask(np.ascontiguousarray(vectors[lo:lo + args.batch],
                                        np.uint32))
        out["scores"].extend(np.asarray(resp["scores"]).tolist())
        out["labels"].extend(np.asarray(resp["labels"]).tolist())
        out["ids"].extend(resp["ids"])
        rows_scored += store_rows * int(
            min(args.batch, n - lo))
    wall = time.monotonic() - t0
    if args.out:
        from .utils.atomic import atomic_write

        with atomic_write(args.out) as f:
            json.dump(out, f)
    summary = {"ok": True, "queries": n, "k": int(args.k),
               "store_rows": store_rows,
               "pairs_scored": rows_scored,
               "wall_s": round(wall, 3),
               "pairs_scored_s": round(rows_scored / wall, 1)
               if wall > 0 else 0.0}
    if args.out:
        summary["out"] = args.out
    else:
        summary["results"] = out
    print(json.dumps(summary))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tse1m")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("synth", help="generate + load a synthetic study")
    p.add_argument("--db", default=None)
    p.add_argument("--projects", type=int, default=24)
    p.add_argument("--days", type=int, default=450)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--csv-dir", default=None)
    p.set_defaults(fn=_cmd_synth)

    p = sub.add_parser("stats", help="study inventory + severity breakdown")
    p.add_argument("--db")
    p.set_defaults(fn=_cmd_stats)

    p = sub.add_parser("ingest", help="load collector CSVs into the DB")
    p.add_argument("--db", default=None)
    p.add_argument("--csv-dir", required=True)
    p.set_defaults(fn=_cmd_ingest)

    p = sub.add_parser("restore",
                       help="restore a SQL dump (reference backup_clean.sql "
                            "workflow) into the configured DB")
    p.add_argument("dump", help="path to the .sql dump")
    p.add_argument("--db", default=None)
    p.set_defaults(fn=_cmd_restore)

    for name in ("rq1", "rq2a", "rq2b", "rq3", "rq4a", "rq4b", "all"):
        p = sub.add_parser(name, help=f"run {name} analysis")
        p.add_argument("--db", default=None)
        p.add_argument("--backend", choices=("pandas", "jax_tpu", "auto"),
                       default=None)
        p.add_argument("--result-dir", default=None,
                       help="artifact root (default data/result_data; also "
                            "settable via TSE1M_RESULT_DIR)")
        p.set_defaults(fn=_cmd_rq)

    p = sub.add_parser("collect", help="run an offline collection step")
    p.add_argument("step", choices=("projects", "gcs-metadata", "coverage",
                                    "buildlogs", "issues", "corpus"))
    p.add_argument("--repo", default="data/collect_data/repos/oss-fuzz")
    p.add_argument("--data-dir", default="data/processed_data/csv")
    p.add_argument("--no-clone", action="store_true")
    p.add_argument("--max-pages", type=int, default=None)
    p.add_argument("--limit", type=int, default=None)
    p.add_argument("--ids-file", default=None)
    p.add_argument("--workers", type=int, default=8)
    p.set_defaults(fn=_cmd_collect)

    p = sub.add_parser("lint",
                       help="graftlint: enforce the repo's JAX/DB/"
                            "resilience invariants (LINTING.md)")
    p.add_argument("paths", nargs="*",
                   help="files to lint (default: tse1m_tpu/ + bench.py)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--write-baseline", action="store_true")
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--rules", default=None)
    p.add_argument("--changed", metavar="REF", default=None,
                   help="incremental: lint files differing from REF plus "
                        "their reverse-dependency closure")
    p.add_argument("--why", metavar="RULE:PATH:LINE", default=None,
                   help="print the witness call chain for one finding")
    p.add_argument("--graph", action="store_true",
                   help="print the import/call-graph summary")
    p.set_defaults(fn=_cmd_lint)

    p = sub.add_parser("spec",
                       help="graftspec: model-check the executable "
                            "protocol specs (README 'Protocol specs & "
                            "model checking')")
    p.add_argument("action", choices=("check", "trace", "mutants"))
    p.add_argument("names", nargs="*",
                   help="spec (or mutant) names; check defaults to all "
                        "three committed specs")
    p.add_argument("--mode", choices=("bfs", "dfs"), default="bfs",
                   help="exploration order (BFS counterexamples are "
                        "shortest)")
    p.add_argument("--max-states", type=int, default=None,
                   help="state-count safety valve (default 200000)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_spec)

    p = sub.add_parser("scrub",
                       help="walk a signature store: verify CRC frames, "
                            "quarantine corruption, report store_scrub_* "
                            "health keys (see README 'Surviving failures')")
    p.add_argument("store", nargs="?", default=None,
                   help="store directory (default: TSE1M_SIG_STORE / the "
                        "INI's sig_store)")
    p.add_argument("--repair", action="store_true",
                   help="re-frame legacy (pre-CRC) shards and sweep orphans")
    p.add_argument("--compact", action="store_true",
                   help="fold the append shards into one large shard")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero when any corruption was found")
    p.add_argument("--verify-sigs", action="store_true",
                   help="sampled recompute of stored signatures from raw "
                        "rows — catches pre-framing corruption the CRC "
                        "frame inherited as 'correct' "
                        "(store_scrub_verify_* keys)")
    p.add_argument("--verify-n", type=int, default=2000,
                   help="rows of the synthetic corpus to verify against")
    p.add_argument("--verify-seed", type=int, default=0)
    p.add_argument("--verify-set-size", type=int, default=64)
    p.add_argument("--verify-sample", type=int, default=256,
                   help="max sampled rows recomputed on host")
    p.set_defaults(fn=_cmd_scrub)

    p = sub.add_parser("serve",
                       help="online near-duplicate serving daemon over a "
                            "signature store (README 'Online serving'); "
                            "--status pings a running daemon instead")
    p.add_argument("--sig-store", default=None,
                   help="signature store directory the daemon serves "
                        "(also TSE1M_SIG_STORE / the INI's sig_store)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port (0 = pick a free one; see --port-file)")
    p.add_argument("--port-file", default=None,
                   help="write the bound port here (atomic) so clients "
                        "and --status can find a 0-port daemon")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--use-pallas", default="auto",
                   choices=("auto", "never", "force", "interpret"))
    p.add_argument("--state-every", type=int, default=None,
                   help="commit the LSH state to the store every N ingest "
                        "generations (acks are durable regardless; this "
                        "bounds recovery work after a crash); default 8, "
                        "or 1 in shard mode (--range) so the router can "
                        "rely on committed local row ids")
    p.add_argument("--root", default=None,
                   help="sharded serve root (shard mode; with --range)")
    p.add_argument("--range", type=int, default=None,
                   help="digest range this daemon owns as single writer "
                        "(shard mode: serves <root>/range_NNNN, claims "
                        "the range's epoch lease, writes a heartbeat and "
                        "defaults --port-file to <root>/serve_NNNN.port)")
    p.add_argument("--status", action="store_true",
                   help="client mode: print a running daemon's status "
                        "(index generation, rows, queue depth + backlog "
                        "high-water/rejection history, SLO counters, "
                        "last scrub) and record it as a serve_status "
                        "step in run_manifest.json")
    p.set_defaults(fn=_cmd_serve)

    p = sub.add_parser("serve-client",
                       help="one client request against a running serve "
                            "daemon")
    p.add_argument("op", choices=("ping", "status", "query", "topk",
                                  "ingest", "metrics", "trace", "slowlog",
                                  "profile", "quiesce", "shutdown"))
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--port-file", default=None)
    p.add_argument("--npy", default=None,
                   help="[K, S] uint32 .npy of coverage vectors "
                        "(query/topk/ingest)")
    p.add_argument("--k", type=int, default=10,
                   help="topk: neighbours per query vector")
    p.add_argument("--mode", default="candidates",
                   choices=("candidates", "scan"),
                   help="topk: band-candidate probe (interactive) or "
                        "exact full-store device scan (recall 1.0)")
    p.add_argument("--limit", type=int, default=None,
                   help="slowlog: at most N most-recent captures")
    p.add_argument("--dump", action="store_true",
                   help="profile: also write profile_NNN.json daemon-side "
                        "and return its path")
    p.set_defaults(fn=_cmd_serve_client)

    p = sub.add_parser("backfill",
                       help="bulk re-label: exact top-k device scan of a "
                            "signature store for every query vector "
                            "(README 'Top-k search & bulk scoring')")
    p.add_argument("--npy", required=True,
                   help="[K, S] uint32 .npy of coverage vectors to "
                        "re-label")
    p.add_argument("--sig-store", default=None,
                   help="scan this store directory in-process "
                        "(read-only); otherwise --port/--port-file "
                        "drives a running daemon's topk verb")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--port-file", default=None)
    p.add_argument("--k", type=int, default=1,
                   help="nearest stored sessions per query (default 1: "
                        "the re-label assignment)")
    p.add_argument("--batch", type=int, default=256,
                   help="query vectors per scan pass")
    p.add_argument("--timeout", type=float, default=None,
                   help="TCP mode: per-batch budget override (scan "
                        "requests default to the ingest-class budget)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None,
                   help="write full (scores, ids, labels) JSON here "
                        "(atomic); default prints them inline")
    p.set_defaults(fn=_cmd_backfill)

    p = sub.add_parser("serve-router",
                       help="stateless fan-out router over digest-range "
                            "shard daemons (README 'Sharded serving'); "
                            "serve-client works unchanged against it")
    p.add_argument("--root", required=True,
                   help="sharded serve root holding the shards' "
                        "serve_NNNN.port files and heartbeats")
    p.add_argument("--shards", type=int, default=2,
                   help="number of digest-range shard daemons")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--shard-host", default="127.0.0.1",
                   help="host the shard daemons listen on")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--port-file", default=None)
    p.set_defaults(fn=_cmd_serve_router)

    p = sub.add_parser("serve-replica",
                       help="read replica over a streamed store copy "
                            "(stale-bounded query/status; writes refuse)")
    p.add_argument("--src", required=True,
                   help="writer store directory to stream shards from")
    p.add_argument("--dir", required=True,
                   help="replica store directory (created/refreshed)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between replication pulls (staleness "
                        "bound)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--port-file", default=None)
    p.set_defaults(fn=_cmd_serve_replica)

    p = sub.add_parser("cluster", help="MinHash+LSH session dedup demo")
    p.add_argument("--n", type=int, default=100_000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ari-sample", type=int, default=10_000,
                   help="subsample size for the device-vs-host ARI gate")
    p.add_argument("--checkpoint-dir", default=None,
                   help="persist per-chunk signature shards here; a killed "
                        "run re-invoked with the same dir resumes at the "
                        "first unfinished chunk (single-process path)")
    p.add_argument("--sig-store", default=None,
                   help="persistent content-addressed signature store "
                        "directory (cluster/store.py): warm re-runs probe "
                        "cached MinHash signatures and ship only novel "
                        "rows; accreted re-runs merge labels on host. "
                        "Also settable via TSE1M_SIG_STORE / the INI's "
                        "sig_store; recorded in run_manifest.json")
    p.add_argument("--prefilter", default="auto",
                   choices=("off", "auto", "on"),
                   help="wire v3 host-side LSH prefilter "
                        "(cluster/prefilter.py): rows bucketed singleton "
                        "in every host band skip the device and the wire "
                        "entirely; labels stay elementwise-equal to the "
                        "unfiltered run (storeless single-host only)")
    p.add_argument("--entropy", default="auto",
                   choices=("off", "auto", "force"),
                   help="wire v3 rANS lane coding (cluster/entropy.py): "
                        "'auto' entropy-codes wire lanes that beat their "
                        "bit-packed form, per chunk/lane; 'force' codes "
                        "everything (testing)")
    p.add_argument("--scheme", default="kminhash",
                   choices=("kminhash", "cminhash", "weighted"),
                   help="signature kernel family (cluster/schemes.py): "
                        "'kminhash' = K-permutation multiply-shift (the "
                        "original family, default); 'cminhash' = one-"
                        "permutation C-MinHash + densification (~H x "
                        "fewer hash evaluations per row); 'weighted' = "
                        "exact weighted minwise over per-edge hit counts "
                        "(replica expansion; a NEW workload — the paper "
                        "models set membership only). Joins the store/"
                        "checkpoint policy tuple: mixed-scheme stores "
                        "refuse like mixed-seed stores")
    p.add_argument("--profile", action="store_true",
                   help="graftprof: host sampling profiler (span/plane/"
                        "lock-wait attribution) + jax device trace + "
                        "compile-duration histograms around the run; "
                        "writes profile_NNN.json (and device_trace/) into "
                        "the result dir next to run_manifest.json. "
                        "TSE1M_PROFILING=0 kills the whole plane")
    p.set_defaults(fn=_cmd_cluster)

    args = ap.parse_args(argv)
    _activate_config_fault_plan()
    _activate_xla_cache()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
