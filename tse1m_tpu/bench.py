"""``python -m tse1m_tpu.bench`` — the BENCH-trajectory toolbelt.

Thin argparse front over :mod:`.observability.regress`: the committed
``BENCH_r*.json`` rounds are the paper's "measure the fleet over time"
artifact in miniature, and this is the tool that reads them.

    python -m tse1m_tpu.bench diff BENCH_r08.json BENCH_r09.json
    python -m tse1m_tpu.bench gate /tmp/bench.json \
        --baseline BENCH_baseline_smoke.json
    python -m tse1m_tpu.bench baseline BENCH_baseline_smoke.json \
        run1.json run2.json run3.json --note "2k CPU smoke"
    python -m tse1m_tpu.bench keys serve

``gate`` exits nonzero on a regression — that exit code IS the CI
perf-gate job.  (The top-level ``bench.py`` *produces* rounds; this
module *judges* them.)
"""

from __future__ import annotations

import argparse
import json
import sys

from .observability import regress


def _load_one(path: str) -> dict:
    """One bench result: the last JSON line of the file (bench.py
    streams logs above its final JSON) or the whole file."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        raise ValueError("file is empty")
    try:
        return json.loads(text.splitlines()[-1])
    except json.JSONDecodeError:
        return json.loads(text)


def _fail_input(path: str, err: Exception) -> int:
    """A missing/truncated/corrupt input is an operator mistake, not a
    traceback: say which file, why, and how to mint a fresh baseline."""
    reason = str(err) or type(err).__name__
    print(f"bench: cannot read {path}: {reason} — check the path, or "
          f"regenerate with `python -m tse1m_tpu.bench baseline "
          f"<out.json> <run.json>...`", file=sys.stderr)
    return 2


def _cmd_diff(args) -> int:
    rounds = []
    for path in (args.round_a, args.round_b):
        try:
            rounds.append(_load_one(path))
        except (OSError, ValueError) as e:  # JSONDecodeError is a ValueError
            return _fail_input(path, e)
    a, b = rounds
    print(regress.diff(a, b, name_a=args.round_a, name_b=args.round_b,
                       show_all=args.all))
    return 0


def _cmd_gate(args) -> int:
    try:
        current = _load_one(args.current)
    except (OSError, ValueError) as e:
        return _fail_input(args.current, e)
    try:
        baseline = regress.load_runs(args.baseline)
    except (OSError, ValueError) as e:
        return _fail_input(args.baseline, e)
    report = regress.gate(current, baseline)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(regress.format_gate_report(report))
    return 0 if report["ok"] else 1


def _cmd_baseline(args) -> int:
    runs = [_load_one(p) for p in args.runs]
    regress.write_baseline(args.out, runs, note=args.note)
    print(f"baseline: {len(runs)} run(s) -> {args.out}")
    return 0


def _cmd_keys(args) -> int:
    if args.context:
        for key in regress.required_keys(args.context):
            print(key)
    else:
        for key, spec in regress.BENCH_SCHEMA.items():
            flags = ",".join(spec["contexts"]) or "-"
            gate_s = (f" gate(tol={spec['tol']}, abs={spec['abs']})"
                      if spec["gate"] else "")
            print(f"{key:<32} [{flags}] {spec['dir'] or '-':<6}"
                  f"{gate_s}  {spec['desc']}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tse1m_tpu.bench",
        description="diff/gate the BENCH_r*.json trajectory")
    sub = p.add_subparsers(dest="cmd", required=True)

    d = sub.add_parser("diff", help="delta report between two rounds")
    d.add_argument("round_a")
    d.add_argument("round_b")
    d.add_argument("--all", action="store_true",
                   help="include <2%% deltas and ungated keys")
    d.set_defaults(fn=_cmd_diff)

    g = sub.add_parser("gate",
                       help="noise-aware regression gate vs a baseline")
    g.add_argument("current", help="fresh bench JSON to judge")
    g.add_argument("--baseline", required=True,
                   help="committed baseline (single run or "
                        "{'runs': [...]})")
    g.add_argument("--json", action="store_true",
                   help="machine-readable report")
    g.set_defaults(fn=_cmd_gate)

    b = sub.add_parser("baseline",
                       help="assemble a median-of-k baseline file")
    b.add_argument("out")
    b.add_argument("runs", nargs="+")
    b.add_argument("--note", default="")
    b.set_defaults(fn=_cmd_baseline)

    k = sub.add_parser("keys", help="print the bench-key schema")
    k.add_argument("context", nargs="?",
                   help="bench | degradation | fault | serve")
    k.set_defaults(fn=_cmd_keys)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
