"""tse1m_tpu — TPU-native framework with the capabilities of the TSE
"1 Million Fuzzing Sessions" replication package.

The reference (``/root/reference``, see SURVEY.md) is a pandas/Postgres data
pipeline answering four research questions over ~1.19M OSS-Fuzz build
sessions.  This package keeps its *contract* — the same entry points
(``run_all_analysis.sh``, ``program/research_questions/rq*.py``), config file
(``program/envFile.ini``) and artifact formats — but replaces the engine:

- ``db``        canonical schema, parameterized queries, sqlite/postgres
                drivers, and the CSV->DB ingestion the reference lacks
                (reference: ``program/__module/dbFile.py``, ``queries1.py``)
- ``data``      bulk columnar extraction into CSR struct-of-arrays + the
                synthetic fixture generator (the real dump is gitignored
                in the reference)
- ``ops``       device kernels: segment searchsorted/reductions, masked
                percentiles, rank stats, MinHash (pallas), banded LSH,
                connected components
- ``parallel``  mesh construction, shardings, collectives (ICI/DCN seat
                that NCCL holds in the reference's GPU analogues: none —
                see SURVEY.md §2.4)
- ``backend``   the {pandas, jax_tpu} dispatcher behind envFile.ini
- ``cluster``   north-star session dedup: MinHash signatures (pallas),
                banded LSH, label propagation, host oracle, ARI
- ``analysis``  RQ1..RQ4b re-implemented over backend primitives
                (reference: ``program/research_questions/*.py``)
- ``collect``   the six offline ETL collectors
                (reference: ``program/preparation/*.py``)
- ``utils``     structured logging, phase timing, run manifests
"""

__version__ = "0.1.0"
