"""Typed metrics registry: one instrumentation substrate for the repo.

Before this module every plane kept its own ad-hoc tallies —
degradation counts in a module list, admission rejections inside the
controller, retry hits in a per-call stats dict, stage walls in
StageRecorder — each with its own snapshot shape and none queryable
while the process runs.  The registry absorbs them behind the three
Prometheus-shaped types:

- :class:`Counter` — monotonically increasing event tallies
  (``degradations_total{kind=...}``, ``retries_total{site=...}``,
  ``fault_injections_total{site=...,kind=...}``,
  ``lease_superseded_total``, ``serve_ingest_rejected_total``)
- :class:`Gauge` — point-in-time or high-water levels
  (``serve_queue_depth``, ``serve_ingest_backlog_max``,
  ``serve_store_generation``, ``serve_store_rows``)
- :class:`Histogram` — distributions on the log-bucketed
  :class:`~.latency.LatencyRecorder` core
  (``stage_seconds{stage=...}``, the serve latency classes)

Metrics are get-or-create keyed by ``(name, sorted labels)``, so an
instrumentation site never checks existence — it asks the registry and
increments.  ``export.py`` renders the registry as Prometheus text
(the TCP ``metrics`` verb), a structured snapshot (``run_manifest``),
and flat ``metrics_*`` keys (bench JSON); ``merge.py`` folds fragment
snapshots across a pod.

Every type is thread-safe behind the traced-lock primitives, so the
lockset detector audits the metrics plane like production state.
"""

from __future__ import annotations

from ..trace import sync as tsync
from ..trace.hooks import shared_access
from .latency import LatencyRecorder


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic event counter.  ``inc`` only goes up; a decrement is a
    modelling error (use a Gauge)."""

    def __init__(self, name: str, labels: dict) -> None:
        self.name = name
        self.labels = dict(labels)
        self._lock = tsync.Lock(f"Counter.{name}")
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            shared_access(self, "value", write=True)
            self._value += int(n)

    @property
    def value(self) -> int:
        with self._lock:
            shared_access(self, "value", write=False)
            return self._value


class Gauge:
    """Settable level.  ``set_max`` keeps the high-water mark — the
    shape backlog/queue-depth telemetry wants (a backpressure episode
    must stay visible after the queue drains)."""

    def __init__(self, name: str, labels: dict) -> None:
        self.name = name
        self.labels = dict(labels)
        self._lock = tsync.Lock(f"Gauge.{name}")
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            shared_access(self, "value", write=True)
            self._value = float(v)

    def set_max(self, v: float) -> None:
        with self._lock:
            shared_access(self, "value", write=True)
            if float(v) > self._value:
                self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            shared_access(self, "value", write=False)
            return self._value


class Histogram:
    """Distribution on the log-bucketed LatencyRecorder core (values
    are seconds unless the name says otherwise).  The recorder brings
    its own traced lock; this wrapper only adds the registry shape."""

    def __init__(self, name: str, labels: dict) -> None:
        self.name = name
        self.labels = dict(labels)
        self._rec = LatencyRecorder(name)

    def observe(self, value_s: float) -> None:
        self._rec.add(float(value_s))

    def time(self):
        return self._rec.time()

    def snapshot(self) -> dict:
        return self._rec.snapshot()

    def buckets(self) -> dict:
        return self._rec.buckets()


class MetricsRegistry:
    """Get-or-create registry over the three metric types.

    One process-global default instance backs the module-level helpers;
    tests that need isolation construct their own or call
    :func:`reset_metrics`."""

    def __init__(self) -> None:
        self._lock = tsync.Lock("MetricsRegistry")
        self._metrics: dict = {}

    def _get(self, kind, name: str, labels: dict):
        key = (name, _label_key(labels))
        with self._lock:
            shared_access(self, "metrics", write=True)
            m = self._metrics.get(key)
            if m is None:
                m = kind(name, labels)
                self._metrics[key] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {kind.__name__}")
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def collect(self) -> list:
        """All registered metrics, sorted by (name, labels) so every
        export is deterministic."""
        with self._lock:
            shared_access(self, "metrics", write=False)
            items = sorted(self._metrics.items())
        return [m for _, m in items]

    def clear(self) -> None:
        with self._lock:
            shared_access(self, "metrics", write=True)
            self._metrics = {}


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


def counter(name: str, **labels) -> Counter:
    return _registry.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return _registry.gauge(name, **labels)


def histogram(name: str, **labels) -> Histogram:
    return _registry.histogram(name, **labels)


def reset_metrics() -> None:
    """Drop every registered metric (test isolation; a fresh process
    starts empty anyway)."""
    _registry.clear()


__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "counter",
           "gauge", "get_registry", "histogram", "reset_metrics"]
