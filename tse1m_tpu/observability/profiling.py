"""graftprof: host sampling profiler with span & lock-wait attribution.

The telemetry plane (PR 13) tells you *that* a request was slow — a
span duration, a histogram tail.  This module tells you *why*: a
daemon thread samples ``sys._current_frames()`` at ``TSE1M_PROF_HZ``
(default 97 Hz — prime, so the sampler never phase-locks to a
periodic workload) and tags each sampled thread with its active span
via the per-thread open-span mirror in :mod:`.tracing`.  Samples
aggregate three ways: per-plane self-time (which subpackage owns the
wall), per-span self-time (which unit of work owns it), and collapsed
stacks (``a;b;c count`` — the flamegraph input format), all readable
while the process runs and dumped atomically into ``profile_NNN.json``
next to the flight files.

Lock-wait attribution rides the traced-lock seat in
:mod:`..trace.sync`: when enabled, every untraced acquire is timed on
``deadline_clock`` and the time-to-acquire lands in the metrics
registry as ``lock_wait_seconds{site=<lock name>}``.  This is the
direct, quantified picture of a lock convoy — e.g. the BENCH_r08
anecdote of queries stuck 250 ms+ behind a big ingest absorb shows up
as a fat ``SignatureStore.*`` / absorb-site tail here.  The recorder
never touches the registry directly: the acquire it just timed may BE
the registry's own lock, still held by the caller, so observations
buffer in a GIL-atomic dict and :func:`flush_lock_waits` folds them
into the histograms from lock-free entry points.

The slow-request log closes the loop for serving: when a query or
ingest blows its SLO budget, :func:`capture_slow_request` freezes the
evidence — open-span chain, completed spans of the same trace, the
sampler stacks overlapping the request window, the lock waits the
request's thread just suffered, and the daemon's in-flight absorb
state — into a bounded ring exported over the TCP ``slowlog`` verb.

Overhead discipline (the ``prof-overhead`` lint rule's contract):
every sampling thread is a ``daemon=True`` thread named
``tse1m-prof-sampler``, and the whole plane sits behind one kill
switch — ``TSE1M_PROFILING=0`` (or :func:`set_profiling`) refuses to
start samplers, detaches the lock-wait recorder, and makes a running
sampler loop exit.  CI gates the residual cost: profiled query p99
must stay within 1.1x + 0.5 ms of unprofiled.

This module lives in the ``watchdog-clock`` lint plane: all timing is
``deadline_clock`` (one time base with the deadlines and histograms
the profiles explain) and the only file write is the atomic profile
dump.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import threading

from ..resilience.watchdog import deadline_clock
from ..trace import sync as tsync
from ..trace.hooks import shared_access, trace_point
from ..utils.atomic import atomic_write
from ..utils.logging import get_logger
from . import tracing
from .flight import get_flight_dir
from .metrics import counter, get_registry, histogram

log = get_logger("observability.profiling")

_DEFAULT_HZ = 97.0
_STACK_DEPTH = 48
_STACK_CAP = 5000
_RECENT_SAMPLES = 4096
_DEFAULT_SLOWLOG = 64
_WAIT_FLOOR_MS = 0.5      # per-thread recent-wait floor (noise gate)
_WAIT_KEEP = 16           # per-thread recent waits retained for capture
_PROFILE_FMT = "profile_{:03d}.json"
_SAMPLER_THREAD_NAME = "tse1m-prof-sampler"


# -- kill switch --------------------------------------------------------------

_override: bool | None = None


def profiling_enabled() -> bool:
    """The plane-wide kill switch: ``TSE1M_PROFILING=0`` wins unless a
    runtime :func:`set_profiling` call overrode it.  Checked on sampler
    start AND inside the sampler loop, so flipping the env var kills a
    live sampler within one period."""
    if _override is not None:
        return _override
    return os.environ.get("TSE1M_PROFILING", "1") != "0"


def set_profiling(on: bool | None) -> None:
    """Runtime override of the kill switch (``None`` restores the env
    var's verdict).  Turning profiling off tears down the live seats:
    the global sampler is stopped and joined, and the lock-wait
    recorder is detached — "off" must mean no sampling threads exist."""
    global _override
    _override = None if on is None else bool(on)
    if on is not None and not on:
        stop_sampler()
        tsync.set_lock_wait_recorder(None)


# -- sample attribution helpers ----------------------------------------------

def _plane_of(filename: str) -> str:
    """Map a frame's file to its plane: the subpackage under
    ``tse1m_tpu/`` (``serve``, ``cluster``, ...), a top-level module's
    own name, or ``ext`` for everything outside the package."""
    p = filename.replace("\\", "/")
    i = p.rfind("tse1m_tpu/")
    if i < 0:
        return "ext"
    rest = p[i + len("tse1m_tpu/"):]
    j = rest.find("/")
    return rest[:j] if j >= 0 else rest.rsplit(".", 1)[0]


def _frame_label(code) -> str:
    base = os.path.basename(code.co_filename)
    return f"{base.rsplit('.', 1)[0]}:{code.co_name}"


# -- the sampler --------------------------------------------------------------

class Sampler:
    """Periodic whole-process stack sampler (one daemon thread).

    State is guarded by one traced lock; the sampler thread is the
    only writer, readers (``snapshot``/``stacks_between``/the dump)
    see a consistent cut.  The thread never samples itself — its own
    frames are pure overhead, not workload."""

    def __init__(self, hz: float | None = None) -> None:
        if hz is None:
            hz = float(os.environ.get("TSE1M_PROF_HZ", _DEFAULT_HZ))
        self.hz = max(1.0, float(hz))
        self._period = 1.0 / self.hz
        self._lock = tsync.Lock("Sampler")
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self._samples = 0
        self._plane_self: dict = {}
        self._span_self: dict = {}
        self._stacks: dict = {}
        self._recent: collections.deque = collections.deque(
            maxlen=_RECENT_SAMPLES)
        self._started_at = deadline_clock()

    # lifecycle ---------------------------------------------------------------

    def start(self) -> bool:
        """Spawn the sampler thread; False (and no thread) when the
        TSE1M_PROFILING kill switch is off."""
        if not profiling_enabled():
            return False
        with self._lock:
            shared_access(self, "thread", write=True)
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop_evt = threading.Event()
            th = threading.Thread(target=self._loop,
                                  name=_SAMPLER_THREAD_NAME, daemon=True)
            self._thread = th
            self._started_at = deadline_clock()
        th.start()
        return True

    def stop(self, timeout: float = 2.0) -> None:
        with self._lock:
            shared_access(self, "thread", write=True)
            th = self._thread
            evt = self._stop_evt
            self._thread = None
        evt.set()
        if th is not None and th.is_alive():
            th.join(timeout)

    def alive(self) -> bool:
        with self._lock:
            shared_access(self, "thread", write=False)
            th = self._thread
        return th is not None and th.is_alive()

    def _loop(self) -> None:
        evt = self._stop_evt
        while not evt.wait(self._period):
            if not profiling_enabled():
                break
            self._sample_once()

    # sampling ----------------------------------------------------------------

    def _sample_once(self) -> None:
        now = deadline_clock()
        me = threading.get_ident()
        frames = sys._current_frames()
        with self._lock:
            shared_access(self, "stacks", write=True)
            for tid, frame in frames.items():
                if tid == me:
                    continue
                leaf_plane = _plane_of(frame.f_code.co_filename)
                entry = tracing.thread_span(tid)
                span_name = entry[2] if entry else "(no-span)"
                parts = []
                f = frame
                depth = 0
                while f is not None and depth < _STACK_DEPTH:
                    parts.append(_frame_label(f.f_code))
                    f = f.f_back
                    depth += 1
                parts.reverse()
                collapsed = ";".join(parts)
                self._samples += 1
                self._plane_self[leaf_plane] = (
                    self._plane_self.get(leaf_plane, 0) + 1)
                self._span_self[span_name] = (
                    self._span_self.get(span_name, 0) + 1)
                if collapsed in self._stacks or len(
                        self._stacks) < _STACK_CAP:
                    self._stacks[collapsed] = (
                        self._stacks.get(collapsed, 0) + 1)
                else:
                    self._stacks["(other)"] = (
                        self._stacks.get("(other)", 0) + 1)
                self._recent.append((now, tid, span_name, collapsed))

    # readers -----------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-safe aggregate: total samples, per-plane and per-span
        self-time shares, and how long the sampler has run."""
        with self._lock:
            shared_access(self, "stacks", write=False)
            samples = self._samples
            planes = dict(self._plane_self)
            spans = dict(self._span_self)
            t0 = self._started_at
        return {
            "hz": self.hz,
            "samples": samples,
            "window_s": round(deadline_clock() - t0, 3),
            "plane_self": dict(sorted(planes.items(),
                                      key=lambda kv: -kv[1])),
            "span_self": dict(sorted(spans.items(),
                                     key=lambda kv: -kv[1])[:32]),
        }

    def collapsed(self, limit: int | None = None) -> list:
        """Flamegraph lines ``frame;frame;frame count``, hottest first
        — feed straight to flamegraph.pl / speedscope."""
        with self._lock:
            shared_access(self, "stacks", write=False)
            items = sorted(self._stacks.items(), key=lambda kv: -kv[1])
        if limit is not None:
            items = items[:int(limit)]
        return [f"{stack} {count}" for stack, count in items]

    def stacks_between(self, t0: float, t1: float,
                       tid: int | None = None) -> list:
        """Samples whose timestamp falls in ``[t0, t1]`` (deadline_clock
        axis), optionally for one thread — the slow-request log's
        "what was the process doing during my window" query."""
        with self._lock:
            shared_access(self, "stacks", write=False)
            recent = list(self._recent)
        out = []
        for t, sample_tid, span_name, collapsed in recent:
            if t < t0 or t > t1:
                continue
            if tid is not None and sample_tid != tid:
                continue
            out.append({"t_s": round(t, 4), "tid": sample_tid,
                        "span": span_name, "stack": collapsed})
        return out


# -- global sampler seat ------------------------------------------------------

_sampler: Sampler | None = None


def start_sampler(hz: float | None = None) -> Sampler | None:
    """Start (or return the running) process-wide sampler; None when
    the kill switch is off — callers never need to branch."""
    global _sampler
    if not profiling_enabled():
        return None
    s = _sampler
    if s is None:
        s = Sampler(hz)
        _sampler = s
    if not s.start():
        return None
    return s


def get_sampler() -> Sampler | None:
    return _sampler


def stop_sampler(timeout: float = 2.0) -> None:
    global _sampler
    s = _sampler
    _sampler = None
    if s is not None:
        s.stop(timeout)


# -- lock-wait attribution ----------------------------------------------------

_wait_state = threading.local()

# Pending per-site wait samples, folded into the registry's
# ``lock_wait_seconds`` histograms by flush_lock_waits().  The recorder
# CANNOT observe into the registry directly: the acquire it just timed
# may be the registry's own lock (every histogram lives behind one),
# and observing would re-acquire that non-reentrant lock on the same
# thread — a self-deadlock no reentrancy flag can prevent.  setdefault
# and append are GIL-atomic, so this buffer needs no lock of its own.
_pending_waits: dict = {}
_PENDING_CAP = 4096


def _record_lock_wait(lock, acquire, blocking: bool = True,
                      timeout: float = -1) -> bool:
    """The recorder installed into trace.sync: time the raw acquire on
    deadline_clock, buffer it per lock site (see ``_pending_waits``),
    and remember notable waits per-thread for slow-request capture.
    The ``busy`` flag stops acquires made *while recording* from
    re-entering the recorder."""
    st = _wait_state
    if getattr(st, "busy", False):
        return acquire(blocking, timeout)
    st.busy = True
    try:
        t0 = deadline_clock()
        ok = acquire(blocking, timeout)
        dt = deadline_clock() - t0
        pend = _pending_waits.setdefault(lock.name, [])
        if len(pend) < _PENDING_CAP:
            pend.append(dt)
        if dt * 1e3 >= _WAIT_FLOOR_MS:
            waits = getattr(st, "waits", None)
            if waits is None:
                waits = st.waits = []
            waits.append((lock.name, round(dt * 1e3, 3)))
            del waits[:-_WAIT_KEEP]
        return ok
    finally:
        st.busy = False


def flush_lock_waits() -> None:
    """Fold the pending wait samples into the registry's
    ``lock_wait_seconds`` histograms.  Callers must not hold any traced
    lock (every summary/dump entry point qualifies).  Best-effort: a
    sample appended to a site list between our pop and a concurrent
    setdefault is dropped — profiling data, not accounting."""
    st = _wait_state
    st.busy = True  # don't record the registry's own acquires below
    try:
        for site in list(_pending_waits):
            samples = _pending_waits.pop(site, [])
            if samples:
                h = histogram("lock_wait_seconds", site=site)
                for dt in samples:
                    h.observe(dt)
    finally:
        st.busy = False


def enable_lock_wait(on: bool = True) -> bool:
    """Attach (or detach) the lock-wait recorder to the traced-lock
    seat.  Refuses to attach when TSE1M_PROFILING kills the plane."""
    if on and not profiling_enabled():
        return False
    tsync.set_lock_wait_recorder(_record_lock_wait if on else None)
    return bool(on)


def drain_lock_waits() -> list:
    """``(site, wait_ms)`` pairs the *calling thread* accumulated since
    its last drain — a slow request drains its own thread to learn
    which locks it just queued on."""
    waits = getattr(_wait_state, "waits", None)
    if not waits:
        return []
    out = list(waits)
    del waits[:]
    return out


def lock_wait_summary(top: int | None = None) -> list:
    """Per-site wait stats from the registry's ``lock_wait_seconds``
    histograms, worst p99 first: ``{site, count, p99_ms, max_ms}``."""
    flush_lock_waits()
    out = []
    for m in get_registry().collect():
        if m.name != "lock_wait_seconds" or not hasattr(m, "snapshot"):
            continue
        snap = m.snapshot()
        if not snap.get("count"):
            continue
        out.append({"site": m.labels.get("site", "?"),
                    "count": snap["count"],
                    "p99_ms": snap["p99_ms"],
                    "max_ms": snap["max_ms"]})
    out.sort(key=lambda r: (-r["p99_ms"], r["site"]))
    if top is not None:
        out = out[:int(top)]
    return out


# -- slow-request log ---------------------------------------------------------

class SlowRequestLog:
    """Bounded ring of SLO-violation captures (thread-safe,
    overwrite-oldest).  Records are JSON-safe dicts: the ``slowlog``
    verb and ``serve --status`` ship them without translation."""

    def __init__(self, capacity: int | None = None) -> None:
        if capacity is None:
            capacity = int(os.environ.get("TSE1M_SLOWLOG_CAP",
                                          _DEFAULT_SLOWLOG))
        self.capacity = max(1, int(capacity))
        self._lock = tsync.Lock("SlowRequestLog")
        self._buf: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._total = 0

    def append(self, record: dict) -> None:
        trace_point("profiling.slowlog.append")
        with self._lock:
            shared_access(self, "buf", write=True)
            self._buf.append(record)
            self._total += 1

    def recent(self, n: int | None = None) -> list:
        with self._lock:
            shared_access(self, "buf", write=False)
            out = list(self._buf)
        if n is not None:
            out = out[-int(n):]
        return out

    def total(self) -> int:
        with self._lock:
            shared_access(self, "buf", write=False)
            return self._total

    def clear(self) -> None:
        with self._lock:
            shared_access(self, "buf", write=True)
            self._buf.clear()
            self._total = 0


_slowlog = SlowRequestLog()


def slow_request_log() -> SlowRequestLog:
    return _slowlog


def recent_slow_requests(n: int | None = None) -> list:
    return _slowlog.recent(n)


def slow_requests_total() -> int:
    return _slowlog.total()


def capture_slow_request(kind: str, wall_s: float, budget_ms: float,
                         t0: float | None = None,
                         absorb: dict | None = None, **tags) -> dict:
    """Freeze the evidence for one budget-blowing request.  Call from
    the request's own thread right after it finishes: the open-span
    chain, the per-thread lock waits, the sampler window and the
    in-flight absorb state are all read relative to the caller."""
    now = deadline_clock()
    if t0 is None:
        t0 = now - wall_s
    trace = tracing.current_trace()
    record = {
        "kind": str(kind),
        "wall_ms": round(wall_s * 1e3, 3),
        "budget_ms": round(float(budget_ms), 3),
        "at_s": round(now, 3),
        "trace": trace,
        "span_chain": tracing.thread_span_chain(),
        "lock_waits_ms": drain_lock_waits(),
        "absorb": dict(absorb) if absorb else None,
    }
    sampler = _sampler
    if sampler is not None:
        record["stacks"] = sampler.stacks_between(t0, now)[-8:]
    else:
        record["stacks"] = []
    if trace:
        record["trace_spans"] = [
            s for s in tracing.recent_spans(64)
            if s and s.get("trace") == trace["t"]][-8:]
    if tags:
        record["tags"] = {str(k): v for k, v in tags.items()}
    _slowlog.append(record)
    counter("slow_requests_total", kind=str(kind)).inc()
    return record


# -- compile-duration histograms ----------------------------------------------

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_compile_listener_installed = False


def install_compile_listener() -> bool:
    """Idempotently route XLA backend-compile durations into the
    registry as ``jit_compile_seconds`` (jax.monitoring has no removal
    API, so ONE process-lifetime listener; the registry histogram it
    feeds is reset with the registry).  Returns availability."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return True
    try:
        import jax.monitoring as monitoring

        def _on_event(event: str, duration: float = 0.0, **kw) -> None:
            if event == _COMPILE_EVENT:
                histogram("jit_compile_seconds",
                          event=event.rsplit("/", 1)[-1]).observe(duration)

        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception as e:  # graftlint: disable=broad-except -- jax absent/too old; compile histograms degrade to unavailable
        log.warning("compile-duration listener unavailable (%s: %s)",
                    type(e).__name__, e)
        return False
    _compile_listener_installed = True
    return True


@contextlib.contextmanager
def device_trace(outdir: str | None):
    """``jax.profiler`` device-trace capture around a block; a no-op
    when ``outdir`` is falsy, the kill switch is off, or jax's profiler
    is unavailable — call sites never branch."""
    if not outdir or not profiling_enabled():
        yield
        return
    try:
        from jax import profiler as jprof
        os.makedirs(outdir, exist_ok=True)
        jprof.start_trace(outdir)
    except Exception as e:  # graftlint: disable=broad-except -- profiler backend optional; trace capture degrades to no-op
        log.warning("device trace unavailable (%s: %s)",
                    type(e).__name__, e)
        yield
        return
    try:
        yield
    finally:
        jprof.stop_trace()


# -- artifact + status --------------------------------------------------------

def _next_profile_path(d: str) -> str:
    n = 0
    for name in os.listdir(d):
        if name.startswith("profile_") and name.endswith(".json"):
            try:
                n = max(n, int(name[len("profile_"):-len(".json")]) + 1)
            except ValueError:
                continue
    return os.path.join(d, _PROFILE_FMT.format(n))


def dump_profile(extra: dict | None = None,
                 d: str | None = None) -> str | None:
    """Write ``profile_NNN.json`` (atomic, numbered like the flight
    files) into ``d`` or the flight directory; returns the path, or
    None when no directory is configured.  All timestamps are on the
    deadline_clock axis — profiles and flight dumps line up."""
    if d is None:
        d = get_flight_dir()
    if not d:
        return None
    sampler = _sampler
    payload = {
        "pid": os.getpid(),
        "uptime_s": round(deadline_clock(), 3),
        "trace_id": tracing.pinned_trace(),
        "profiling_enabled": profiling_enabled(),
        "sampler": sampler.snapshot() if sampler is not None else None,
        "collapsed_stacks": (sampler.collapsed(200)
                             if sampler is not None else []),
        "lock_wait_sites": lock_wait_summary(),
        "slow_requests": _slowlog.recent(32),
        "slow_requests_total": _slowlog.total(),
    }
    if extra:
        payload["extra"] = dict(extra)
    os.makedirs(d, exist_ok=True)
    path = _next_profile_path(d)
    with atomic_write(path) as f:
        json.dump(payload, f, indent=2, default=str)
    log.info("profile dumped to %s", path)
    return path


def profile_status() -> dict:
    """JSON-safe live summary for the serve ``profile`` verb and
    ``--status``: kill-switch state, sampler aggregate, worst lock
    sites, slow-request tally."""
    sampler = _sampler
    return {
        "profiling_enabled": profiling_enabled(),
        "sampler_alive": bool(sampler is not None and sampler.alive()),
        "sampler": sampler.snapshot() if sampler is not None else None,
        "lock_wait_top": lock_wait_summary(top=3),
        "slow_requests_total": _slowlog.total(),
    }


__all__ = ["Sampler", "SlowRequestLog", "capture_slow_request",
           "device_trace", "drain_lock_waits", "dump_profile",
           "enable_lock_wait", "flush_lock_waits", "get_sampler",
           "install_compile_listener", "lock_wait_summary",
           "profile_status", "profiling_enabled", "recent_slow_requests",
           "set_profiling", "slow_request_log", "slow_requests_total",
           "start_sampler", "stop_sampler"]
