"""Per-stage pipeline telemetry (encode / h2d / compute / d2h).

The north-star wall is dominated by stages a single wall clock cannot
separate: host bit-pack encoding, the H2D transfer, device compute, and
the label fetch.  BENCH_r05 showed 1.86 s of device compute inside a
15.2 s wall — the other 13 s were wire and host encode, invisible in the
bench JSON.  This module is the one place those stages are measured:

- :class:`StageRecorder` — thread-safe per-stage (wall seconds, bytes)
  accumulator.  The double-buffered streaming pipeline records `encode`
  and `h2d` from its producer thread while `compute` accrues on the main
  thread, so summed stage walls exceed the elapsed wall exactly when the
  overlap works; :meth:`as_dict` reports that surplus as
  ``h2d_overlap_fraction`` (fraction of H2D seconds hidden behind the
  other stages — 0 means fully sequential, 1 means the wire was free).
- a module-level handoff slot (:func:`record_last_stages` /
  :func:`pop_last_stages`) so layers that cannot see each other —
  `cluster/pipeline.py` producing timings, `resilience/runner.py`
  embedding them into ``run_manifest.json``, `bench.py` emitting
  ``stage_*`` keys — share one record without coupling their APIs.

Stage names are part of the bench-JSON contract (``stage_<name>_s`` /
``stage_<name>_mb`` keys, PARITY.md "Wire format & streaming pipeline"):
``encode`` host-side packing, ``h2d`` host->device transfer, ``compute``
device dispatch+wait, ``d2h`` device->host result fetch — plus the
signature-store warm path's ``probe`` (content hashing + store
bulk-probe) and ``load`` (cached-signature mmap reads, bytes = gathered
signature bytes), recorded by `cluster/pipeline.py`'s store paths, and
wire v3's ``prefilter`` (the host one-permutation band-key pass) and
``entropy`` (rANS lane coding; its *bytes* column counts bytes SAVED vs
the bit-packed alternative, so ``stage_entropy_mb`` reads as the
codec's win).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

from ..trace import sync as tsync
from ..trace.hooks import shared_access

STAGES = ("encode", "h2d", "compute", "d2h", "probe", "load",
          "prefilter", "entropy")


class StageRecorder:
    """Accumulates (wall seconds, payload bytes) per pipeline stage.

    Thread-safe: the streaming pipeline's producer thread records encode
    and h2d concurrently with the main thread's compute — and the
    readers (``as_dict`` / ``h2d_overlap_fraction``) snapshot under the
    same lock.  They used to iterate the live dicts unlocked, which the
    graftrace lockset detector flagged: a producer adding a NEW stage
    key mid-``sum(self.wall.values())`` is a dict-changed-size crash,
    and even without one the reader could tear a wall against its
    bytes (regression schedule: tests/test_trace.py)."""

    def __init__(self) -> None:
        self._lock = tsync.Lock("StageRecorder")
        self.wall: dict[str, float] = defaultdict(float)
        self.nbytes: dict[str, int] = defaultdict(int)
        self.total_wall_s: float = 0.0

    def add(self, stage: str, seconds: float, nbytes: int = 0) -> None:
        with self._lock:
            shared_access(self, "stages", write=True)
            self.wall[stage] += seconds
            self.nbytes[stage] += nbytes
        # Outside the lock: the registry histogram brings its own (the
        # lock-order pass sees StageRecorder -> LatencyRecorder nowhere
        # else, so keep the sections disjoint).  Stage walls land in the
        # same substrate as every other distribution (`metrics.py`)
        # while `as_dict` keeps emitting the bench-JSON contract keys.
        from . import metrics

        metrics.histogram("stage_seconds", stage=stage).observe(seconds)

    @contextlib.contextmanager
    def stage(self, name: str, nbytes: int = 0):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0, nbytes)

    def set_total(self, seconds: float) -> None:
        # Under the lock like every other mutation: the producer thread
        # can still be adding its last h2d record when the main thread
        # closes out the run (caught by graftlint unlocked-shared-state).
        with self._lock:
            shared_access(self, "stages", write=True)
            self.total_wall_s = seconds

    def _snapshot_locked(self) -> tuple[dict, dict, float]:
        with self._lock:
            shared_access(self, "stages", write=False)
            return dict(self.wall), dict(self.nbytes), self.total_wall_s

    @staticmethod
    def _overlap(walls: dict, total: float) -> float:
        h2d = walls.get("h2d", 0.0)
        if h2d <= 0.0 or total <= 0.0:
            return 0.0
        hidden = sum(walls.values()) - total
        return round(min(1.0, max(0.0, hidden / h2d)), 4)

    def h2d_overlap_fraction(self) -> float:
        """Fraction of H2D seconds hidden behind other stages.

        ``hidden = sum(stage walls) - elapsed wall`` is the time at least
        two stages ran concurrently; expressing it as a fraction of the
        H2D wall answers the question the double-buffer exists for: how
        much of the wire time did compute/encode absorb?
        """
        walls, _, total = self._snapshot_locked()
        return self._overlap(walls, total)

    def as_dict(self) -> dict:
        """Flat bench-JSON form: stage_<name>_s / stage_<name>_mb keys."""
        walls, nbytes, total = self._snapshot_locked()
        out: dict = {}
        for name in sorted(walls):
            out[f"stage_{name}_s"] = round(walls[name], 4)
            if nbytes.get(name):
                out[f"stage_{name}_mb"] = round(nbytes[name] / 2**20, 2)
        if total:
            out["stage_total_wall_s"] = round(total, 4)
        out["h2d_overlap_fraction"] = self._overlap(walls, total)
        return out


# -- cross-layer handoff ----------------------------------------------------
# Last completed run's stage dict.  Written by the pipeline (and anything
# else that times stages), consumed destructively by resilience.StepRunner
# (into run_manifest.json) and non-destructively by bench.py.  A plain
# slot, not an API: one producer at a time, same contract as
# cluster.pipeline.last_run_info.
_last_stages: dict | None = None
_last_lock = tsync.Lock("observability._last_lock")


def record_last_stages(stages: dict) -> None:
    global _last_stages
    with _last_lock:
        _last_stages = dict(stages)


def peek_last_stages() -> dict | None:
    with _last_lock:
        return dict(_last_stages) if _last_stages is not None else None


def pop_last_stages() -> dict | None:
    """Take (and clear) the last run's stage record — StepRunner calls
    this after each step so a step that timed nothing doesn't inherit a
    predecessor's stages."""
    global _last_stages
    with _last_lock:
        out = _last_stages
        _last_stages = None
        return out


# -- degradation events -----------------------------------------------------
# The supervision/degradation plane's observable log: every time the
# system survives a failure by degrading — a stalled transfer cancelled
# and retried, a chunk halved under RESOURCE_EXHAUSTED, a device failover,
# a quarantined store shard, an interrupted DB statement — one event lands
# here.  Consumed destructively by resilience.StepRunner (per-step
# ``degradations`` list in run_manifest.json) and by bench.py
# (``degradation_events`` / ``chunk_halvings`` keys), same handoff
# contract as the stage record above.  Events are deterministic (no
# wall-clock): ``seq`` orders them within a process.

_degradations: list = []
_degradation_lock = tsync.Lock("observability._degradation_lock")
_degradation_seq = 0


def record_degradation(kind: str, site: str = "",
                       detail: dict | None = None) -> dict:
    """Append one degradation event; returns the event dict."""
    global _degradation_seq
    with _degradation_lock:
        _degradation_seq += 1
        event = {"seq": _degradation_seq, "kind": kind, "site": site,
                 "detail": dict(detail or {})}
        _degradations.append(event)
    from . import metrics

    metrics.counter("degradations_total", kind=kind).inc()
    return event


def peek_degradation_events() -> list:
    with _degradation_lock:
        return [dict(e) for e in _degradations]


def pop_degradation_events() -> list:
    """Take (and clear) the accumulated degradation events."""
    with _degradation_lock:
        out = list(_degradations)
        _degradations.clear()
        return out


def degradation_counts(events: list) -> dict:
    """kind -> count summary for manifests/bench JSON."""
    by: dict[str, int] = {}
    for e in events:
        by[e["kind"]] = by.get(e["kind"], 0) + 1
    return by


from .export import flat_metrics, metrics_snapshot, prometheus_text
from .flight import dump_flight, get_flight_dir, set_flight_dir
from .latency import LatencyRecorder
from .merge import (MERGED_MANIFEST, fragment_manifest_path,
                    merge_run_manifests, sweep_stale_fragments)
from .metrics import (MetricsRegistry, counter, gauge, get_registry,
                      histogram, reset_metrics)
from .tracing import (adopt_trace, continue_trace, current_trace,
                      new_trace_id, pinned_trace, recent_spans, set_tracing,
                      span, spans_recorded, tracing_enabled)

__all__ = ["LatencyRecorder", "MERGED_MANIFEST", "MetricsRegistry",
           "STAGES", "StageRecorder", "adopt_trace", "continue_trace",
           "counter", "current_trace", "degradation_counts", "dump_flight",
           "flat_metrics", "fragment_manifest_path", "gauge",
           "get_flight_dir", "get_registry", "histogram",
           "merge_run_manifests", "metrics_snapshot", "new_trace_id",
           "peek_degradation_events", "pinned_trace",
           "pop_degradation_events", "prometheus_text", "recent_spans",
           "record_degradation", "record_last_stages", "peek_last_stages",
           "pop_last_stages", "reset_metrics", "set_flight_dir",
           "set_tracing", "span", "spans_recorded", "sweep_stale_fragments",
           "tracing_enabled"]
