"""Span-based tracing on the watchdog plane's one blessed clock.

The span model is Dapper's: a trace is a tree of timed spans sharing
one 16-hex trace id, each span naming one unit of work (a pipeline
step, a serve request, a retry attempt).  Everything here rides
``deadline_clock`` — the same monotonic base every watchdog deadline
and latency histogram compares against — so a span duration and the
budget that would have reaped it are always on one time axis.

Propagation is explicit and JSON-friendly: ``current_trace()`` returns
a tiny ``{"t": trace_id, "s": span_id}`` context that rides the serve
envelope, a ticket, a heartbeat payload or an ``fs_exchange`` array,
and ``continue_trace(ctx)`` adopts it on the far side so the remote
work lands in the same trace.  A pod run pins one process-wide trace
id derived from the negotiated run nonce (``adopt_trace``), which is
how two worker processes end up in one cross-process trace without a
collector.

Completed spans land in a bounded ring buffer (:class:`SpanRing`)
guarded by the traced-lock primitives, so the lockset detector and the
deterministic scheduler audit the telemetry plane like any other
shared-state class.  The ring is the flight recorder's span source and
the TCP ``trace`` verb's backing store.

Discipline: spans are opened with ``with span(name): ...`` (or an
``ExitStack.enter_context``).  The manual ``start_span``/``Span.end``
pair exists for the rare cross-callback shape and must sit in a
``try/finally`` — graftlint's ``span-discipline`` rule enforces both.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading

from ..resilience.watchdog import deadline_clock
from ..trace import sync as tsync
from ..trace.hooks import shared_access, trace_point

_DEFAULT_RING = 512


def _hex_id() -> str:
    return os.urandom(8).hex()


def new_trace_id() -> str:
    return _hex_id()


# -- the span ring ------------------------------------------------------------


class SpanRing:
    """Bounded ring of completed span records (thread-safe).

    Overwrite-oldest semantics: a long run keeps the most recent N
    spans, which is exactly the window a post-mortem wants.  Records
    are plain JSON-safe dicts so the flight recorder and the ``trace``
    verb serialise them without translation."""

    def __init__(self, capacity: int | None = None) -> None:
        if capacity is None:
            capacity = int(os.environ.get("TSE1M_TRACE_RING",
                                          _DEFAULT_RING))
        self.capacity = max(1, int(capacity))
        self._lock = tsync.Lock("SpanRing")
        self._buf: list = [None] * self.capacity
        self._next = 0
        self._total = 0

    def append(self, record: dict) -> None:
        trace_point("tracing.ring.append")
        with self._lock:
            shared_access(self, "buf", write=True)
            self._buf[self._next] = record
            self._next = (self._next + 1) % self.capacity
            self._total += 1

    def recent(self, n: int | None = None) -> list:
        """Last ``n`` completed spans, oldest first."""
        with self._lock:
            shared_access(self, "buf", write=False)
            if self._total < self.capacity:
                out = list(self._buf[:self._next])
            else:
                out = self._buf[self._next:] + self._buf[:self._next]
        if n is not None:
            out = out[-int(n):]
        return out

    def total(self) -> int:
        with self._lock:
            shared_access(self, "buf", write=False)
            return self._total

    def clear(self) -> None:
        with self._lock:
            shared_access(self, "buf", write=True)
            self._buf = [None] * self.capacity
            self._next = 0
            self._total = 0


_ring = SpanRing()


def span_ring() -> SpanRing:
    return _ring


def recent_spans(n: int | None = None) -> list:
    return _ring.recent(n)


def spans_recorded() -> int:
    return _ring.total()


def clear_spans() -> None:
    return _ring.clear()


# -- enable gate + process-pinned trace ---------------------------------------

_enabled = os.environ.get("TSE1M_TRACING", "1") != "0"
_pinned: str | None = None


def tracing_enabled() -> bool:
    return _enabled


def set_tracing(on: bool) -> None:
    """Runtime gate — the bench's untraced control window flips this
    off around its measurement loop.  Disabled means ``span()`` hands
    back a shared no-op and nothing touches the ring."""
    global _enabled
    _enabled = bool(on)


def adopt_trace(trace_id: str | None) -> None:
    """Pin a process-wide trace id: root spans opened with no active
    parent join this trace instead of minting their own.  The pod
    plane derives it from the negotiated run nonce, so every worker
    process pins the same id."""
    global _pinned
    _pinned = str(trace_id) if trace_id else None


def pinned_trace() -> str | None:
    return _pinned


# -- span context -------------------------------------------------------------

_current: contextvars.ContextVar = contextvars.ContextVar(
    "tse1m_current_span", default=None)

# Thread-id -> stack of (trace, span_id, name) for OPEN spans.  The
# contextvar above is invisible from other threads, but the sampling
# profiler (observability/profiling.py) must tag a ``sys._current_frames``
# sample with the sampled thread's active span — this mirror is that
# join table.  Each thread only ever mutates its own entry (one dict
# store / pop under the GIL), so readers get a consistent-enough view
# without a lock on the span hot path.
_thread_spans: dict = {}


def thread_span(tid: int):
    """(trace, span_id, name) of the innermost open span on thread
    ``tid``, or None — the sampler's attribution lookup."""
    stack = _thread_spans.get(tid)
    return stack[-1] if stack else None


def thread_span_chain(tid: int | None = None) -> list:
    """Open-span names outermost-first for ``tid`` (default: the calling
    thread) — the slow-request log's span chain for spans that have not
    closed into the ring yet."""
    if tid is None:
        tid = threading.get_ident()
    stack = _thread_spans.get(tid)
    return [entry[2] for entry in stack] if stack else []


def current_trace() -> dict | None:
    """The propagation context of the innermost active span:
    ``{"t": trace_id, "s": span_id}``, or None outside any span."""
    cur = _current.get()
    if cur is None:
        return None
    return {"t": cur[0], "s": cur[1]}


class Span:
    """One in-flight span.  ``end()`` is idempotent; the record only
    reaches the ring on the first call."""

    __slots__ = ("trace", "span_id", "parent", "name", "tags",
                 "_start", "_token", "_done", "_tid")

    def __init__(self, trace: str, span_id: str, parent: str,
                 name: str, tags: dict, token) -> None:
        self.trace = trace
        self.span_id = span_id
        self.parent = parent
        self.name = name
        self.tags = tags
        self._start = deadline_clock()
        self._token = token
        self._done = False
        self._tid = threading.get_ident()
        _thread_spans.setdefault(self._tid, []).append(
            (trace, span_id, name))

    def set_tag(self, key: str, value) -> None:
        self.tags[str(key)] = value

    def end(self, ok: bool = True) -> None:
        if self._done:
            return
        self._done = True
        dur = deadline_clock() - self._start
        if self._token is not None:
            with contextlib.suppress(ValueError):
                _current.reset(self._token)
        stack = _thread_spans.get(self._tid)
        if stack:
            # Normally the top frame; a span ended from another thread
            # (rare cross-callback shape) searches down for its id.
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][1] == self.span_id:
                    del stack[i]
                    break
            if not stack:
                _thread_spans.pop(self._tid, None)
        _ring.append({"trace": self.trace, "span": self.span_id,
                      "parent": self.parent, "name": self.name,
                      "start_s": round(self._start, 6),
                      "dur_s": round(dur, 6), "ok": bool(ok),
                      "tags": dict(self.tags), "pid": os.getpid()})


class _NoopSpan:
    __slots__ = ()

    def set_tag(self, key: str, value) -> None:
        pass

    def end(self, ok: bool = True) -> None:
        pass


_NOOP = _NoopSpan()


def start_span(name: str, **tags):
    """Open a span manually.  Pair with ``end()`` in a ``finally`` —
    ``span-discipline`` flags anything looser.  Prefer ``span()``."""
    if not _enabled:
        return _NOOP
    cur = _current.get()
    if cur is not None:
        trace, parent = cur
    else:
        trace, parent = (_pinned or _hex_id()), ""
    span_id = _hex_id()
    token = _current.set((trace, span_id))
    return Span(trace, span_id, parent, str(name), dict(tags), token)


@contextlib.contextmanager
def span(name: str, **tags):
    """The blessed way to open a span: closes on every exit path and
    marks the record failed when the body raised."""
    sp = start_span(name, **tags)
    ok = True
    try:
        yield sp
    except BaseException:
        ok = False
        raise
    finally:
        sp.end(ok=ok)


@contextlib.contextmanager
def continue_trace(ctx: dict | None):
    """Adopt a remote propagation context (``current_trace()`` output
    that rode an envelope/ticket/heartbeat): spans opened inside
    become children of the remote span.  A falsy ctx is a no-op, so
    call sites never branch on whether the peer traced."""
    if not ctx or not ctx.get("t"):
        yield
        return
    token = _current.set((str(ctx["t"]), str(ctx.get("s") or "")))
    try:
        yield
    finally:
        with contextlib.suppress(ValueError):
            _current.reset(token)


__all__ = ["Span", "SpanRing", "adopt_trace", "clear_spans",
           "continue_trace", "current_trace", "new_trace_id",
           "pinned_trace", "recent_spans", "set_tracing", "span",
           "span_ring", "spans_recorded", "start_span", "thread_span",
           "thread_span_chain", "tracing_enabled"]
