"""Three views of one metrics registry.

- :func:`prometheus_text` — the Prometheus text exposition format,
  served live over the TCP ``metrics`` verb (stdlib-only: the pull
  model needs a string, not a client library).
- :func:`metrics_snapshot` — a structured JSON-safe dict with full
  label detail, embedded in ``run_manifest.json`` fragments and in
  flight-recorder dumps, and folded across a pod by ``merge.py``.
- :func:`flat_metrics` — stable ``metrics_<name>`` scalars for bench
  JSON (labels are aggregated: counters sum, gauges take the max,
  histograms export ``_count``/``_p99_ms``), so the CI contract can
  assert key presence without depending on which label sets a round
  happened to touch.
"""

from __future__ import annotations

from .metrics import Counter, Gauge, Histogram, MetricsRegistry, get_registry


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", "\\\\").replace('"', '\\"')
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def prometheus_text(registry: MetricsRegistry | None = None) -> str:
    reg = registry or get_registry()
    lines: list = []
    typed: set = set()
    for m in reg.collect():
        kind = ("counter" if isinstance(m, Counter)
                else "gauge" if isinstance(m, Gauge) else "histogram")
        if m.name not in typed:
            typed.add(m.name)
            lines.append(f"# TYPE {m.name} {kind}")
        if isinstance(m, Histogram):
            b = m.buckets()
            for bk in b["buckets"]:
                lab = _label_str({**m.labels, "le": bk["le"]})
                lines.append(f"{m.name}_bucket{lab} {bk['count']}")
            inf = _label_str({**m.labels, "le": "+Inf"})
            lines.append(f"{m.name}_bucket{inf} {b['count']}")
            lab = _label_str(m.labels)
            lines.append(f"{m.name}_sum{lab} {_fmt(b['sum'])}")
            lines.append(f"{m.name}_count{lab} {b['count']}")
        else:
            lines.append(f"{m.name}{_label_str(m.labels)} {_fmt(m.value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def metrics_snapshot(registry: MetricsRegistry | None = None) -> dict:
    reg = registry or get_registry()
    out: dict = {"counters": [], "gauges": [], "histograms": []}
    for m in reg.collect():
        if isinstance(m, Counter):
            out["counters"].append(
                {"name": m.name, "labels": m.labels, "value": m.value})
        elif isinstance(m, Gauge):
            out["gauges"].append(
                {"name": m.name, "labels": m.labels, "value": m.value})
        else:
            snap = m.snapshot()
            out["histograms"].append(
                {"name": m.name, "labels": m.labels, **snap,
                 **{k: v for k, v in m.buckets().items()
                    if k in ("buckets", "sum")}})
    return out


def flat_metrics(registry: MetricsRegistry | None = None,
                 prefix: str = "metrics_") -> dict:
    reg = registry or get_registry()
    out: dict = {}
    for m in reg.collect():
        if isinstance(m, Counter):
            key = f"{prefix}{m.name}"
            out[key] = out.get(key, 0) + m.value
        elif isinstance(m, Gauge):
            key = f"{prefix}{m.name}"
            out[key] = max(out.get(key, 0.0), m.value)
        else:
            snap = m.snapshot()
            ck, pk = f"{prefix}{m.name}_count", f"{prefix}{m.name}_p99_ms"
            out[ck] = out.get(ck, 0) + snap["count"]
            out[pk] = max(out.get(pk, 0.0), snap["p99_ms"])
    return out


__all__ = ["flat_metrics", "metrics_snapshot", "prometheus_text"]
