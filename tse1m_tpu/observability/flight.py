"""Crash-time flight recorder: the black box for chaos post-mortems.

A fault-matrix seat that SIGKILLs the serve daemon, fences a zombie
worker, or breaches a watchdog deadline leaves a process that cannot
explain itself — the bench JSON never materialises and the manifest
fragment stops mid-step.  The flight recorder closes that gap: crash
paths call :func:`dump_flight` and the last N spans, a full metrics
snapshot, and the recent degradation events land atomically in
``flight_NNN.json`` next to the manifest (or the store, for the serve
daemon) *before* the process dies.

The dump prepends a terminal span named ``flight.<reason>`` tagged
with the firing seat, so the last span in every dump identifies what
killed the process — the acceptance contract the fault matrix asserts.

``dump_flight`` must never make a crash worse: with no directory
configured it is a no-op, and any internal failure is swallowed
(injected faults excepted — the chaos plane stays transparent).
Triggers wired in this PR: ``kill``-kind fault injection (before the
SIGKILL), ``LeaseSupersededError`` self-fencing, the serve CLI's
SIGTERM handler, watchdog deadline breaches, ingest-thread crashes,
and StepRunner step failures after retries.
"""

from __future__ import annotations

import json
import os
import time

from ..resilience.faults import reraise_if_fault
from ..resilience.watchdog import deadline_clock
from ..utils.atomic import atomic_write
from ..utils.logging import get_logger
from . import tracing
from .export import metrics_snapshot

log = get_logger("observability.flight")

_FLIGHT_FMT = "flight_{:03d}.json"
_SPAN_WINDOW = 256

_flight_dir: str | None = None


def set_flight_dir(path: str | None) -> None:
    """Point the recorder at the run's artifact directory (manifest
    dir for pod workers, store dir for the serve daemon).  The
    ``TSE1M_FLIGHT_DIR`` env var seeds it across process spawns; an
    explicit call wins."""
    global _flight_dir
    _flight_dir = str(path) if path else None


def get_flight_dir() -> str | None:
    if _flight_dir is not None:
        return _flight_dir
    return os.environ.get("TSE1M_FLIGHT_DIR") or None


def _next_path(d: str) -> str:
    n = 0
    for name in os.listdir(d):
        if name.startswith("flight_") and name.endswith(".json"):
            try:
                n = max(n, int(name[len("flight_"):-len(".json")]) + 1)
            except ValueError:
                continue
    return os.path.join(d, _FLIGHT_FMT.format(n))


def dump_flight(reason: str, site: str | None = None,
                extra: dict | None = None) -> str | None:
    """Write one flight file; returns its path, or None when no
    directory is configured or the dump itself failed (a recorder
    failure must never mask the crash being recorded)."""
    d = get_flight_dir()
    if not d:
        return None
    try:
        with tracing.span(f"flight.{reason}", site=site or ""):
            pass
        payload = {
            "reason": str(reason),
            "site": site,
            "pid": os.getpid(),
            "written_at": time.time(),
            "uptime_s": round(deadline_clock(), 3),
            "trace_id": tracing.pinned_trace(),
            "spans": tracing.recent_spans(_SPAN_WINDOW),
            "metrics": metrics_snapshot(),
            "degradation_events": _recent_degradations(),
        }
        if extra:
            payload["extra"] = dict(extra)
        os.makedirs(d, exist_ok=True)
        path = _next_path(d)
        with atomic_write(path) as f:
            json.dump(payload, f, indent=2, default=str)
        log.warning("flight recorder: %s dumped to %s", reason, path)
        return path
    except Exception as e:
        reraise_if_fault(e)
        log.error("flight recorder: dump for %s failed (%s: %s)", reason,
                  type(e).__name__, e)
        return None


def _recent_degradations() -> list:
    from . import peek_degradation_events

    return peek_degradation_events()


__all__ = ["dump_flight", "get_flight_dir", "set_flight_dir"]
