"""Bench-JSON schema + noise-aware perf-regression harness (graftprof).

The repo has accumulated nine committed ``BENCH_r*.json`` rounds and
three *separately maintained* copies of "which keys must a bench JSON
carry" — the bench-smoke heredoc, the serve-smoke heredoc, and
``tests/ci_fault_matrix.py``'s ``BENCH_KEYS``.  Three-way drift is a
matter of time, and none of the copies can answer the question the
trajectory exists for: *did this round regress?*

This module is the single source of truth for both:

- :data:`BENCH_SCHEMA` — one machine-readable entry per contract key:
  which CI contexts require it (``bench`` / ``degradation`` / ``fault``
  / ``serve``), which direction is better, and — for gated keys — the
  noise tolerance the perf gate allows before it goes red.  The CI
  smokes and the fault matrix import :func:`required_keys` /
  :func:`assert_bench_keys`; a missing key fails with the offending
  key named.
- the regression harness: :func:`diff` renders a human-readable delta
  report across any two rounds, and :func:`gate` checks a fresh run
  against a committed baseline with noise-aware bands —
  ``median(history) * (1 +/- tolerance) +/- 3*MAD +/- abs_slack`` per
  key, direction-aware.  MAD (median absolute deviation) makes the
  band robust to one outlier round; the relative tolerance absorbs
  machine-class skew; the absolute slack keeps near-zero baselines
  (an 0.05 s stage) from turning timer jitter into a red build.

Edge-case contract (tests/test_regress.py): a gated key missing from
the *current* run fails (the contract shrank); missing from the
*baseline* only warns (the contract grew — re-baseline); zero or NaN
baselines degrade to the absolute band or a skip, never a crash; a
single-run baseline gates on tolerance alone (MAD needs history).

Lives in the ``watchdog-clock`` lint plane: no wall-clock reads, and
the only write (``baseline`` assembly) goes through ``atomic_write``.
"""

from __future__ import annotations

import json
import math
import statistics

from ..utils.atomic import atomic_write

# -- the schema ---------------------------------------------------------------
#
# One entry per contract key.  Fields:
#   contexts  - CI contexts that require the key's *presence*
#               ("bench" = bench-smoke, "degradation" = the clean-run
#               degradation-key step, "fault" = the fault-matrix
#               driver, "serve" = serve-smoke)
#   dir       - "lower" / "higher" when the key is a quality/perf
#               number with a better direction; None for identity
#               keys (encodings, flags, ids)
#   gate      - the perf gate checks this key against the baseline
#   tol       - relative tolerance band for gated keys
#   abs       - absolute slack added to the band (same unit as key)
#   desc      - one line for reports

def _k(contexts=(), direction=None, gate=False, tol=0.0, abs_slack=0.0,
       desc=""):
    return {"contexts": tuple(contexts), "dir": direction, "gate": gate,
            "tol": float(tol), "abs": float(abs_slack), "desc": desc}


BENCH_SCHEMA: dict = {
    # headline
    "value": _k(("bench",), "lower", gate=True, tol=0.75, abs_slack=0.5,
                desc="headline wall seconds (best of runs_s)"),
    "stage_total_wall_s": _k((), "lower", gate=True, tol=0.75,
                             abs_slack=0.5,
                             desc="stage-recorder total wall"),
    "ari_vs_planted": _k(("bench",), "higher", gate=True, tol=0.02,
                         abs_slack=0.005,
                         desc="label quality vs planted clusters"),
    # stage walls
    "stage_compute_s": _k(("bench",), "lower", gate=True, tol=0.75,
                          abs_slack=0.5, desc="device compute wall"),
    "stage_encode_s": _k(("bench",), "lower", gate=True, tol=0.75,
                         abs_slack=0.5, desc="host wire-encode wall"),
    "stage_h2d_s": _k(("bench",), "lower", gate=True, tol=1.0,
                      abs_slack=0.5, desc="host-to-device copy wall"),
    "stage_entropy_s": _k(("bench", "fault"), "lower", gate=True, tol=1.0,
                          abs_slack=0.5, desc="rANS entropy-lane wall"),
    "stage_prefilter_s": _k(("bench",), "lower", gate=True, tol=1.0,
                            abs_slack=0.5, desc="host prefilter wall"),
    "h2d_overlap_fraction": _k(("bench",), "higher",
                               desc="H2D/compute overlap"),
    # wire accounting
    "cluster_wire_mb": _k(("bench",), "lower", gate=True, tol=0.02,
                          abs_slack=0.5, desc="bytes shipped to device"),
    "cluster_encoding": _k(("bench",), desc="wire encoding in use"),
    "transfer_mb": _k(("bench",), "lower", desc="transfer-probe MB"),
    "transfer_chunk_bits": _k(("bench",), desc="probe chunk widths"),
    "wire_drift_bytes": _k(("bench",), "lower",
                           desc="probe-vs-stage byte drift (must be 0)"),
    "wire_v3_saved_mb": _k(("bench", "fault"), "higher",
                           desc="entropy+prefilter lever savings"),
    "prefilter_hit_rate": _k(("bench", "fault"), "higher",
                             desc="prefilter rows dropped fraction"),
    "prefilter_recall": _k(("bench", "fault"), "higher", gate=True,
                           tol=0.0, abs_slack=0.001,
                           desc="prefilter recall (must stay 1.0)"),
    # warm store / cache
    "cluster_warm_wall_s": _k(("bench",), "lower",
                              desc="warm re-cluster wall"),
    "cache_hit_rate": _k(("bench",), "higher",
                         desc="signature-store hit rate"),
    "cache_wire_saved_mb": _k(("bench",), "higher",
                              desc="wire skipped via store"),
    # degradation / scrub plane (present, zero, on clean runs)
    "degradation_events": _k(("degradation", "fault"), "lower",
                             desc="degradation ladder events"),
    "degradation_counts": _k(("degradation", "fault"),
                             desc="per-kind degradation tally"),
    "chunk_halvings": _k(("degradation", "fault"), "lower",
                         desc="OOM-ladder chunk halvings"),
    "store_scrub_shards": _k(("degradation", "fault"),
                             desc="store shards scrubbed"),
    "store_scrub_corrupt": _k(("degradation", "fault"), "lower",
                              desc="corrupt shards found"),
    "store_scrub_quarantined": _k(("fault",), "lower",
                                  desc="shards quarantined"),
    "store_scrub_state_ok": _k(("degradation", "fault"),
                               desc="store state file verdict"),
    # runtime sanitizer
    "sanitizer_transfer_guard": _k((), desc="transfer guard was on"),
    "sanitizer_compile_count": _k((), "lower",
                                  desc="compiles in timed window"),
    # telemetry plane
    "trace_id": _k(("fault", "serve"), desc="pinned round trace id"),
    "trace_spans_recorded": _k(("fault", "serve"), "higher",
                               desc="spans recorded this round"),
    "metrics_stage_seconds_count": _k(("fault",), "higher",
                                      desc="flat registry export proof"),
    # serving plane
    "serve_p50_ms": _k(("serve",), "lower", desc="daemon query p50"),
    "serve_p99_ms": _k(("serve",), "lower", gate=True, tol=1.0,
                       abs_slack=1.0, desc="daemon query p99"),
    "serve_qps": _k(("serve",), "higher", desc="sustained query rate"),
    "serve_client_p50_ms": _k(("serve",), "lower",
                              desc="TCP round-trip p50"),
    "serve_client_p99_ms": _k(("serve",), "lower",
                              desc="TCP round-trip p99"),
    "serve_query_count": _k(("serve",), "higher",
                            desc="queries served in window"),
    "serve_rows": _k(("serve",), desc="rows ingested"),
    "serve_generation": _k(("serve",), desc="final store generation"),
    "ingest_backlog_max": _k(("serve",), "lower",
                             desc="ingest backlog high-water"),
    "serve_ingest_rejected": _k(("serve",), "lower",
                                desc="admission rejections"),
    "serve_slo_violations": _k(("serve",), "lower",
                               desc="queries past SLO target"),
    "serve_parity": _k(("serve",), desc="post-quiesce parity gate"),
    "serve_ingest_rows_s": _k(("serve",), "higher",
                              desc="sustained ingest rate"),
    "serve_untraced_p99_ms": _k(("serve",), "lower",
                                desc="probe p99, tracing off"),
    "serve_traced_p99_ms": _k(("serve",), "lower",
                              desc="probe p99, tracing on"),
    # graftprof (this PR)
    "serve_unprofiled_p99_ms": _k(("serve",), "lower",
                                  desc="probe p99, profiler off"),
    "serve_profiled_p99_ms": _k(("serve",), "lower",
                                desc="probe p99, sampler+lock-wait on"),
    "serve_lock_wait_sites": _k(("serve",),
                                desc="per-site lock-wait p99 table"),
    "serve_slow_requests": _k(("serve",), "lower",
                              desc="slow-request captures in round"),
    # sharded serve plane (this PR)
    "serve_shards": _k(("serve",), desc="shard daemons behind the router"),
    "serve_router_p99_ms": _k(("serve",), "lower", gate=True, tol=1.0,
                              abs_slack=1.0,
                              desc="router fan-out query p99"),
    "serve_replica_qps": _k(("serve",), "higher",
                            desc="read-replica sustained query rate"),
    "serve_failover_lost_acks": _k(("serve",), "lower", gate=True,
                                   tol=0.0, abs_slack=0.0,
                                   desc="acked rows lost across a shard "
                                        "writer failover (must be 0)"),
    # batched scoring plane (this PR)
    "bulk_score_rows_s": _k(("serve",), "higher", gate=True, tol=0.75,
                            desc="store row-visits/s in the sanitized "
                                 "bulk top-k scan"),
    "topk_p99_ms": _k(("serve",), "lower", gate=True, tol=1.0,
                      abs_slack=1.0,
                      desc="candidate-path topk verb p99"),
    "topk_recall": _k(("serve",), "higher", gate=True, tol=0.0,
                      abs_slack=0.0,
                      desc="scan top-k vs the exact host oracle "
                           "(must stay 1.0)"),
    "topk_parity": _k(("serve",),
                      desc="device/host rank parity sweep over "
                           "schemes x quant bits"),
}


def required_keys(context: str) -> tuple:
    """Keys whose presence the given CI context asserts."""
    return tuple(k for k, spec in BENCH_SCHEMA.items()
                 if context in spec["contexts"])


def assert_bench_keys(result: dict, context: str) -> None:
    """The one key-contract assert all CI smokes share: fails naming
    the first offending key and the schema context that requires it."""
    for key in required_keys(context):
        assert key in result, (
            f"bench JSON lost key {key!r} "
            f"(required by schema context {context!r} — see "
            f"tse1m_tpu/observability/regress.py)")


def gated_keys() -> tuple:
    return tuple(k for k, spec in BENCH_SCHEMA.items() if spec["gate"])


# -- shared number plumbing ---------------------------------------------------

def _num(v):
    """The value as a finite float, else None (bools are flags, not
    measurements)."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    f = float(v)
    return f if math.isfinite(f) else None


def load_runs(path: str) -> list:
    """A baseline file is either one bench result or
    ``{"runs": [...]}`` (median-of-k history)."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("runs"), list):
        runs = [r for r in data["runs"] if isinstance(r, dict)]
    elif isinstance(data, dict):
        runs = [data]
    else:
        raise ValueError(f"{path}: expected a bench result object or "
                         "{'runs': [...]}")
    if not runs:
        raise ValueError(f"{path}: no runs")
    return runs


def write_baseline(out_path: str, runs: list, note: str = "") -> None:
    """Assemble ``{"runs": [...]}`` atomically (re-baselining is a
    reviewed commit, not a side effect of a green build)."""
    payload = {"note": note, "runs": runs}
    with atomic_write(out_path) as f:
        json.dump(payload, f, indent=2, sort_keys=True)


# -- the gate -----------------------------------------------------------------

def gate(current: dict, baseline_runs: list, keys=None) -> dict:
    """Check one fresh run against the baseline history.

    Returns ``{"ok": bool, "rows": [...]}``; each row carries the key,
    the current value, the baseline median/MAD/n, the computed bound
    and a verdict — ``format_gate_report`` renders it, the CI job acts
    on ``ok``."""
    rows = []
    ok = True
    for key in (keys if keys is not None else gated_keys()):
        spec = BENCH_SCHEMA.get(key) or _k(gate=True)
        hist = [_num(r.get(key)) for r in baseline_runs]
        hist = [v for v in hist if v is not None]
        cur = _num(current.get(key))
        if not hist:
            rows.append({"key": key, "current": cur, "ok": True,
                         "note": "no baseline history — re-baseline to "
                                 "start gating this key"})
            continue
        med = statistics.median(hist)
        mad = (statistics.median(abs(v - med) for v in hist)
               if len(hist) > 1 else 0.0)
        if key not in current:
            rows.append({"key": key, "current": None, "median": med,
                         "ok": False,
                         "note": "gated key missing from current run — "
                                 "the bench contract shrank"})
            ok = False
            continue
        if cur is None:
            rows.append({"key": key, "current": current.get(key),
                         "median": med, "ok": True,
                         "note": "non-finite current value — skipped"})
            continue
        direction = spec["dir"] or "lower"
        band = abs(med) * spec["tol"] + 3.0 * mad + spec["abs"]
        if direction == "lower":
            bound = med + band
            key_ok = cur <= bound
        else:
            bound = med - band
            key_ok = cur >= bound
        row = {"key": key, "current": cur, "median": round(med, 4),
               "mad": round(mad, 4), "n": len(hist),
               "bound": round(bound, 4), "dir": direction, "ok": key_ok}
        if len(hist) == 1:
            row["note"] = "single-run baseline (no MAD term)"
        rows.append(row)
        ok = ok and key_ok
    return {"ok": ok, "rows": rows}


def format_gate_report(report: dict) -> str:
    lines = ["perf gate: " + ("PASS" if report["ok"] else "FAIL")]
    for row in report["rows"]:
        mark = "ok " if row["ok"] else "REG"
        if "bound" in row:
            arrow = "<=" if row["dir"] == "lower" else ">="
            lines.append(
                f"  [{mark}] {row['key']:<28} {row['current']:>12.4f} "
                f"{arrow} {row['bound']:>12.4f}  "
                f"(median {row['median']} of {row['n']}, "
                f"MAD {row['mad']})" + (
                    f"  -- {row['note']}" if row.get("note") else ""))
        else:
            lines.append(f"  [{mark}] {row['key']:<28} "
                         f"{row.get('note', '')}")
    return "\n".join(lines)


# -- the diff -----------------------------------------------------------------

def _short(v, width: int = 48) -> str:
    s = repr(v)
    return s if len(s) <= width else s[:width - 3] + "..."


def _group_of(key: str) -> str:
    for prefix in ("stage_", "cluster_", "transfer_", "serve_",
                   "scheme_", "cache_", "store_", "link_", "trace_",
                   "metrics_", "sanitizer_", "degradation_",
                   "prefilter_", "wire_", "profile_", "lock_"):
        if key.startswith(prefix):
            return prefix.rstrip("_")
    return "core"


def diff(a: dict, b: dict, name_a: str = "A", name_b: str = "B",
         show_all: bool = False) -> str:
    """Human-readable delta report between two bench rounds.

    Numeric keys show value, delta and percent with a direction-aware
    verdict (``better`` / ``WORSE`` / ``~`` within 2%); identity keys
    show ``old -> new`` when changed; keys present on only one side
    are listed so a contract change is visible in the same report.
    Scale changes (different ``n_sessions``/``metric``) are flagged up
    top — walls across different scales are context, not regressions."""
    lines = [f"bench diff: {name_a} -> {name_b}"]
    for ctx_key in ("metric", "n_sessions", "backend", "scheme"):
        va, vb = a.get(ctx_key), b.get(ctx_key)
        if va != vb:
            lines.append(f"  NOTE {ctx_key}: {va!r} -> {vb!r} — "
                         "rounds are not scale-comparable on walls")
    shared = sorted(set(a) & set(b))
    by_group: dict = {}
    for key in shared:
        va, vb = a[key], b[key]
        fa, fb = _num(va), _num(vb)
        spec = BENCH_SCHEMA.get(key)
        if fa is not None and fb is not None:
            delta = fb - fa
            if delta == 0:
                pct = 0.0
            elif fa:
                pct = delta / abs(fa) * 100.0
            else:
                pct = float("inf")
            if abs(pct) < 2.0:
                verdict = "~"
            elif spec and spec["dir"]:
                better = (delta < 0) == (spec["dir"] == "lower")
                verdict = "better" if better else "WORSE"
            else:
                verdict = ""
            if not show_all and verdict == "~" and not (spec and
                                                        spec["gate"]):
                continue
            pct_s = f"{pct:+8.1f}%" if math.isfinite(pct) else "     new"
            by_group.setdefault(_group_of(key), []).append(
                f"    {key:<32} {fa:>12.4f} -> {fb:>12.4f}  "
                f"{pct_s}  {verdict}")
        elif va != vb:
            by_group.setdefault(_group_of(key), []).append(
                f"    {key:<32} {_short(va)} -> {_short(vb)}")
    for group in sorted(by_group):
        lines.append(f"  [{group}]")
        lines.extend(by_group[group])
    only_a = sorted(set(a) - set(b))
    only_b = sorted(set(b) - set(a))
    if only_a:
        lines.append(f"  only in {name_a}: {', '.join(only_a)}")
    if only_b:
        lines.append(f"  only in {name_b}: {', '.join(only_b)}")
    if len(lines) == 1:
        lines.append("  (no differences)")
    return "\n".join(lines)


__all__ = ["BENCH_SCHEMA", "assert_bench_keys", "diff", "gate",
           "format_gate_report", "gated_keys", "load_runs",
           "required_keys", "write_baseline"]
