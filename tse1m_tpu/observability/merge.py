"""Pod manifest aggregation: one merged ``run_manifest.json`` per run.

Before the pod plane, a multi-process run recorded only process 0's
manifest — every other host's degradation events, scrub stats and stage
timings were simply lost.  Now each process's StepRunner writes a
per-process FRAGMENT (``run_manifest.p<NNN>.json``) and the coordinator
(process 0, or the failover survivor) folds every fragment into the one
``run_manifest.json`` operators read:

- ``degradation_counts`` sums across processes — the one-glance answer
  to "what did the supervision plane absorb, pod-wide";
- ``steps`` concatenates every process's step records, each tagged with
  its ``process`` id (stage timings and per-step degradation events ride
  along inside the records, exactly as single-process);
- ``pod`` records the topology and which fragments were merged vs
  missing — a host that died before writing its fragment shows up as
  ``missing`` rather than silently narrowing the record;
- ``ok`` is the pod-wide conjunction: any failed step on any host, or
  any missing fragment, marks the merged run not-ok;
- ``metrics`` folds the fragments' registry snapshots (counters sum,
  gauges keep the pod-wide max, histogram counts sum with the worst
  p99/max), and ``trace_id`` carries the run's shared trace id when
  every fragment agrees (the negotiated nonce, so they do unless a
  fragment predates the telemetry plane).

Fragments are merged, never deleted: the per-host originals stay next to
the merged manifest for post-mortems.
"""

from __future__ import annotations

import glob
import json
import os

from ..utils.atomic import atomic_write
from ..utils.logging import get_logger

log = get_logger("observability.merge")

MERGED_MANIFEST = "run_manifest.json"
_FRAGMENT_FMT = "run_manifest.p{:03d}.json"
_FRAGMENT_GLOB = "run_manifest.p*.json"


def fragment_manifest_path(result_dir: str, process_id: int) -> str:
    """The per-process manifest fragment path for a pod run."""
    return os.path.join(result_dir, _FRAGMENT_FMT.format(int(process_id)))


def _load_fragment(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        log.warning("unreadable manifest fragment %s (%s); recording as "
                    "missing", path, e)
        return None


def _merge_metric_snapshots(snapshots: list) -> dict:
    """Fold per-process registry snapshots (`export.metrics_snapshot`
    shape) into one pod-wide view.  Counters are additive by nature;
    gauges here are levels/high-water marks so the pod-wide max is the
    honest aggregate; histograms cannot be re-bucketed from their
    summaries, so counts/sums add and the worst p99/max is kept."""
    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    for snap in snapshots:
        for c in (snap or {}).get("counters", []):
            key = (c["name"], tuple(sorted((c.get("labels") or {}).items())))
            if key not in counters:
                counters[key] = {"name": c["name"],
                                 "labels": dict(c.get("labels") or {}),
                                 "value": 0}
            counters[key]["value"] += int(c.get("value", 0))
        for g in (snap or {}).get("gauges", []):
            key = (g["name"], tuple(sorted((g.get("labels") or {}).items())))
            if key not in gauges:
                gauges[key] = {"name": g["name"],
                               "labels": dict(g.get("labels") or {}),
                               "value": 0.0}
            gauges[key]["value"] = max(gauges[key]["value"],
                                       float(g.get("value", 0.0)))
        for h in (snap or {}).get("histograms", []):
            key = (h["name"], tuple(sorted((h.get("labels") or {}).items())))
            if key not in hists:
                hists[key] = {"name": h["name"],
                              "labels": dict(h.get("labels") or {}),
                              "count": 0, "sum": 0.0, "p99_ms": 0.0,
                              "max_ms": 0.0}
            agg = hists[key]
            agg["count"] += int(h.get("count", 0))
            agg["sum"] = round(agg["sum"] + float(h.get("sum", 0.0)), 6)
            agg["p99_ms"] = max(agg["p99_ms"], float(h.get("p99_ms", 0.0)))
            agg["max_ms"] = max(agg["max_ms"], float(h.get("max_ms", 0.0)))
    return {"counters": [counters[k] for k in sorted(counters)],
            "gauges": [gauges[k] for k in sorted(gauges)],
            "histograms": [hists[k] for k in sorted(hists)]}


def merge_run_manifests(result_dir: str, n_processes: int,
                        out_path: str | None = None) -> dict:
    """Fold every process's manifest fragment into the merged manifest.

    Fragments beyond ``n_processes`` (stale from an earlier, larger pod)
    are ignored; expected-but-absent fragments are recorded under
    ``pod.missing``.  Returns the merged payload (also written atomically
    to ``out_path`` / ``<result_dir>/run_manifest.json``)."""
    out_path = out_path or os.path.join(result_dir, MERGED_MANIFEST)
    fragments: dict[int, dict] = {}
    missing: list[int] = []
    for pid in range(int(n_processes)):
        frag = _load_fragment(fragment_manifest_path(result_dir, pid))
        if frag is None:
            missing.append(pid)
        else:
            fragments[pid] = frag
    counts: dict[str, int] = {}
    steps: list[dict] = []
    summary: dict[str, int] = {}
    epochs: dict[str, int] = {}
    metric_snaps: list = []
    trace_ids: set = set()
    started = None
    wall = 0.0
    for pid in sorted(fragments):
        frag = fragments[pid]
        if frag.get("metrics"):
            metric_snaps.append(frag["metrics"])
        if frag.get("trace_id"):
            trace_ids.add(str(frag["trace_id"]))
        # Each fragment's degradation events are popped destructively
        # into exactly one step record by its own StepRunner, so summing
        # the per-fragment counts here counts every event exactly once —
        # across processes AND across membership epochs (a host that
        # re-admitted in a later epoch writes one fragment, tagged).
        for kind, n in (frag.get("degradation_counts") or {}).items():
            counts[kind] = counts.get(kind, 0) + int(n)
        for status, n in (frag.get("summary") or {}).items():
            summary[status] = summary.get(status, 0) + int(n)
        epoch = frag.get("epoch")
        if epoch is not None:
            epochs[str(pid)] = int(epoch)
        for step in frag.get("steps", []):
            tagged = {**step, "process": pid}
            if epoch is not None:
                tagged["epoch"] = int(epoch)
            steps.append(tagged)
        if frag.get("started_at") is not None:
            started = (frag["started_at"] if started is None
                       else min(started, frag["started_at"]))
        wall = max(wall, float(frag.get("wall_seconds", 0.0)))
    payload = {
        "started_at": started,
        "wall_seconds": wall,
        "ok": (not missing
               and all(f.get("ok", False) for f in fragments.values())),
        "summary": summary,
        "degradation_counts": counts,
        # One shared id means the pod really ran as one trace; multiple
        # ids are preserved verbatim (a diagnostic in themselves).
        "trace_id": (trace_ids.pop() if len(trace_ids) == 1
                     else sorted(trace_ids) or None),
        "metrics": _merge_metric_snapshots(metric_snaps),
        "pod": {
            "n_processes": int(n_processes),
            "merged_from": sorted(fragments),
            "missing": missing,
            # Membership accounting: the epoch each fragment ran under
            # (a re-admitted host appears at its later epoch) and the
            # pod-wide latest epoch.
            "epochs": epochs,
            "epoch": (max(epochs.values()) if epochs else None),
        },
        "steps": steps,
    }
    os.makedirs(result_dir or ".", exist_ok=True)
    with atomic_write(out_path) as f:
        json.dump(payload, f, indent=2, default=str)
    if missing:
        log.warning("pod manifest merged with %d missing fragment(s): %s "
                    "(hosts that died before recording)", len(missing),
                    missing)
    return payload


def sweep_stale_fragments(result_dir: str) -> int:
    """Remove fragments from a PREVIOUS pod run so a smaller re-run's
    merge cannot pick up a dead topology's records; returns count."""
    n = 0
    for p in glob.glob(os.path.join(result_dir, _FRAGMENT_GLOB)):
        try:
            os.remove(p)
            n += 1
        except OSError:
            pass
    return n


__all__ = ["MERGED_MANIFEST", "fragment_manifest_path",
           "merge_run_manifests", "sweep_stale_fragments"]
