"""Request-latency histograms for the online serving plane.

The serving SLO is a percentile, not a mean: one slow query hidden in an
average is exactly the regression the plane exists to catch.  This is a
fixed-size log-bucketed histogram (~`_BUCKETS_PER_DECADE` buckets per
decade over 1 µs .. ~17 min), so p50/p99 cost O(buckets) to read, memory
is constant under sustained load, and `add` is a single increment under
the lock — cheap enough to sit on the query hot path.

Time is read through the watchdog plane's one monotonic clock
(`resilience.watchdog.deadline_clock`): latency windows must never jump
with NTP/DST any more than deadlines may (graftlint ``watchdog-clock``).

Percentiles interpolate within the matched bucket's log-spaced bounds —
error is bounded by the bucket ratio (~12%), far below the 2x-and-worse
swings the SLO layer acts on.
"""

from __future__ import annotations

import math

from ..resilience.watchdog import deadline_clock
from ..trace import sync as tsync
from ..trace.hooks import shared_access

_BUCKETS_PER_DECADE = 20
_N_BUCKETS = 9 * _BUCKETS_PER_DECADE  # 1e-6 s .. 1e3 s
_LOG_MIN = -6.0  # log10 of the first bucket bound (1 µs)


def _bucket_of(seconds: float) -> int:
    if seconds <= 1e-6:
        return 0
    b = int((math.log10(seconds) - _LOG_MIN) * _BUCKETS_PER_DECADE)
    return min(max(b, 0), _N_BUCKETS - 1)


def _bucket_upper_s(b: int) -> float:
    return 10.0 ** (_LOG_MIN + (b + 1) / _BUCKETS_PER_DECADE)


def _bucket_lower_s(b: int) -> float:
    return 10.0 ** (_LOG_MIN + b / _BUCKETS_PER_DECADE)


class LatencyRecorder:
    """Thread-safe per-request-class latency histogram.

    One instance per request class (query / ingest / status); the serve
    daemon publishes ``summary()`` into its status endpoint and bench.py
    flattens it into the ``serve_*`` bench-JSON keys."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = tsync.Lock(f"LatencyRecorder.{name}")
        self._counts = [0] * _N_BUCKETS
        self._n = 0
        self._total_s = 0.0
        self._max_s = 0.0
        self._t0 = deadline_clock()

    def add(self, seconds: float) -> None:
        b = _bucket_of(seconds)
        with self._lock:
            shared_access(self, "buckets", write=True)
            self._counts[b] += 1
            self._n += 1
            self._total_s += seconds
            if seconds > self._max_s:
                self._max_s = seconds

    def time(self):
        """Context manager timing one request into the histogram."""
        return _Timed(self)

    def _percentile_locked(self, q: float) -> float:
        """q in [0, 1] -> seconds, log-interpolated inside the bucket."""
        if self._n == 0:
            return 0.0
        target = q * self._n
        seen = 0
        for b, c in enumerate(self._counts):
            if c == 0:
                continue
            if seen + c >= target:
                frac = (target - seen) / c
                lo, hi = _bucket_lower_s(b), _bucket_upper_s(b)
                return lo * (hi / lo) ** frac
            seen += c
        return self._max_s

    def snapshot(self) -> dict:
        with self._lock:
            shared_access(self, "buckets", write=False)
            if self._n == 0:
                return {"count": 0, "p50_ms": 0.0, "p99_ms": 0.0,
                        "max_ms": 0.0, "mean_ms": 0.0, "qps": 0.0}
            elapsed = max(deadline_clock() - self._t0, 1e-9)
            return {
                "count": self._n,
                "p50_ms": round(self._percentile_locked(0.50) * 1e3, 3),
                "p99_ms": round(self._percentile_locked(0.99) * 1e3, 3),
                "max_ms": round(self._max_s * 1e3, 3),
                "mean_ms": round(self._total_s / self._n * 1e3, 3),
                "qps": round(self._n / elapsed, 1),
            }

    def summary(self) -> dict:
        """snapshot() keyed for flat JSON: ``<name>_p99_ms`` etc."""
        return {f"{self.name}_{k}": v for k, v in self.snapshot().items()}

    def buckets(self) -> dict:
        """Cumulative-bucket export for the Prometheus text format:
        only occupied buckets are emitted (the 180-slot grid would be
        noise), each as ``{"le": upper_bound_s, "count": cumulative}``,
        plus the ``sum``/``count`` pair the histogram type requires."""
        with self._lock:
            shared_access(self, "buckets", write=False)
            out = []
            cum = 0
            for b, c in enumerate(self._counts):
                if c == 0:
                    continue
                cum += c
                out.append({"le": round(_bucket_upper_s(b), 9),
                            "count": cum})
            return {"buckets": out, "sum": round(self._total_s, 6),
                    "count": self._n}

    def reset_window(self) -> None:
        """Restart the qps window (and counts) — bench rounds measure a
        steady-state window, not the warmup."""
        with self._lock:
            shared_access(self, "buckets", write=True)
            self._counts = [0] * _N_BUCKETS
            self._n = 0
            self._total_s = 0.0
            self._max_s = 0.0
            self._t0 = deadline_clock()


class _Timed:
    __slots__ = ("_rec", "_t0")

    def __init__(self, rec: LatencyRecorder) -> None:
        self._rec = rec

    def __enter__(self) -> "_Timed":
        self._t0 = deadline_clock()
        return self

    def __exit__(self, et, ev, tb) -> None:
        self._rec.add(deadline_clock() - self._t0)


__all__ = ["LatencyRecorder"]
