"""Selenium-backed :class:`~tse1m_tpu.collect.issues.IssuePageClient`.

Captures the live tracker's Angular DOM into the structured
:class:`RawIssuePage` the pure parsers consume.  Selectors follow the
reference (``5_get_issue_reports.py:127-291``): ``b-issue-details`` /
``edit-issue-metadata`` as load sentinels, throttle detection via the
"Request throttled" snackbar, metadata out of ``edit-issue-metadata``
field containers, events from ``issue-event-list``, and the shadow-DOM
``revisions-info`` table on revision sub-pages.

This module imports selenium lazily — the rest of the collection layer
(and its tests) never touches it.  It cannot be exercised offline; its
logic floor is kept deliberately thin, with everything parseable pushed
into :mod:`.issues`.
"""

from __future__ import annotations

import time

from .issues import (IssueEvent, RawIssuePage, RevisionTable, issue_url,
                     revision_buildtime_from_url, split_revision_range)
from ..utils.logging import get_logger

log = get_logger("collect.issues.selenium")

METADATA_LABELS = ("Reporter", "Type", "Priority", "Severity", "Status",
                   "Assignee", "Verifier", "Collaborators", "CC", "Project",
                   "Disclosure", "Reported", "Code Changes",
                   "Pending Code Changes", "Staffing", "Found In",
                   "Targeted To", "Verified In")
USER_LABELS = ("Reporter", "Assignee", "Verifier", "Collaborators", "CC")


class SeleniumIssueClient:
    """One headless Chrome per client instance (one per worker window)."""

    def __init__(self, load_timeout: int = 20, max_retries: int = 5,
                 throttle_wait: float = 10.0, page_delay: tuple = (1.0, 3.0)):
        from selenium import webdriver

        options = webdriver.ChromeOptions()
        for arg in ("--headless", "--disable-gpu", "--no-sandbox",
                    "--disable-dev-shm-usage",
                    "--blink-settings=imagesEnabled=false"):
            options.add_argument(arg)
        self.driver = webdriver.Chrome(options=options)
        self.load_timeout = load_timeout
        self.max_retries = max_retries
        self.throttle_wait = throttle_wait
        self.page_delay = page_delay

    def close(self) -> None:
        try:
            self.driver.quit()
        except Exception:  # graftlint: disable=broad-except -- best-effort driver teardown; no fault seat fires inside quit()
            pass

    # -- helpers ------------------------------------------------------------

    def _wait(self, timeout=None):
        from selenium.webdriver.support.ui import WebDriverWait

        return WebDriverWait(self.driver, timeout or self.load_timeout)

    def _throttled(self) -> bool:
        from selenium.common.exceptions import NoSuchElementException
        from selenium.webdriver.common.by import By

        try:
            el = self.driver.find_element(
                By.XPATH, "//*[contains(@class, 'snackbar-content') and "
                          "contains(., 'Request throttled')]")
            return el.is_displayed()
        except NoSuchElementException:
            return False

    # -- IssuePageClient ----------------------------------------------------

    def fetch_issue(self, issue_no: int) -> RawIssuePage:
        import random

        from selenium.common.exceptions import (NoSuchElementException,
                                                TimeoutException)
        from selenium.webdriver.common.by import By
        from selenium.webdriver.support import expected_conditions as EC

        url = issue_url(issue_no)
        loaded = False
        for attempt in range(self.max_retries):
            self.driver.get(url)
            try:
                self._wait().until(EC.presence_of_element_located(
                    (By.CSS_SELECTOR, "b-issue-details, edit-issue-metadata")))
                loaded = True
                break
            except TimeoutException:
                if self._throttled():
                    log.info("throttled on %s; waiting %.0fs", issue_no,
                             self.throttle_wait)
                    time.sleep(self.throttle_wait)
                    continue
                log.info("load timeout for %s (attempt %d/%d)", issue_no,
                         attempt + 1, self.max_retries)
        if not loaded:
            return RawIssuePage(final_id=str(issue_no), url=url,
                                load_error=True)
        time.sleep(1)
        page = RawIssuePage(final_id=self.driver.current_url.split("/")[-1],
                            url=self.driver.current_url)

        for selector in ("h3.heading-m.ng-star-inserted", "issue-header h3"):
            try:
                page.title = self.driver.find_element(
                    By.CSS_SELECTOR, selector).text
                break
            except NoSuchElementException:
                continue
        else:
            page.load_error = True

        try:
            page.hotlists = [el.text for el in self.driver.find_elements(
                By.CSS_SELECTOR, "b-hotlist-chip-smart span.name a")
                if el.text]
        except Exception:  # graftlint: disable=broad-except -- optional hotlist-chip scrape; the driver raises arbitrary exceptions and no fault seat fires inside
            pass

        try:
            el = self._wait(10).until(EC.presence_of_element_located(
                (By.CSS_SELECTOR, "b-formatted-date-time time")))
            page.reported_time_iso = el.get_attribute("datetime")
        except TimeoutException:
            pass

        page.metadata = self._scrape_metadata()
        page.events = self._scrape_events()
        try:
            page.description = self._wait(10).until(
                EC.presence_of_element_located(
                    (By.TAG_NAME, "b-issue-description"))).text
        except TimeoutException:
            log.info("no description container for %s", page.final_id)

        time.sleep(random.uniform(*self.page_delay))  # graftlint: disable=nondeterminism -- human-like page pacing against the live tracker; scrape cadence is deliberately not replayable
        return page

    def _scrape_metadata(self) -> dict:
        from selenium.common.exceptions import (NoSuchElementException,
                                                TimeoutException)
        from selenium.webdriver.common.by import By
        from selenium.webdriver.support import expected_conditions as EC

        out: dict = {}
        try:
            container = self._wait(10).until(EC.presence_of_element_located(
                (By.TAG_NAME, "edit-issue-metadata")))
        except TimeoutException:
            return out
        fields = container.find_elements(
            By.CSS_SELECTOR, "b-edit-field, b-multi-user-control, "
                             "b-staffing-row")
        for field in fields:
            try:
                label = field.find_element(By.TAG_NAME, "label").text.strip()
                if label not in METADATA_LABELS:
                    continue
                if label in USER_LABELS:
                    values = [v.text.strip() for v in field.find_elements(
                        By.TAG_NAME, "b-person-hovercard")
                        if v.text.strip() and v.text.strip() != "--"]
                    if not values:
                        out[label] = None
                    elif label in ("CC", "Collaborators"):
                        out[label] = values
                    else:
                        out[label] = values[0] if len(values) == 1 else values
                else:
                    value = field.find_element(
                        By.CSS_SELECTOR, ".bv2-metadata-field-value, "
                                         ".staffing-summaries, .no-value"
                    ).text.strip()
                    out[label] = None if value in ("--", "") else value
            except NoSuchElementException:
                continue
        return out

    def _scrape_events(self) -> list:
        from selenium.common.exceptions import (NoSuchElementException,
                                                TimeoutException)
        from selenium.webdriver.common.by import By
        from selenium.webdriver.support import expected_conditions as EC

        events: list = []
        try:
            container = self._wait(10).until(EC.presence_of_element_located(
                (By.TAG_NAME, "issue-event-list")))
        except TimeoutException:
            return events
        for event in container.find_elements(By.CSS_SELECTOR, "div.bv2-event"):
            try:
                section = event.find_element(
                    By.CSS_SELECTOR, "b-plain-format-unquoted-section, "
                                     "b-markdown-format-presenter")
            except NoSuchElementException:
                continue
            time_iso = None
            try:
                time_iso = event.find_element(
                    By.CSS_SELECTOR, "h4 b-formatted-date-time time"
                ).get_attribute("datetime")
            except NoSuchElementException:
                pass
            links = [a.get_attribute("href") for a in event.find_elements(
                By.CSS_SELECTOR, 'a[href*="/revisions"]')]
            events.append(IssueEvent(text=section.text, time_iso=time_iso,
                                     revision_links=links))
        return events

    def fetch_revisions(self, url: str) -> RevisionTable | None:
        from selenium.common.exceptions import (NoSuchElementException,
                                                TimeoutException)
        from selenium.webdriver.common.by import By
        from selenium.webdriver.support import expected_conditions as EC

        original = self.driver.current_url
        for attempt in range(3):
            try:
                self.driver.get(url)
                self._wait(15).until(
                    lambda d: d.current_url != original
                    and "about:blank" not in d.current_url)
                break
            except TimeoutException:
                log.info("revision page stuck; retry %d/3", attempt + 1)
        else:
            self.driver.get(original)
            return None

        try:
            if self.driver.find_element(
                    By.XPATH, "//*[contains(text(), 'Failed to get component "
                              "revisions.')]").is_displayed():
                return None
        except NoSuchElementException:
            pass

        components: list = []
        revisions: list = []
        try:
            host = self._wait(10).until(EC.presence_of_element_located(
                (By.TAG_NAME, "revisions-info")))
            self._wait(10).until(lambda d: host.shadow_root.find_elements(
                By.CSS_SELECTOR, "table tr.body"))
            time.sleep(1)  # let the JS table settle (5_…py:94)
            for row in host.shadow_root.find_elements(
                    By.CSS_SELECTOR, "table tr.body"):
                cells = row.find_elements(By.TAG_NAME, "td")
                if len(cells) >= 2:
                    comp = cells[0].text.strip()
                    rev = cells[1].text.strip()
                    if comp and rev:
                        components.append(comp)
                        revisions.append(split_revision_range(rev))
        except (TimeoutException, NoSuchElementException):
            log.info("revision table missing at %s", url)
        finally:
            if self.driver.current_url != original:
                self.driver.get(original)
        return RevisionTable(components=components, revisions=revisions,
                             buildtime=revision_buildtime_from_url(url))
