"""C4 — build-log metadata pager (reference: ``2_get_buildlog_metadata.py``).

Pages the GCS JSON objects API for the ``oss-fuzz-gcb-logs`` bucket,
keeps only objects whose name has the ``log-<uuid>.txt`` shape, and
checkpoints every ``pages_per_batch`` pages through the shared
:class:`~tse1m_tpu.collect.checkpoint.CsvBatchCheckpointer` before merging
into ``buildlog_metadata.csv``.

Deviation from the reference, documented: names are matched with a UUID
regex instead of an exact-length check (``2_…py:98,134-138``) — equal
acceptance on real names, but length-44 non-log objects no longer slip
through.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from .checkpoint import CsvBatchCheckpointer
from .transport import Fetcher
from ..resilience import reraise_if_fault
from ..utils.logging import get_logger

log = get_logger("collect.gcs")

BUCKET = "oss-fuzz-gcb-logs"
API_URL_TEMPLATE = "https://storage.googleapis.com/storage/v1/b/{bucket}/o"
TARGET_KEYS = ("name", "selfLink", "mediaLink", "size", "timeCreated")
LOG_NAME_RE = re.compile(
    r"^log-[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{12}\.txt$")


def is_build_log_name(name: str | None) -> bool:
    return bool(name) and LOG_NAME_RE.match(name) is not None


def extract_log_records(items: list[dict]) -> list[dict]:
    """Filter one API page's objects down to build-log records with the
    target metadata keys (2_…py:133-138)."""
    return [{key: item.get(key) for key in TARGET_KEYS}
            for item in items if is_build_log_name(item.get("name"))]


@dataclass
class GcsMetadataCollector:
    fetcher: Fetcher
    batch_dir: str
    pages_per_batch: int = 10
    max_pages: int | None = None   # safety valve for tests/partial runs
    bucket: str = BUCKET
    pages_fetched: int = field(default=0, init=False)

    def collect(self, final_csv: str) -> int:
        """Walk all pages, checkpoint batches, merge.  Returns the merged
        record count.  A transport failure stops the walk but still merges
        what was collected (the reference likewise breaks and finalises,
        2_…py:126-128)."""
        url = API_URL_TEMPLATE.format(bucket=self.bucket)
        ckpt = CsvBatchCheckpointer(self.batch_dir, "buildlog_metadata",
                                    # flush on page boundaries, not records
                                    batch_size=10 ** 9,
                                    fieldnames=list(TARGET_KEYS))
        params: dict = {}
        while True:
            if self.max_pages is not None and self.pages_fetched >= self.max_pages:
                log.info("page limit %d reached", self.max_pages)
                break
            try:
                resp = self.fetcher.get(url, params=params or None)
            except Exception as e:
                reraise_if_fault(e)  # retried upstream; faults stay visible
                log.error("page fetch failed (%s); finalising partial run", e)
                break
            self.pages_fetched += 1
            if resp is None:
                log.error("bucket listing returned 404; finalising")
                break
            data = resp.json()
            for record in extract_log_records(data.get("items", [])):
                ckpt.add(record)
            if self.pages_fetched % self.pages_per_batch == 0:
                ckpt.flush()
            token = data.get("nextPageToken")
            if not token:
                break
            params = {"pageToken": token}
        return ckpt.merge(final_csv)
