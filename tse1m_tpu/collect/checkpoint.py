"""Checkpoint/resume helpers shared by all collectors (SURVEY.md §5: A4).

The reference implements the same three patterns independently per script:

- batch-CSV checkpointing: flush every N pages / 50 issues to numbered batch
  files, then merge + delete (``2_get_buildlog_metadata.py:141-147,24-68``;
  ``5_get_issue_reports.py:333-334,293-309``);
- processed-id resume: scan prior output CSVs for already-done ids and skip
  them (``4_get_buildlog_analysis.py:263-272``; ``5_…py:29-51``);
- resume-from-last-date: continue a per-project time series from the day
  after its max recorded date (``3_get_coverage_data.py:255-259``).

Here each is one tested helper used by every driver.
"""

from __future__ import annotations

import csv
import glob
import json
import os
from datetime import date, timedelta

import pandas as pd

from ..resilience import fault_point, io_retry_policy, retry_call
from ..utils.logging import get_logger

log = get_logger("collect.checkpoint")


class CsvBatchCheckpointer:
    """Accumulate records; flush to ``<prefix>_batch_<k>.csv`` every
    ``batch_size`` records; ``merge()`` concatenates all batches into the
    final CSV and removes them.

    A crash between flushes loses at most one unflushed batch — the same
    durability contract as the reference's page/issue batching.
    """

    def __init__(self, directory: str, prefix: str, batch_size: int,
                 fieldnames: list[str] | None = None):
        self.directory = directory
        self.prefix = prefix
        self.batch_size = batch_size
        self.fieldnames = fieldnames
        self._pending: list[dict] = []
        os.makedirs(directory, exist_ok=True)
        existing = self._batch_files()
        self._next_index = len(existing) + 1

    def _batch_files(self) -> list[str]:
        return sorted(glob.glob(
            os.path.join(self.directory, f"{self.prefix}_batch_*.csv")))

    def add(self, record: dict) -> None:
        self._pending.append(record)
        if len(self._pending) >= self.batch_size:
            self.flush()

    def flush(self) -> str | None:
        if not self._pending:
            return None
        path = os.path.join(self.directory,
                            f"{self.prefix}_batch_{self._next_index}.csv")
        fields = self.fieldnames or sorted(
            {k for r in self._pending for k in r})

        def write_batch() -> None:
            # tmp + rename: a crash (or injected tear) mid-write can never
            # surface as a silently short batch file — merge() and resume
            # only ever see complete batches.  A retried attempt rewrites
            # the tmp file from the start, so a torn write self-heals.
            tmp = path + ".tmp"
            with open(tmp, "w", newline="", encoding="utf-8") as f:
                w = csv.DictWriter(f, fieldnames=fields,
                                   extrasaction="ignore")
                w.writeheader()
                w.writerows(self._pending)
            fault_point("checkpoint.csv.flush", path=tmp)
            os.replace(tmp, path)

        retry_call(write_batch, policy=io_retry_policy(),
                   site="checkpoint.csv.flush")
        log.info("checkpointed %d records to %s", len(self._pending), path)
        self._pending.clear()
        self._next_index += 1
        return path

    def merge(self, final_path: str, cleanup: bool = True) -> int:
        """Concatenate all batch files into ``final_path``; returns the
        merged row count.  Batches are deleted only after a successful
        write (the reference deletes as it goes, 2_…py:61-67)."""
        self.flush()
        files = self._batch_files()
        if not files:
            log.info("no batch files to merge for %s", self.prefix)
            return 0
        frames = []
        for path in files:
            try:
                frames.append(pd.read_csv(path))
            except (OSError, ValueError) as e:
                # pandas parse failures (ParserError/EmptyDataError/
                # UnicodeDecodeError) are ValueError subclasses; anything
                # broader — including an injected fault — must surface.
                log.warning("skipping unreadable batch %s: %s", path, e)
        if not frames:
            return 0
        merged = pd.concat(frames, ignore_index=True)
        os.makedirs(os.path.dirname(final_path) or ".", exist_ok=True)
        merged.to_csv(final_path, index=False, encoding="utf-8")
        log.info("merged %d records from %d batches into %s",
                 len(merged), len(files), final_path)
        if cleanup:
            for path in files:
                os.remove(path)
            # Orphaned tmp files from a crash mid-flush (the torn write
            # that atomic rename made invisible) still occupy disk.
            for path in glob.glob(os.path.join(
                    self.directory, f"{self.prefix}_batch_*.csv.tmp")):
                os.remove(path)
        return len(merged)


def processed_ids_from_csvs(base_dir: str, id_column: str = "id",
                            json_encoded: bool = False) -> set:
    """Recursively scan CSVs under ``base_dir`` for already-processed ids.

    ``json_encoded=True`` decodes each cell as JSON first — the issue
    scraper stores every value json.dumps'd (``5_…py:303``)."""
    found: set = set()
    if not os.path.isdir(base_dir):
        return found
    for root, _, files in os.walk(base_dir):
        for name in files:
            if not name.endswith(".csv"):
                continue
            path = os.path.join(root, name)
            try:
                with open(path, newline="", encoding="utf-8") as f:
                    reader = csv.DictReader(f)
                    if not reader.fieldnames or id_column not in reader.fieldnames:
                        continue
                    for row in reader:
                        raw = row.get(id_column)
                        if raw in (None, ""):
                            continue
                        if json_encoded:
                            try:
                                raw = json.loads(raw)
                            except (json.JSONDecodeError, TypeError):
                                continue
                        if raw is None:
                            continue
                        s = str(raw)
                        found.add(int(s) if s.isdigit() else s)
            except (OSError, ValueError, csv.Error) as e:
                log.warning("could not scan %s: %s", path, e)
    return found


def last_date_in_csv(path: str, column: str = "date") -> date | None:
    """Max recorded date in a per-project CSV, or None if absent/empty."""
    if not os.path.exists(path):
        return None
    try:
        df = pd.read_csv(path)
    except (OSError, ValueError):
        return None
    if column not in df.columns or df.empty:
        return None
    # YYYYMMDD stamps read back from CSV as ints; normalise through str so
    # 20250105 parses as a date, not an epoch offset.
    parsed = pd.to_datetime(df[column].astype(str), errors="coerce",
                            format="mixed")
    if parsed.isna().all():
        return None
    return parsed.max().date()


def resume_start_date(csv_path: str, default_start: date,
                      column: str = "date") -> date:
    """Day after the last recorded date, clamped to ``default_start``
    (3_get_coverage_data.py:255-267)."""
    last = last_date_in_csv(csv_path, column)
    if last is None:
        return default_start
    nxt = last + timedelta(days=1)
    return max(nxt, default_start)
