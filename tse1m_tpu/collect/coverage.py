"""C5 — daily coverage-report collector (reference: ``3_get_coverage_data.py``).

For each supported project, walks day by day from its first-commit date,
fetching the OSS-Fuzz coverage report for that day and parsing the summary
row with language-specific rules (``3_…py:139-202``):

- C/C++/Rust/Swift: ``file_view_index.html``, totals row's "Line Coverage"
  cell, format ``"90.00% (180/200)"``;
- Python: ``index.html``, totals row's ``statements`` / ``missing`` columns;
- JVM: ``index.html``, totals row's ``Lines`` and second ``Missed`` columns
  (pandas would surface it as ``Missed_1``/``Missed.1``; here it is simply
  the second column named ``Missed``).

Tables are extracted with a stdlib ``html.parser`` state machine — no
bs4/lxml dependency — and each per-project CSV resumes from the day after
its last recorded date (``3_…py:255-267``).  A 404 means "no report today"
and is skipped silently (``3_…py:79-80``).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from datetime import date, timedelta
from html.parser import HTMLParser

import pandas as pd

from .checkpoint import resume_start_date
from .transport import Fetcher
from ..utils.logging import get_logger

log = get_logger("collect.coverage")

REPORT_URL_TEMPLATE = ("https://storage.googleapis.com/oss-fuzz-coverage/"
                       "{project}/reports/{day}/linux/")
C_FAMILY = ("c", "c++", "rust", "swift")
INDEX_FAMILY = ("go", "python", "jvm")
SUPPORTED_LANGUAGES = ("c", "c++", "rust", "swift", "python", "jvm")


class _TableParser(HTMLParser):
    """Collect every <table> as a list of rows of stripped cell texts."""

    def __init__(self):
        super().__init__(convert_charrefs=True)
        self.tables: list[list[list[str]]] = []
        self._rows: list[list[str]] | None = None
        self._cell: list[str] | None = None

    def handle_starttag(self, tag, attrs):
        if tag == "table":
            self._rows = []
        elif tag == "tr" and self._rows is not None:
            self._rows.append([])
        elif tag in ("td", "th") and self._rows is not None:
            self._cell = []

    def handle_endtag(self, tag):
        if tag == "table" and self._rows is not None:
            self.tables.append([r for r in self._rows if r])
            self._rows = None
        elif tag in ("td", "th") and self._cell is not None:
            if self._rows and self._rows[-1] is not None:
                self._rows[-1].append(" ".join(self._cell).strip())
            self._cell = None

    def handle_data(self, data):
        if self._cell is not None and data.strip():
            self._cell.append(data.strip())


def extract_tables(html: str) -> list[list[list[str]]]:
    parser = _TableParser()
    parser.feed(html)
    return parser.tables


def _to_number(cell: str) -> float | None:
    m = re.search(r"-?[\d,]+(?:\.\d+)?", cell)
    if not m:
        return None
    return float(m.group(0).replace(",", ""))


@dataclass(frozen=True)
class CoverageStats:
    coverage: float
    covered_line: float
    total_line: float


def parse_c_family_report(html: str) -> CoverageStats | None:
    """Totals row of the first table's "Line Coverage" column:
    ``"<pct>% (<covered>/<total>)"`` (3_…py:145-158)."""
    for table in extract_tables(html):
        if len(table) < 2:
            continue
        header = table[0]
        try:
            col = next(i for i, h in enumerate(header)
                       if "line coverage" in h.lower())
        except StopIteration:
            continue
        last = table[-1]
        if col >= len(last):
            continue
        numbers = re.findall(r"[\d.]+", last[col])
        if len(numbers) >= 3:
            return CoverageStats(coverage=float(numbers[0]),
                                 covered_line=float(numbers[1]),
                                 total_line=float(numbers[2]))
    return None


def _totals_from_columns(html: str, total_col_name: str,
                         missed_col_name: str,
                         missed_occurrence: int = 1) -> CoverageStats | None:
    """Shared shape of the Python/JVM parsers: covered = total - missed from
    the totals (last) row; coverage derived as a percentage."""
    for table in extract_tables(html):
        if len(table) < 2:
            continue
        header = [h.strip() for h in table[0]]
        total_idx = None
        missed_idxs = []
        for i, h in enumerate(header):
            name = h.lower()
            if name == total_col_name and total_idx is None:
                total_idx = i
            if name == missed_col_name:
                missed_idxs.append(i)
        if total_idx is None or len(missed_idxs) < missed_occurrence:
            continue
        missed_idx = missed_idxs[missed_occurrence - 1]
        last = table[-1]
        if max(total_idx, missed_idx) >= len(last):
            continue
        total = _to_number(last[total_idx])
        missed = _to_number(last[missed_idx])
        if total is None or missed is None or total <= 0:
            return None
        covered = total - missed
        return CoverageStats(coverage=covered / total * 100.0,
                             covered_line=covered, total_line=total)
    return None


def parse_python_report(html: str) -> CoverageStats | None:
    """``statements``/``missing`` columns (3_…py:174-185)."""
    return _totals_from_columns(html, "statements", "missing")


def parse_jvm_report(html: str) -> CoverageStats | None:
    """``Lines`` total with the *second* ``Missed`` column (3_…py:188-202:
    pandas renames the duplicate to ``Missed_1``/``Missed.1``)."""
    return _totals_from_columns(html, "lines", "missed", missed_occurrence=2)


def fetch_day_coverage(fetcher: Fetcher, project: str, language: str,
                       day: str) -> CoverageStats | None:
    """One day's stats, or None when the report is absent/unparseable.
    ``day`` is YYYYMMDD (the report path format, 3_…py:130)."""
    base = REPORT_URL_TEMPLATE.format(project=project, day=day)
    if language in C_FAMILY:
        resp = fetcher.get(base + "file_view_index.html")
        if resp is None:
            return None
        return parse_c_family_report(resp.text)
    if language in INDEX_FAMILY:
        resp = fetcher.get(base + "index.html")
        if resp is None:
            return None
        if language == "python":
            return parse_python_report(resp.text)
        if language == "jvm":
            return parse_jvm_report(resp.text)
        return None  # go reports carry no parse rule in the reference
    return None


@dataclass
class CoverageCollector:
    """Per-project day-walk with resume, per-project CSVs, final merge
    (3_…py:226-298)."""

    fetcher: Fetcher
    per_project_dir: str
    finish_date: date

    def collect_project(self, project: str, language: str,
                        start: date) -> int:
        """Scrape ``project`` from max(start, resume point) through
        ``finish_date``; append to its CSV.  Returns new-row count."""
        os.makedirs(self.per_project_dir, exist_ok=True)
        csv_path = os.path.join(self.per_project_dir, f"{project}.csv")
        begin = resume_start_date(csv_path, start)
        rows = []
        day = begin
        while day <= self.finish_date:
            stamp = day.strftime("%Y%m%d")
            stats = fetch_day_coverage(self.fetcher, project, language, stamp)
            if stats is not None:
                rows.append({"date": stamp, "project": project,
                             "coverage": stats.coverage,
                             "covered_line": stats.covered_line,
                             "total_line": stats.total_line,
                             "exist": True})
            day += timedelta(days=1)
        if rows:
            new_df = pd.DataFrame(rows)
            if os.path.exists(csv_path):
                new_df = pd.concat([pd.read_csv(csv_path), new_df],
                                   ignore_index=True)
            new_df.to_csv(csv_path, index=False, encoding="utf-8")
        log.info("%s: %d new coverage rows (from %s)", project, len(rows),
                 begin)
        return len(rows)

    def collect_all(self, project_info: pd.DataFrame, final_csv: str) -> int:
        """Walk every supported-language project from its first-commit date
        (3_…py:240-282), then merge the per-project CSVs."""
        total = 0
        for _, row in project_info.iterrows():
            language = row.get("language")
            if language not in SUPPORTED_LANGUAGES:
                continue
            first = pd.to_datetime(row["first_commit_datetime"],
                                   errors="coerce", utc=True)
            if pd.isna(first):
                continue
            total += self.collect_project(row["project"], language,
                                          first.date())
        self.merge(final_csv)
        return total

    def merge(self, final_csv: str) -> int:
        import glob

        files = sorted(glob.glob(os.path.join(self.per_project_dir, "*.csv")))
        if not files:
            log.warning("no per-project coverage CSVs to merge")
            return 0
        merged = pd.concat([pd.read_csv(f) for f in files], ignore_index=True)
        os.makedirs(os.path.dirname(final_csv) or ".", exist_ok=True)
        merged.to_csv(final_csv, index=False, encoding="utf-8")
        log.info("merged %d files -> %s (%d rows)", len(files), final_csv,
                 len(merged))
        return len(merged)
