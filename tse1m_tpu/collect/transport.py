"""Injectable fetch transport — the collection layer's failure-detection seat.

The reference configures ``requests`` retry adapters ad hoc per script
(``2_get_buildlog_metadata.py:106-108``: total=5, backoff 1, on 502/503/504;
``3_get_coverage_data.py:73-74``: total=3, backoff 0.5, on 5xx) and treats
404 as "no report today" (``3_get_coverage_data.py:79-80``).  Here that
policy is one dataclass, and the transport itself is a protocol so every
collector runs against a directory-backed fake in tests (no network).
"""

from __future__ import annotations

import email.utils
import json
import os
import threading
import time
import urllib.parse
from dataclasses import dataclass
from typing import Protocol

from ..resilience import RetryError, RetryPolicy, fault_point, retry_call
from ..utils.logging import get_logger

log = get_logger("collect.transport")


@dataclass(frozen=True)
class FetchPolicy:
    """Retry/backoff/politeness policy applied by real transports."""

    retries: int = 3
    backoff_factor: float = 0.5
    retry_statuses: tuple = (429, 500, 502, 503, 504)
    timeout: float = 10.0
    # Fixed sleep between *successive* requests — the reference sleeps 0.5 s
    # per coverage page (3_get_coverage_data.py:135) and 5 s per GCS page
    # (2_get_buildlog_metadata.py:100,152).
    politeness_delay: float = 0.0
    # Wall-clock budget over ALL attempts for one get() — also the cap on
    # any server-sent Retry-After hint.  None = attempts-bounded only.
    deadline: float | None = None


@dataclass
class Response:
    url: str
    status: int
    content: bytes

    @property
    def text(self) -> str:
        return self.content.decode("utf-8", errors="replace")

    def json(self):
        return json.loads(self.text)


class Fetcher(Protocol):
    def get(self, url: str, params: dict | None = None) -> Response | None:
        """Fetch a URL.  Returns None for 404 (absent resource — a normal
        outcome for daily reports); raises on persistent transport failure."""
        ...


class FetchError(RuntimeError):
    """A request failed after exhausting the retry budget.

    ``retry_after`` (seconds, optional) carries a server ``Retry-After``
    hint for 429/503 responses; the shared retry engine raises its next
    backoff to at least that, capped by the policy deadline."""

    def __init__(self, message: str, retry_after: float | None = None):
        super().__init__(message)
        self.retry_after = retry_after


def parse_retry_after(value) -> float | None:
    """``Retry-After`` header -> seconds (int form or HTTP-date form),
    None when absent/unparseable.  Negative values clamp to 0."""
    if value is None:
        return None
    s = str(value).strip()
    if not s:
        return None
    try:
        return max(0.0, float(s))
    except ValueError:
        pass
    try:
        dt = email.utils.parsedate_to_datetime(s)
    except (TypeError, ValueError):
        return None
    if dt is None:
        return None
    import datetime as _dt

    now = _dt.datetime.now(_dt.timezone.utc)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return max(0.0, (dt - now).total_seconds())


# Without a policy deadline, a server-sent Retry-After still cannot stall
# a collector indefinitely.
_RETRY_AFTER_CAP = 60.0


def _with_params(url: str, params: dict | None) -> str:
    if not params:
        return url
    sep = "&" if "?" in url else "?"
    return url + sep + urllib.parse.urlencode(sorted(params.items()))


class HttpFetcher:
    """Real transport over ``requests`` with the shared policy.

    Uses explicit retry loops rather than urllib3's Retry so the same
    semantics hold for connection errors and status retries alike, and so
    the policy is visible in one place.
    """

    def __init__(self, policy: FetchPolicy | None = None, session=None):
        self.policy = policy or FetchPolicy()
        if session is None:
            import requests

            session = requests.Session()
        self.session = session
        self._last_request_t = 0.0
        self._pause_lock = threading.Lock()

    def _politeness_pause(self) -> None:
        # Serialized so concurrent callers (BuildLogAnalyzer workers>1)
        # still honor the promised aggregate request rate instead of each
        # racing past a stale _last_request_t.
        delay = self.policy.politeness_delay
        with self._pause_lock:
            if delay > 0:
                elapsed = time.monotonic() - self._last_request_t
                if elapsed < delay:
                    time.sleep(delay - elapsed)
            self._last_request_t = time.monotonic()

    def get(self, url: str, params: dict | None = None) -> Response | None:
        p = self.policy

        def attempt() -> Response | None:
            fault_point("http.fetch")
            self._politeness_pause()
            r = self.session.get(url, params=params, timeout=p.timeout)
            if r.status_code == 404:
                return None
            if r.status_code in p.retry_statuses:
                # 429/503 servers often say when to come back; honor it,
                # capped at the policy deadline (or a sane bound).
                hint = parse_retry_after(
                    getattr(r, "headers", {}).get("Retry-After"))
                if hint is not None:
                    hint = min(hint, p.deadline if p.deadline is not None
                               else _RETRY_AFTER_CAP)
                raise FetchError(f"HTTP {r.status_code} for {url}",
                                 retry_after=hint)
            try:
                r.raise_for_status()
            except Exception as e:
                e.no_retry = True  # hard 4xx: retrying cannot help
                raise
            return Response(url=url, status=r.status_code, content=r.content)

        try:
            return retry_call(
                attempt,
                policy=RetryPolicy(max_attempts=p.retries + 1,
                                   base_delay=p.backoff_factor,
                                   deadline=p.deadline),
                site=f"http.fetch {url}",
                should_retry=lambda e: not getattr(e, "no_retry", False))
        except RetryError as e:
            raise FetchError(
                f"giving up on {url} after {e.attempts} attempts"
            ) from e.__cause__


class DirFetcher:
    """Directory-backed transport for tests and offline replay.

    URL ``scheme://host/path?query`` maps to ``root/host/path`` with the
    query string (if any) appended as ``#<urlencoded-query>`` — flat, human
    -readable fixture layouts.  A missing file is a 404 (returns None).
    """

    def __init__(self, root: str):
        self.root = root
        self.requests: list[str] = []  # observability for tests

    def path_for(self, url: str, params: dict | None = None) -> str:
        full = _with_params(url, params)
        self.requests.append(full)
        rest = full.split("://", 1)[-1]
        if "?" in rest:
            rest, query = rest.split("?", 1)
            rest = rest.rstrip("/") + "#" + urllib.parse.quote(query, safe="=&")
        return os.path.join(self.root, *rest.split("/"))

    def get(self, url: str, params: dict | None = None) -> Response | None:
        path = self.path_for(url, params)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return Response(url=url, status=200, content=f.read())
