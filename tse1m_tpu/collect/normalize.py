"""Adapters from collector outputs to the ``ingest_csv_dir`` table CSVs.

The reference never closes this loop — its collectors emit CSVs in
scraper-native shapes and the DB ships pre-built (SURVEY.md §1, "gap in
the reference").  These functions map each collector's output onto the
canonical table schemas in :mod:`tse1m_tpu.db.schema`:

- C6 analyzed batches       -> ``buildlog_data.csv``
- C7 merged issue records   -> ``issues.csv``
- C5 merged coverage rows   -> ``total_coverage.csv``
- C3 project rows           -> ``project_info.csv`` (already table-shaped)
"""

from __future__ import annotations

import json

import pandas as pd

from ..db.ingest import pg_array_literal
from ..utils.logging import get_logger

log = get_logger("collect.normalize")


def _json_cell(value):
    """Issue CSVs store every value JSON-encoded (5_…py:303)."""
    if value is None or (isinstance(value, float) and value != value):
        return None
    if not isinstance(value, str):
        return value
    try:
        return json.loads(value)
    except (json.JSONDecodeError, TypeError):
        return value


def buildlog_table_rows(analyzed: pd.DataFrame) -> pd.DataFrame:
    """C6 batch rows -> buildlog_data.csv columns.  Arrays go out as
    Postgres literals so the CSV round-trips through ``parse_array`` and
    matches the golden artifact format."""
    out = pd.DataFrame({
        "name": analyzed["id"],
        "project": analyzed["project"],
        "timecreated": analyzed["timecreated"],
        "build_type": analyzed["build_type"],
        "result": analyzed["result"],
        "modules": [pg_array_literal(_json_cell(v) or [])
                    for v in analyzed["modules"]],
        "revisions": [pg_array_literal(_json_cell(v) or [])
                      for v in analyzed["revisions"]],
    })
    # Rows whose log never revealed a project cannot join to anything.
    dropped = int((out["project"] == "").sum())
    if dropped:
        log.warning("dropping %d buildlog rows with no project", dropped)
    return out[out["project"] != ""].reset_index(drop=True)


def coverage_table_rows(merged: pd.DataFrame) -> pd.DataFrame:
    """C5 merged rows -> total_coverage.csv columns; the scrape-side
    ``exist`` flag is internal."""
    date = pd.to_datetime(merged["date"], format="%Y%m%d", errors="coerce")
    return pd.DataFrame({
        "project": merged["project"],
        "date": date.dt.strftime("%Y-%m-%d"),
        "coverage": merged["coverage"],
        "covered_line": merged["covered_line"],
        "total_line": merged["total_line"],
    })


def _severity(record: dict):
    """Prefer tracker metadata Severity; fall back to the description's
    recommended security severity."""
    return (record.get("Severity")
            or record.get("Recommended Security Severity"))


def _flatten_revisions(value) -> list[str]:
    """regressed_revisions is a list of 1- or 2-element ranges
    (5_…py:113); the DB's regressed_build array stores the endpoints."""
    out: list[str] = []
    if isinstance(value, list):
        for item in value:
            if isinstance(item, list):
                out.extend(str(v) for v in item)
            else:
                out.append(str(item))
    elif value:
        out.append(str(value))
    return out


def issue_table_rows(merged: pd.DataFrame,
                     requested_ids: dict | None = None) -> pd.DataFrame:
    """C7 merged records (JSON-encoded cells) -> issues.csv columns.

    ``number`` is the id the study targeted (Monorail numbering where one
    exists); ``new_id`` the tracker id the page resolved to.
    ``requested_ids`` optionally maps final id -> originally requested id
    for redirected fetches."""
    requested_ids = requested_ids or {}
    rows = []
    for _, raw in merged.iterrows():
        rec = {k: _json_cell(v) for k, v in raw.items()}
        if rec.get("error"):
            continue
        final_id = str(rec.get("id", ""))
        project = rec.get("Project")
        rts = rec.get("reported_time") or rec.get("Metadata_Reported_Date")
        if not project or not rts:
            continue
        crash_type = rec.get("Crash Type")
        if isinstance(crash_type, list):
            crash_type = crash_type[0] if crash_type else None
        rows.append({
            "project": project,
            "number": str(requested_ids.get(final_id, final_id)),
            "rts": rts,
            "status": rec.get("Status"),
            "crash_type": crash_type,
            "severity": _severity(rec),
            "type": rec.get("Type"),
            "regressed_build": pg_array_literal(
                _flatten_revisions(rec.get("regressed_revisions"))),
            "new_id": final_id,
        })
    kept = pd.DataFrame(rows, columns=["project", "number", "rts", "status",
                                       "crash_type", "severity", "type",
                                       "regressed_build", "new_id"])
    log.info("normalized %d/%d issue records", len(kept), len(merged))
    return kept
