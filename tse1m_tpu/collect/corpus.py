"""C8 collection half — seed-corpus archaeology (reference:
``user_corpus.py:39-240``).

Per project in an oss-fuzz checkout:

- project creation time: first commit that *added* files under
  ``projects/<name>`` (``git log --reverse --diff-filter=A``,
  user_corpus.py:178-179);
- corpus introduction: first commit whose ``build.sh`` change mentions
  ``_seed_corpus.zip`` (``git log -S``, user_corpus.py:189-190), plus that
  commit's PR merge time via the GitHub API (user_corpus.py:102-154) when a
  token/transport is available;
- elapsed seconds for both, feeding the RQ4 grouping (the *analysis* half
  lives in :mod:`tse1m_tpu.analysis.corpus`).

Output: ``project_corpus_analysis.csv`` with the reference's 7 columns
(user_corpus.py:225-233).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from datetime import datetime

import pandas as pd

from .projects import run_git
from .transport import Fetcher
from ..utils.logging import get_logger

log = get_logger("collect.corpus")

SEED_CORPUS_NEEDLE = "_seed_corpus.zip"
GITHUB_API = "https://api.github.com/repos/{owner}/{repo}"

CSV_HEADER = ["project_name", "is_Corpus", "corpus_commit_time",
              "corpus_merged_time", "project_creation_time",
              "time_elapsed_seconds", "merged_time_elapsed_seconds"]


def _parse_iso(s: str | None) -> datetime | None:
    if not s:
        return None
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    try:
        return datetime.fromisoformat(s)
    except ValueError:
        return None


def project_creation_time(repo_path: str, project: str) -> datetime | None:
    """First commit adding files under the project dir
    (user_corpus.py:178-183)."""
    rel = os.path.join("projects", project)
    out = run_git(["log", "--reverse", "--diff-filter=A",
                   "--pretty=format:%cI", "--", rel], repo_path)
    if not out:
        return None
    return _parse_iso(out.splitlines()[0].strip())


def corpus_commit(repo_path: str, project: str,
                  needle: str = SEED_CORPUS_NEEDLE
                  ) -> tuple[str | None, datetime | None]:
    """(sha, time) of the first build.sh commit mentioning the seed-corpus
    archive (user_corpus.py:86-98,189-190)."""
    rel = os.path.join("projects", project, "build.sh")
    out = run_git(["log", "--reverse", f"-S{needle}",
                   "--pretty=format:%H%n%cI", "--", rel], repo_path)
    if not out:
        return None, None
    lines = [ln.strip() for ln in out.splitlines() if ln.strip()]
    if len(lines) < 2:
        return None, None
    return lines[0], _parse_iso(lines[1])


@dataclass
class GitHubMergeTimeResolver:
    """Commit sha -> containing PR's merge time, via two API hops
    (user_corpus.py:113-142).  ``fetcher`` handles retries; a missing token
    downgrades to never resolving (the reference skips the call,
    user_corpus.py:108-111)."""

    fetcher: Fetcher | None
    token: str | None = None
    owner: str = "google"
    repo: str = "oss-fuzz"

    def merge_time(self, commit_sha: str) -> datetime | None:
        if self.fetcher is None or not self.token:
            return None
        base = GITHUB_API.format(owner=self.owner, repo=self.repo)
        resp = self.fetcher.get(f"{base}/commits/{commit_sha}/pulls",
                                params={"state": "closed", "per_page": 1})
        if resp is None:
            return None
        pulls = resp.json()
        if not pulls:
            return None
        pr_resp = self.fetcher.get(f"{base}/pulls/{pulls[0]['number']}")
        if pr_resp is None:
            return None
        return _parse_iso(pr_resp.json().get("merged_at"))


def analyze_repository(repo_path: str, project_names: list[str],
                       resolver: GitHubMergeTimeResolver | None = None
                       ) -> pd.DataFrame:
    """Per-project corpus timeline rows (user_corpus.py:157-217).
    Projects with no creation commit are skipped; projects without a
    build.sh get a row with null corpus fields."""
    resolver = resolver or GitHubMergeTimeResolver(fetcher=None)
    rows = []
    for name in project_names:
        created = project_creation_time(repo_path, name)
        if created is None:
            continue
        build_sh = os.path.join(repo_path, "projects", name, "build.sh")
        row = {"project_name": name, "is_Corpus": False,
               "corpus_commit_time": None, "corpus_merged_time": None,
               "project_creation_time": created,
               "time_elapsed_seconds": None,
               "merged_time_elapsed_seconds": None}
        if os.path.exists(build_sh):
            sha, commit_time = corpus_commit(repo_path, name)
            if commit_time is not None:
                row["is_Corpus"] = True
                row["corpus_commit_time"] = commit_time
                row["time_elapsed_seconds"] = (
                    commit_time - created).total_seconds()
                merged = resolver.merge_time(sha) if sha else None
                if merged is not None:
                    row["corpus_merged_time"] = merged
                    row["merged_time_elapsed_seconds"] = (
                        merged - created).total_seconds()
        rows.append(row)
    return pd.DataFrame(rows, columns=CSV_HEADER)


def run_corpus_collector(repo_path: str, out_csv: str,
                         resolver: GitHubMergeTimeResolver | None = None,
                         force: bool = False) -> pd.DataFrame:
    """Analyze every project dir and write the CSV; an existing CSV short
    -circuits unless ``force`` (user_corpus.py:367-370)."""
    if os.path.exists(out_csv) and not force:
        log.info("%s exists; skipping git analysis", out_csv)
        return pd.read_csv(out_csv)
    projects_dir = os.path.join(repo_path, "projects")
    names = sorted(d for d in os.listdir(projects_dir)
                   if os.path.isdir(os.path.join(projects_dir, d)))
    df = analyze_repository(repo_path, names, resolver)
    os.makedirs(os.path.dirname(out_csv) or ".", exist_ok=True)
    df.to_csv(out_csv, index=False, encoding="utf-8")
    log.info("wrote %d corpus rows to %s", len(df), out_csv)
    return df
