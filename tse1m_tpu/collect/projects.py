"""C3 — project-info collector (reference: ``1_get_projects_infos.py``).

Walks an oss-fuzz checkout's ``projects/`` tree, flattens each project's
``project.yaml`` into scalar columns, stamps the first commit that touched
the project directory, and writes ``project_info.csv`` in the layout the
reference produces (``project, first_commit_datetime`` first, remaining
yaml keys sorted — ``1_…py:130-133``).

Git access is plain ``subprocess git`` (the reference pulls in GitPython
for two one-liner queries, ``1_…py:12-23``); parsing is pure and the repo
path is injected, so tests drive it against a tiny synthetic repo.
"""

from __future__ import annotations

import json
import os
import subprocess
from datetime import datetime

import pandas as pd
import yaml

from ..utils.logging import get_logger

log = get_logger("collect.projects")

OSS_FUZZ_URL = "https://github.com/google/oss-fuzz.git"


def run_git(args: list[str], repo_path: str) -> str | None:
    """Run git in ``repo_path``; None on failure (missing path/history)."""
    try:
        out = subprocess.run(["git", *args], cwd=repo_path, check=True,
                             capture_output=True, text=True, encoding="utf-8")
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        log.debug("git %s failed in %s: %s", " ".join(args), repo_path, e)
        return None
    return out.stdout.strip()


def clone_repo(url: str, clone_path: str) -> None:
    """Clone once; an existing checkout is reused (1_…py:35-44)."""
    if os.path.exists(clone_path):
        log.info("repository already present at %s; skipping clone", clone_path)
        return
    os.makedirs(os.path.dirname(clone_path) or ".", exist_ok=True)
    log.info("cloning %s -> %s", url, clone_path)
    subprocess.run(["git", "clone", url, clone_path], check=True)


def first_commit_time(repo_path: str, rel_path: str) -> datetime | None:
    """Committer datetime of the first commit touching ``rel_path``
    (1_…py:12-19: ``iter_commits(paths=…, reverse=True)[0]``)."""
    out = run_git(["log", "--reverse", "--format=%cI", "--", rel_path],
                  repo_path)
    if not out:
        return None
    first = out.splitlines()[0].strip()
    try:
        return datetime.fromisoformat(first)
    except ValueError:
        return None


def flatten_yaml_value(value):
    """project.yaml values -> CSV scalars (1_…py:25-33): dicts as JSON,
    empty sequences as None, lists via str()."""
    if isinstance(value, dict):
        return json.dumps(value)
    if isinstance(value, (list, tuple)) and not value:
        return None
    if isinstance(value, list):
        return str(value)
    return value


def read_project_yaml(path: str) -> dict | None:
    with open(path, encoding="utf-8") as f:
        try:
            data = yaml.safe_load(f)
        except yaml.YAMLError as e:
            log.warning("unparseable project.yaml at %s: %s", path, e)
            return None
    return data if isinstance(data, dict) else None


def collect_project_info(repo_path: str) -> pd.DataFrame:
    """One row per project directory that carries a project.yaml."""
    projects_dir = os.path.join(repo_path, "projects")
    if not os.path.isdir(projects_dir):
        raise FileNotFoundError(f"no projects/ directory under {repo_path}")
    names = sorted(d for d in os.listdir(projects_dir)
                   if os.path.isdir(os.path.join(projects_dir, d)))
    log.info("found %d project directories", len(names))

    records = []
    for name in names:
        yaml_path = os.path.join(projects_dir, name, "project.yaml")
        if not os.path.exists(yaml_path):
            log.warning("no project.yaml for %s; skipping", name)
            continue
        row: dict = {"project": name}
        row["first_commit_datetime"] = first_commit_time(
            repo_path, os.path.join("projects", name))
        data = read_project_yaml(yaml_path)
        if data:
            for key, value in data.items():
                row[key] = flatten_yaml_value(value)
        records.append(row)

    df = pd.DataFrame(records)
    if "first_commit_datetime" in df.columns:
        lead = ["project", "first_commit_datetime"]
        df = df[lead + sorted(c for c in df.columns if c not in lead)]
    return df


def run_project_info_collector(repo_path: str, out_csv: str,
                               clone_url: str | None = None) -> pd.DataFrame:
    if clone_url:
        clone_repo(clone_url, repo_path)
    df = collect_project_info(repo_path)
    os.makedirs(os.path.dirname(out_csv) or ".", exist_ok=True)
    df.to_csv(out_csv, index=False, encoding="utf-8")
    log.info("wrote %d project rows to %s", len(df), out_csv)
    return df
