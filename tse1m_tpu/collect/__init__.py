"""Offline collection layer — the TPU build's seat for the reference's
``program/preparation/`` scripts (C3-C8, SURVEY.md §2.1).

Design: every collector splits into a *pure parser* (unit-testable against
recorded fixtures, no network) and a thin *driver* that wires the parser to
an injectable :class:`~tse1m_tpu.collect.transport.Fetcher` plus the shared
checkpoint/resume helpers.  The reference interleaves IO with parsing inside
monolithic ``main()`` scripts; here the IO boundary is explicit so the whole
layer runs under tests with a directory-backed fake transport.

- ``transport``    HTTP fetch policy: retries w/ backoff, 404-as-absent,
                   politeness delays (reference: retry adapters in
                   ``2_get_buildlog_metadata.py:106-108``,
                   ``3_get_coverage_data.py:73-74``)
- ``checkpoint``   batch-CSV checkpointing, processed-id resume scans,
                   resume-from-last-date (``2_…py:141-147``, ``3_…py:255-267``,
                   ``4_…py:263-272``, ``5_…py:29-51``)
- ``projects``     C3: oss-fuzz clone + project.yaml flatten + first-commit
                   times (``1_get_projects_infos.py``)
- ``gcs_metadata`` C4: GCS JSON API pager for build-log object metadata
                   (``2_get_buildlog_metadata.py``)
- ``coverage``     C5: daily coverage-report scraping with per-language HTML
                   parsing rules (``3_get_coverage_data.py``)
- ``buildlogs``    C6: raw build-log -> structured record regex engine
                   (``4_get_buildlog_analysis.py``)
- ``issues``       C7: issue-tracker scraping — pure page parsing + a
                   process-parallel driver with resume/recovery
                   (``5_get_issue_reports.py``)
- ``corpus``       C8 collection half: git seed-corpus archaeology + GitHub
                   PR merge times (``user_corpus.py:102-240``)
- ``normalize``    adapters from collector outputs to the ``ingest_csv_dir``
                   table schemas (the reference's missing CSV->DB link)
"""

from .transport import DirFetcher, FetchPolicy, Fetcher, HttpFetcher, Response
from .checkpoint import (CsvBatchCheckpointer, last_date_in_csv,
                         processed_ids_from_csvs)

__all__ = [
    "DirFetcher", "FetchPolicy", "Fetcher", "HttpFetcher", "Response",
    "CsvBatchCheckpointer", "last_date_in_csv", "processed_ids_from_csvs",
]
