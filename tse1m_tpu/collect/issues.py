"""C7 — issue-tracker collector (reference: ``5_get_issue_reports.py``).

The reference is one 500-line Selenium script; here the concerns are
separated so the scraping *logic* is testable offline:

- **URL routing**: old Monorail vs new tracker by id threshold
  (5_…py:128-131).
- **Pure parsing** over a :class:`RawIssuePage`: description key/value
  extraction with parenthesis-tolerant labels (5_…py:231-267), "Fixed"
  commit extraction from the event stream (5_…py:198-228), revision-range
  splitting (5_…py:53-57).
- **Client protocol**: :class:`IssuePageClient` yields structured pages;
  the Selenium implementation (:mod:`.issues_selenium`) drives the live
  shadow-DOM tracker when selenium is installed; tests use a fake.
- **Driver**: process-parallel windows with private output dirs
  (5_…py:486-497,320-322), checkpoint every ``save_interval`` issues
  (5_…py:333-334), client restart on unhandled errors (5_…py:328-332),
  processed-id resume and the re-scrape filter DSL (5_…py:364-454).
"""

from __future__ import annotations

import json
import math
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Protocol

import pandas as pd

from .checkpoint import processed_ids_from_csvs
from ..resilience import reraise_if_fault
from ..utils.atomic import atomic_write
from ..utils.logging import get_logger

log = get_logger("collect.issues")

MONORAIL_THRESHOLD = 10_000_000
MONORAIL_URL = "https://bugs.chromium.org/p/oss-fuzz/issues/detail?id={}"
TRACKER_URL = "https://issues.oss-fuzz.com/issues/{}"

# Description labels harvested into the record (5_…py:231).
DESCRIPTION_KEYS = (
    "Project", "Fuzzing Engine", "Fuzz Target", "Job Type", "Platform Id",
    "Crash Type", "Crash Address", "Crash State", "Sanitizer", "Regressed",
    "Reproducer Testcase", "Crash Revision", "Download", "Fixed", "Fuzzer",
    "Fuzzer binary", "Fuzz target binary", "Minimized Testcase",
    "Recommended Security Severity", "Unminimized Testcase", "Build log",
    "Build type",
)
# Labels whose value is a URL possibly followed by extra text (5_…py:254).
URL_VALUE_KEYS = ("Regressed", "Fixed", "Crash Revision", "Build log",
                  "Reproducer Testcase", "Minimized Testcase")
# Sub-pages scraped for component/revision tables (5_…py:272).
REVISION_SUBPAGES = {"Regressed": "regressed", "Fixed": "fixed",
                     "Crash Revision": "crash"}

_LABEL_RES = {key: re.compile(rf"^{re.escape(key)}(?:\s*\(.*\))?\s*:",
                              re.IGNORECASE)
              for key in DESCRIPTION_KEYS}


def issue_url(issue_no: int) -> str:
    """Monorail ids are < 10M; everything newer lives on the new tracker."""
    if int(issue_no) < MONORAIL_THRESHOLD:
        return MONORAIL_URL.format(issue_no)
    return TRACKER_URL.format(issue_no)


def split_revision_range(text: str) -> list[str]:
    """``"<sha>:<sha>"`` -> both endpoints; anything else stays whole
    (5_…py:53-57: both sides must look like revisions, > 10 chars)."""
    parts = text.split(":")
    if len(parts) == 2 and len(parts[0]) > 10 and len(parts[1]) > 10:
        return parts
    return [text]


def parse_description(text: str) -> dict:
    """Key/value extraction from the issue description (5_…py:234-267).

    A line starting with a known label (optionally ``(size)``-annotated)
    opens that key; later unlabeled lines continue it as a list until a
    blank line, an auto-filing boilerplate line, or the next label."""
    out: dict = {}
    current: str | None = None
    for line in text.split("\n"):
        stripped = line.strip().replace("<b>", "").replace("</b>", "")
        if not stripped:
            current = None
            continue
        clean = stripped.replace("**", "")
        matched = False
        for key, pattern in _LABEL_RES.items():
            if pattern.match(clean):
                current = key
                value = stripped.split(":", 1)[1].strip()
                if key in URL_VALUE_KEYS and "http" in value:
                    value = value.split(" ")[0]
                out[key] = value
                matched = True
                break
        if matched or current is None:
            continue
        if "Issue filed automatically" in stripped or "See " in stripped:
            current = None
            continue
        existing = out.get(current)
        if isinstance(existing, list):
            existing.append(stripped)
        elif existing:
            out[current] = [existing, stripped]
        else:
            out[current] = [stripped]
    return out


@dataclass
class IssueEvent:
    """One timeline event: its visible comment text, ISO timestamp, and any
    ``/revisions`` links it contains."""

    text: str
    time_iso: str | None = None
    revision_links: list = field(default_factory=list)


def extract_fixed_from_events(events: list[IssueEvent]) -> tuple[str | None, str | None]:
    """Latest-first scan for the fix notice (5_…py:198-228): either an
    explicit ``Fixed: http…/revisions`` line or a "is verified as fixed in"
    comment with a revisions link.  Returns (fixed_url, fixed_time_iso)."""
    for event in reversed(events):
        for line in event.text.split("\n"):
            stripped = line.strip()
            if stripped.startswith("Fixed: http") and "/revisions" in stripped:
                return stripped.split(" ", 1)[1], event.time_iso
        if "is verified as fixed in" in event.text and event.revision_links:
            return event.revision_links[0], event.time_iso
    return None, None


@dataclass
class RevisionTable:
    components: list
    revisions: list            # list of [rev] or [start, end] ranges
    buildtime: list | None = None


@dataclass
class RawIssuePage:
    """Structured capture of one issue page, produced by a client."""

    final_id: str
    url: str
    title: str | None = None
    reported_time_iso: str | None = None
    metadata: dict = field(default_factory=dict)   # label -> value
    events: list = field(default_factory=list)     # [IssueEvent]
    description: str = ""
    hotlists: list = field(default_factory=list)
    load_error: bool = False


class IssuePageClient(Protocol):
    def fetch_issue(self, issue_no: int) -> RawIssuePage: ...

    def fetch_revisions(self, url: str) -> RevisionTable | None: ...


def _fmt_minute(iso: str | None) -> str | None:
    if not iso:
        return None
    from datetime import datetime

    try:
        return (datetime.fromisoformat(iso.replace("Z", "+00:00"))
                .strftime("%Y-%m-%d %H:%M"))
    except ValueError:
        return None


def assemble_issue_record(page: RawIssuePage,
                          client: IssuePageClient) -> dict:
    """Page -> flat record, including the three revision sub-scrapes
    (5_…py:155-291).  Keys mirror the reference's CSV columns."""
    record: dict = {"id": page.final_id, "url": page.url,
                    "error": page.load_error}
    if page.load_error:
        record["title"] = "Failed to load page"
        return record
    record["title"] = page.title
    if page.hotlists:
        record["hotlists"] = page.hotlists
    rt = _fmt_minute(page.reported_time_iso)
    if rt:
        record["reported_time"] = rt
    for label, value in page.metadata.items():
        key = "Metadata_Reported_Date" if label == "Reported" else label
        record[key] = value

    fixed_url, fixed_iso = extract_fixed_from_events(page.events)
    if fixed_url:
        record["Fixed"] = fixed_url
        ft = _fmt_minute(fixed_iso)
        if ft:
            record["fixed_time"] = ft

    record.update(parse_description(page.description))

    for info_key, prefix in REVISION_SUBPAGES.items():
        sub_url = record.get(info_key)
        if not (isinstance(sub_url, str) and sub_url.startswith("http")):
            continue
        try:
            table = client.fetch_revisions(sub_url)
        except Exception as e:
            # Selenium raises arbitrary driver exceptions — stay broad,
            # but keep the fault plane visible through this seat.
            reraise_if_fault(e)
            log.warning("revision sub-scrape failed for %s: %s", sub_url, e)
            continue
        if table is None:
            continue
        record[f"{prefix}_components"] = table.components
        record[f"{prefix}_revisions"] = table.revisions
        record[f"{prefix}_buildtime"] = table.buildtime
    return record


def revision_buildtime_from_url(url: str) -> list | None:
    """The ``?range=<t1>:<t2>`` tail doubles as the build-time pair
    (5_…py:87)."""
    return url.split("=")[-1].split(":") if "=" in url else None


def save_issue_batch(records: list[dict], directory: str,
                     file_index: int) -> str | None:
    """Numbered CSV with every value JSON-encoded and a sorted union header
    (5_…py:293-309) — the format ``processed_ids_from_csvs`` and the filter
    DSL read back."""
    if not records:
        return None
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{file_index:03d}.csv")
    header = sorted({k for r in records for k in r})
    import csv

    # Atomic: a worker killed mid-batch must not leave a torn CSV that
    # plan_run later reads as "these ids are processed".
    with atomic_write(path, newline="") as f:
        w = csv.DictWriter(f, fieldnames=header)
        w.writeheader()
        for r in records:
            w.writerow({k: json.dumps(r.get(k), ensure_ascii=False)
                        for k in header})
    log.info("saved %d issues to %s", len(records), path)
    return path


def run_scraper_window(client_factory: Callable[[], IssuePageClient],
                       issue_numbers: list[int], window_index: int,
                       base_output_dir: str, save_interval: int = 50) -> int:
    """One worker: private output dir, checkpoint every ``save_interval``
    issues, client restart on unhandled errors (5_…py:311-340)."""
    out_dir = os.path.join(base_output_dir, f"window_{window_index}")
    client = client_factory()
    batch: list[dict] = []
    file_counter = 1
    done = 0
    for issue_no in issue_numbers:
        try:
            page = client.fetch_issue(issue_no)
            batch.append(assemble_issue_record(page, client))
            done += 1
        except Exception as e:
            reraise_if_fault(e)  # chaos plans must see through the restart
            log.error("window %d: unhandled error on issue %s: %s",
                      window_index, issue_no, e)
            if batch:
                save_issue_batch(batch, out_dir, file_counter)
                batch = []
                file_counter += 1
            close = getattr(client, "close", None)
            if close:
                try:
                    close()
                except Exception as ce:  # best-effort teardown of a dead client
                    reraise_if_fault(ce)
            client = client_factory()
        if len(batch) >= save_interval:
            save_issue_batch(batch, out_dir, file_counter)
            batch = []
            file_counter += 1
    if batch:
        save_issue_batch(batch, out_dir, file_counter)
    close = getattr(client, "close", None)
    if close:
        try:
            close()
        except Exception as ce:  # best-effort teardown at window end
            reraise_if_fault(ce)
    log.info("window %d finished: %d issues", window_index, done)
    return done


def scrape_issues(client_factory: Callable[[], IssuePageClient],
                  ids_to_process: list[int], output_dir: str,
                  num_workers: int = 8, save_interval: int = 50,
                  parallel: bool = True) -> None:
    """Fan the id list across worker processes (5_…py:486-497).  Each
    window owns a disjoint output dir, so concurrent runs cannot corrupt
    each other.  ``parallel=False`` runs the windows inline (tests, or
    clients that cannot cross a fork)."""
    if not ids_to_process:
        log.info("no issues to scrape")
        return
    workers = max(1, min(num_workers, len(ids_to_process)))
    chunk = math.ceil(len(ids_to_process) / workers)
    chunks = [ids_to_process[i:i + chunk]
              for i in range(0, len(ids_to_process), chunk)]
    if not parallel or len(chunks) == 1:
        for i, ids in enumerate(chunks):
            run_scraper_window(client_factory, ids, i, output_dir,
                               save_interval)
        return
    import multiprocessing

    procs = []
    for i, ids in enumerate(chunks):
        p = multiprocessing.Process(
            target=run_scraper_window,
            args=(client_factory, ids, i, output_dir, save_interval))
        procs.append(p)
        p.start()
    for p in procs:
        p.join()


def select_rescrape_ids(df: pd.DataFrame, conditions: dict) -> list[int]:
    """The re-scrape filter DSL over the merged CSV (5_…py:364-454):
    ``True`` = column missing (NaN or JSON ``null``), ``False`` = present,
    ``str`` = case-insensitive substring; conditions AND together."""
    if df.empty or not conditions:
        return []
    mask = pd.Series(True, index=df.index)
    for column, cond in conditions.items():
        if column not in df.columns:
            log.warning("filter column %r not in CSV; skipping", column)
            continue
        col = df[column]
        if cond is True:
            mask &= col.isnull() | (col == "null")
        elif cond is False:
            mask &= col.notnull() & (col != "null")
        elif isinstance(cond, str):
            mask &= col.astype(str).str.contains(re.escape(cond), case=False,
                                                 na=False)
        else:
            log.warning("unsupported condition %r for %r", cond, column)
    ids = (df.loc[mask, "id"].dropna().astype(str).str.strip('"')
           if "id" in df.columns else pd.Series([], dtype=str))
    return pd.to_numeric(ids, errors="coerce").dropna().astype(int).tolist()


def plan_run(target_ids: set, results_dir: str,
             merged_csv: str | None = None,
             rescrape_conditions: dict | None = None) -> list[int]:
    """Resume plan (5_…py:457-466): targets minus already-processed ids,
    plus any re-scrape matches, newest first."""
    processed = processed_ids_from_csvs(results_dir, id_column="id",
                                        json_encoded=True)
    todo = set(target_ids) - processed
    if merged_csv and rescrape_conditions and os.path.exists(merged_csv):
        df = pd.read_csv(merged_csv, low_memory=False)
        todo.update(select_rescrape_ids(df, rescrape_conditions))
    plan = sorted(todo, reverse=True)
    log.info("plan: %d targets, %d already processed, %d to scrape",
             len(target_ids), len(processed), len(plan))
    return plan


def merge_window_csvs(results_dir: str, merged_csv: str) -> int:
    """Union-merge every window CSV under ``results_dir`` (the reference
    reads these into ``merged_output.csv`` for the filter DSL)."""
    frames = []
    for root, _, files in os.walk(results_dir):
        for name in sorted(files):
            if name.endswith(".csv"):
                try:
                    frames.append(pd.read_csv(os.path.join(root, name),
                                              low_memory=False))
                except (OSError, ValueError) as e:
                    log.warning("skipping %s: %s", name, e)
    if not frames:
        return 0
    merged = pd.concat(frames, ignore_index=True)
    os.makedirs(os.path.dirname(merged_csv) or ".", exist_ok=True)
    merged.to_csv(merged_csv, index=False, encoding="utf-8")
    return len(merged)
