"""C6 — raw build-log analyzer (reference: ``4_get_buildlog_analysis.py``).

Turns a raw GCB build log into the structured record behind the
``buildlog_data`` table: project name, build_type, result, and the
(path, type, url, revision) module tuples from ``jq_inplace`` lines and
embedded srcmap JSON blocks.

The parser is a pure function over the log text; the driver streams logs
through the injected transport with processed-id resume and batch-CSV
checkpoints.  Two documented deviations from the reference:

- build_type values are canonical ``Fuzzing/Coverage/Introspector/Error/
  Unknown`` — the reference emits mixed-case variants (``'coverage'`` at
  4_…py:109 vs ``'Coverage'`` at :131) that the shipped DB never contains;
- srcmap JSON blocks are delimited by brace depth; the reference ends a
  block at the first line ending in ``}`` (4_…py:196), which truncates any
  multi-module srcmap before parsing.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

import pandas as pd

from .checkpoint import CsvBatchCheckpointer, processed_ids_from_csvs
from .transport import Fetcher
from ..resilience import reraise_if_fault
from ..utils.logging import get_logger

log = get_logger("collect.buildlogs")

PUBLIC_LOG_URL_TEMPLATE = ("https://oss-fuzz-build-logs.storage.googleapis"
                           ".com/log-{build_id}.txt")

# Log-format constants (4_…py:62-72).  The format is OSS-Fuzz's GCB output;
# the patterns describe that format, the classification logic around them is
# restructured as an ordered rule table.
_IMAGE_RE = re.compile(r"Already have image: gcr\.io/oss-fuzz/([^\s:]+)")
_GCS_RE = re.compile(
    r"No URLs matched: gs://oss-fuzz-coverage/([^/]+)/textcov_reports")
_STARTING_STEP_RE = re.compile(r"Starting Step #\d+\s*(.*)")
_PULL_BASE_RUNNER_RE = re.compile(
    r"Step #(\d+): Pulling image: gcr.io/oss-fuzz-base/base-runner")
_REPORT_HTML_RE = re.compile(r"/report/.*\.html")
_BASE_RUNNER_MISS_RE = re.compile(
    r"Unable to find image 'gcr.io/oss-fuzz-base/base-runner:latest' locally")
_COMPILE_RE = re.compile(r"compile-(.*)-(.*)-x86_64")
_PUSH_DONE_RE = re.compile(r"PUSH\s*DONE", re.DOTALL)
_JQ_INPLACE_RE = re.compile(r"jq_inplace [^ ]+ '(.*?)'")
_STEP_PAYLOAD_RE = re.compile(r"Step #\d+:\s?(.*)")

_FUZZ_SANITIZERS = ("address", "memory", "undefined", "none")
_STEP_SANITIZER_KEYWORDS = ("address-x86_64", "undefined-x86_64",
                            "memory-x86_64", "none-x86_64", "address-i386")
# Step index -> build type for the base-runner pull (4_…py:127-135).
_PULL_STEP_TYPES = {"0": "Introspector", "4": "Coverage", "5": "Fuzzing"}


@dataclass
class ModuleEntry:
    path: str
    type: str
    url: str
    revision: str

    @property
    def module(self) -> str:
        """Display name: last path component, capitalised (4_…py:219)."""
        return self.path.split("/")[-1].capitalize()


@dataclass
class BuildLogRecord:
    build_id: str
    project: str = ""
    build_type: str = ""
    result: str = ""
    modules: list = field(default_factory=list)        # display names
    paths: list = field(default_factory=list)
    types: list = field(default_factory=list)
    repo_urls: list = field(default_factory=list)
    revisions: list = field(default_factory=list)


def _classify_starting_step(text: str) -> str | None:
    """The 'Starting Step #N "<name>"' rule (4_…py:101-118): srcmap/build
    steps carry no signal; coverage/introspector by name; sanitizer
    suffixes mean a fuzzing step."""
    name = text.strip().replace('"', "")
    if not name or "srcmap" in name or "build" in name:
        return None
    if "coverage" in name:
        return "Coverage"
    if "introspector" in name:
        return "Introspector"
    if any(k in name for k in _STEP_SANITIZER_KEYWORDS):
        return "Fuzzing"
    return "Unknown"


def _classify_compile(sanitizer: str) -> str:
    if sanitizer in _FUZZ_SANITIZERS:
        return "Fuzzing"
    if sanitizer == "coverage":
        return "Coverage"
    if sanitizer == "introspector":
        return "Introspector"
    return "Unknown"


class _SrcmapCollector:
    """Accumulates ``Step #N: ...`` JSON payload lines into complete srcmap
    objects, delimited by brace depth."""

    def __init__(self):
        self._lines: list[str] = []
        self._depth = 0
        self.objects: list[dict] = []

    def feed(self, line: str) -> None:
        payload_m = _STEP_PAYLOAD_RE.search(line)
        if payload_m is None:
            return
        payload = payload_m.group(1)
        if not self._lines:
            if payload.strip() != "{":
                return
            self._lines = [payload]
            self._depth = 1
            return
        self._lines.append(payload)
        self._depth += payload.count("{") - payload.count("}")
        if self._depth <= 0:
            text = "".join(self._lines)
            self._lines = []
            self._depth = 0
            try:
                obj = json.loads(text)
            except json.JSONDecodeError:
                return
            if isinstance(obj, dict):
                self.objects.append(obj)


def _final_result(lines: list[str]) -> str:
    """Result from the tail of the log (4_…py:228-237): an ERROR in the
    second-to-last line or an exact ERROR/deadline line in the last 200
    means Error; exact PUSH and DONE lines mean Success."""
    tail = [t.strip() for t in lines[-200:]]
    if len(lines) >= 2 and "ERROR" in lines[-2]:
        return "Error"
    if "ERROR" in tail or "ERROR: context deadline exceeded" in tail:
        return "Error"
    if "PUSH" in tail and "DONE" in tail:
        return "Success"
    return "Unknown"


def parse_build_log(build_id: str, text: str) -> BuildLogRecord:
    """Pure log-text -> structured record (the body of 4_…py:54-246)."""
    rec = BuildLogRecord(build_id=build_id)
    lines = text.splitlines()
    if not lines:
        return rec

    entries: list[ModuleEntry] = []
    srcmaps = _SrcmapCollector()

    for line in lines:
        m = _IMAGE_RE.search(line)
        if m and not rec.project:
            rec.project = m.group(1)
        m = _GCS_RE.search(line)
        if m and not rec.project:
            rec.project = m.group(1)

        step_m = _STARTING_STEP_RE.match(line)
        if step_m:
            kind = _classify_starting_step(step_m.group(1))
            if kind:
                rec.build_type = kind
        else:
            # The remaining signals only fire on non-"Starting Step" lines
            # (the reference's else-branch, 4_…py:119-159); later signals
            # override earlier ones except where guarded.
            pull_m = _PULL_BASE_RUNNER_RE.search(line)
            if pull_m:
                rec.build_type = _PULL_STEP_TYPES.get(pull_m.group(1),
                                                      "Unknown")
            if _REPORT_HTML_RE.search(line):
                rec.build_type = "Coverage"
            if _BASE_RUNNER_MISS_RE.search(line):
                rec.build_type = "Fuzzing"
            compile_m = _COMPILE_RE.search(line)
            if compile_m:
                rec.build_type = _classify_compile(compile_m.group(2))
            if _PUSH_DONE_RE.search(line) and rec.build_type not in (
                    "Coverage", "Introspector"):
                rec.build_type = "Fuzzing"

        jq_m = _JQ_INPLACE_RE.search(line)
        if jq_m:
            content = jq_m.group(1)
            path_m = re.search(r'"(.+?)"\s*=', content)
            type_m = re.search(r'type:\s*"(.+?)"', content)
            url_m = re.search(r'url:\s*"(.+?)"', content)
            rev_m = re.search(r'rev:\s*"(.+?)"', content)
            if path_m and type_m and url_m and rev_m:
                entries.append(ModuleEntry(path=path_m.group(1),
                                           type=type_m.group(1),
                                           url=url_m.group(1),
                                           revision=rev_m.group(1)))

        srcmaps.feed(line)

    for obj in srcmaps.objects:
        for path, details in obj.items():
            if not isinstance(details, dict):
                continue
            entries.append(ModuleEntry(path=path,
                                       type=details.get("type", ""),
                                       url=details.get("url", ""),
                                       revision=details.get("rev", "")))

    rec.modules = [e.module for e in entries]
    rec.paths = [e.path for e in entries]
    rec.types = [e.type for e in entries]
    rec.repo_urls = [e.url for e in entries]
    rec.revisions = [e.revision for e in entries]
    rec.result = _final_result(lines)
    return rec


def _windowed_map(pool, fn, items, window: int):
    """Ordered map over ``pool`` with at most ``window`` tasks submitted at
    once, so neither futures nor completed-but-unconsumed results accumulate
    beyond the window (Executor.map submits everything eagerly)."""
    from collections import deque
    from itertools import islice

    it = iter(items)
    pending = deque(pool.submit(fn, item) for item in islice(it, window))
    while pending:
        yield pending.popleft().result()
        for item in islice(it, 1):
            pending.append(pool.submit(fn, item))


@dataclass
class BuildLogAnalyzer:
    """Streams raw logs through the parser with resume + checkpointing
    (4_…py:249-288).  ``limit`` bounds one run (the reference processes 10
    rows per invocation, 4_…py:281); None = all pending.

    ``workers > 1`` fans the log fetches out over a thread pool — the run
    is network-bound, so this is the lever that matters at the study's
    1.19M-log scale (the pure parse is microseconds per log).  Results are
    checkpointed in submission order either way, so resume state and batch
    CSVs are deterministic.  The fetcher must be thread-safe at
    ``workers > 1`` (requests.Session generally is for plain GETs; the
    reference instead runs whole processes in parallel,
    5_get_issue_reports.py:486-497)."""

    fetcher: Fetcher
    batch_dir: str
    batch_size: int = 200
    limit: int | None = None
    workers: int = 1

    def pending(self, metadata: pd.DataFrame) -> pd.DataFrame:
        done = processed_ids_from_csvs(self.batch_dir, id_column="id")
        return metadata[~metadata["name"].isin(done)]

    def analyze(self, metadata: pd.DataFrame) -> int:
        """``metadata`` rows need name/mediaLink/size/timeCreated (C4's
        output).  Returns the number of logs analyzed this run."""
        todo = self.pending(metadata)
        if self.limit is not None:
            todo = todo.head(self.limit)
        if todo.empty:
            log.info("no new build logs to analyze")
            return 0
        cols = {c.lower(): c for c in todo.columns}

        def col(key, default=None):
            name = cols.get(key.lower(), key)
            return (todo[name].tolist() if name in todo.columns
                    else [default] * len(todo))

        ids = col("name")
        links = col("mediaLink")
        sizes = col("size")
        created = col("timeCreated")
        urls = [link if isinstance(link, str) and link
                else PUBLIC_LOG_URL_TEMPLATE.format(build_id=bid)
                for bid, link in zip(ids, links)]

        def fetch_and_parse(task):
            build_id, url = task
            try:
                resp = self.fetcher.get(url)
            except Exception as e:
                # The fetcher already retried (transport.py); an injected
                # fault that survived it must still surface here.
                reraise_if_fault(e)
                log.warning("log fetch failed for %s: %s", build_id, e)
                resp = None
            return parse_build_log(
                build_id, resp.text if resp is not None else "")

        tasks = list(zip(ids, urls))
        ckpt = CsvBatchCheckpointer(self.batch_dir, "buildlog_analyzed",
                                    self.batch_size)
        # Stream results through the checkpointer so a crash loses at most
        # one unflushed batch (CsvBatchCheckpointer's contract) and memory
        # stays bounded at 1.19M-log scale.  Results are yielded in
        # submission order, so batch CSVs are identical to the serial
        # path's.  Submission is windowed (not Executor.map, which submits
        # every task — and so holds every future + parsed record — up
        # front): at most ``4 * workers`` fetches are in flight or awaiting
        # consumption at any time.
        if self.workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            pool = ThreadPoolExecutor(self.workers)
            recs = _windowed_map(pool, fetch_and_parse, tasks,
                                 window=4 * self.workers)
        else:
            pool = None
            recs = map(fetch_and_parse, tasks)
        n = 0
        try:
            for rec, size, tc, url in zip(recs, sizes, created, urls):
                ckpt.add({
                    "id": rec.build_id,
                    "size": size,
                    "project": rec.project,
                    "build_type": rec.build_type,
                    "result": rec.result,
                    "timecreated": tc,
                    "modules": json.dumps(rec.modules),
                    "path": json.dumps(rec.paths),
                    "revisions": json.dumps(rec.revisions),
                    "types": json.dumps(rec.types),
                    "repo_urls": json.dumps(rec.repo_urls),
                    "download_link": url,
                })
                n += 1
        finally:
            if pool is not None:
                pool.shutdown(wait=False, cancel_futures=True)
        ckpt.flush()
        log.info("analyzed %d build logs", n)
        return n
