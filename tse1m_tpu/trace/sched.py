"""Deterministic schedule control: serialize threads onto one token.

The scheduler owns a set of named threads (spawned via :meth:`run`) and
grants exactly ONE of them the run token at a time; every instrumented
seat (`hooks.trace_point`, `hooks.shared_access`, traced-lock
acquire/release) is a *yield point* where the running thread re-enters
the ready pool and the schedule policy picks who runs next.  Because
all participating threads are serialized, a run is a pure function of
(program, schedule) — the realized decision sequence replays exactly.

Two policies, both serializable as a schedule string (printed by every
failure, the way fault plans print ``TSE1M_FAULT_PLAN``):

- ``v1:pct:<seed>:<depth>`` — PCT-style randomized priorities (Burckhardt
  et al., "A randomized scheduler with probabilistic guarantees of
  finding bugs"): each thread draws a fixed priority from the seeded
  RNG, the highest-priority ready thread runs, and at ``depth`` random
  decision indices the current leader is demoted — covering bugs that
  need d ordered context switches with known probability.
- ``v1:fix:a,b,a,...`` — an explicit decision list (thread names); past
  its end, the lowest-name ready thread runs.  ``realized()`` converts
  any finished run into this form for exact replay, and the bounded
  exhaustive explorer (trace/explore.py) enumerates these prefixes.

Locks: a scheduled thread never blocks the token on a real mutex — the
traced acquire try-acquires and, on failure, parks the thread as
*blocked* until the holder's release readies it again.  A schedule in
which every non-done thread is blocked is reported as a deadlock (with
the replay string), not a hang.
"""

from __future__ import annotations

import random
import threading

from ..resilience.watchdog import deadline_clock

_WAIT_SLICE_S = 0.02
# PCT change points are drawn from this many leading decisions; the
# explored scenarios realize ~15-50 decisions, so a change lands inside
# most runs (the d ordered context switches PCT's guarantee needs).
_PCT_HORIZON = 48


class ScheduleError(AssertionError):
    """An invariant, deadlock or hang under a specific schedule; the
    message carries the replay string."""

    def __init__(self, message: str, schedule_str: str = "") -> None:
        if schedule_str:
            message = f"{message}\n  replay: {schedule_str}"
        super().__init__(message)
        self.schedule_str = schedule_str


class _Abort(BaseException):
    """Internal unwind for threads parked when a run dies (BaseException
    so production ``except Exception`` seats cannot absorb it)."""


class Schedule:
    """A replayable scheduling policy (see module docstring)."""

    def __init__(self, kind: str, seed: int = 0, depth: int = 3,
                 choices: tuple = ()) -> None:
        if kind not in ("pct", "fix"):
            raise ValueError(f"unknown schedule kind {kind!r}")
        self.kind = kind
        self.seed = int(seed)
        self.depth = int(depth)
        self.choices = tuple(choices)
        self._prio: dict[str, float] = {}
        self._rng = random.Random(self.seed)
        self._change_points = (
            frozenset(random.Random(self.seed ^ 0x5EED).sample(
                range(_PCT_HORIZON), min(self.depth, _PCT_HORIZON)))
            if kind == "pct" else frozenset())

    @classmethod
    def pct(cls, seed: int, depth: int = 3) -> "Schedule":
        return cls("pct", seed=seed, depth=depth)

    @classmethod
    def fixed(cls, choices) -> "Schedule":
        return cls("fix", choices=tuple(choices))

    @classmethod
    def from_string(cls, s: str) -> "Schedule":
        parts = s.strip().split(":")
        if len(parts) < 2 or parts[0] != "v1":
            raise ValueError(f"bad schedule string {s!r} (want "
                             "'v1:pct:<seed>:<depth>' or 'v1:fix:a,b,...')")
        if parts[1] == "pct":
            return cls.pct(int(parts[2]),
                           int(parts[3]) if len(parts) > 3 else 3)
        if parts[1] == "fix":
            names = parts[2].split(",") if len(parts) > 2 and parts[2] \
                else []
            return cls.fixed(n for n in names if n)
        raise ValueError(f"bad schedule string {s!r}")

    def to_string(self) -> str:
        if self.kind == "pct":
            return f"v1:pct:{self.seed}:{self.depth}"
        return "v1:fix:" + ",".join(self.choices)

    def choose(self, ready: list, idx: int) -> str:
        """Pick the next thread name from the (ordered) ready list."""
        if self.kind == "fix":
            if idx < len(self.choices) and self.choices[idx] in ready:
                return self.choices[idx]
            return min(ready)
        for name in ready:
            if name not in self._prio:
                self._prio[name] = self._rng.random()
        if idx % _PCT_HORIZON in self._change_points:
            leader = max(ready, key=lambda n: self._prio[n])
            self._prio[leader] -= 1.0
        return max(ready, key=lambda n: self._prio[n])


def fixed_schedule_string(names) -> str:
    """Export a decision-name sequence as a replayable ``v1:fix:...``
    schedule string — the hook graftspec's model checker uses so a
    spec-level counterexample round-trips through the SAME format the
    explorer and ScheduleError replay lines speak.  Names must be
    schedule-safe (no separator characters)."""
    names = tuple(names)
    for n in names:
        if not n or any(ch in n for ch in ",:\n "):
            raise ValueError(f"decision name {n!r} is not "
                             "schedule-safe (no ',', ':' or whitespace)")
    return Schedule.fixed(names).to_string()


class _TState:
    __slots__ = ("name", "status", "blocked_on", "thread")

    def __init__(self, name: str) -> None:
        self.name = name
        self.status = "ready"   # ready | running | blocked | done
        self.blocked_on = None  # lock id while status == "blocked"
        self.thread: threading.Thread | None = None


class DeterministicScheduler:
    """One controlled run of a set of named thread bodies."""

    def __init__(self, schedule: Schedule, timeout_s: float = 60.0,
                 max_decisions: int = 100_000) -> None:
        self.schedule = schedule
        self.timeout_s = float(timeout_s)
        self.max_decisions = int(max_decisions)
        self._cv = threading.Condition()
        self._states: dict[int, _TState] = {}     # thread ident -> state
        self._by_name: dict[str, _TState] = {}
        self._running: _TState | None = None
        self._error: BaseException | None = None
        self._decision_idx = 0
        self.decisions: list[str] = []      # realized choices
        self.alternatives: list[tuple] = []  # ready set at each decision
        self.sites: list[str] = []          # seat names, for diagnostics

    # -- public --------------------------------------------------------------

    def realized(self) -> Schedule:
        """The finished run as an exact-replay fixed schedule."""
        return Schedule.fixed(self.decisions)

    def run(self, bodies: dict) -> None:
        """Execute ``{name: callable}`` to completion under the
        schedule; re-raises the first failure with the replay string."""
        for name in sorted(bodies):
            st = _TState(name)
            self._by_name[name] = st
            t = threading.Thread(target=self._body, name=f"trace-{name}",
                                 args=(st, bodies[name]), daemon=True)
            st.thread = t
        barrier = threading.Barrier(len(bodies) + 1)
        self._barrier = barrier
        for st in self._by_name.values():
            st.thread.start()
        barrier.wait(timeout=10)  # all registered in _states
        with self._cv:
            self._grant_locked()
        limit = deadline_clock() + self.timeout_s
        with self._cv:
            while not all(s.status == "done"
                          for s in self._by_name.values()):
                if self._error is not None:
                    break
                if deadline_clock() > limit:
                    self._error = ScheduleError(
                        "scheduled run hung (" + ", ".join(
                            f"{s.name}={s.status}"
                            for s in self._by_name.values()) + ")",
                        self._replay_str())
                    break
                self._cv.wait(_WAIT_SLICE_S)
            err = self._error
            if err is not None:
                # Unpark everyone so the worker threads unwind via _Abort.
                self._cv.notify_all()
        for st in self._by_name.values():
            st.thread.join(timeout=5)
        if err is not None:
            if isinstance(err, ScheduleError):
                raise err
            raise ScheduleError(
                f"{type(err).__name__}: {err}", self._replay_str()) \
                from err

    def owns_current_thread(self) -> bool:
        return threading.get_ident() in self._states

    # -- seats ---------------------------------------------------------------

    def yield_point(self, site: str) -> None:
        st = self._states.get(threading.get_ident())
        if st is None:
            return
        with self._cv:
            self.sites.append(site)
            st.status = "ready"
            if self._running is st:
                self._running = None
            self._grant_locked()
            self._wait_for_token_locked(st)

    def acquire(self, lock) -> None:
        """Traced-lock acquire for a scheduled thread: never blocks the
        token — try-acquire, else park as blocked until release."""
        st = self._states[threading.get_ident()]
        while True:
            self.yield_point(f"lock:{lock.name}")
            if lock._real.acquire(blocking=False):
                return
            with self._cv:
                st.status = "blocked"
                st.blocked_on = id(lock)
                if self._running is st:
                    self._running = None
                self._grant_locked()
                self._wait_for_token_locked(st)

    def released(self, lock) -> None:
        # Runs for ANY releasing thread (an unscheduled one must still
        # ready the scheduled waiters it unblocks).
        scheduled = threading.get_ident() in self._states
        with self._cv:
            for other in self._by_name.values():
                if other.blocked_on == id(lock):
                    other.blocked_on = None
                    other.status = "ready"
            if not scheduled:
                self._grant_locked()
            # A scheduled releaser keeps the token; its waiters are
            # granted at its next yield point.

    # -- internals -----------------------------------------------------------

    def _body(self, st: _TState, fn) -> None:
        self._states[threading.get_ident()] = st
        try:
            self._barrier.wait(timeout=10)
            with self._cv:
                self._wait_for_token_locked(st)
            fn()
        except _Abort:
            pass
        except BaseException as e:  # graftlint: disable=broad-except -- cross-thread relay: re-raised on the main thread by run() with the replay string attached
            with self._cv:
                if self._error is None:
                    self._error = e
                self._cv.notify_all()
        finally:
            with self._cv:
                st.status = "done"
                if self._running is st:
                    self._running = None
                self._grant_locked()
                self._cv.notify_all()

    def _replay_str(self) -> str:
        return Schedule.fixed(self.decisions).to_string()

    def _grant_locked(self) -> None:
        if self._running is not None or self._error is not None:
            return
        ready = [s.name for s in self._by_name.values()
                 if s.status == "ready"]
        if not ready:
            blocked = [s.name for s in self._by_name.values()
                       if s.status == "blocked"]
            if blocked:
                self._error = ScheduleError(
                    f"deadlock: thread(s) {blocked} blocked with no "
                    "runnable thread", self._replay_str())
                self._cv.notify_all()
            return
        if self._decision_idx >= self.max_decisions:
            self._error = ScheduleError(
                f"schedule exceeded {self.max_decisions} decisions",
                self._replay_str())
            self._cv.notify_all()
            return
        if len(ready) > 1:
            name = self.schedule.choose(sorted(ready), self._decision_idx)
            self.decisions.append(name)
            self.alternatives.append(tuple(sorted(ready)))
            self._decision_idx += 1
        else:
            name = ready[0]
        chosen = self._by_name[name]
        chosen.status = "running"
        self._running = chosen
        self._cv.notify_all()

    def _wait_for_token_locked(self, st: _TState) -> None:
        while st.status != "running":
            if self._error is not None:
                raise _Abort()
            if st.status == "done":  # pragma: no cover — defensive
                raise _Abort()
            self._cv.wait(_WAIT_SLICE_S)


__all__ = ["DeterministicScheduler", "Schedule", "ScheduleError",
           "fixed_schedule_string"]
