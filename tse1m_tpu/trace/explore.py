"""Schedule exploration over the serve/store concurrency surface.

Each *scenario* builds the production objects fresh in a temp directory
and hands the scheduler a set of named thread bodies exercising the
real critical sections — no mocks, no test-only branches:

- ``serve`` — the daemon's ingest-absorb-swap path (``_ingest_batch``
  on a ``ServeDaemon`` with the host signature backend) racing
  membership queries and an independent read-only store handle doing
  ``refresh()`` + probes.  Invariants: every query answers from ONE
  published snapshot (its labels for acknowledged rows equal the cold
  host clustering of exactly that generation's row prefix,
  elementwise), generations observed by each thread never decrease
  (snapshot monotonicity), and reader probe coverage is always a whole
  committed generation.
- ``store`` — ``SignatureStore.append`` (with the LSM delta threshold
  forced low so appends consolidate) racing a shared read-only handle's
  ``refresh()`` and ``bulk_probe`` from two more threads.  Invariants:
  a probe sees either the pre- or post-consolidation generation, never
  a torn index (coverage is exactly the committed shard set of some
  manifest generation), and gathered signatures match what was
  appended.
- ``store-evict`` — the same with a byte cap so appends evict LRU
  shards; probe coverage must equal a committed (possibly evicted)
  shard view, never a mix.

:func:`explore` drives N seeded PCT schedules plus a bounded exhaustive
enumeration of decision prefixes; every failure raises
:class:`~tse1m_tpu.trace.sched.ScheduleError` whose message carries the
exact replay string (``v1:fix:...``), and :func:`replay` re-runs one.
"""

from __future__ import annotations

import os
import shutil
import tempfile

from .hooks import Tracer, clear_tracer, install_tracer
from .lockset import LocksetChecker
from .sched import DeterministicScheduler, Schedule, ScheduleError

_POLICY = {"n_hashes": 16, "seed": 0, "quant_bits": 0}
_BATCH = 4
_N_ROWS = 12


# -- scenario: serve ----------------------------------------------------------


def _serve_scenario(tmp: str):
    import numpy as np

    from ..cluster import ClusterParams, host_cluster
    from ..cluster.store import SignatureStore, row_digests
    from ..data.synth import synth_session_sets
    from ..serve.daemon import ServeDaemon

    params = ClusterParams(n_hashes=_POLICY["n_hashes"], n_bands=4,
                           seed=_POLICY["seed"], use_pallas="never")
    items = synth_session_sets(_N_ROWS, set_size=16, seed=5,
                               dup_fraction=0.0)[0]
    digests = row_digests(items)
    expected = {0: np.empty(0, np.int32)}
    for k in range(_BATCH, _N_ROWS + 1, _BATCH):
        expected[k] = host_cluster(items[:k], n_hashes=params.n_hashes,
                                   n_bands=params.n_bands,
                                   seed=params.seed)
    daemon = ServeDaemon(os.path.join(tmp, "store"), params=params,
                         signer="host")
    reader = SignatureStore(os.path.join(tmp, "store"),
                            daemon.store.policy, read_only=True)
    query_obs: list = []
    probe_obs: list = []

    def writer() -> None:
        for lo in range(0, _N_ROWS, _BATCH):
            daemon._ingest_batch(items[lo:lo + _BATCH])
            idx = daemon._index
            k = idx.n_rows
            if not np.array_equal(idx.labels, expected[k]):
                raise AssertionError(
                    f"absorb broke label parity at generation "
                    f"{idx.generation}: {idx.labels.tolist()} != "
                    f"{expected[k].tolist()}")

    def querier() -> None:
        for _ in range(4):
            resp = daemon.query(items)
            query_obs.append((int(resp["generation"]),
                              np.asarray(resp["known"]).copy(),
                              np.asarray(resp["labels"]).copy()))

    def refresher() -> None:
        for _ in range(3):
            reader.refresh()
            hit, _, _ = reader.bulk_probe(digests)
            probe_obs.append(np.asarray(hit).copy())

    def validate() -> None:
        last_gen = -1
        for gen, known, labels in query_obs:
            if gen < last_gen:
                raise AssertionError(
                    f"query generations regressed: {gen} after {last_gen}")
            last_gen = gen
            k = gen * _BATCH
            if not (known[:k].all() and not known[k:].any()):
                raise AssertionError(
                    f"membership at generation {gen} is not the row "
                    f"prefix of that snapshot: {known.tolist()}")
            if not np.array_equal(labels[:k], expected[k]):
                raise AssertionError(
                    f"query labels at generation {gen} do not match the "
                    f"cold clustering of its {k}-row prefix: "
                    f"{labels[:k].tolist()} != {expected[k].tolist()}")
        for hit in probe_obs:
            k = int(hit.sum())
            if k % _BATCH or not hit[:k].all():
                raise AssertionError(
                    "reader probe saw a torn store view: hits "
                    f"{np.flatnonzero(hit).tolist()} are not a whole "
                    "committed generation")

    bodies = {"w": writer, "q": querier, "r": refresher}
    return bodies, validate


# -- scenario: store ----------------------------------------------------------


def _store_scenario(tmp: str, evict: bool, reader_cls=None):
    import numpy as np

    from ..cluster.store import SignatureStore

    if reader_cls is None:
        reader_cls = SignatureStore
    rng = np.random.default_rng(11)
    n_batches, rows = 5, 3
    digests = rng.integers(1, 2**63, size=(n_batches * rows, 2),
                           dtype=np.uint64)
    sigs = rng.integers(0, 2**32, size=(n_batches * rows,
                                        _POLICY["n_hashes"]),
                        dtype=np.uint64).astype(np.uint32)
    max_bytes = (2 * rows * _POLICY["n_hashes"] * 4 + 1) if evict else None
    writer_store = SignatureStore(os.path.join(tmp, "store"), _POLICY,
                                  max_bytes=max_bytes)
    reader = reader_cls(os.path.join(tmp, "store"), _POLICY,
                        read_only=True)
    probe_obs: list = []
    batch_of = np.repeat(np.arange(n_batches), rows)
    # Every manifest state the writer will commit, in order: the append
    # commit (shard added, eviction pending) and each single-victim
    # eviction step write the manifest, and all of them are views a
    # reader may legitimately adopt.  Victim order is lowest shard id
    # (probe_gen never advances here: the append dedup-probe misses).
    committed: list = [frozenset()]
    shard_sets: list = [set()]
    live: set = set()
    for b in range(n_batches):
        live = live | {b}
        shard_sets.append(set(live))
        while evict and len(live) > 2:
            live = live - {min(live)}
            shard_sets.append(set(live))
    for s in shard_sets:
        committed.append(frozenset(
            i for i in range(n_batches * rows) if int(batch_of[i]) in s))

    def writer() -> None:
        for b in range(n_batches):
            blk = slice(b * rows, (b + 1) * rows)
            writer_store.append(digests[blk], sigs[blk])

    def refresher() -> None:
        for _ in range(4):
            reader.refresh()
            live = {int(e["id"]) for e in reader.shards}
            hit, _, _ = reader.bulk_probe(digests)
            view = frozenset(int(i) for i in np.flatnonzero(hit))
            want = frozenset(i for i in range(n_batches * rows)
                             if int(batch_of[i]) in live)
            if view != want:
                raise AssertionError(
                    f"refresh adopted shards {sorted(live)} but probe "
                    f"coverage is {sorted(view)} (want {sorted(want)}) "
                    "— torn probe index")

    def prober() -> None:
        for _ in range(6):
            hit, shard, row = reader.bulk_probe(digests)
            view = frozenset(int(i) for i in np.flatnonzero(hit))
            probe_obs.append(view)
            if not evict and hit.any():
                got = reader.load_signatures(shard[hit], row[hit])
                if not np.array_equal(got, sigs[hit]):
                    raise AssertionError(
                        "probe locators gathered wrong signatures "
                        "(torn index published mid-consolidation)")

    def validate() -> None:
        valid = set(committed)
        for view in probe_obs:
            if view not in valid:
                raise AssertionError(
                    "probe saw a store view that was never committed "
                    f"(torn index): rows {sorted(view)}; committed "
                    f"views: {[sorted(v) for v in valid]}")

    bodies = {"w": writer, "rp": prober, "rr": refresher}
    return bodies, validate


SCENARIOS = {
    "serve": lambda tmp: _serve_scenario(tmp),
    "store": lambda tmp: _store_scenario(tmp, evict=False),
    "store-evict": lambda tmp: _store_scenario(tmp, evict=True),
}

# Env forced during a scenario run: a tiny LSM delta threshold makes
# appends/refreshes consolidate inside the explored window (the
# interleaving under test), and a low consolidation bound on the live
# index exercises its delta-run path too.
_SCENARIO_ENV = {"TSE1M_SIG_STORE_DELTA_SHARDS": "2",
                 "TSE1M_LIVE_DELTA_RUNS": "2"}


class RunOutcome:
    """One schedule's realized trace (for dedup + exhaustive branching)."""

    __slots__ = ("decisions", "alternatives", "schedule_str", "races")

    def __init__(self, decisions, alternatives, schedule_str, races):
        self.decisions = tuple(decisions)
        self.alternatives = tuple(alternatives)
        self.schedule_str = schedule_str
        self.races = races


def run_scenario(scenario: str, schedule: Schedule,
                 timeout_s: float = 60.0,
                 build=None) -> RunOutcome:
    """Run one scenario under one schedule; raises ScheduleError (with
    the replay string) on any invariant violation, deadlock, hang or
    detected race.  ``build`` overrides the scenario factory (the
    planted-bug tests inject broken subclasses through it)."""
    if build is None:
        if scenario not in SCENARIOS:
            raise ValueError(f"unknown scenario {scenario!r}; have "
                             f"{sorted(SCENARIOS)}")
        build = SCENARIOS[scenario]
    tmp = tempfile.mkdtemp(prefix=f"graftrace_{scenario.replace('-', '_')}_")
    saved = {k: os.environ.get(k) for k in _SCENARIO_ENV}
    os.environ.update(_SCENARIO_ENV)
    sched = DeterministicScheduler(schedule, timeout_s=timeout_s)
    lockset = LocksetChecker()
    try:
        bodies, validate = build(tmp)
        install_tracer(Tracer(lockset=lockset, scheduler=sched))
        try:
            sched.run(bodies)
        finally:
            clear_tracer()
        try:
            validate()
        except AssertionError as e:
            raise ScheduleError(str(e),
                                sched.realized().to_string()) from e
        if lockset.races:
            raise ScheduleError(
                "lockset race(s) under this schedule:\n" + "\n".join(
                    r.describe() for r in lockset.races),
                sched.realized().to_string())
        return RunOutcome(sched.decisions, sched.alternatives,
                          schedule.to_string(), len(lockset.races))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)


def explore(scenario: str, n_seeded: int = 200, exhaustive_bound: int = 4,
            base_seed: int = 0, pct_depth: int = 3,
            build=None) -> dict:
    """N seeded PCT schedules plus bounded-exhaustive prefix
    enumeration; returns summary stats, raises on the first failing
    schedule (message carries the replay string)."""
    traces: set = set()
    runs = 0
    for i in range(n_seeded):
        out = run_scenario(scenario, Schedule.pct(base_seed + i,
                                                  depth=pct_depth),
                           build=build)
        traces.add(out.decisions)
        runs += 1
    # Bounded exhaustive: branch every alternative at the first
    # ``exhaustive_bound`` decision points, depth-first over realized
    # traces (stateless model checking over the yield-point graph).
    frontier: list[tuple] = [()]
    seen_prefix: set = set()
    while frontier:
        prefix = frontier.pop()
        if prefix in seen_prefix:
            continue
        seen_prefix.add(prefix)
        out = run_scenario(scenario, Schedule.fixed(prefix), build=build)
        runs += 1
        traces.add(out.decisions)
        for i in range(len(prefix),
                       min(len(out.decisions), exhaustive_bound)):
            for alt in out.alternatives[i]:
                if alt != out.decisions[i]:
                    frontier.append(out.decisions[:i] + (alt,))
    return {"trace_schedules_explored": runs,
            "trace_distinct_traces": len(traces),
            "trace_races_found": 0}


def replay(schedule_str: str, scenario: str = "serve") -> RunOutcome:
    """Re-run one committed/reported schedule string exactly."""
    return run_scenario(scenario, Schedule.from_string(schedule_str))


__all__ = ["RunOutcome", "SCENARIOS", "explore", "replay", "run_scenario"]
