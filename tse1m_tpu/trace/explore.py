"""Schedule exploration over the serve/store concurrency surface.

Each *scenario* builds the production objects fresh in a temp directory
and hands the scheduler a set of named thread bodies exercising the
real critical sections — no mocks, no test-only branches:

- ``serve`` — the daemon's ingest-absorb-swap path (``_ingest_batch``
  on a ``ServeDaemon`` with the host signature backend) racing
  membership queries and an independent read-only store handle doing
  ``refresh()`` + probes.  Invariants: every query answers from ONE
  published snapshot (its labels for acknowledged rows equal the cold
  host clustering of exactly that generation's row prefix,
  elementwise), generations observed by each thread never decrease
  (snapshot monotonicity), and reader probe coverage is always a whole
  committed generation.
- ``store`` — ``SignatureStore.append`` (with the LSM delta threshold
  forced low so appends consolidate) racing a shared read-only handle's
  ``refresh()`` and ``bulk_probe`` from two more threads.  Invariants:
  a probe sees either the pre- or post-consolidation generation, never
  a torn index (coverage is exactly the committed shard set of some
  manifest generation), and gathered signatures match what was
  appended.
- ``store-evict`` — the same with a byte cap so appends evict LRU
  shards; probe coverage must equal a committed (possibly evicted)
  shard view, never a mix.
- ``router`` — the sharded serve plane's fan-out router
  (`serve.router.ShardRouter`) over two shard daemons, with a writer
  ingesting through the router, a second writer replaying one batch
  under the SAME idempotent request id, and a querier broadcasting.
  Invariants: the replayed slice is never double-absorbed (total index
  rows across shards equal the unique submission count), the two acks
  for one request id carry identical labels, exact duplicates always
  share their original's label, per-shard generations never regress
  and a routed (>= 0) label, once observed, never changes.
- ``replica`` — shard-streaming replication (`serve.replicate`): an
  evicting source writer races a replication streamer and a replica's
  ``refresh()``/rebuild.  Invariants: replica probe coverage is always
  exactly a committed source manifest view (never a torn mix), and the
  adopted generation never decreases.

:func:`explore` drives N seeded PCT schedules plus a bounded exhaustive
enumeration of decision prefixes; every failure raises
:class:`~tse1m_tpu.trace.sched.ScheduleError` whose message carries the
exact replay string (``v1:fix:...``), and :func:`replay` re-runs one.
"""

from __future__ import annotations

import os
import shutil
import tempfile

from .hooks import Tracer, clear_tracer, install_tracer
from .lockset import LocksetChecker
from .sched import DeterministicScheduler, Schedule, ScheduleError

_POLICY = {"n_hashes": 16, "seed": 0, "quant_bits": 0}
_BATCH = 4
_N_ROWS = 12


# -- scenario: serve ----------------------------------------------------------


def _serve_scenario(tmp: str):
    import numpy as np

    from ..cluster import ClusterParams, host_cluster
    from ..cluster.store import SignatureStore, row_digests
    from ..data.synth import synth_session_sets
    from ..serve.daemon import ServeDaemon

    params = ClusterParams(n_hashes=_POLICY["n_hashes"], n_bands=4,
                           seed=_POLICY["seed"], use_pallas="never")
    items = synth_session_sets(_N_ROWS, set_size=16, seed=5,
                               dup_fraction=0.0)[0]
    digests = row_digests(items)
    expected = {0: np.empty(0, np.int32)}
    for k in range(_BATCH, _N_ROWS + 1, _BATCH):
        expected[k] = host_cluster(items[:k], n_hashes=params.n_hashes,
                                   n_bands=params.n_bands,
                                   seed=params.seed)
    daemon = ServeDaemon(os.path.join(tmp, "store"), params=params,
                         signer="host")
    reader = SignatureStore(os.path.join(tmp, "store"),
                            daemon.store.policy, read_only=True)
    query_obs: list = []
    probe_obs: list = []

    def writer() -> None:
        for lo in range(0, _N_ROWS, _BATCH):
            daemon._ingest_batch(items[lo:lo + _BATCH])
            idx = daemon._index
            k = idx.n_rows
            if not np.array_equal(idx.labels, expected[k]):
                raise AssertionError(
                    f"absorb broke label parity at generation "
                    f"{idx.generation}: {idx.labels.tolist()} != "
                    f"{expected[k].tolist()}")

    def querier() -> None:
        for _ in range(4):
            resp = daemon.query(items)
            query_obs.append((int(resp["generation"]),
                              np.asarray(resp["known"]).copy(),
                              np.asarray(resp["labels"]).copy()))

    def refresher() -> None:
        for _ in range(3):
            reader.refresh()
            hit, _, _ = reader.bulk_probe(digests)
            probe_obs.append(np.asarray(hit).copy())

    def validate() -> None:
        last_gen = -1
        for gen, known, labels in query_obs:
            if gen < last_gen:
                raise AssertionError(
                    f"query generations regressed: {gen} after {last_gen}")
            last_gen = gen
            k = gen * _BATCH
            if not (known[:k].all() and not known[k:].any()):
                raise AssertionError(
                    f"membership at generation {gen} is not the row "
                    f"prefix of that snapshot: {known.tolist()}")
            if not np.array_equal(labels[:k], expected[k]):
                raise AssertionError(
                    f"query labels at generation {gen} do not match the "
                    f"cold clustering of its {k}-row prefix: "
                    f"{labels[:k].tolist()} != {expected[k].tolist()}")
        for hit in probe_obs:
            k = int(hit.sum())
            if k % _BATCH or not hit[:k].all():
                raise AssertionError(
                    "reader probe saw a torn store view: hits "
                    f"{np.flatnonzero(hit).tolist()} are not a whole "
                    "committed generation")

    bodies = {"w": writer, "q": querier, "r": refresher}
    return bodies, validate


# -- scenario: store ----------------------------------------------------------


def _store_scenario(tmp: str, evict: bool, reader_cls=None):
    import numpy as np

    from ..cluster.store import SignatureStore

    if reader_cls is None:
        reader_cls = SignatureStore
    rng = np.random.default_rng(11)
    n_batches, rows = 5, 3
    digests = rng.integers(1, 2**63, size=(n_batches * rows, 2),
                           dtype=np.uint64)
    sigs = rng.integers(0, 2**32, size=(n_batches * rows,
                                        _POLICY["n_hashes"]),
                        dtype=np.uint64).astype(np.uint32)
    max_bytes = (2 * rows * _POLICY["n_hashes"] * 4 + 1) if evict else None
    writer_store = SignatureStore(os.path.join(tmp, "store"), _POLICY,
                                  max_bytes=max_bytes)
    reader = reader_cls(os.path.join(tmp, "store"), _POLICY,
                        read_only=True)
    probe_obs: list = []
    batch_of = np.repeat(np.arange(n_batches), rows)
    # Every manifest state the writer will commit, in order: the append
    # commit (shard added, eviction pending) and each single-victim
    # eviction step write the manifest, and all of them are views a
    # reader may legitimately adopt.  Victim order is lowest shard id
    # (probe_gen never advances here: the append dedup-probe misses).
    committed: list = [frozenset()]
    shard_sets: list = [set()]
    live: set = set()
    for b in range(n_batches):
        live = live | {b}
        shard_sets.append(set(live))
        while evict and len(live) > 2:
            live = live - {min(live)}
            shard_sets.append(set(live))
    for s in shard_sets:
        committed.append(frozenset(
            i for i in range(n_batches * rows) if int(batch_of[i]) in s))

    def writer() -> None:
        for b in range(n_batches):
            blk = slice(b * rows, (b + 1) * rows)
            writer_store.append(digests[blk], sigs[blk])

    def refresher() -> None:
        for _ in range(4):
            reader.refresh()
            live = {int(e["id"]) for e in reader.shards}
            hit, _, _ = reader.bulk_probe(digests)
            view = frozenset(int(i) for i in np.flatnonzero(hit))
            want = frozenset(i for i in range(n_batches * rows)
                             if int(batch_of[i]) in live)
            if view != want:
                raise AssertionError(
                    f"refresh adopted shards {sorted(live)} but probe "
                    f"coverage is {sorted(view)} (want {sorted(want)}) "
                    "— torn probe index")

    def prober() -> None:
        for _ in range(6):
            hit, shard, row = reader.bulk_probe(digests)
            view = frozenset(int(i) for i in np.flatnonzero(hit))
            probe_obs.append(view)
            if not evict and hit.any():
                got = reader.load_signatures(shard[hit], row[hit])
                if not np.array_equal(got, sigs[hit]):
                    raise AssertionError(
                        "probe locators gathered wrong signatures "
                        "(torn index published mid-consolidation)")

    def validate() -> None:
        valid = set(committed)
        for view in probe_obs:
            if view not in valid:
                raise AssertionError(
                    "probe saw a store view that was never committed "
                    f"(torn index): rows {sorted(view)}; committed "
                    f"views: {[sorted(v) for v in valid]}")

    bodies = {"w": writer, "rp": prober, "rr": refresher}
    return bodies, validate


# -- scenario: router ---------------------------------------------------------


def _router_scenario(tmp: str):
    import numpy as np

    from ..cluster import ClusterParams
    from ..cluster.store import digest_range_ids, row_digests
    from ..serve.daemon import ServeDaemon
    from ..serve.router import ShardRouter
    from ..serve.server import decode_vectors
    from . import sync as tsync

    params = ClusterParams(n_hashes=_POLICY["n_hashes"], n_bands=4,
                           seed=_POLICY["seed"], use_pallas="never")
    # Craft a corpus that populates BOTH digest ranges and carries exact
    # duplicates (only exact dups co-shard): rejection-sample unique
    # rows until each range owns five, then append dups of rows 0/1.
    rng = np.random.default_rng(7)
    picked: list = []
    want = {0: 5, 1: 5}
    while want[0] or want[1]:
        row = rng.integers(0, 2**32, size=(1, 16),
                           dtype=np.int64).astype(np.uint32)
        rid = int(digest_range_ids(row_digests(row), 2)[0])
        if want[rid]:
            want[rid] -= 1
            picked.append(row[0])
    items = np.stack(picked + [picked[0], picked[1]])  # 12 rows, 10 unique

    daemons = {sid: ServeDaemon(os.path.join(tmp, f"range_{sid:04d}"),
                                params=params, signer="host")
               for sid in (0, 1)}

    def direct(daemon, ing_lock):
        # In production one ingest-loop thread serializes a shard's
        # absorbs behind the TCP queue; the traced lock models exactly
        # that, while queries stay lock-free (snapshot reads).
        def call(msg: dict, timeout_s=None) -> dict:
            if msg.get("op") == "ingest":
                rid = msg.get("request_id")
                with ing_lock:
                    return daemon._ingest_batch(
                        decode_vectors(msg),
                        request_id=str(rid) if rid else None)
            res = daemon.query(decode_vectors(msg))
            return {"ok": True,
                    "labels": res["labels"].astype(int).tolist(),
                    "known": res["known"].astype(bool).tolist(),
                    "generation": int(res["generation"])}
        return call

    router = ShardRouter({
        sid: direct(d, tsync.Lock(f"shard{sid}.ingest"))
        for sid, d in daemons.items()})
    acks: list = []
    fix0_acks: list = []
    query_obs: list = []

    def writer() -> None:
        r = router.ingest(items[0:4], request_id="fix0")
        fix0_acks.append(r)
        acks.append(r)
        acks.append(router.ingest(items[4:8]))
        acks.append(router.ingest(items[8:12]))

    def replayer() -> None:
        # Same content, SAME request id: whichever of the two "fix0"
        # submissions runs second must replay the per-shard journal
        # acks, not absorb a second copy.
        r = router.ingest(items[0:4], request_id="fix0")
        fix0_acks.append(r)

    def querier() -> None:
        for _ in range(3):
            resp = router.query(items)
            query_obs.append((np.asarray(resp["labels"]).copy(),
                              np.asarray(resp["known"]).copy(),
                              dict(resp["shard_generations"])))

    def validate() -> None:
        for a in acks + fix0_acks:
            if int(a["acked"]) != 4 or len(a["labels"]) != 4:
                raise AssertionError(f"short ack: {a}")
        l0, l1 = fix0_acks[0]["labels"], fix0_acks[1]["labels"]
        if l0 != l1:
            raise AssertionError(
                "the two acks for request id fix0 disagree: "
                f"{l0} != {l1} (replay answered from a different view)")
        total = sum(d._index.n_rows for d in daemons.values())
        if total != 12:
            raise AssertionError(
                f"double-absorb: shards hold {total} index rows for 12 "
                "submitted rows (the replayed slice re-absorbed)")
        prev_known = prev_labels = None
        last_gens: dict = {}
        for labels, known, gens in query_obs:
            for sid, g in gens.items():
                if g < last_gens.get(sid, 0):
                    raise AssertionError(
                        f"shard {sid} generation regressed: {g} after "
                        f"{last_gens.get(sid)}")
                last_gens[sid] = g
            for j, orig in ((10, 0), (11, 1)):
                if known[j] != known[orig] or labels[j] != labels[orig]:
                    raise AssertionError(
                        f"exact duplicate {j} of row {orig} diverged: "
                        f"known {known[j]}/{known[orig]}, labels "
                        f"{labels[j]}/{labels[orig]}")
            if prev_known is not None:
                for i in range(12):
                    if prev_known[i] and not known[i]:
                        raise AssertionError(
                            f"membership regressed for row {i}")
                    if prev_labels[i] >= 0 and labels[i] != prev_labels[i]:
                        raise AssertionError(
                            f"routed label for row {i} changed: "
                            f"{prev_labels[i]} -> {labels[i]}")
            prev_known, prev_labels = known, labels
        final = router.query(items)
        fl = np.asarray(final["labels"])
        if not np.asarray(final["known"]).all():
            raise AssertionError("post-run rows missing from membership")
        if (fl < 0).any() or len(set(fl[:10].tolist())) != 10:
            raise AssertionError(
                f"post-run global labels malformed: {fl.tolist()}")
        if fl[10] != fl[0] or fl[11] != fl[1]:
            raise AssertionError(
                f"post-run duplicate labels diverged: {fl.tolist()}")

    bodies = {"w": writer, "rp": replayer, "q": querier}
    return bodies, validate


# -- scenario: replica --------------------------------------------------------


def _replica_scenario(tmp: str):
    import numpy as np

    from ..cluster import ClusterParams
    from ..cluster.store import SignatureStore
    from ..serve.replicate import ServeReplica, stream_shards

    params = ClusterParams(n_hashes=_POLICY["n_hashes"], n_bands=4,
                           seed=_POLICY["seed"], use_pallas="never")
    rng = np.random.default_rng(11)
    n_batches, rows = 4, 3
    digests = rng.integers(1, 2**63, size=(n_batches * rows, 2),
                           dtype=np.uint64)
    sigs = rng.integers(0, 2**32, size=(n_batches * rows,
                                        _POLICY["n_hashes"]),
                        dtype=np.uint64).astype(np.uint32)
    max_bytes = 2 * rows * _POLICY["n_hashes"] * 4 + 1  # keep 2 live shards
    src = os.path.join(tmp, "src")
    dst = os.path.join(tmp, "replica")
    writer_store = SignatureStore(src, _POLICY, max_bytes=max_bytes)
    # Bootstrap: one committed batch + one pull BEFORE the explored
    # window, so the replica adopts the writer's policy from a streamed
    # manifest (the production ctor path).
    writer_store.append(digests[:rows], sigs[:rows])
    stream_shards(src, dst)
    replica = ServeReplica(dst, params=params)
    batch_of = np.repeat(np.arange(n_batches), rows)
    # Every source manifest state, in commit order (append + each
    # single-victim eviction step) — any of them is a view the streamer
    # may copy and the replica may adopt.
    committed: list = []
    shard_sets: list = []
    live: set = set()
    for b in range(n_batches):
        live = live | {b}
        shard_sets.append(set(live))
        while len(live) > 2:
            live = live - {min(live)}
            shard_sets.append(set(live))
    for s in shard_sets:
        committed.append(frozenset(
            i for i in range(n_batches * rows) if int(batch_of[i]) in s))
    probe_obs: list = []
    gen_obs: list = []

    def writer() -> None:
        for b in range(1, n_batches):
            blk = slice(b * rows, (b + 1) * rows)
            writer_store.append(digests[blk], sigs[blk])

    def streamer() -> None:
        for _ in range(3):
            try:
                stream_shards(src, dst)
            except OSError:
                # All bounded retries raced the writer's eviction: the
                # pull gives up for this interval (the production
                # puller's behaviour); the replica stays on its last
                # adopted generation, which the invariant tolerates.
                pass

    def refresher() -> None:
        for _ in range(4):
            replica.refresh()
            gen_obs.append(int(replica._generation_adopted))
            hit, _, _ = replica.store.bulk_probe(digests)
            probe_obs.append(frozenset(
                int(i) for i in np.flatnonzero(hit)))

    def validate() -> None:
        valid = set(committed)
        for view in probe_obs:
            if view not in valid:
                raise AssertionError(
                    "replica adopted a store view the writer never "
                    f"committed (torn stream): rows {sorted(view)}; "
                    f"committed views: {[sorted(v) for v in valid]}")
        last = -1
        for g in gen_obs:
            if g < last:
                raise AssertionError(
                    f"replica adopted generation regressed: {g} after "
                    f"{last}")
            last = g

    bodies = {"w": writer, "s": streamer, "rr": refresher}
    return bodies, validate


SCENARIOS = {
    "serve": lambda tmp: _serve_scenario(tmp),
    "store": lambda tmp: _store_scenario(tmp, evict=False),
    "store-evict": lambda tmp: _store_scenario(tmp, evict=True),
    "router": lambda tmp: _router_scenario(tmp),
    "replica": lambda tmp: _replica_scenario(tmp),
}

# Env forced during a scenario run: a tiny LSM delta threshold makes
# appends/refreshes consolidate inside the explored window (the
# interleaving under test), and a low consolidation bound on the live
# index exercises its delta-run path too.
_SCENARIO_ENV = {"TSE1M_SIG_STORE_DELTA_SHARDS": "2",
                 "TSE1M_LIVE_DELTA_RUNS": "2"}


class RunOutcome:
    """One schedule's realized trace (for dedup + exhaustive branching)."""

    __slots__ = ("decisions", "alternatives", "schedule_str", "races")

    def __init__(self, decisions, alternatives, schedule_str, races):
        self.decisions = tuple(decisions)
        self.alternatives = tuple(alternatives)
        self.schedule_str = schedule_str
        self.races = races


def run_scenario(scenario: str, schedule: Schedule,
                 timeout_s: float = 60.0,
                 build=None) -> RunOutcome:
    """Run one scenario under one schedule; raises ScheduleError (with
    the replay string) on any invariant violation, deadlock, hang or
    detected race.  ``build`` overrides the scenario factory (the
    planted-bug tests inject broken subclasses through it)."""
    if build is None:
        if scenario not in SCENARIOS:
            raise ValueError(f"unknown scenario {scenario!r}; have "
                             f"{sorted(SCENARIOS)}")
        build = SCENARIOS[scenario]
    tmp = tempfile.mkdtemp(prefix=f"graftrace_{scenario.replace('-', '_')}_")
    saved = {k: os.environ.get(k) for k in _SCENARIO_ENV}
    os.environ.update(_SCENARIO_ENV)
    sched = DeterministicScheduler(schedule, timeout_s=timeout_s)
    lockset = LocksetChecker()
    try:
        bodies, validate = build(tmp)
        install_tracer(Tracer(lockset=lockset, scheduler=sched))
        try:
            sched.run(bodies)
        finally:
            clear_tracer()
        try:
            validate()
        except AssertionError as e:
            raise ScheduleError(str(e),
                                sched.realized().to_string()) from e
        if lockset.races:
            raise ScheduleError(
                "lockset race(s) under this schedule:\n" + "\n".join(
                    r.describe() for r in lockset.races),
                sched.realized().to_string())
        return RunOutcome(sched.decisions, sched.alternatives,
                          schedule.to_string(), len(lockset.races))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)


def explore(scenario: str, n_seeded: int = 200, exhaustive_bound: int = 4,
            base_seed: int = 0, pct_depth: int = 3,
            build=None) -> dict:
    """N seeded PCT schedules plus bounded-exhaustive prefix
    enumeration; returns summary stats, raises on the first failing
    schedule (message carries the replay string)."""
    traces: set = set()
    runs = 0
    for i in range(n_seeded):
        out = run_scenario(scenario, Schedule.pct(base_seed + i,
                                                  depth=pct_depth),
                           build=build)
        traces.add(out.decisions)
        runs += 1
    # Bounded exhaustive: branch every alternative at the first
    # ``exhaustive_bound`` decision points, depth-first over realized
    # traces (stateless model checking over the yield-point graph).
    frontier: list[tuple] = [()]
    seen_prefix: set = set()
    while frontier:
        prefix = frontier.pop()
        if prefix in seen_prefix:
            continue
        seen_prefix.add(prefix)
        out = run_scenario(scenario, Schedule.fixed(prefix), build=build)
        runs += 1
        traces.add(out.decisions)
        for i in range(len(prefix),
                       min(len(out.decisions), exhaustive_bound)):
            for alt in out.alternatives[i]:
                if alt != out.decisions[i]:
                    frontier.append(out.decisions[:i] + (alt,))
    return {"trace_schedules_explored": runs,
            "trace_distinct_traces": len(traces),
            "trace_races_found": 0}


def replay(schedule_str: str, scenario: str = "serve") -> RunOutcome:
    """Re-run one committed/reported schedule string exactly."""
    return run_scenario(scenario, Schedule.from_string(schedule_str))


__all__ = ["RunOutcome", "SCENARIOS", "explore", "replay", "run_scenario"]
