"""graftrace's production seats: one global tracer slot, zero test-only
branches.

Mirrors the fault plane (`resilience.faults`): production concurrency
seats call :func:`trace_point` / :func:`shared_access`, and with no
tracer installed — the production default — each call is a module-global
read and a ``None`` check.  Installing a :class:`Tracer` (what
``trace.traced()`` and the schedule explorer do) turns the *production*
code paths into instrumented ones:

- ``trace_point("dotted.site")`` — a scheduling yield point at a named
  concurrency seat (queue ops, snapshot swaps, store append / refresh /
  consolidation).  Under a deterministic scheduler the calling thread
  may be descheduled here; without one the seat is inert.
- ``shared_access(obj, field, write=...)`` — an instrumented
  shared-state access for the Eraser-style lockset detector
  (`trace.lockset`), keyed per instance.  ``atomic=True`` marks the
  publish-then-never-mutate discipline (one-reference snapshot swaps):
  those accesses still serve as scheduling points but are exempt from
  lockset checking — their correctness is proven by the schedule
  explorer's invariants and the static ``snapshot-publish`` lint pass,
  not by lock discipline.
- `trace.sync.Lock` / `RLock` (the traced lock primitives the audited
  classes create) report acquire/release through the same slot, so the
  detector knows the held-lock set at every instrumented access and the
  scheduler can interleave threads *around* lock boundaries without
  ever blocking the token on a real mutex.

Instrumented sites (grep for ``trace_point(`` / ``shared_access(`` to
audit): serve/daemon.py (queue put/get, index swap, state commit),
cluster/store.py (append, refresh, probe-index delta push /
consolidation / publication, evict, compact), observability
(StageRecorder, LatencyRecorder, degradation/stage handoff slots),
serve/slo.py (admission + SLO counters).
"""

from __future__ import annotations

import sys
import threading


class Tracer:
    """The installed instrumentation: an optional lockset checker plus
    an optional deterministic scheduler, and the per-thread held-lock
    bookkeeping both share."""

    def __init__(self, lockset=None, scheduler=None) -> None:
        self.lockset = lockset
        self.scheduler = scheduler
        self._held = threading.local()

    # -- held-lock bookkeeping ----------------------------------------------

    def _held_list(self) -> list:
        lst = getattr(self._held, "locks", None)
        if lst is None:
            lst = []
            self._held.locks = lst
        return lst

    def held_keys(self) -> frozenset:
        return frozenset(k for k, _ in self._held_list())

    def held_names(self) -> tuple:
        return tuple(n for _, n in self._held_list())

    # -- seat callbacks ------------------------------------------------------

    def on_point(self, site: str) -> None:
        if self.scheduler is not None:
            self.scheduler.yield_point(site)

    def on_shared_access(self, obj, field: str, write: bool,
                         atomic: bool) -> None:
        name = f"{type(obj).__name__}.{field}"
        if self.scheduler is not None:
            self.scheduler.yield_point(
                f"{'write' if write else 'read'}:{name}")
        if self.lockset is not None and not atomic:
            self.lockset.on_access(
                key=(id(obj), field), name=name, write=write,
                held=self.held_keys(), held_names=self.held_names(),
                site=_caller_site())

    # -- traced-lock callbacks (trace.sync) ----------------------------------

    def lock_acquire(self, lock, blocking: bool = True,
                     timeout: float = -1) -> bool:
        sched = self.scheduler
        if sched is not None and sched.owns_current_thread():
            sched.acquire(lock)
        else:
            if not lock._real.acquire(blocking, timeout):
                return False
        self._held_list().append((id(lock), lock.name))
        return True

    def lock_release(self, lock) -> None:
        lst = self._held_list()
        for i in range(len(lst) - 1, -1, -1):
            if lst[i][0] == id(lock):
                del lst[i]
                break
        lock._real.release()
        if self.scheduler is not None:
            self.scheduler.released(lock)


def _caller_site(skip_prefixes: tuple = ("tse1m_tpu/trace/",)) -> str:
    """'path:line in func' of the nearest frame outside the trace
    plane — the access seat the report should point at."""
    f = sys._getframe(2)
    for _ in range(8):
        if f is None:
            break
        fname = f.f_code.co_filename.replace("\\", "/")
        if not any(p in fname for p in skip_prefixes):
            short = "/".join(fname.rsplit("/", 3)[-3:])
            return f"{short}:{f.f_lineno} in {f.f_code.co_name}"
        f = f.f_back
    return "<unknown>"


# -- process-global tracer ----------------------------------------------------

_tracer: Tracer | None = None


def install_tracer(tracer: Tracer) -> None:
    global _tracer
    if _tracer is not None:
        raise RuntimeError("a graftrace tracer is already installed "
                           "(traced()/the explorer do not nest)")
    _tracer = tracer


def clear_tracer() -> None:
    global _tracer
    _tracer = None


def active_tracer() -> Tracer | None:
    return _tracer


def trace_point(site: str) -> None:
    """The scheduling seat production concurrency code calls.  No
    tracer: a global read and a None check."""
    t = _tracer
    if t is not None:
        t.on_point(site)


def shared_access(obj, field: str, write: bool = False,
                  atomic: bool = False) -> None:
    """An instrumented shared-state access (see module docstring)."""
    t = _tracer
    if t is not None:
        t.on_shared_access(obj, field, write, atomic)


__all__ = ["Tracer", "active_tracer", "clear_tracer", "install_tracer",
           "shared_access", "trace_point"]
