"""graftrace — the concurrency-correctness plane.

Three layers over the serve/store planes' thread concurrency, the
analogue of what graftlint does for fault transparency:

1. **Deterministic schedule exploration** (`sched`, `explore`):
   production concurrency seats (`hooks.trace_point`,
   `hooks.shared_access`, `sync.Lock`) become yield points under an
   installed tracer; the explorer serializes the daemon's
   writer/query/refresh critical sections onto one scheduler token and
   drives seeded PCT schedules plus bounded-exhaustive interleavings,
   asserting label parity and snapshot monotonicity on every schedule.
   Failures print a replayable ``v1:fix:...`` schedule string (the
   ``TSE1M_FAULT_PLAN`` idiom for thread interleavings).
2. **Eraser-style lockset race detection** (`lockset`): `traced()`
   wraps any test/bench block the way ``lint.runtime.sanitized()``
   wraps the transfer guard — every instrumented shared-state access
   (StageRecorder, LatencyRecorder, SLO/admission counters, ...) is
   checked against the held-lock set; a shared-modified location whose
   candidate lockset goes empty raises :class:`~.lockset.RaceError`
   with both access sites.
3. **Static publication discipline** (graftlint's ``snapshot-publish``
   and ``atomic-swap`` interprocedural passes, lint/interproc.py):
   classes marked immutable-after-publish (frozen dataclasses, or
   ``__immutable_after_publish__ = True``) must never be mutated after
   construction, and declared ``__publish_slots__`` references may only
   be rebound whole — never ``.append``-ed, item-assigned or
   aug-assigned.  The runtime layers validate the schedules; the static
   pass proves the swap discipline those schedules rely on.
"""

from __future__ import annotations

import contextlib

from .hooks import (Tracer, active_tracer, clear_tracer, install_tracer,
                    shared_access, trace_point)
from .lockset import LocksetChecker, Race, RaceError
from .sched import DeterministicScheduler, Schedule, ScheduleError


@contextlib.contextmanager
def traced(raise_on_race: bool = True):
    """Run the block under the lockset race detector (the ``traced()``
    tier-1 wiring): production code runs unmodified, every instrumented
    shared-state access is checked against the held-lock set, and on
    exit any detected race raises :class:`RaceError` (or is left on
    ``tracer.lockset.races`` when ``raise_on_race=False``)."""
    tracer = Tracer(lockset=LocksetChecker())
    install_tracer(tracer)
    try:
        yield tracer
    finally:
        clear_tracer()
    if raise_on_race and tracer.lockset.races:
        raise RaceError(tracer.lockset.races)


__all__ = ["DeterministicScheduler", "LocksetChecker", "Race",
           "RaceError", "Schedule", "ScheduleError", "Tracer",
           "active_tracer", "clear_tracer", "install_tracer",
           "shared_access", "trace_point", "traced"]
