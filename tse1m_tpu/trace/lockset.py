"""Eraser-style lockset race detection over the instrumented seats.

The classic algorithm (Savage et al., "Eraser: a dynamic data race
detector for multithreaded programs"): every shared location starts
*virgin*; the first accessing thread owns it *exclusive* (single-thread
init is never a race); once a second thread touches it the location
turns *shared* (reads) or *shared-modified* (any write), and from then
on its **candidate lockset** — the intersection of the lock sets held
at every access — must stay non-empty.  A shared-modified location
whose candidate set goes empty has no lock that consistently guards it:
a real data race, reported with BOTH access sites (the one that emptied
the set and the previous access), thread names, and the locks each side
held.

Locations are the `hooks.shared_access` seats (keyed per instance, so
two StageRecorders never alias), and the held sets come from the traced
`trace.sync` locks.  Publication-discipline state (one-reference
snapshot swaps: the daemon's live index, the store's probe index) is
instrumented ``atomic=True`` and exempt here — lock-free by design,
verified by the schedule explorer's invariants and the static
``snapshot-publish`` pass instead.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


@dataclass
class Access:
    """One instrumented access, as the report shows it."""

    site: str
    thread: str
    write: bool
    held: tuple

    def __str__(self) -> str:
        kind = "WRITE" if self.write else "READ"
        locks = ", ".join(self.held) if self.held else "NO locks"
        return f"{kind} at {self.site} [thread {self.thread}, " \
               f"holding {locks}]"


@dataclass
class Race:
    """A shared-modified location whose candidate lockset went empty."""

    name: str
    current: Access
    previous: Access | None

    def describe(self) -> str:
        lines = [f"race on {self.name}: no lock consistently guards it",
                 f"  - {self.current}"]
        if self.previous is not None:
            lines.append(f"  - {self.previous}")
        return "\n".join(lines)


class RaceError(AssertionError):
    """Raised by ``traced()`` on exit when the lockset detector found
    races (carries them for programmatic inspection)."""

    def __init__(self, races: list) -> None:
        super().__init__(
            f"{len(races)} data race(s) detected:\n"
            + "\n".join(r.describe() for r in races))
        self.races = list(races)


@dataclass
class _Cell:
    state: str                       # exclusive | shared | shared_mod
    owner: int
    lockset: frozenset | None = None  # None = not yet shared
    last: Access | None = None
    last_write: Access | None = None
    reported: bool = field(default=False)


class LocksetChecker:
    """Process-wide Eraser state for one ``traced()`` window.

    Internals use raw ``threading`` locks — instrumenting the
    instrumentation would recurse through the tracer."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cells: dict[tuple, _Cell] = {}
        self.races: list[Race] = []

    def on_access(self, key: tuple, name: str, write: bool,
                  held: frozenset, held_names: tuple, site: str) -> None:
        # Thread identity includes the (unique-per-process) name: raw
        # idents are reused by the OS after a join, which would alias a
        # dead writer with a fresh one and mask the shared transition.
        me = (threading.get_ident(), threading.current_thread().name)
        acc = Access(site=site, thread=threading.current_thread().name,
                     write=write, held=held_names)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                self._cells[key] = _Cell(
                    state="exclusive", owner=me, last=acc,
                    last_write=acc if write else None)
                return
            if cell.state == "exclusive" and cell.owner == me:
                cell.last = acc
                if write:
                    cell.last_write = acc
                return
            # Second thread: enter the shared states and start (or
            # continue) intersecting candidate locksets.
            cell.lockset = (held if cell.lockset is None
                            else cell.lockset & held)
            if write or cell.state == "shared_mod":
                cell.state = "shared_mod"
            else:
                cell.state = "shared"
            if (cell.state == "shared_mod" and not cell.lockset
                    and not cell.reported):
                cell.reported = True
                prev = cell.last_write if (not write and cell.last_write
                                           ) else cell.last
                self.races.append(Race(name=name, current=acc,
                                       previous=prev))
            cell.last = acc
            if write:
                cell.last_write = acc

    def summary(self) -> dict:
        with self._lock:
            return {"trace_cells": len(self._cells),
                    "trace_races_found": len(self.races)}


__all__ = ["Access", "LocksetChecker", "Race", "RaceError"]
