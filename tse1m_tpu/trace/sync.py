"""Traced lock primitives for the audited shared-state classes.

``Lock`` / ``RLock`` wrap the real ``threading`` primitives behind the
graftrace seat check: with no tracer installed (production default) an
acquire is one global read, a ``None`` check and the real C acquire —
cheap enough for the latency-histogram hot path.  With a tracer
installed, every acquire/release updates the per-thread held-lock set
(the Eraser lockset detector's input) and, under a deterministic
scheduler, becomes a yield point that never blocks the scheduler token
on a real mutex (the scheduler try-acquires and deschedules the thread
instead, so a descheduled lock holder cannot deadlock the exploration).

Classes whose state the lockset detector audits create their locks from
this module (``self._lock = tsync.Lock()``); the ``Lock``/``RLock``
constructor leaf is what graftlint's ``unlocked-shared-state`` and
``lock-order`` passes already key on, so the lint planes see these
exactly like raw ``threading`` locks.  The trace plane's own internals
use raw ``threading`` primitives — instrumenting the instrumentation
would recurse.
"""

from __future__ import annotations

import threading

from . import hooks

# Lock-wait attribution seat (observability/profiling.py): when a
# recorder is installed, every untraced acquire routes through it so the
# profiler can histogram time-to-acquire per lock site — the direct
# measurement of a lock convoy (e.g. queries stuck behind an ingest
# absorb).  ``None`` (the default) keeps the production fast path at one
# extra global read; the recorder itself must never touch a traced lock
# without its own reentrancy guard, or recording a wait would recurse.
_lock_wait_recorder = None


def set_lock_wait_recorder(recorder) -> None:
    """Install (or clear, with ``None``) the lock-wait recorder:
    ``recorder(lock, acquire, blocking, timeout) -> bool`` wraps the raw
    acquire and owns the timing."""
    global _lock_wait_recorder
    _lock_wait_recorder = recorder


class Lock:
    """Traced non-reentrant mutex (context-manager capable)."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str | None = None) -> None:
        self._real = self._factory()
        self.name = name or "anon"

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t = hooks.active_tracer()
        if t is None:
            rec = _lock_wait_recorder
            if rec is None:
                return self._real.acquire(blocking, timeout)
            return rec(self, self._real.acquire, blocking, timeout)
        return t.lock_acquire(self, blocking, timeout)

    def release(self) -> None:
        t = hooks.active_tracer()
        if t is None:
            self._real.release()
            return
        t.lock_release(self)

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self) -> "Lock":
        self.acquire()
        return self

    def __exit__(self, et, ev, tb) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<trace.sync.{type(self).__name__} {self.name}>"


class RLock(Lock):
    """Traced reentrant mutex.

    The real RLock handles reentrancy; the held-set sees one entry per
    nesting level, which keeps release bookkeeping symmetric."""

    _factory = staticmethod(threading.RLock)


__all__ = ["Lock", "RLock", "set_lock_wait_recorder"]
