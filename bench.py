"""North-star benchmark: cluster ~1M session coverage vectors on TPU.

Target (BASELINE.json / BASELINE.md): < 60 s wall on a TPU slice at
ARI >= 0.98 vs the host baseline.  Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
vs_baseline = 60 / wall_s, i.e. >1.0 beats the published target.

`value` is the MEDIAN of --iters (>=3) timed steady-state runs; `best_s`
and `runs_s` are also recorded so round-over-round artifacts are comparable
(a single-iteration bench produced 12.5 s vs 37.5 s round-to-round noise on
the same chip).  A second stage times the columnar extraction layer — the
host stage that feeds the device kernels — over a synthetic study at the
reference's ~1.19M-build scale (rq1_detection_rate.py:362), as
`extract_*` keys.

Env overrides (also flags): BENCH_N sessions, BENCH_ITERS timed iters,
BENCH_EXTRACT_BUILDS extraction scale (0 disables the extraction stage).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time


def bench_extraction(target_builds: int, seed: int = 0) -> dict:
    """Synth study at ~target_builds fuzzing builds -> sqlite -> timed
    StudyArrays.from_db (the bulk columnar decode; SURVEY §7.2 step 2)."""
    from tse1m_tpu.config import Config
    from tse1m_tpu.data.columnar import StudyArrays
    from tse1m_tpu.data.synth import SynthSpec, generate_study
    from tse1m_tpu.db.connection import DB

    # builds ~= n_projects * days * fuzz_rate
    days = 1600
    rate = 1.4
    n_projects = max(8, round(target_builds / (days * rate)))
    # ineligible_fraction=0: every project passes the 365-day eligibility
    # gate, so the extracted build count actually hits target_builds.
    spec = SynthSpec(n_projects=n_projects, days=days, seed=seed,
                     fuzz_rate=rate, ineligible_fraction=0.0)
    study = generate_study(spec)
    with tempfile.TemporaryDirectory() as d:
        cfg = Config(engine="sqlite",
                     sqlite_path=os.path.join(d, "bench.sqlite"),
                     limit_date="2026-01-01")
        db = DB(config=cfg).connect()
        study.to_db(db)
        StudyArrays.from_db(db, cfg)  # warm sqlite page cache
        t0 = time.perf_counter()
        arrays = StudyArrays.from_db(db, cfg)
        wall = time.perf_counter() - t0
        db.closeConnection()
    n_builds = len(arrays.fuzz)
    result = {
        "extract_builds": n_builds,
        "extract_rows_total": (len(arrays.fuzz) + len(arrays.covb)
                               + len(arrays.issues) + len(arrays.cov)),
        "extract_wall_s": round(wall, 4),
        "extract_builds_per_s": round(n_builds / wall),
        # Whether the C++ sqlite decoder (native/decode.cc) actually carried
        # every timed fetch — False means the pandas fallback (~2x slower)
        # produced extract_wall_s.
        "extract_native": bool(getattr(arrays, "native_decode", False)),
    }
    result.update(bench_rq_suite(arrays, cfg, wall))
    return result


# The reference's only published wall-clock numbers: RQ1 Phase 1 (10m51s,
# 878 projects) + Phase 2 (19m29s, 43,254 issues) on the author's machine
# with dockerized Postgres — rq1_detection_rate.py:361,367 (SURVEY §6).
_REFERENCE_RQ1_WALL_S = 10 * 60 + 51 + 19 * 60 + 29


def bench_rq_suite(arrays, cfg, extract_wall_s: float, iters: int = 3) -> dict:
    """Analysis stage: ALL SIX RQ engines over the extracted study on BOTH
    backends (reference semantics; file:line seats in each engine's
    docstring), parity-checked per RQ.

    Honest-backend reporting (round-3 verdict weak #3): per-RQ walls land
    as ``<rq>_{jax,pandas}_wall_s``; the flagship ``rq1_end_to_end_s``
    (= extraction + RQ1) names which engine produced it in
    ``rq1_end_to_end_backend`` so the derived ``rq1_vs_reference`` can't be
    misread as a device speedup when the host engine won.  The device
    backend runs through the per-study device cache + fused dispatch
    (backend/jax_backend.py module docstring); on a tunneled PJRT link its
    floor is the network round-trip per RQ (see the ``link_*`` keys)."""
    import numpy as np

    from tse1m_tpu.backend.jax_backend import JaxBackend
    from tse1m_tpu.backend.pandas_backend import PandasBackend

    limit_ns = int(np.datetime64(cfg.limit_date, "ns").astype(np.int64))
    # Reference filter (rq1:233) needs >=100 projects per iteration; small
    # bench studies drop it to 1 exactly like the reference's TEST_MODE
    # (rq1_detection_rate.py:20,233) so the parity check is non-vacuous.
    min_projects = 100 if arrays.n_projects >= 100 else 1
    # Synthetic G1/G2 corpus split (even/odd projects): rq4a/rq4b group
    # inputs without requiring the corpus-analysis CSV at bench time.
    g1 = np.arange(0, arrays.n_projects, 2)
    g2 = np.arange(1, arrays.n_projects, 2)

    calls = {
        "rq1": lambda b: b.rq1_detection(arrays, limit_ns, min_projects),
        "rq2cp": lambda b: b.rq2_change_points(arrays, limit_ns),
        "rq2tr": lambda b: b.rq2_trends(arrays, limit_ns),
        "rq3": lambda b: b.rq3_coverage_at_detection(arrays, limit_ns),
        "rq4a": lambda b: b.rq4a_detection_trend(arrays, limit_ns, g1, g2,
                                                 min_projects),
        "rq4b": lambda b: b.rq4b_group_trends(arrays, limit_ns, g1, g2),
    }

    from tse1m_tpu.backend import get_backend
    from tse1m_tpu.config import Config

    # The auto router is timed as a third column, constructed through the
    # SHIPPED resolution path (off-TPU or probe failure -> host oracle, on
    # TPU -> per-RQ router) so the column reports the configuration a user
    # actually gets.  It shares the device backend's study cache, so its
    # device-routed calls are warm too.
    backends = {"jax": JaxBackend(), "pandas": PandasBackend(),
                "auto": get_backend(Config(backend="auto"))}
    out = {}
    suite = {k: 0.0 for k in backends}
    res = {}
    for name, call in calls.items():
        for key, be in backends.items():
            call(be)  # warm (compile + device cache)
            runs = []
            for _ in range(iters):
                t0 = time.perf_counter()
                res[(name, key)] = call(be)
                runs.append(time.perf_counter() - t0)
            med = statistics.median(runs)
            out[f"{name}_{key}_wall_s"] = round(med, 4)
            suite[key] += med

    # Parity: the device suite must agree with the host oracle before its
    # timings count (integer fields exact, float fields to fp tolerance).
    eq, close = np.testing.assert_array_equal, np.testing.assert_allclose
    j, p = (res[("rq1", "jax")], res[("rq1", "pandas")])
    for f in ("iterations", "total_projects", "detected_counts"):
        eq(getattr(j, f), getattr(p, f), err_msg=f"rq1.{f}")
    j, p = (res[("rq2cp", "jax")], res[("rq2cp", "pandas")])
    eq(j.end_i, p.end_i, err_msg="rq2cp.end_i")
    close(j.covered_i, p.covered_i, err_msg="rq2cp.covered_i")
    j, p = (res[("rq2tr", "jax")], res[("rq2tr", "pandas")])
    eq(j.counts, p.counts, err_msg="rq2tr.counts")
    close(j.percentiles, p.percentiles, rtol=2e-5, atol=2e-5,
          err_msg="rq2tr.percentiles")
    j, p = (res[("rq3", "jax")], res[("rq3", "pandas")])
    eq(j.det_issue_idx, p.det_issue_idx, err_msg="rq3.det_issue_idx")
    close(j.det_diff_percent, p.det_diff_percent, err_msg="rq3.det_diff")
    j, p = (res[("rq4a", "jax")], res[("rq4a", "pandas")])
    for f in ("iterations", "g1_total", "g1_detected", "g2_total",
              "g2_detected"):
        eq(getattr(j, f), getattr(p, f), err_msg=f"rq4a.{f}")
    j, p = (res[("rq4b", "jax")], res[("rq4b", "pandas")])
    close(j.g1_percentiles, p.g1_percentiles, err_msg="rq4b.g1")
    close(j.g2_percentiles, p.g2_percentiles, err_msg="rq4b.g2")

    # Fused suite (backend.rq_suite): the device backend runs all six RQ
    # bodies in ONE dispatch + ONE packed fetch (jax_backend.
    # _rq_suite_kernel), so the whole suite costs ~1 link round-trip; the
    # host backend's rq_suite is the six sequential calls.  Parity of the
    # fused results vs the per-RQ calls is asserted in
    # tests/test_rq_suite.py; here we spot-check the flagship fields.
    min_p, limit = min_projects, limit_ns
    for key, be in backends.items():
        suite_res = be.rq_suite(arrays, limit, min_p, g1, g2)  # warm
        runs = []
        for _ in range(iters):
            t0 = time.perf_counter()
            suite_res = be.rq_suite(arrays, limit, min_p, g1, g2)
            runs.append(time.perf_counter() - t0)
        out[f"rq_suite_fused_{key}_wall_s"] = round(statistics.median(runs),
                                                    4)
        eq(suite_res["rq1"].iterations, res[("rq1", key)].iterations,
           err_msg=f"fused/{key} rq1.iterations")
        eq(suite_res["rq4a"].iterations, res[("rq4a", key)].iterations,
           err_msg=f"fused/{key} rq4a.iterations")
    out["rq_suite_fused_winner"] = (
        "jax_tpu" if out["rq_suite_fused_jax_wall_s"]
        <= out["rq_suite_fused_pandas_wall_s"] else "pandas")

    jax_s = out["rq1_jax_wall_s"]
    pd_s = out["rq1_pandas_wall_s"]
    winner = "jax_tpu" if jax_s <= pd_s else "pandas"
    end_to_end = extract_wall_s + min(jax_s, pd_s)
    out.update({
        "rq1_iterations": int(len(res[("rq1", "jax")].iterations)),
        "rq_suite_jax_wall_s": round(suite["jax"], 4),
        "rq_suite_pandas_wall_s": round(suite["pandas"], 4),
        "rq_suite_auto_wall_s": round(suite["auto"], 4),
        "rq_suite_winner": ("jax_tpu" if suite["jax"] <= suite["pandas"]
                            else "pandas"),
        "rq1_end_to_end_s": round(end_to_end, 4),
        # Which engine's RQ1 wall produced rq1_end_to_end_s (and thus
        # rq1_vs_reference) — do NOT read the ratio as a device speedup
        # unless this says jax_tpu.
        "rq1_end_to_end_backend": winner,
        "rq1_ref_wall_s": _REFERENCE_RQ1_WALL_S,
        # >1 beats the reference's committed RQ1 transcript wall time.
        "rq1_vs_reference": round(_REFERENCE_RQ1_WALL_S / end_to_end, 1),
    })
    return out


def bench_link(probe_mb: int = 32) -> dict:
    """Honest link microbench (round-3 verdict: 'measure the link bound,
    don't infer it').

    - dispatch RTT: tiny jitted op + 4-byte fetch, the per-call floor of
      EVERY device RQ (a tunneled PJRT backend pays the network round-trip;
      block_until_ready returns early there, so sync is a tiny D2H).
    - H2D MB/s for random bytes (what the packed cluster transfer sees) and
      for all-zero bytes: the zeros rate bounds what ANY entropy-reducing
      encoding could achieve on the wire, separating 'link is slow' from
      'payload is big'.
    """
    import numpy as np

    from tse1m_tpu.backend import _dispatch_rtt_s

    rtt_s = _dispatch_rtt_s()
    n = probe_mb * 1024 * 1024
    rng = np.random.default_rng(0)
    rand = rng.integers(0, 256, size=n, dtype=np.uint8)
    zeros = np.zeros(n, dtype=np.uint8)
    return {
        "link_dispatch_rtt_ms": round(rtt_s * 1e3, 2),
        "link_h2d_rand_MBps": round(_timed_h2d(rand)[1], 1),
        "link_h2d_zeros_MBps": round(_timed_h2d(zeros)[1], 1),
        "link_probe_mb": probe_mb,
    }


def _timed_h2d(payload, reps: int = 3) -> tuple:
    """device_put + 4-byte D2H completion sync (the only honest sync over a
    tunneled PJRT link — block_until_ready returns early there), median
    over `reps`.  Returns (median_s, MB_per_s)."""
    import jax

    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        d = jax.device_put(payload)  # graftlint: disable=wire-layer -- raw-link probe measures the wire itself
        int(d[(0,) * payload.ndim])
        samples.append(time.perf_counter() - t0)
    med = statistics.median(samples)
    return med, payload.nbytes / med / 1e6


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int,
                   default=int(os.environ.get("BENCH_N", 1_000_000)))
    p.add_argument("--iters", type=int,
                   default=int(os.environ.get("BENCH_ITERS", 5)),
                   help="timed steady-state iterations; median reported "
                        "(default 5 — transfer over a tunneled PJRT link "
                        "varies ~2x run-to-run, and the driver artifact "
                        "needs a stable median for round-over-round "
                        "comparability)")
    p.add_argument("--set-size", type=int, default=64)
    p.add_argument("--hashes", type=int, default=128)
    p.add_argument("--bands", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--extract-builds", type=int,
                   default=int(os.environ.get("BENCH_EXTRACT_BUILDS",
                                              1_000_000)),
                   help="extraction-stage scale in fuzzing builds "
                        "(0 disables)")
    p.add_argument("--ari-sample", type=int, default=100_000,
                   help="if >0, also ARI-check a host-clustered subsample "
                        "(the BASELINE.json acceptance gate: >= 0.98 vs the "
                        "CPU/pandas baseline)")
    p.add_argument("--sig-store", default=os.environ.get("BENCH_SIG_STORE")
                   or None,
                   help="persistent signature-store directory "
                        "(cluster/store.py): after the cold timed runs, "
                        "run the store-enabled pipeline twice (populate "
                        "if needed, then warm) and emit "
                        "cluster_warm_wall_s / cache_hit_rate / "
                        "cache_wire_saved_mb.  Persists across "
                        "invocations — a second bench run starts warm "
                        "(also BENCH_SIG_STORE)")
    p.add_argument("--warm-novel-frac", type=float,
                   default=float(os.environ.get("BENCH_WARM_NOVEL", 0.0)),
                   help="append this fraction of fresh synthetic rows to "
                        "the warm run's input (the continuous-fuzzing "
                        "accretion shape); 0 re-clusters the identical "
                        "corpus and asserts warm labels == cold labels")
    p.add_argument("--prefilter", default=os.environ.get("BENCH_PREFILTER",
                                                         "auto"),
                   choices=("off", "auto", "on"),
                   help="wire v3 host-side one-permutation LSH prefilter "
                        "(cluster/prefilter.py): rows bucketed singleton "
                        "in every band skip the wire and label "
                        "themselves; 'auto' engages on large runs, 'on' "
                        "forces it (also BENCH_PREFILTER). Labels are "
                        "asserted elementwise-equal either way, and "
                        "prefilter_recall is self-checked against the "
                        "planted truth")
    p.add_argument("--entropy", default=os.environ.get("BENCH_ENTROPY",
                                                       "auto"),
                   choices=("off", "auto", "force"),
                   help="wire v3 rANS lane coding (cluster/entropy.py): "
                        "'auto' codes lanes that beat their bit-packed "
                        "form, 'force' codes everything — the CI lever "
                        "for proving degraded-width re-encode paths "
                        "(also BENCH_ENTROPY)")
    p.add_argument("--serve", action="store_true",
                   default=os.environ.get("BENCH_SERVE", "")
                   not in ("", "0"),
                   help="serving round (tse1m_tpu/serve): populate a "
                        "store with the leading 90%% of the corpus, run "
                        "the ingest daemon + TCP API, stream the last "
                        "10%% in while query threads fire concurrently, "
                        "then assert post-quiesce membership answers "
                        "elementwise-equal to the cold batch labels — "
                        "emits serve_p99_ms / serve_qps / "
                        "ingest_backlog_max (also BENCH_SERVE=1)")
    p.add_argument("--serve-query-threads", type=int,
                   default=int(os.environ.get("BENCH_SERVE_QUERY_THREADS",
                                              2)))
    p.add_argument("--serve-batch", type=int,
                   default=int(os.environ.get("BENCH_SERVE_BATCH", 1024)),
                   help="ingest batch size for the serving round")
    p.add_argument("--serve-sharded", action="store_true",
                   default=os.environ.get("BENCH_SERVE_SHARDED", "")
                   not in ("", "0"),
                   help="sharded serving round (BENCH_r10 contract): "
                        "--serve-shards digest-range shard daemons behind "
                        "a fan-out router plus one read replica; runs an "
                        "in-process failover drill (lost-ack window drop "
                        "+ epoch-advanced writer replacement + zombie "
                        "fence check) and emits serve_shards / "
                        "serve_router_p99_ms / serve_replica_qps / "
                        "serve_failover_lost_acks (also "
                        "BENCH_SERVE_SHARDED=1)")
    p.add_argument("--serve-shards", type=int,
                   default=int(os.environ.get("BENCH_SERVE_SHARDS", 2)),
                   help="shard-daemon count for --serve-sharded")
    p.add_argument("--topk", action="store_true",
                   default=os.environ.get("BENCH_TOPK", "")
                   not in ("", "0"),
                   help="batched scoring round (cluster/kernels/score.py "
                        "+ the topk serve verb): device/host top-k rank "
                        "parity across schemes x quant bits, a "
                        "sanitizer-clean bulk scan over the populated "
                        "store asserted elementwise against the exact "
                        "host oracle (topk_recall must be 1.0), and a "
                        "candidate-path serve probe — emits "
                        "bulk_score_rows_s / topk_recall / topk_p99_ms "
                        "(also BENCH_TOPK=1)")
    p.add_argument("--scheme", default=os.environ.get("BENCH_SCHEME",
                                                      "kminhash"),
                   choices=("kminhash", "cminhash", "weighted"),
                   help="signature kernel family for the timed cluster "
                        "round (cluster/schemes.py); 'weighted' expands "
                        "synthetic hit counts into replica rows first "
                        "(also BENCH_SCHEME)")
    p.add_argument("--schemes-round", action="store_true",
                   default=os.environ.get("BENCH_SCHEMES", "")
                   not in ("", "0"),
                   help="run the scheme-comparison round (BENCH_r09 "
                        "contract): per-scheme signature wall, analytic "
                        "hash evaluations, estimator error vs exact "
                        "Jaccard on planted pairs, clustering quality, "
                        "and host/device bit-parity across quantization "
                        "rungs + resume (also BENCH_SCHEMES=1)")
    p.add_argument("--traced", action="store_true",
                   default=os.environ.get("BENCH_TRACED", "")
                   not in ("", "0"),
                   help="run the serving round under the graftrace "
                        "lockset race detector (tse1m_tpu/trace) and a "
                        "bounded deterministic-schedule explorer sweep; "
                        "emits trace_schedules_explored / "
                        "trace_races_found into the bench JSON and fails "
                        "the round on any detected race (also "
                        "BENCH_TRACED=1; explorer size via "
                        "BENCH_TRACE_SCHEDULES, default 40)")
    p.add_argument("--sanitize", action="store_true",
                   default=os.environ.get("BENCH_SANITIZE", "")
                   not in ("", "0"),
                   help="run the timed iterations under the runtime "
                        "sanitizer (tse1m_tpu/lint/runtime.py): implicit "
                        "host->device transfers raise, and the XLA compile "
                        "count must stay within --compile-budget (also "
                        "BENCH_SANITIZE=1)")
    p.add_argument("--profile", action="store_true",
                   default=os.environ.get("BENCH_PROFILE", "")
                   not in ("", "0"),
                   help="graftprof: host sampling profiler (span/plane/"
                        "lock-wait attribution) + compile-duration "
                        "histograms + a jax device trace over the round; "
                        "writes profile_NNN.json next to the flight files "
                        "at the end (also BENCH_PROFILE=1; "
                        "TSE1M_PROFILING=0 kills the plane)")
    p.add_argument("--compile-budget", type=int,
                   default=int(os.environ.get("BENCH_COMPILE_BUDGET", 2)),
                   help="max XLA compiles allowed during the timed "
                        "steady-state iterations under --sanitize (the "
                        "warmup run compiles everything first; steady "
                        "state should be 0 — 2 leaves headroom for "
                        "backend-dependent constant folding)")
    args = p.parse_args()
    iters = max(1, args.iters)

    # Persistent XLA compilation cache (Config.xla_cache_dir /
    # TSE1M_XLA_CACHE_DIR): repeat bench rounds skip every kernel
    # recompile — each fresh compile pays several 129 ms dispatch RTTs on
    # the measured tunneled link.  Must happen before the first jit.
    cache_dir = os.environ.get("TSE1M_XLA_CACHE_DIR")
    if cache_dir:
        from tse1m_tpu.utils.compat import enable_persistent_compilation_cache

        enable_persistent_compilation_cache(cache_dir)

    # Record-and-reuse auto-router calibration (backend/auto.py): persist
    # measured per-RQ walls so the next bench round's `auto` column routes
    # on this round's measurements instead of bootstrap priors — the
    # BENCH_r05 rq2tr mispick cannot recur across rounds.  Opt out with
    # TSE1M_ROUTER_CAL="".
    os.environ.setdefault("TSE1M_ROUTER_CAL",
                          "data/result_data/router_calibration.json")

    import jax

    from tse1m_tpu.cluster import (ClusterParams, adjusted_rand_index,
                                   cluster_sessions)
    from tse1m_tpu.data.synth import synth_session_sets
    from tse1m_tpu.observability.tracing import adopt_trace, new_trace_id

    # Pin one trace id for the whole bench round: every span any layer
    # opens below (client, daemon, store append, retry attempts) roots
    # under it, and the result JSON reports it as `trace_id`.
    adopt_trace(new_trace_id())

    items, truth = synth_session_sets(args.n, set_size=args.set_size,
                                      seed=args.seed)
    if args.scheme == "weighted":
        # The weighted workload consumes per-edge hit counts: replica-
        # expand host-side (schemes.expand_weighted) and bench the
        # pipeline over the replica rows — the similarity being
        # estimated is weighted Jaccard, a different (new) workload.
        from tse1m_tpu.cluster.schemes import expand_weighted
        from tse1m_tpu.data.synth import synth_session_hitcounts

        items = expand_weighted(
            items, synth_session_hitcounts(items, truth, seed=args.seed))
    dev = jax.devices()[0]
    params = ClusterParams(n_hashes=args.hashes, n_bands=args.bands,
                           prefilter=args.prefilter, entropy=args.entropy,
                           scheme=args.scheme)

    # TSE1M_PROFILE_DIR=<dir> wraps ONE steady-state run in a
    # jax.profiler trace (same knob utils/timing.py gives the RQ drivers)
    # — open the trace with tensorboard/xprof to see the on-device stage
    # breakdown that wall clocks can't separate over a remote PJRT link.
    profile_dir = os.environ.get("TSE1M_PROFILE_DIR")

    # --profile (graftprof): the host sampler + lock-wait recorder +
    # compile-duration listener ride the whole round, and the device
    # trace lands under the result dir unless TSE1M_PROFILE_DIR already
    # points somewhere.  profile_NNN.json is dumped before the final
    # JSON.  The TSE1M_PROFILING=0 kill switch beats the flag.
    from tse1m_tpu.observability import profiling

    if args.profile and profiling.profiling_enabled():
        profiling.install_compile_listener()
        profiling.enable_lock_wait(True)
        profiling.start_sampler()
        if not profile_dir:
            profile_dir = os.path.join("data", "result_data",
                                       "device_trace")
    else:
        args.profile = False

    def timed(prm):
        """Timed steady-state runs; under --sanitize the whole window runs
        with the transfer guard up and a compile budget — a warm hot loop
        that implicitly stages bytes or recompiles fails the bench instead
        of silently regressing (lint/runtime.py)."""
        import contextlib

        sanitize_ctx = contextlib.nullcontext()
        if args.sanitize:
            from tse1m_tpu.lint.runtime import sanitized

            sanitize_ctx = sanitized(args.compile_budget)
        from tse1m_tpu.observability.tracing import span

        runs = []
        with sanitize_ctx as san:
            # Root span for the timed window: even a storeless, serveless
            # round records at least this one span under the pinned
            # trace (one ring append per run — noise-level overhead).
            with span("bench.cluster", n=int(args.n), iters=int(iters)):
                for i in range(iters):
                    ctx = contextlib.nullcontext()
                    if profile_dir and i == 0:
                        ctx = jax.profiler.trace(
                            os.path.join(profile_dir, "cluster"))
                    t0 = time.perf_counter()
                    with ctx:
                        labels = cluster_sessions(items, prm)
                    runs.append(time.perf_counter() - t0)
        return labels, runs, san

    try:
        cluster_sessions(items, params)  # compile + warm
        labels, runs, sanitizer = timed(params)
    except Exception as e:  # pallas path unavailable on this backend  # graftlint: disable=broad-except -- probe fallback; bench must run on every backend
        from tse1m_tpu.lint.runtime import SanitizerViolation

        if isinstance(e, SanitizerViolation):
            raise  # a sanitizer trip is the regression, not a missing path
        print(f"# pallas path failed ({type(e).__name__}: {e}); "
              "falling back to fused-jax", file=sys.stderr)
        params = ClusterParams(n_hashes=args.hashes, n_bands=args.bands,
                               prefilter=args.prefilter,
                               entropy=args.entropy, use_pallas="never",
                               scheme=args.scheme)
        cluster_sessions(items, params)
        labels, runs, sanitizer = timed(params)

    wall = statistics.median(runs)
    # Snapshot now: the ARI subsample below runs cluster_sessions again and
    # would overwrite the timed runs' encoding stats.
    from tse1m_tpu.cluster.pipeline import last_run_info

    cluster_info = dict(last_run_info)
    # Per-stage walls of the LAST timed run (observability.StageRecorder):
    # stage_encode_s / stage_h2d_s / stage_compute_s / stage_d2h_s plus
    # h2d_overlap_fraction — the round-over-round answer to "which stage
    # moved".  Emitted at top level, not cluster_-prefixed: they are the
    # bench contract keys (PARITY.md "Wire format & streaming pipeline").
    stage_info = cluster_info.pop("stages", {})
    if stage_info.get("stage_encode_s") and cluster_info.get("wire_mb"):
        # Host packing throughput over the shipped wire bytes — separates
        # "encode got slower" from "wire got bigger" between rounds.
        stage_info["encode_MBps"] = round(
            cluster_info["wire_mb"] / stage_info["stage_encode_s"], 1)
    # Wire-v3 bench contract: the codec/prefilter stage keys exist (0.0)
    # even on rounds where neither lever engaged, so CI can assert them.
    stage_info.setdefault("stage_entropy_s", 0.0)
    stage_info.setdefault("stage_prefilter_s", 0.0)

    # Wire-v3 top-level keys + prefilter recall self-check: when the
    # timed run dropped rows, recompute the (deterministic) keep mask
    # and assert no member of a multi-row planted cluster was dropped —
    # a recall miss is a parity bug, not a degraded measurement.
    v3_stats = {
        "wire_v3_saved_mb": cluster_info.get("wire_v3_saved_mb", 0.0),
        "prefilter_hit_rate": cluster_info.get("prefilter_hit_rate", 0.0),
        "prefilter_rows_dropped": cluster_info.get(
            "prefilter_rows_dropped", 0),
        "prefilter_recall": 1.0,
    }
    if v3_stats["prefilter_rows_dropped"]:
        from tse1m_tpu.cluster.pipeline import _prefilter_mask
        from tse1m_tpu.cluster.prefilter import prefilter_recall

        keep = _prefilter_mask(items, params)
        recall = prefilter_recall(keep, truth)
        v3_stats["prefilter_recall"] = round(recall, 6)
        if recall < 1.0:
            raise AssertionError(
                f"prefilter dropped planted near-duplicates "
                f"(recall {recall}) — label parity is at risk; "
                "run with --prefilter off and file the seed")

    def compute_only() -> float:
        """Device-compute wall with items already resident on device —
        separates real kernel time from host->device link noise (on this
        tunneled PJRT setup the same 192MB upload varies ~2x run-to-run,
        dominating `value`; on a co-located TPU VM the two converge).
        Sync via a 4-byte D2H: block_until_ready does not actually block
        over the tunnel."""
        import jax

        from tse1m_tpu.cluster.pipeline import _cluster_from_sig_jit
        from tse1m_tpu.cluster.schemes import (make_params,
                                               scheme_sig_and_keys)

        hp = make_params(params.scheme, params.n_hashes,
                         params.seed).device()
        items_d = jax.device_put(items)  # graftlint: disable=wire-layer -- compute-only probe pre-stages items to exclude the link
        float(items_d[0, 0])  # finish the staging transfer
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            sig, keys = scheme_sig_and_keys(items_d, hp, params.n_bands,
                                            use_pallas=params.use_pallas,
                                            block_n=params.block_n)
            lab = _cluster_from_sig_jit(sig, keys, params.threshold,
                                        params.n_iters)
            float(lab[0])
            samples.append(time.perf_counter() - t0)
        return statistics.median(samples)

    try:
        compute_s = compute_only()
    except Exception as e:  # graftlint: disable=broad-except -- optional probe; bench JSON stays valid without it
        print(f"# compute-only probe failed ({type(e).__name__}: {e})",
              file=sys.stderr)
        compute_s = None

    def transfer_probe() -> dict:
        """Measured H2D wall for the exact payload the cluster pipeline
        ships — `pipeline.wire_payloads` returns the pipeline's OWN wire
        plan (quantization, delta lanes, adaptive bit-packing), so the
        probe cannot drift from the shipped format; median of 3 —
        `value` minus this minus `compute_only_s` is dispatch/encode
        overhead, so the link bound is measured rather than inferred from
        subtraction."""
        import jax.numpy as jnp

        from dataclasses import replace

        from tse1m_tpu.cluster import pipeline as pl

        # Pin the probe to the SURVIVING wire policy the timed run
        # actually used: a degraded run persists a quant floor that the
        # clean run's quant_restore heal then CLEARS, so re-planning
        # from the calibration here would inventory a wider wire than
        # the one measured (the drift guard below would fire on its own
        # artifact, not on a real format divergence).
        qb_timed = int(cluster_info.get("wire_quant_bits") or 0)
        probe_params = replace(params,
                               wire_quant_bits=qb_timed if qb_timed else -1)
        payloads, winfo = pl.wire_payloads(items, probe_params)
        kind = winfo["encoding"]
        # An all-exact-duplicate workload has zero diffs: empty lanes can't
        # be indexed by the sync op and ship nothing anyway.
        payloads = [p for p in payloads if p.size]
        nbytes = sum(p.nbytes for p in payloads)

        @jax.jit
        def _touch(*xs):
            # One 4-byte completion sync covering every lane (a per-array
            # int() would pay the ~0.11 s tunnel RTT once per lane).
            return sum(x.ravel()[0].astype(jnp.uint32) for x in xs)

        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            ds = [jax.device_put(p) for p in payloads]  # graftlint: disable=wire-layer -- transfer probe times the pipeline's own payloads
            int(_touch(*ds))
            samples.append(time.perf_counter() - t0)
        med = statistics.median(samples)
        return {
            "transfer_mb": round(nbytes / 2**20, 1),
            "transfer_bytes": nbytes,
            "transfer_s": round(med, 4),
            # The tunnel varies ~2x minute-to-minute; the per-rep list
            # (and best) keep one slow window from reading as the bound.
            "transfer_runs_s": [round(s, 4) for s in samples],
            "transfer_best_s": round(min(samples), 4),
            "transfer_MBps": round(nbytes / med / 1e6, 1),
            "transfer_chunk_bits": winfo["chunk_bits"],
            "transfer_quant_bits": winfo["wire_quant_bits"],
            "transfer_encoding": kind,
        }

    try:
        transfer_stats = transfer_probe()
    except Exception as e:  # graftlint: disable=broad-except -- optional probe; bench JSON stays valid without it
        print(f"# transfer probe failed ({type(e).__name__}: {e})",
              file=sys.stderr)
        transfer_stats = {}

    # Wire-accounting drift guard (outside the probe's failure guard on
    # purpose — a mismatch must FAIL the bench, not degrade it): the
    # probe's byte inventory must equal the H2D bytes the timed run's
    # StageRecorder actually recorded, so `transfer_mb` can never diverge
    # from what the pipeline ships.  A nonzero drift means wire_payloads
    # and the pipeline disagree about the wire format — a lying artifact.
    wire_drift = None
    if transfer_stats and cluster_info.get("wire_bytes") is not None:
        wire_drift = (transfer_stats["transfer_bytes"]
                      - cluster_info["wire_bytes"])
        if wire_drift != 0:
            raise AssertionError(
                f"wire accounting drift: transfer probe inventories "
                f"{transfer_stats['transfer_bytes']} B but the timed run "
                f"recorded {cluster_info['wire_bytes']} B over h2d")

    def bench_warm_store() -> dict:
        """Signature-store warm rounds: one store-enabled run to populate
        (a no-op when the on-disk store already covers the corpus), then
        ONE timed warm run — under the runtime sanitizer when --sanitize,
        proving the warm path stays zero-implicit-transfer and within the
        compile budget.  With --warm-novel-frac 0 the warm labels are
        asserted equal to the cold run's elementwise."""
        import contextlib

        import numpy as np

        from dataclasses import replace

        from tse1m_tpu.cluster.pipeline import last_run_info as lri

        # The store caches a signature per row, so the prefilter cannot
        # ride along (prefilter='on' + sig_store refuses in the
        # pipeline); warm rounds measure the store lever in isolation.
        store_params = replace(params, sig_store=args.sig_store,
                               prefilter="off")
        warm_items = items
        k_nov = int(args.n * args.warm_novel_frac)
        if k_nov > 0:
            nov, _ = synth_session_sets(k_nov, set_size=args.set_size,
                                        seed=args.seed + 7919)
            warm_items = np.concatenate([items, nov])
        # Cover the BASE corpus (a no-op when a previous invocation's
        # on-disk store already has it) so the timed run below is the
        # realistic warm shape: yesterday's corpus cached, the novel
        # tail seen for the first time.
        cluster_sessions(items, store_params)
        ctx = contextlib.nullcontext()
        if args.sanitize:
            from tse1m_tpu.lint.runtime import sanitized

            ctx = sanitized(args.compile_budget)
        t0 = time.perf_counter()
        with ctx:
            warm_labels = cluster_sessions(warm_items, store_params)
        warm_wall = time.perf_counter() - t0
        winfo = dict(lri)
        if k_nov == 0:
            # Label-parity gate.  Elementwise only when the two runs
            # shipped the SAME universe: a cold run that survived the
            # quant-drop rung ran at a degraded width, while the store
            # policy pins its own quant_bits — cross-universe labels
            # agree on structure (ARI), not on every collapsed id.
            cold_qb = int(cluster_info.get("wire_quant_bits") or 0)
            warm_qb = int(winfo.get("wire_quant_bits") or 0)
            if cold_qb == warm_qb:
                if not np.array_equal(warm_labels, labels):
                    raise AssertionError(
                        "warm store labels differ from the cold run's — "
                        "the incremental path broke label parity")
            else:
                cross = adjusted_rand_index(warm_labels, labels)
                if cross < 0.98:
                    raise AssertionError(
                        f"warm store labels diverged (ARI {cross:.4f}) "
                        f"from the degraded cold run (cold universe "
                        f"2^{cold_qb}, warm 2^{warm_qb})")
        warm_wire = winfo.get("wire_mb", 0.0)
        return {
            "cluster_warm_wall_s": round(warm_wall, 4),
            "cache_hit_rate": winfo.get("cache_hit_rate"),
            "cache_mode": winfo.get("cache_mode"),
            "cache_novel_rows": winfo.get("cache_novel_rows"),
            "cache_warm_wire_mb": warm_wire,
            # Wire the warm run did NOT ship, vs the measured cold run.
            "cache_wire_saved_mb": round(
                max(0.0, cluster_info.get("wire_mb", 0.0) - warm_wire), 2),
            "cache_warm_novel_frac": args.warm_novel_frac,
            "cache_warm_sanitized": bool(args.sanitize),
        }

    def bench_serve() -> dict:
        """Serving round: sustained ingest QPS with concurrent query p99.

        The leading 90% of the corpus populates the store through the
        BATCH path (committing the LSH state the daemon adopts — the
        production shape: yesterday's cron populated, today's sessions
        stream in), then the daemon serves over TCP while one client
        streams the remaining 10% in ingest batches and
        ``--serve-query-threads`` clients fire single-vector membership
        queries against already-acknowledged rows.  After quiesce, the
        membership answer for EVERY session is asserted elementwise-
        equal to the cold batch labels (cross-universe runs fall back to
        the ARI gate, same as the warm round).  The query hot path runs
        under the runtime sanitizer when --sanitize: it is host-only by
        construction, so zero implicit transfers and zero compiles."""
        import contextlib
        import tempfile
        import threading

        import numpy as np

        from dataclasses import replace

        from tse1m_tpu.cluster.pipeline import last_run_info as lri
        from tse1m_tpu.serve import (Backpressure, ServeClient, ServeDaemon,
                                     ServeServer, SloPolicy)

        # graftprof: per-site lock-wait attribution across the whole
        # serving round — the concurrent ingest/query phase is where
        # absorb-lock queueing and the GIL convoy live, and the round
        # reports serve_lock_wait_sites + the slow-request count.
        profiling.enable_lock_wait(True)
        store_dir = ((args.sig_store.rstrip("/") + "_serve")
                     if args.sig_store else
                     tempfile.mkdtemp(prefix="tse1m_serve_"))
        split = max(1, int(args.n * 0.9))
        base, tail = items[:split], items[split:]
        populate_params = replace(params, sig_store=store_dir,
                                  prefilter="off")
        cluster_sessions(base, populate_params)
        base_qb = int(lri.get("wire_quant_bits") or 0)
        daemon = ServeDaemon(store_dir, params=params,
                             slo=SloPolicy.from_env()).start()
        server = ServeServer(daemon)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        acked = [split]  # rows queryable so far (daemon-order prefix)
        ingest_walls = []
        stop_queries = threading.Event()
        errors: list = []

        def ingest_client() -> None:
            try:
                with ServeClient(port=server.port) as c:
                    for lo in range(0, tail.shape[0], args.serve_batch):
                        batch = tail[lo:lo + args.serve_batch]
                        t0 = time.perf_counter()
                        while True:
                            try:
                                c.ingest(batch)
                                break
                            except Backpressure as e:
                                time.sleep(e.retry_after_s)
                        ingest_walls.append(time.perf_counter() - t0)
                        acked[0] = split + lo + batch.shape[0]
            except Exception as e:  # graftlint: disable=broad-except -- cross-thread relay: collected and re-raised on the main thread below
                errors.append(e)
            finally:
                stop_queries.set()

        client_walls: list = []

        def query_client(seed: int) -> None:
            rng = np.random.default_rng(seed)
            walls = []
            try:
                with ServeClient(port=server.port) as c:
                    while not stop_queries.is_set():
                        i = int(rng.integers(0, acked[0]))
                        t0 = time.perf_counter()
                        resp = c.query(items[i:i + 1])
                        walls.append(time.perf_counter() - t0)
                        if not bool(resp["known"][0]):
                            raise AssertionError(
                                f"acked row {i} unknown to the daemon")
            except Exception as e:  # graftlint: disable=broad-except -- cross-thread relay: collected and re-raised on the main thread below
                errors.append(e)
            finally:
                client_walls.append(walls)

        # Warm the query path (first-digest numpy warmup etc.), then
        # measure a clean window.
        daemon.query(items[:1])
        daemon.lat_query.reset_window()
        threads = [threading.Thread(target=ingest_client, daemon=True)]
        threads += [threading.Thread(target=query_client, args=(7 + i,),
                                     daemon=True)
                    for i in range(max(1, args.serve_query_threads))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1800)
        if errors:
            raise errors[0]
        with ServeClient(port=server.port) as c:
            c.quiesce(timeout_s=600)
            status = c.status()
        qstats = daemon.lat_query.snapshot()
        # Post-quiesce parity: membership answers for the WHOLE corpus
        # vs the cold batch labels — under the sanitizer when asked
        # (the query path must stay host-only).
        ctx = contextlib.nullcontext()
        if args.sanitize:
            from tse1m_tpu.lint.runtime import sanitized

            ctx = sanitized(0)
        serve_labels = np.empty(args.n, np.int64)
        with ctx:
            for lo in range(0, args.n, 65536):
                resp = daemon.query(items[lo:lo + 65536])
                if not bool(resp["known"].all()):
                    raise AssertionError(
                        "post-quiesce query misses ingested rows")
                serve_labels[lo:lo + 65536] = resp["labels"]
        cold_qb = int(cluster_info.get("wire_quant_bits") or 0)
        if cold_qb == base_qb:
            if not np.array_equal(serve_labels, labels):
                raise AssertionError(
                    "serving-plane membership answers differ from the "
                    "cold batch run — the live index broke label parity")
            parity = "elementwise"
        else:
            cross = adjusted_rand_index(serve_labels, labels)
            if cross < 0.98:
                raise AssertionError(
                    f"serving labels diverged (ARI {cross:.4f}) from "
                    f"the degraded cold run (cold 2^{cold_qb}, serve "
                    f"2^{base_qb})")
            parity = f"ari:{round(cross, 5)}"
        # Tracing-overhead gate (telemetry plane): post-quiesce the
        # daemon is query-only, so alternating untraced/traced windows
        # over the same single-vector queries isolate the span plane's
        # cost on the hot path.  Best-of-3 per mode absorbs scheduler
        # noise; CI asserts the traced p99 stays within 10% of untraced.
        from tse1m_tpu.observability.tracing import set_tracing

        probe_idx = np.random.default_rng(11).integers(0, args.n, size=200)

        def _query_window() -> float:
            walls = []
            with ServeClient(port=server.port) as c:
                for i in probe_idx:
                    t0 = time.perf_counter()
                    c.query(items[int(i):int(i) + 1])
                    walls.append(time.perf_counter() - t0)
            return round(
                float(np.percentile(np.asarray(walls), 99)) * 1e3, 3)

        overhead: dict = {"untraced": [], "traced": []}
        try:
            for _ in range(3):
                set_tracing(False)
                overhead["untraced"].append(_query_window())
                set_tracing(True)
                overhead["traced"].append(_query_window())
        finally:
            set_tracing(True)
        # Profiled-overhead gate (graftprof): the same alternating-
        # window probe for the profiling plane — sampler stopped +
        # lock-wait recorder detached vs the full profiler (sampler at
        # default Hz + per-site lock-wait timing).  Best-of-3 per mode;
        # CI asserts profiled p99 <= 1.1 x unprofiled + 0.5 ms.
        prof_overhead: dict = {"unprofiled": [], "profiled": []}
        try:
            for _ in range(3):
                profiling.stop_sampler()
                profiling.enable_lock_wait(False)
                prof_overhead["unprofiled"].append(_query_window())
                profiling.enable_lock_wait(True)
                profiling.start_sampler()
                prof_overhead["profiled"].append(_query_window())
        finally:
            profiling.stop_sampler()
            profiling.enable_lock_wait(True)
            if args.profile:
                # Restore the round-long --profile sampler the probe's
                # windows tore down (lock-wait histograms live in the
                # registry and survived).
                profiling.start_sampler()
        with ServeClient(port=server.port) as c:
            c.shutdown()
        daemon.stop()
        server.server_close()
        tail_rows = int(tail.shape[0])
        ingest_wall = sum(ingest_walls) or 1e-9
        # Client-PERCEIVED latency (request to response over TCP, incl.
        # any retried/timed-out attempts) alongside the daemon-side
        # histogram: under heavy concurrent ingest the GIL convoy shows
        # up here first, so the honest SLO number is this one.
        all_walls = np.sort(np.concatenate(
            [np.asarray(w) for w in client_walls if w] or [np.zeros(1)]))
        cp = {q: round(float(np.percentile(all_walls, q)) * 1e3, 3)
              for q in (50, 99)}
        return {
            "serve_rows": int(status["rows"]),
            "serve_generation": int(status["generation"]),
            "serve_client_p50_ms": cp[50],
            "serve_client_p99_ms": cp[99],
            "serve_p50_ms": qstats["p50_ms"],
            "serve_p99_ms": qstats["p99_ms"],
            "serve_qps": qstats["qps"],
            "serve_query_count": qstats["count"],
            "serve_ingest_rows_s": round(tail_rows / ingest_wall, 1),
            "serve_ingest_batches": len(ingest_walls),
            "ingest_backlog_max": int(status["ingest_backlog_max"]),
            "serve_ingest_rejected": int(status["ingest_rejected"]),
            "serve_slo_violations": int(status["query_slo_violations"]),
            "serve_parity": parity,
            "serve_sanitized": bool(args.sanitize),
            "serve_untraced_p99_ms": min(overhead["untraced"]),
            "serve_traced_p99_ms": min(overhead["traced"]),
            "serve_unprofiled_p99_ms": min(prof_overhead["unprofiled"]),
            "serve_profiled_p99_ms": min(prof_overhead["profiled"]),
            "serve_lock_wait_sites": profiling.lock_wait_summary(top=8),
            "serve_slow_requests": int(profiling.slow_requests_total()),
        }

    def bench_serve_sharded() -> dict:
        """Sharded serving round (the BENCH_r10 contract): N digest-range
        shard daemons — each a single-writer ``ServeDaemon`` over its
        ``range_NNNN/`` slice, fenced by an epoch lease — behind the
        fan-out router, plus ONE read replica streaming shard 0.

        Three phases, all in-process (the multi-process SIGKILL shape
        lives in tests/test_serve_chaos.py and the CI fault matrix):

        1. Ingest the corpus through the router in batches, then measure
           the router's broadcast-query p99 (``serve_router_p99_ms``).
        2. Failover drill: (a) an injected connection drop at the
           ``serve.router.forward`` lost-ack window — the retry carries
           the SAME request id, so the shard's journal replays the
           committed ack instead of double-absorbing; (b) an
           epoch-advanced replacement writer takes shard 0's lease, the
           superseded zombie is asserted to append ZERO rows, and every
           previously acked row must still answer ``known`` through the
           router: ``serve_failover_lost_acks`` is the count that does
           not (gated at exactly 0).
        3. Replica round: stream shard 0's store, adopt, assert zero
           staleness after the final pull, and measure sustained replica
           query rate (``serve_replica_qps``)."""
        import shutil as _shutil
        import tempfile

        import numpy as np

        from tse1m_tpu.resilience.coordinator import (LeaseSupersededError,
                                                      RangeLeaseGuard)
        from tse1m_tpu.resilience.faults import (FaultPlan, FaultRule,
                                                 clear_plan, install_plan)
        from tse1m_tpu.serve import (LocalTransport, ServeDaemon,
                                     ServeReplica, ShardRouter, SloPolicy,
                                     replica_staleness, stream_shards)

        n_shards = max(2, int(args.serve_shards))
        n_sh = int(min(args.n,
                       int(os.environ.get("BENCH_SHARDED_N", "8192"))))
        corpus = items[:n_sh]
        batch = max(1, min(int(args.serve_batch), 512))
        root = tempfile.mkdtemp(prefix="tse1m_serve_sharded_")

        def spawn(sid: int, guard=None):
            guard = guard or RangeLeaseGuard.claim(root, sid, owner=sid)
            return ServeDaemon(os.path.join(root, f"range_{sid:04d}"),
                               params=params, signer="host",
                               state_commit_every=1, lease_guard=guard,
                               slo=SloPolicy.from_env()).start()

        daemons = {sid: spawn(sid) for sid in range(n_shards)}
        router = ShardRouter(
            {sid: LocalTransport(d) for sid, d in daemons.items()})
        try:
            # Phase 1: routed ingest + router query p99.
            ingest_walls = []
            for lo in range(0, n_sh, batch):
                t0 = time.perf_counter()
                router.ingest(corpus[lo:lo + batch])
                ingest_walls.append(time.perf_counter() - t0)
            probe = np.random.default_rng(11).integers(0, n_sh, size=200)
            walls = []
            for i in probe:
                t0 = time.perf_counter()
                resp = router.query(corpus[int(i):int(i) + 1])
                walls.append(time.perf_counter() - t0)
                if not bool(resp["known"][0]):
                    raise AssertionError(
                        f"routed row {int(i)} unknown to its shard owner")
            router_p99_ms = round(
                float(np.percentile(np.asarray(walls), 99)) * 1e3, 3)

            # Phase 2a: lost-ack window drop -> journal replay, not a
            # double absorb.
            rows_before = sum(d.store.n_rows for d in daemons.values())
            # dup_fraction=0: content-unique drill rows, so the store-row
            # accounting below is exact (novel == unique digests).
            drill, _ = synth_session_sets(batch, set_size=args.set_size,
                                          seed=args.seed + 104729,
                                          dup_fraction=0.0)
            install_plan(FaultPlan([FaultRule(
                site="serve.router.forward", kind="connection_drop",
                times=1)]))
            try:
                ack = router.ingest(drill, request_id="bench-failover-ack")
            finally:
                clear_plan()
            if int(ack["acked"]) != batch:
                raise AssertionError(
                    f"short ack across the dropped forward: {ack}")

            # Phase 2b: epoch-advanced replacement writer for shard 0;
            # the superseded zombie must append zero rows.
            zombie = daemons[0]
            z_rows = zombie.store.n_rows
            replacement_guard = RangeLeaseGuard.claim(root, 0, owner=100)
            fenced = False
            try:
                zombie.ingest(corpus[:1], timeout=60)
            except (RuntimeError, LeaseSupersededError):
                fenced = True
            if not fenced or zombie.store.n_rows != z_rows:
                raise AssertionError(
                    "superseded shard writer was not fenced (rows "
                    f"{z_rows} -> {zombie.store.n_rows})")
            zombie.stop(commit=False)
            daemons[0] = spawn(0, guard=replacement_guard)
            router.transports[0] = LocalTransport(daemons[0])
            # Re-send the drill batch under the SAME request id across
            # the writer swap: committed slices replay, nothing absorbs
            # twice.
            ack2 = router.ingest(drill, request_id="bench-failover-ack")
            if int(ack2["acked"]) != batch:
                raise AssertionError(f"failover re-ack short: {ack2}")
            rows_after = sum(d.store.n_rows for d in daemons.values())
            expect_rows = rows_before + int(ack["novel"])
            if rows_after != expect_rows:
                raise AssertionError(
                    f"failover double-absorbed: {rows_after} store rows, "
                    f"expected {expect_rows}")
            # Zero lost acks: every row acked before the failover still
            # answers known through the router.
            lost = 0
            for lo in range(0, n_sh, 2048):
                resp = router.query(corpus[lo:lo + 2048])
                lost += int((~np.asarray(resp["known"])).sum())
            lost += int((~np.asarray(
                router.query(drill)["known"])).sum())
            if lost:
                raise AssertionError(
                    f"{lost} acked row(s) lost across the shard failover")

            # Phase 3: read replica over shard 0's streamed store.
            replica_dir = os.path.join(root, "replica_0000")
            src = daemons[0].store.directory
            router.quiesce(timeout=600)  # commit state for the stream
            stream_shards(src, replica_dir)
            replica = ServeReplica(replica_dir, params=params)
            replica.refresh()
            staleness = replica_staleness(src, replica)
            if staleness:
                raise AssertionError(
                    f"replica {staleness} generation(s) stale after a "
                    "completed pull")
            rep_walls = []
            t_rep = time.perf_counter()
            for i in probe[:100]:
                t0 = time.perf_counter()
                replica.query(corpus[int(i):int(i) + 1])
                rep_walls.append(time.perf_counter() - t0)
            rep_window = time.perf_counter() - t_rep
            status = router.status()
            if not status["ok"]:
                raise AssertionError(
                    f"sharded status degraded: {status}")
            return {
                "serve_shards": n_shards,
                "serve_router_p99_ms": router_p99_ms,
                "serve_router_rows": int(status["router_rows"]),
                "serve_router_replayed_acks":
                    int(status["router_replayed_acks"]),
                "serve_replica_qps": round(
                    len(rep_walls) / max(rep_window, 1e-9), 1),
                "serve_replica_p99_ms": round(float(np.percentile(
                    np.asarray(rep_walls), 99)) * 1e3, 3),
                "serve_replica_staleness": int(staleness),
                "serve_failover_lost_acks": int(lost),
                "serve_sharded_rows": rows_after,
                "serve_sharded_ingest_rows_s": round(
                    n_sh / max(sum(ingest_walls), 1e-9), 1),
            }
        finally:
            for d in daemons.values():
                try:
                    d.stop(commit=False)
                except Exception:  # graftlint: disable=broad-except -- teardown best-effort; the round already passed/failed above
                    pass
            _shutil.rmtree(root, ignore_errors=True)

    def bench_topk() -> dict:
        """Batched scoring round (the topk-verb contract): the scoring
        plane's three claims, each asserted — not sampled.

        1. Rank parity: ``topk_agreement`` (device path) equals the
           numpy oracle ELEMENTWISE across every scheme x quant-bits
           combination — counts and rows, ties included.
        2. Exact recall: a streamed ``bulk_topk_store`` scan over the
           store the timed round populated equals ``score_topk_host``
           over the concatenated shards (recall exactly 1.0, reported
           from the actual set overlap, not assumed).  Under --sanitize
           the timed scan runs inside ``sanitized(0)``: one warm pass,
           then zero compiles and only the scorer's explicit wire-layer
           transfers.
        3. Serve-verb latency: 100 single-vector candidate-mode
           ``topk`` probes against the live daemon — the interactive
           path's p99 joins the gated keys next to serve_p99_ms."""
        import contextlib
        import shutil
        import tempfile

        import numpy as np

        from dataclasses import replace

        from tse1m_tpu.cluster.encode import quantize_ids
        from tse1m_tpu.cluster.kernels.score import (bulk_topk_store,
                                                     score_topk_host,
                                                     topk_agreement)
        from tse1m_tpu.cluster.schemes import (make_params,
                                               scheme_host_signatures)
        from tse1m_tpu.serve import ServeDaemon, SloPolicy

        # 1) device/host rank parity across schemes x quant bits: the
        # determinism contract (-count, ascending row) must survive
        # every signature family and every degraded wire width.
        combos = [(sc, qb)
                  for sc in ("kminhash", "cminhash", "weighted")
                  for qb in (0, 10, 8)]
        rng = np.random.default_rng(args.seed)
        for scheme, qbits in combos:
            rows = rng.integers(0, 2**32, size=(96, 12), dtype=np.uint32)
            if qbits:
                rows = quantize_ids(rows, qbits)
            sigs = scheme_host_signatures(
                rows, make_params(scheme, 16, seed=args.seed))
            q = sigs[:8]  # self-hits force known full-agreement ranks
            ref = score_topk_host(q, sigs, 8)
            got = topk_agreement(q, sigs, 8, use_pallas=params.use_pallas)
            if not (np.array_equal(got[0], ref[0])
                    and np.array_equal(got[1], ref[1])):
                raise AssertionError(
                    f"device/host top-k rank divergence "
                    f"({scheme}, quant 2^{qbits or 32})")
        parity = f"elementwise:{len(combos)}/{len(combos)}"

        # 2) + 3) need a populated store and a live daemon: the same
        # BATCH-path populate the serving round uses.
        store_dir = tempfile.mkdtemp(prefix="tse1m_topk_")
        n_store = min(args.n, 8192)
        corpus = items[:n_store]
        cluster_sessions(corpus, replace(params, sig_store=store_dir,
                                         prefilter="off"))
        daemon = ServeDaemon(store_dir, params=params,
                             slo=SloPolicy.from_env()).start()
        try:
            store = daemon.reader
            store.refresh()
            nq = min(64, n_store)
            probe = np.random.default_rng(args.seed + 1).integers(
                0, n_store, size=nq)
            q_sigs = daemon._sign_novel(corpus[probe])
            k = 10
            # Warm pass compiles the chunk scorer for this (query pad,
            # k, chunk) shape; the timed pass must then be clean.
            warm = bulk_topk_store(store, q_sigs, k,
                                   use_pallas=params.use_pallas)
            ctx = contextlib.nullcontext()
            if args.sanitize:
                from tse1m_tpu.lint.runtime import sanitized

                ctx = sanitized(0)
            t0 = time.perf_counter()
            with ctx:
                counts, rows_g = bulk_topk_store(store, q_sigs, k,
                                                 use_pallas=params.use_pallas)
            scan_wall = time.perf_counter() - t0
            if not (np.array_equal(counts, warm[0])
                    and np.array_equal(rows_g, warm[1])):
                raise AssertionError("bulk scan is not deterministic "
                                     "across repeat passes")
            # Exact-recall oracle: every committed signature row, in
            # scan order (sorted shard id), scored on the host.
            all_sigs = np.concatenate(
                [np.asarray(store._sig_mmap(int(e["id"])))
                 for e in sorted(store.shards,
                                 key=lambda e: int(e["id"]))])
            ref_counts, ref_rows = score_topk_host(q_sigs, all_sigs, k)
            if not (np.array_equal(counts, ref_counts)
                    and np.array_equal(rows_g, ref_rows)):
                raise AssertionError(
                    "bulk store scan diverged from the host oracle — "
                    "the scan path broke exact recall")
            want = int((ref_rows >= 0).sum())
            hit = sum(
                len(set(g[g >= 0].tolist()) & set(r[r >= 0].tolist()))
                for g, r in zip(rows_g, ref_rows))
            recall = hit / max(want, 1)
            if recall != 1.0:
                raise AssertionError(f"topk_recall {recall} != 1.0")

            # 3) candidate-path serve probe: single-vector topk against
            # the live index, daemon-side histogram after a warm reset.
            daemon.topk(corpus[:1], k=k, mode="candidates")
            daemon.lat_topk.reset_window()
            for i in np.random.default_rng(args.seed + 2).integers(
                    0, n_store, size=100):
                daemon.topk(corpus[int(i):int(i) + 1], k=k,
                            mode="candidates")
            tstats = daemon.lat_topk.snapshot()
        finally:
            daemon.stop(commit=False)
            shutil.rmtree(store_dir, ignore_errors=True)
        return {
            "topk_parity": parity,
            "bulk_score_rows_s": round(
                n_store * nq / max(scan_wall, 1e-9), 1),
            "topk_recall": recall,
            "topk_p99_ms": tstats["p99_ms"],
            "topk_scan_rows": int(n_store),
            "topk_scan_queries": int(nq),
            "topk_candidate_probes": int(tstats["count"]),
            "topk_sanitized": bool(args.sanitize),
        }

    def bench_schemes() -> dict:
        """Scheme-comparison round (the BENCH_r09 contract): every member
        of the kernel family over the same planted corpus — signature
        pass wall, ANALYTIC element-hash evaluations (the honest FLOP
        comparison: C-MinHash hashes each element once, kminhash once
        per hash function), estimator error vs exact Jaccard on planted
        pairs, clustering quality, and bit-parity of host vs device vs
        pallas signatures across the b-bit quantization rungs plus a
        checkpointed resume."""
        import tempfile
        from dataclasses import replace

        import jax.numpy as jnp
        import numpy as np

        from tse1m_tpu.cluster import cluster_sessions_resumable
        from tse1m_tpu.cluster.encode import quantize_ids
        from tse1m_tpu.cluster.schemes import (expand_weighted,
                                               make_params,
                                               scheme_hash_evals,
                                               scheme_host_signatures,
                                               scheme_sig_and_keys)
        from tse1m_tpu.data.synth import synth_session_hitcounts

        sn = int(os.environ.get("BENCH_SCHEMES_N",
                                min(args.n, 200_000)))
        base, struth = synth_session_sets(sn, set_size=args.set_size,
                                          seed=args.seed + 17)
        weights = synth_session_hitcounts(base, struth,
                                          seed=args.seed + 17)
        out = {"schemes_round_n": sn}
        evals = {}
        for scheme in ("kminhash", "cminhash", "weighted"):
            rows = (expand_weighted(base, weights)
                    if scheme == "weighted" else base)
            prm = replace(params, scheme=scheme, sig_store=None,
                          prefilter="off")
            hp = make_params(scheme, prm.n_hashes, prm.seed)
            # Clustering quality + wall (median of 2 after a warm run).
            cluster_sessions(rows, prm)
            walls = []
            for _ in range(2):
                t0 = time.perf_counter()
                lab = cluster_sessions(rows, prm)
                walls.append(time.perf_counter() - t0)
            out[f"scheme_{scheme}_wall_s"] = round(
                statistics.median(walls), 4)
            out[f"scheme_{scheme}_ari_vs_planted"] = round(
                adjusted_rand_index(lab, struth), 5)
            evals[scheme] = scheme_hash_evals(scheme, rows.shape[0],
                                              rows.shape[1], prm.n_hashes)
            out[f"scheme_{scheme}_sig_hash_evals"] = evals[scheme]
            # Host/device/pallas bit-parity across the quantization
            # rungs the degradation ladder can land on (None/10/8-bit
            # universes — a mid-run quant drop re-hashes in the smaller
            # universe, so parity must hold at every rung).
            parity = True
            sample = rows[:4096]
            for qb in (0, 10, 8):
                sub = quantize_ids(sample, qb) if qb else sample
                want = scheme_host_signatures(sub, hp)
                got, _ = scheme_sig_and_keys(jnp.asarray(sub),
                                             hp.device(), prm.n_bands,
                                             use_pallas="never")
                pall, _ = scheme_sig_and_keys(jnp.asarray(sub),
                                              hp.device(), prm.n_bands,
                                              use_pallas="interpret")
                parity &= bool(np.array_equal(want, np.asarray(got)))
                parity &= bool(np.array_equal(want, np.asarray(pall)))
            out[f"scheme_{scheme}_sig_parity"] = parity
            # Resume parity: a checkpointed run, then a resume against
            # the committed shards — labels must match the direct run.
            with tempfile.TemporaryDirectory() as ck:
                sl = rows[:min(sn, 50_000)]
                r1 = cluster_sessions_resumable(sl, prm,
                                                checkpoint_dir=ck,
                                                cleanup=False)
                r2 = cluster_sessions_resumable(sl, prm,
                                                checkpoint_dir=ck)
            out[f"scheme_{scheme}_resume_parity"] = bool(
                np.array_equal(r1, r2)
                and np.array_equal(r1, cluster_sessions(sl, prm)))
            # Estimator error vs exact Jaccard over planted pairs —
            # host signatures only for the SAMPLED pair rows (the
            # kminhash oracle broadcasts [rows, S, H]; 20k rows would
            # be a 13 GB temporary).
            uniq, counts = np.unique(struth, return_counts=True)
            rng = np.random.default_rng(args.seed)
            labs = rng.choice(uniq[counts >= 2],
                              size=min(128, int((counts >= 2).sum())),
                              replace=False)
            pairs = [np.flatnonzero(struth == lab_id)[:2]
                     for lab_id in labs]
            need = np.unique(np.concatenate(pairs))
            pos = {int(i): p for p, i in enumerate(need)}
            hs = scheme_host_signatures(rows[need], hp)
            errs = []
            for a_i, b_i in pairs:
                sa = set(rows[a_i].tolist())
                sb = set(rows[b_i].tolist())
                j = len(sa & sb) / len(sa | sb)
                est = float((hs[pos[int(a_i)]]
                             == hs[pos[int(b_i)]]).mean())
                errs.append(abs(est - j))
            out[f"scheme_{scheme}_est_err_mean"] = round(
                float(np.mean(errs)), 5)
        out["scheme_hash_eval_ratio_cminhash"] = round(
            evals["kminhash"] / max(evals["cminhash"], 1), 1)
        out["scheme_label_quality_delta"] = round(
            abs(out["scheme_kminhash_ari_vs_planted"]
                - out["scheme_cminhash_ari_vs_planted"]), 5)
        return out

    warm_stats = {}
    if args.sig_store:
        warm_stats = bench_warm_store()
        # Store health after the warm rounds (`store_scrub_*` keys): the
        # same walk `tse1m scrub` does — frames verified, corruption
        # quarantined and counted.  A corrupt-shard fault-matrix round
        # surfaces here as store_scrub_corrupt > 0 while the warm labels
        # above still matched (the quarantined rows recomputed).
        from tse1m_tpu.cluster.store import SignatureStore

        store = SignatureStore.open_existing(args.sig_store)
        warm_stats.update(store.scrub())
        # Past-the-frame check (`store_scrub_verify_*`): sampled raw-row
        # recompute of stored signatures — the CRC frame only proves the
        # bytes have not rotted SINCE framing; corruption that predates
        # the frame is inherited as "correct" and only this catches it.
        warm_stats.update(store.verify_signatures(items, sample=256,
                                                  seed=args.seed))

    serve_stats = {}
    trace_races = 0
    if args.serve and args.traced:
        # The whole serving round (populate + daemon + TCP clients)
        # under the graftrace lockset detector: every instrumented
        # shared-state access is checked against the held-lock set.
        from tse1m_tpu.trace import traced

        with traced(raise_on_race=False) as tracer:
            serve_stats = bench_serve()
        trace_races = len(tracer.lockset.races)
        if trace_races:
            raise AssertionError(
                f"graftrace: {trace_races} data race(s) in the serving "
                "round:\n" + "\n".join(r.describe()
                                       for r in tracer.lockset.races))
    elif args.serve:
        serve_stats = bench_serve()

    sharded_stats = {}
    if args.serve_sharded:
        sharded_stats = bench_serve_sharded()

    topk_stats = {}
    if args.topk:
        topk_stats = bench_topk()

    trace_stats = {}
    if args.traced:
        # Bounded deterministic-schedule sweep over the serve/store
        # critical sections (seeded PCT + small-bound exhaustive); any
        # invariant violation raises with a replayable schedule string.
        from tse1m_tpu.trace.explore import explore as trace_explore

        n_sched = int(os.environ.get("BENCH_TRACE_SCHEDULES", "40"))
        explored = trace_explore("serve", n_seeded=n_sched,
                                 exhaustive_bound=3)
        explored_store = trace_explore("store",
                                       n_seeded=max(10, n_sched // 2),
                                       exhaustive_bound=3)
        total_explored = (explored["trace_schedules_explored"]
                          + explored_store["trace_schedules_explored"])
        if args.serve_sharded:
            # Sharded-plane interleaving classes (router vs. shard
            # writers; replica refresh vs. shard eviction).
            for scn in ("router", "replica"):
                total_explored += trace_explore(
                    scn, n_seeded=max(10, n_sched // 2),
                    exhaustive_bound=3)["trace_schedules_explored"]
        trace_stats = {
            "trace_schedules_explored": total_explored,
            "trace_races_found": trace_races,
        }

    scheme_stats = {}
    if args.schemes_round:
        scheme_stats = bench_schemes()

    ari = adjusted_rand_index(labels, truth)
    ari_host = None
    if args.ari_sample > 0:
        # Acceptance gate vs the host baseline (BASELINE.json: ARI >= 0.98):
        # cluster the same leading subsample independently on device and
        # host and compare labelings apples-to-apples.
        from tse1m_tpu.cluster import host_cluster

        k = min(args.ari_sample, args.n)
        dev_k = cluster_sessions(items[:k], params)
        host_k = host_cluster(items[:k], n_hashes=args.hashes,
                              n_bands=args.bands, seed=params.seed,
                              scheme=params.scheme)
        ari_host = round(adjusted_rand_index(dev_k, host_k), 5)

    result = {
        "metric": f"cluster_{args.n // 1000}k_sessions_wall_s",
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": round(60.0 / wall, 2),
        "best_s": round(min(runs), 4),
        "runs_s": [round(r, 4) for r in runs],
        # Kernel time with items device-resident (median of 3) — the
        # link-noise-free floor of `value`.
        "compute_only_s": (round(compute_s, 4)
                           if compute_s is not None else None),
        "ari_vs_planted": round(ari, 5),
        "n_sessions": args.n,
        "n_hashes": args.hashes,
        "n_bands": args.bands,
        "device": str(dev),
        "backend": jax.default_backend(),
    }
    if ari_host is not None:
        result["ari_vs_host_sample"] = ari_host
    # Encoding stats of the last timed run (cluster/encode.py): lane split,
    # wire bytes, host encode seconds — plus the per-stage walls and
    # overlap fraction (observability plane).
    result.update({f"cluster_{k}": v for k, v in cluster_info.items()})
    result.update(stage_info)
    result.update(v3_stats)
    result.update(transfer_stats)
    if wire_drift is not None:
        result["wire_drift_bytes"] = wire_drift
    result.update(warm_stats)
    result.update(serve_stats)
    result.update(sharded_stats)
    result.update(topk_stats)
    result.update(trace_stats)
    result.update(scheme_stats)
    result["scheme"] = params.scheme
    if sanitizer is not None:
        # Runtime-sanitizer proof for this bench round: the timed window
        # ran under the transfer guard (zero implicit H2D transfers, or it
        # would have raised) within the compile budget.
        result.update(sanitizer.as_dict())
    try:
        link_stats = bench_link()
        result.update(link_stats)
        # Persist the measured link rate to the machine calibration file
        # (utils/calibration.py): the NEXT run's StageWatchdog seeds its
        # adaptive H2D stall budget from this measurement instead of the
        # absolute floor — the bound tracks the link this machine has.
        from tse1m_tpu.utils.calibration import (calibration_path,
                                                 update_calibration)

        update_calibration(calibration_path(), wire={
            "h2d_MBps": link_stats["link_h2d_rand_MBps"]})
    except Exception as e:  # graftlint: disable=broad-except -- optional probe; bench JSON stays valid without it
        print(f"# link probe failed ({type(e).__name__}: {e})",
              file=sys.stderr)
    if args.extract_builds > 0:
        result.update(bench_extraction(args.extract_builds, seed=args.seed))
    # Degradation-ladder telemetry — part of the bench contract (CI's
    # fault-matrix smoke asserts these keys exist, and that they are
    # nonzero under the matching injected fault): every stall retry,
    # chunk halving, device failover and store quarantine this process
    # survived, by kind.  Last, so the extraction/RQ stages' events (e.g.
    # an auto-router device failover) count too.
    from tse1m_tpu.observability import (degradation_counts,
                                         pop_degradation_events)

    events = pop_degradation_events()
    counts = degradation_counts(events)
    result["degradation_events"] = len(events)
    result["degradation_counts"] = counts
    result["chunk_halvings"] = int(counts.get("chunk_halving", 0))
    # Telemetry-plane contract (CI asserts these keys on every round):
    # the round's pinned trace id + span count, and a flat scalar view
    # of the metrics registry (every key prefixed `metrics_`).
    from tse1m_tpu.observability.export import flat_metrics
    from tse1m_tpu.observability.tracing import pinned_trace, spans_recorded

    result["trace_id"] = pinned_trace()
    result["trace_spans_recorded"] = spans_recorded()
    result.update(flat_metrics())
    if args.profile:
        # graftprof artifact for the round: sampler aggregate, collapsed
        # stacks, per-site lock waits, slow-request captures — numbered
        # and atomic like the flight files.
        prof_path = profiling.dump_profile(
            extra={"round": result["metric"], "n": int(args.n)},
            d=os.environ.get("TSE1M_FLIGHT_DIR")
            or os.path.join("data", "result_data"))
        result["profile_path"] = prof_path
        profiling.stop_sampler()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
