"""North-star benchmark: cluster ~1M session coverage vectors on TPU.

Target (BASELINE.json / BASELINE.md): < 60 s wall on a TPU slice at
ARI >= 0.98 vs the host baseline.  Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
vs_baseline = 60 / wall_s, i.e. >1.0 beats the published target.

Runs on whatever jax.devices() offers (the driver provides one real chip);
first invocation pays the XLA compile, the timed run is steady-state.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=1_000_000)
    p.add_argument("--set-size", type=int, default=64)
    p.add_argument("--hashes", type=int, default=128)
    p.add_argument("--bands", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ari-sample", type=int, default=100_000,
                   help="if >0, also ARI-check a host-clustered subsample "
                        "(the BASELINE.json acceptance gate: >= 0.98 vs the "
                        "CPU/pandas baseline)")
    args = p.parse_args()

    import jax

    from tse1m_tpu.cluster import (ClusterParams, adjusted_rand_index,
                                   cluster_sessions)
    from tse1m_tpu.data.synth import synth_session_sets

    items, truth = synth_session_sets(args.n, set_size=args.set_size,
                                      seed=args.seed)
    dev = jax.devices()[0]
    params = ClusterParams(n_hashes=args.hashes, n_bands=args.bands)

    def run(prm):
        labels = cluster_sessions(items, prm)
        return labels

    try:
        run(params)  # compile + warm
        t0 = time.perf_counter()
        labels = run(params)
        wall = time.perf_counter() - t0
    except Exception as e:  # pallas path unavailable on this backend
        print(f"# pallas path failed ({type(e).__name__}: {e}); "
              "falling back to fused-jax", file=sys.stderr)
        params = ClusterParams(n_hashes=args.hashes, n_bands=args.bands,
                               use_pallas="never")
        run(params)
        t0 = time.perf_counter()
        labels = run(params)
        wall = time.perf_counter() - t0

    ari = adjusted_rand_index(labels, truth)
    ari_host = None
    if args.ari_sample > 0:
        # Acceptance gate vs the host baseline (BASELINE.json: ARI >= 0.98):
        # cluster the same leading subsample independently on device and
        # host and compare labelings apples-to-apples.
        from tse1m_tpu.cluster import host_cluster

        k = min(args.ari_sample, args.n)
        dev_k = cluster_sessions(items[:k], params)
        host_k = host_cluster(items[:k], n_hashes=args.hashes,
                              n_bands=args.bands, seed=params.seed)
        ari_host = round(adjusted_rand_index(dev_k, host_k), 5)

    result = {
        "metric": f"cluster_{args.n // 1000}k_sessions_wall_s",
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": round(60.0 / wall, 2),
        "ari_vs_planted": round(ari, 5),
        "n_sessions": args.n,
        "n_hashes": args.hashes,
        "n_bands": args.bands,
        "device": str(dev),
        "backend": jax.default_backend(),
    }
    if ari_host is not None:
        result["ari_vs_host_sample"] = ari_host
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
