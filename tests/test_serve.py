"""Online serving plane (tse1m_tpu/serve): live index parity, the
single-writer ingest daemon, lock-free query snapshots, store
reader/writer concurrency (generation counter + refresh), the TCP
transport, and the SLO/admission layer.

The load-bearing claims:

- post-quiesce membership answers are ELEMENTWISE equal to a cold batch
  run over the same session sequence (the daemon and the batch warm
  path share one LiveClusterIndex implementation);
- queries during ingest are consistent: an acknowledged row is always
  known, and its answer agrees with the final labels' partition;
- a reader handle opened before an append either keeps a consistent
  older generation or adopts the newer one with one cheap `refresh()`;
- the query hot path is host-only (sanitizer: zero implicit transfers,
  zero compiles).
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from tse1m_tpu.cluster import ClusterParams, cluster_sessions, host_cluster
from tse1m_tpu.cluster.host import host_band_keys, host_signatures
from tse1m_tpu.cluster.incremental import LiveClusterIndex
from tse1m_tpu.cluster.minhash import make_hash_params
from tse1m_tpu.cluster.store import SignatureStore, row_digests
from tse1m_tpu.data.synth import synth_session_sets
from tse1m_tpu.serve import (IngestRejected, ServeClient, ServeDaemon,
                             ServeServer, SloPolicy)

PARAMS = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
POLICY = {"n_hashes": 32, "seed": 0, "quant_bits": 0}


def _items(n=600, seed=3, set_size=64):
    return synth_session_sets(n, set_size=set_size, seed=seed)[0]


def _unique_items(n, seed=3):
    """Content-distinct rows (no planted duplicates) — for store tests
    that fabricate one signature per row; the content-addressed store
    would collapse duplicate rows onto the first one's signature."""
    return synth_session_sets(n, set_size=64, seed=seed,
                              dup_fraction=0.0)[0]


def _start_daemon(tmp_path, name="store", **kw):
    return ServeDaemon(str(tmp_path / name), params=PARAMS, **kw).start()


# -- LiveClusterIndex ---------------------------------------------------------

def test_live_index_absorb_matches_batch_labels():
    items = _items(500)
    a, b = make_hash_params(PARAMS.n_hashes, PARAMS.seed)
    sigs = host_signatures(items, a, b)
    keys = host_band_keys(sigs, PARAMS.n_bands)
    idx = LiveClusterIndex.empty(PARAMS.n_bands)
    for lo in range(0, 500, 100):
        blk = slice(lo, lo + 100)
        idx = idx.absorb(
            keys[blk], sigs[blk], lambda u: sigs[u],
            PARAMS.n_hashes, PARAMS.threshold,
            new_digests=row_digests(items[blk]))
        assert idx.generation == lo // 100 + 1
    cold = host_cluster(items, n_hashes=PARAMS.n_hashes,
                        n_bands=PARAMS.n_bands, seed=PARAMS.seed)
    assert np.array_equal(idx.labels, cold)
    # digest membership: every ingested row resolves to itself-or-first
    hit, row = idx.lookup_digests(row_digests(items))
    assert hit.all()
    assert np.array_equal(idx.labels[row], idx.labels)


def test_live_index_snapshots_are_immutable_under_absorb():
    items = _items(200)
    a, b = make_hash_params(PARAMS.n_hashes, PARAMS.seed)
    sigs = host_signatures(items, a, b)
    keys = host_band_keys(sigs, PARAMS.n_bands)
    idx0 = LiveClusterIndex.empty(PARAMS.n_bands)
    idx1 = idx0.absorb(keys[:100], sigs[:100], lambda u: sigs[u],
                       PARAMS.n_hashes, PARAMS.threshold,
                       new_digests=row_digests(items[:100]))
    labels1 = idx1.labels.copy()
    tables1 = [k.copy() for k in idx1.band_keys_sorted]
    idx2 = idx1.absorb(keys[100:], sigs[100:], lambda u: sigs[u],
                       PARAMS.n_hashes, PARAMS.threshold,
                       new_digests=row_digests(items[100:]))
    assert idx2.generation == idx1.generation + 1
    assert np.array_equal(idx1.labels, labels1)
    for k, want in zip(idx1.band_keys_sorted, tables1):
        assert np.array_equal(k, want)


def test_live_index_query_semantics():
    items = _items(300)
    a, b = make_hash_params(PARAMS.n_hashes, PARAMS.seed)
    sigs = host_signatures(items, a, b)
    keys = host_band_keys(sigs, PARAMS.n_bands)
    idx = LiveClusterIndex.empty(PARAMS.n_bands).absorb(
        keys, sigs, lambda u: sigs[u], PARAMS.n_hashes, PARAMS.threshold,
        new_digests=row_digests(items))
    # a copy of row 7 with one element flipped lands in row 7's cluster
    mut = items[7:8].copy()
    mut[0, 0] ^= 1
    qs = host_signatures(mut, a, b)
    qk = host_band_keys(qs, PARAMS.n_bands)
    got = idx.query_labels(qs, qk, lambda u: sigs[u],
                           PARAMS.n_hashes, PARAMS.threshold)
    assert got[0] == idx.labels[7]
    # a genuinely novel vector reads as a new singleton (-1)
    nov = synth_session_sets(1, set_size=64, seed=991)[0]
    ns = host_signatures(nov, a, b)
    nk = host_band_keys(ns, PARAMS.n_bands)
    assert idx.query_labels(ns, nk, lambda u: sigs[u],
                            PARAMS.n_hashes, PARAMS.threshold)[0] == -1


# -- store generation counter / reader refresh (satellite) --------------------

def test_store_generation_counts_layout_changes_only(tmp_path):
    store = SignatureStore(str(tmp_path / "s"), POLICY)
    assert store.generation == 0
    items = _unique_items(64)
    d = row_digests(items)
    sigs = np.ones((64, 32), np.uint32)
    store.append(d, sigs)
    assert store.generation == 1
    # probing (LRU stamps) rewrites nothing layout-shaped
    store.bulk_probe(d)
    gen = store.generation
    store.append(d, sigs)  # all-duplicate append: no new shard
    assert store.generation == gen


def test_probe_during_append_reader_consistency(tmp_path):
    """The satellite regression: a reader opened BEFORE an append keeps
    answering from its (consistent) older generation, and one cheap
    refresh() adopts the newer one."""
    path = str(tmp_path / "s")
    writer = SignatureStore(path, POLICY)
    items = _unique_items(256)
    d = row_digests(items)
    sigs = np.arange(256 * 32, dtype=np.uint32).reshape(256, 32)
    writer.append(d[:128], sigs[:128])
    reader = SignatureStore(path, POLICY, read_only=True)
    hit0, sh0, rw0 = reader.bulk_probe(d)
    assert hit0[:128].all() and not hit0[128:].any()
    # concurrent append by the single writer
    writer.append(d[128:], sigs[128:])
    # un-refreshed reader: same consistent older view, gathers still work
    hit1, sh1, rw1 = reader.bulk_probe(d)
    assert np.array_equal(hit0, hit1)
    assert np.array_equal(reader.load_signatures(sh1[:128], rw1[:128]),
                          sigs[:128])
    # no-op refresh is cheap and idempotent when nothing changed
    assert reader.refresh() is True   # adopt the append
    assert reader.refresh() is False  # nothing new now
    hit2, sh2, rw2 = reader.bulk_probe(d)
    assert hit2.all()
    assert np.array_equal(reader.load_signatures(sh2, rw2), sigs)
    assert reader.generation == writer.generation


def test_reader_refresh_survives_compaction(tmp_path):
    path = str(tmp_path / "s")
    writer = SignatureStore(path, POLICY)
    items = _unique_items(300)
    d = row_digests(items)
    sigs = np.arange(300 * 32, dtype=np.uint32).reshape(300, 32)
    for lo in range(0, 300, 100):
        writer.append(d[lo:lo + 100], sigs[lo:lo + 100])
    reader = SignatureStore(path, POLICY, read_only=True)
    writer.compact()
    assert reader.refresh() is True
    hit, sh, rw = reader.bulk_probe(d)
    assert hit.all()
    assert np.array_equal(reader.load_signatures(sh, rw), sigs)


# -- daemon: ingest + query ---------------------------------------------------

def test_daemon_parity_and_restart(tmp_path):
    items = _items(600)
    dm = _start_daemon(tmp_path)
    try:
        for lo in range(0, 600, 150):
            r = dm.ingest(items[lo:lo + 150], timeout=300)
            assert r["ok"] and r["acked"] == 150
        dm.quiesce(timeout=300)
        cold = cluster_sessions(items, PARAMS)
        res = dm.query(items)
        assert res["known"].all()
        assert np.array_equal(res["labels"], cold)
        # batch `cluster` against the SAME store is one more client of
        # the same index code: warm merge reproduces the daemon's view
        from dataclasses import replace

        warm = cluster_sessions(
            items, replace(PARAMS, sig_store=str(tmp_path / "store")))
        assert np.array_equal(warm, cold)
    finally:
        dm.stop()
    dm2 = ServeDaemon(str(tmp_path / "store"), params=PARAMS)
    res2 = dm2.query(items)
    assert res2["known"].all()
    assert np.array_equal(res2["labels"], cluster_sessions(items, PARAMS))


def test_daemon_recovers_acked_rows_without_state(tmp_path):
    """State commits lag acks; a crash between append and state commit
    must still serve every acknowledged row after restart (content-level
    recovery from the store)."""
    items = _items(400, seed=11)
    dm = _start_daemon(tmp_path, state_commit_every=10**6)
    try:
        for lo in range(0, 400, 100):
            dm.ingest(items[lo:lo + 100], timeout=300)
    finally:
        dm.stop(commit=False)  # crash-shaped: acked, state never written
    dm2 = ServeDaemon(str(tmp_path / "store"), params=PARAMS)
    res = dm2.query(items)
    assert res["known"].all(), "acknowledged rows lost without state"
    # recovered labels form the same partition as a cold batch run
    from tse1m_tpu.cluster import adjusted_rand_index

    cold = cluster_sessions(items, PARAMS)
    assert adjusted_rand_index(res["labels"], cold) == pytest.approx(1.0)


def test_concurrent_ingest_query_consistency(tmp_path):
    """Queries DURING ingest: acked rows are always known and their
    answers agree with the final partition; after quiesce the whole
    sequence equals the cold batch labels elementwise.  Runs under the
    graftrace lockset detector (``traced()``, the tier-1 race-check
    wiring): any instrumented shared-state access whose candidate
    lockset goes empty fails the test with both stacks."""
    from tse1m_tpu.trace import traced

    items = _items(800, seed=5)
    dm = _start_daemon(tmp_path)
    acked = [0]
    observed: list[tuple[int, int]] = []  # (row, label at query time)
    errors: list = []
    done = threading.Event()

    def querier():
        rng = np.random.default_rng(17)
        try:
            while not done.is_set():
                hi = acked[0]
                if hi == 0:
                    continue
                i = int(rng.integers(0, hi))
                res = dm.query(items[i:i + 1])
                if not res["known"][0]:
                    raise AssertionError(f"acked row {i} unknown")
                observed.append((i, int(res["labels"][0])))
        except Exception as e:  # noqa: BLE001 — relayed to the main thread below
            errors.append(e)

    threads = [threading.Thread(target=querier) for _ in range(2)]
    with traced():
        try:
            for t in threads:
                t.start()
            for lo in range(0, 800, 80):
                dm.ingest(items[lo:lo + 80], timeout=300)
                acked[0] = lo + 80
            dm.quiesce(timeout=300)
        finally:
            done.set()
            for t in threads:
                t.join(timeout=60)
            dm.stop()
    assert not errors, errors[0]
    assert observed, "queriers never ran"
    cold = cluster_sessions(items, PARAMS)
    final = dm.query(items)
    assert np.array_equal(final["labels"], cold)
    # a label observed mid-ingest is the min-index of the row's cluster
    # at that generation; merging can only LOWER it, and the final
    # cluster must contain it (labels are row indices)
    # A label observed mid-ingest is the min-index of the row's cluster
    # at that generation; later merges can only LOWER a row's label
    # (union-by-min), and the observed hub row must share the final
    # cluster with the queried row.
    for i, lab in observed:
        assert int(final["labels"][i]) <= lab
        assert final["labels"][lab] == final["labels"][i]


def test_query_hot_path_sanitizer_clean(tmp_path):
    from tse1m_tpu.lint.runtime import sanitized

    items = _items(300, seed=9)
    dm = _start_daemon(tmp_path)
    try:
        dm.ingest(items, timeout=300)
        dm.query(items[:1])  # warm numpy internals
        nov = synth_session_sets(8, set_size=64, seed=997)[0]
        with sanitized(0):
            res = dm.query(items[:64])
            resn = dm.query(nov)  # novel path: host minhash + verify
        assert res["known"].all() and not resn["known"].any()
    finally:
        dm.stop()


# -- SLO / admission ----------------------------------------------------------

def test_backpressure_and_backlog_accounting(tmp_path):
    items = _items(60, seed=21)
    dm = ServeDaemon(str(tmp_path / "store"), params=PARAMS,
                     slo=SloPolicy(max_backlog_batches=2))
    # ingest thread NOT started: the queue can only fill
    dm.submit(items[:20])
    dm.submit(items[20:40])
    with pytest.raises(IngestRejected) as exc:
        dm.submit(items[40:])
    assert exc.value.retry_after_s > 0
    stats = dm.admission.stats()
    assert stats["ingest_rejected"] == 1
    assert stats["ingest_backlog_max"] >= 2
    from tse1m_tpu.observability import peek_degradation_events

    kinds = [e["kind"] for e in peek_degradation_events()]
    assert "serve_backpressure" in kinds
    # draining the queue re-admits
    dm.start()
    try:
        dm.quiesce(timeout=300)
        r = dm.ingest(items[40:], timeout=300)
        assert r["ok"]
    finally:
        dm.stop()


def test_slo_violation_counter(tmp_path):
    dm = ServeDaemon(str(tmp_path / "store"), params=PARAMS,
                     slo=SloPolicy(query_p99_target_ms=0.0))
    try:
        dm.tracker.observe_query(0.5)
        dm.tracker.observe_query(0.5)
        st = dm.status()
        assert st["query_slo_violations"] == 2
    finally:
        dm.stop(commit=False)


def test_request_budgets_env(monkeypatch):
    from tse1m_tpu.resilience.watchdog import request_budget_s

    assert request_budget_s("query") == pytest.approx(0.25)
    monkeypatch.setenv("TSE1M_SERVE_QUERY_BUDGET_S", "1.5")
    assert request_budget_s("query") == pytest.approx(1.5)
    monkeypatch.setenv("TSE1M_WATCHDOG", "0")
    assert request_budget_s("query") == 0.0


def test_latency_recorder_percentiles():
    from tse1m_tpu.observability.latency import LatencyRecorder

    rec = LatencyRecorder("serve_query")
    for ms in range(1, 101):
        rec.add(ms / 1e3)
    snap = rec.snapshot()
    assert snap["count"] == 100
    assert 35 <= snap["p50_ms"] <= 70
    assert 85 <= snap["p99_ms"] <= 115
    assert snap["max_ms"] >= 95
    s = rec.summary()
    assert "serve_query_p99_ms" in s and "serve_query_qps" in s
    rec.reset_window()
    assert rec.snapshot()["count"] == 0


# -- TCP transport ------------------------------------------------------------

def test_tcp_roundtrip_and_status(tmp_path):
    items = _items(300, seed=8)
    dm = _start_daemon(tmp_path)
    server = ServeServer(dm, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        with ServeClient(port=server.port) as c:
            assert c.ping()["ok"]
            r = c.ingest(items, timeout_s=300)
            assert r["ok"] and r["acked"] == 300
            q = c.query(items[:10], timeout_s=60)
            assert q["known"].all()
            assert np.array_equal(
                q["labels"], dm.query(items[:10])["labels"])
            assert c.quiesce(timeout_s=300)["ok"]
            st = c.status()
            for key in ("rows", "generation", "queue_depth",
                        "ingest_backlog_max", "last_scrub",
                        "serve_query_p99_ms", "serve_ingest_p99_ms",
                        "query_slo_violations"):
                assert key in st, key
            assert st["rows"] == 300
            assert st["generation"] >= 1
            c.shutdown()
    finally:
        server.server_close()
        dm.stop()


def test_cli_serve_status_records_manifest(tmp_path, monkeypatch):
    """`tse1m serve --status` is a client ping recorded through
    StepRunner into run_manifest.json (the satellite contract)."""
    import json

    from tse1m_tpu import cli

    items = _items(120, seed=14)
    dm = _start_daemon(tmp_path)
    server = ServeServer(dm, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    result_dir = tmp_path / "results"
    monkeypatch.setenv("TSE1M_RESULT_DIR", str(result_dir))
    try:
        dm.ingest(items, timeout=300)
        rc = cli.main(["serve", "--status", "--port", str(server.port)])
        assert rc == 0
        manifest = json.loads(
            (result_dir / "run_manifest.json").read_text())
        steps = {s["name"]: s for s in manifest["steps"]}
        assert steps["serve_status"]["status"] == "ok"
        res = steps["serve_status"]["result"]
        assert res["rows"] == 120
        assert "generation" in res and "queue_depth" in res
        assert "last_scrub" in res
    finally:
        server.shutdown()
        server.server_close()
        dm.stop()
