"""CI fault-plan matrix driver: one injected failure class over the 2k
bench smoke, asserting the degradation contract end to end.

Usage: ``python tests/ci_fault_matrix.py
{stall|oom|kill|corrupt-shard|hostloss|heartbeat-timeout}``

Each seat runs ``bench.py`` (2k sessions, CPU, runtime sanitizer ON,
persistent signature store) with a fault plan injected at a production
seat, then asserts:

- the bench completes (the degradation ladder absorbed the failure),
- label parity held (``ari_vs_planted`` >= 0.98 AND the bench's internal
  warm-vs-cold elementwise assert — bench.py raises if warm labels
  diverge),
- the bench JSON carries the ``degradation_events`` /
  ``degradation_counts`` / ``chunk_halvings`` / ``store_scrub_*`` keys,
  with the seat's own counter nonzero.

The ``kill`` seat SIGKILLs the first invocation mid store-shard write and
asserts the rerun sweeps the torn temps and recovers parity — the
degraded evidence there is the kill itself (rc -9) plus a clean resume.

The pod seats run a REAL 2-process mesh (tests/pod_harness.py):
``hostloss`` wedges worker 1 (alive but silent — heartbeats suspended),
``heartbeat-timeout`` SIGKILLs it; both assert the survivor fails over
with the lost host's digest range reassigned, labels elementwise-equal
to an uninterrupted run, and the loss counted in the merged
run_manifest.json.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The fault-context key contract lives in the shared machine-readable
# schema (observability/regress.py BENCH_SCHEMA) — the same source of
# truth the bench-smoke and serve-smoke heredocs import, so a renamed
# key fails every job by name instead of drifting one inventory.
from tse1m_tpu.observability.regress import required_keys  # noqa: E402

BENCH_KEYS = required_keys("fault")

# The machine-checked seat inventory (graftlint ``fault-seat-drift``):
# every ``fault_point(...)`` seat in production code must have an entry
# here — naming the fault kinds exercised against it and the test that
# covers it — and every entry must correspond to a live seat.  Adding a
# seat without a matrix entry, leaving a dead entry behind, or listing a
# kind resilience/faults.py does not implement fails lint (and the CI
# fault-matrix job runs that rule before any seat).  Seats this driver
# injects directly draw their site strings from this table via
# ``plan_rule`` so the inventory cannot drift from the plans.
PRODUCTION_SEATS = {
    "http.fetch": {
        "kinds": ("raise", "connection_drop", "delay"),
        "covered_by": "tests/test_resilience.py (HttpFetcher retry/"
                      "Retry-After under injected faults)"},
    "db.connect": {
        "kinds": ("raise", "connection_drop"),
        "covered_by": "tests/test_db.py (reconnect-on-drop)"},
    "db.execute": {
        "kinds": ("raise", "connection_drop", "delay"),
        "covered_by": "tests/test_db.py (statement retry / transaction "
                      "units)"},
    "pglib.exec": {
        "kinds": ("connection_drop",),
        "covered_by": "tests/test_pglib.py (disconnect classification)"},
    "checkpoint.csv.flush": {
        "kinds": ("torn_write", "kill"),
        "covered_by": "tests/test_chaos.py (SIGKILL mid-batch resume)"},
    "checkpoint.cluster.save": {
        "kinds": ("torn_write", "kill"),
        "covered_by": "tests/test_cluster_checkpoint.py + chaos drivers "
                      "(torn-shard detection on resume)"},
    "store.sig.save": {
        "kinds": ("kill", "torn_write", "raise"),
        "covered_by": "this matrix (seat `kill`) + "
                      "tests/test_cluster_store.py"},
    "store.compact.save": {
        "kinds": ("kill",),
        "covered_by": "tests/test_cluster_store.py (SIGKILL "
                      "mid-compaction chaos)"},
    "store.state.save": {
        "kinds": ("kill", "torn_write"),
        "covered_by": "tests/test_cluster_store.py (state-commit kill -> "
                      "union fallback)"},
    "pipeline.h2d": {
        "kinds": ("stall", "raise", "hostloss", "zombie", "kill"),
        "covered_by": "this matrix (seats `stall`, `oom`, `hostloss`, "
                      "`zombie`, `heartbeat-timeout`)"},
    "pipeline.compute": {
        "kinds": ("stall",),
        "covered_by": "tests/test_watchdog_degradation.py (compute-stall "
                      "cancel+retry)"},
    "serve.ingest.commit": {
        "kinds": ("kill", "raise"),
        "covered_by": "this matrix (seat `serve-kill`) + "
                      "tests/test_serve_chaos.py (SIGKILL mid-ingest: "
                      "zero lost acknowledged rows)"},
    "backend.device.call": {
        "kinds": ("raise", "stall"),
        "covered_by": "tests/test_backend_auto.py (host-oracle re-run + "
                      "device demotion)"},
    "serve.router.forward": {
        "kinds": ("connection_drop",),
        "covered_by": "this matrix (seat `router-shard-kill`) + "
                      "tests/test_serve_sharded.py (dropped ack replayed "
                      "by request id: full ack, zero double-absorb)"},
    "serve.replica.stream": {
        "kinds": ("kill",),
        "covered_by": "this matrix (seat `replica-refresh-kill`): SIGKILL "
                      "mid-pull leaves the manifest uncommitted; the "
                      "replica stays on its last adopted generation and "
                      "the next pull converges"},
}


def plan_rule(site: str, **kw) -> dict:
    """A fault-plan rule whose site must be in PRODUCTION_SEATS — the
    inventory is load-bearing for the matrix's own plans."""
    assert site in PRODUCTION_SEATS, \
        f"{site} missing from PRODUCTION_SEATS"
    return {"site": site, **kw}


def run_bench(store: str, plan: dict | None = None, env_extra: dict | None
              = None, expect_kill: bool = False) -> dict | None:
    env = dict(os.environ)
    env.update({"BENCH_N": "2000", "BENCH_ITERS": "1",
                "BENCH_EXTRACT_BUILDS": "0", "BENCH_SANITIZE": "1",
                # headroom for shapes the degradation ladder introduces
                # (a halved chunk is a new compile) — the guard still
                # catches an unbounded recompile loop
                "BENCH_COMPILE_BUDGET": "16",
                "BENCH_SIG_STORE": store, "JAX_PLATFORMS": "cpu"})
    env.pop("TSE1M_FAULT_PLAN", None)
    if plan is not None:
        plan_path = tempfile.mktemp(suffix=".json")
        with open(plan_path, "w") as f:
            json.dump(plan, f)
        env["TSE1M_FAULT_PLAN"] = plan_path
    env.update(env_extra or {})
    proc = subprocess.run([sys.executable, "bench.py"], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=1200)
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL, got rc={proc.returncode}\n{proc.stderr[-2000:]}")
        return None
    assert proc.returncode == 0, (
        f"bench rc={proc.returncode}\n{proc.stderr[-4000:]}")
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    for key in BENCH_KEYS:
        assert key in result, f"bench JSON lost key {key}"
    assert result["ari_vs_planted"] >= 0.98, result["ari_vs_planted"]
    assert result["sanitizer_transfer_guard"] is True
    return result


def seat_stall(store: str) -> dict:
    plan = {"rules": [plan_rule("pipeline.h2d", kind="stall",
                                stall_s=3.0, times=1)]}
    r = run_bench(store, plan,
                  env_extra={"TSE1M_WATCHDOG_MIN_BUDGET_S": "0.5"})
    assert r["degradation_counts"].get("stall_retry", 0) >= 1, r
    assert r["degradation_events"] >= 1, r
    return r


def seat_oom(store: str) -> dict:
    # Three RESOURCE_EXHAUSTED hits walk the whole ladder (quant 10 ->
    # quant 8 -> chunk halving) with BOTH wire-v3 levers forced: the
    # prefilter's raw-space keep mask must survive the width drops, and
    # the rANS codec must re-encode every re-packed chunk at the
    # surviving width — the bench's internal parity asserts (ARI gate +
    # warm-vs-cold elementwise) prove labels held through all of it.
    plan = {"rules": [plan_rule("pipeline.h2d", kind="raise",
                                message="RESOURCE_EXHAUSTED: injected "
                                        "1GiB allocation failure",
                                times=3)]}
    r = run_bench(store, plan, env_extra={"BENCH_PREFILTER": "on",
                                          "BENCH_ENTROPY": "force"})
    assert r["chunk_halvings"] >= 1, r
    assert r["degradation_counts"].get("chunk_halving", 0) >= 1, r
    assert r["degradation_counts"].get("quant_drop", 0) >= 1, r
    assert r["prefilter_rows_dropped"] > 0, r
    assert r["prefilter_recall"] == 1.0, r
    assert r["stage_entropy_s"] > 0, r
    return r


def seat_kill(store: str) -> dict:
    plan = {"rules": [plan_rule("store.sig.save", kind="kill")]}
    run_bench(store, plan, expect_kill=True)
    # the kill stranded torn temp shards; the rerun must sweep them,
    # recompute, and recover full parity (bench's internal warm assert)
    assert glob.glob(os.path.join(store, "*.tmp.npy")), \
        "kill left no torn temps — the seat did not fire mid-write"
    r = run_bench(store)
    assert not glob.glob(os.path.join(store, "*.tmp.npy")), \
        "torn temps survived the on-open orphan sweep"
    assert r["store_scrub_corrupt"] == 0, r
    return r


def seat_corrupt_shard(store: str) -> dict:
    r = run_bench(store)  # populate a committed, CRC-framed store
    shards = sorted(glob.glob(os.path.join(store, "sig_*.npy")))
    assert shards, "populate run committed no shards"
    with open(shards[0], "r+b") as f:  # flip one byte mid-shard
        f.seek(os.path.getsize(shards[0]) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0x40]))
    r = run_bench(store)
    # detected on load, quarantined, recomputed — never wrong labels
    # (run_bench already asserted ARI and bench asserted warm parity)
    assert r["degradation_counts"].get("shard_quarantine", 0) >= 1, r
    assert r["store_scrub_quarantined"] >= 1, r
    return r


def _pod_loss_seat(plan: dict, expect_rc1: tuple) -> dict:
    """Shared body of the two pod-scale seats: a REAL 2-process mesh run
    (tests/pod_harness.py -> chaos_drivers ``pod``) with the given fault
    plan installed in worker 1, asserting the MapReduce failover
    contract — the survivor's labels equal an uninterrupted run
    ELEMENTWISE, the lost host's digest range was reassigned, and the
    merged run_manifest.json counts the loss."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import numpy as np
    from pod_harness import cold_labels, spawn_pod

    with tempfile.TemporaryDirectory() as tmp:
        cold = cold_labels(tmp, n=800, seed=13)
        store = os.path.join(tmp, "store")
        rdir = os.path.join(tmp, "results")
        res = spawn_pod(tmp, store, rdir, n=800, seed=13, plans={1: plan})
        assert res[1]["rc"] in expect_rc1, (
            f"worker 1 rc={res[1]['rc']}, wanted one of {expect_rc1}\n"
            + res[1]["err"][-2000:])
        assert res[0]["rc"] == 0, res[0]["err"][-4000:]
        assert np.array_equal(res[0]["labels"], cold), \
            "failover labels diverged from the uninterrupted run"
        info = res[0]["info"]
        assert info["pod_survivor"] == 0 and info["pod_lost"] == [1], info
        assert 1 in info["pod_reassigned_ranges"], info
        merged = json.load(open(os.path.join(rdir, "run_manifest.json")))
        counts = merged["degradation_counts"]
        for kind in ("host_lost", "pod_failover",
                     "shard_range_reassigned"):
            assert counts.get(kind, 0) >= 1, (kind, counts)
        assert merged["pod"]["missing"] == [1], merged["pod"]
        return {"ari_vs_planted": 1.0,
                "degradation_events": sum(counts.values()),
                "degradation_counts": counts, "chunk_halvings": 0,
                "store_scrub_corrupt": 0, "store_scrub_quarantined": 0}


def seat_hostloss(store: str) -> dict:
    """A WEDGED host: alive but silent (the ``hostloss`` fault kind
    suspends its pod heartbeats then sleeps at pipeline.h2d).  Peers
    declare it lost through the production heartbeat monitor; the
    harness SIGKILLs the zombie afterwards — the fencing a real
    scheduler provides."""
    from pod_harness import SIGKILL, WEDGE_WORKER_PLAN

    # The zombie dies one of two ways, both fencing: the harness's
    # SIGKILL, or SIGABRT from its own XLA client once the exited
    # leader's coordination service socket closes.
    return _pod_loss_seat(WEDGE_WORKER_PLAN,
                          expect_rc1=(SIGKILL, -signal.SIGABRT))


def seat_heartbeat_timeout(store: str) -> dict:
    """A DEAD host: SIGKILL mid-MinHash; its heartbeat file stops
    advancing and the peer monitor times it out — the same detection
    path as hostloss, reached through actual process death."""
    from pod_harness import KILL_WORKER_PLAN, SIGKILL

    return _pod_loss_seat(KILL_WORKER_PLAN, expect_rc1=(SIGKILL,))


def seat_zombie(store: str) -> dict:
    """A wedged writer that WAKES after its range was reassigned: the
    zombie must self-fence on its superseded epoch lease — zero appends
    to the old range, a ``lease_superseded`` degradation in its own
    fragment — while the survivor's labels stay elementwise-equal to an
    uninterrupted run."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import numpy as np
    from pod_harness import (SIGKILL, cold_labels, make_zombie_waker,
                             spawn_pod, zombie_plan)

    with tempfile.TemporaryDirectory() as tmp:
        cold = cold_labels(tmp, n=800, seed=13)
        store_dir = os.path.join(tmp, "store")
        rdir = os.path.join(tmp, "results")
        wake = os.path.join(tmp, "wake_zombie")
        res = spawn_pod(tmp, store_dir, rdir, n=800, seed=13,
                        plans={1: zombie_plan(wake)},
                        expect_finish=(0, 1), straggler_timeout=240,
                        on_poll=make_zombie_waker(store_dir, wake))
        assert res[0]["rc"] == 0, res[0]["err"][-4000:]
        assert np.array_equal(res[0]["labels"], cold), \
            "failover labels diverged from the uninterrupted run"
        info = res[0]["info"]
        assert info["pod_survivor"] == 0 and info["pod_lost"] == [1], info
        assert 1 in info["pod_reassigned_ranges"], info
        # the woken zombie fenced: nonzero exit, no labels, and the
        # lease_superseded event countable in its own fragment
        assert res[1]["rc"] not in (0, SIGKILL), (
            f"zombie rc={res[1]['rc']} — it must wake and self-fence, "
            "not succeed or be killed wedged\n" + res[1]["err"][-2000:])
        assert res[1]["labels"] is None, \
            "fenced zombie must abandon the run, not emit labels"
        frag = json.load(open(os.path.join(
            rdir, "run_manifest.p001.json")))
        counts1 = frag["degradation_counts"]
        assert counts1.get("lease_superseded", 0) >= 1, counts1
        # Flight recorder: the fencing itself leaves a black box next to
        # the manifest fragments, its terminal span naming the fenced
        # range — parseable post-mortem evidence beyond the counters.
        fence_flights = [json.load(open(p)) for p in sorted(
            glob.glob(os.path.join(rdir, "flight_*.json")))]
        fenced = [fl for fl in fence_flights
                  if fl["reason"] == "lease_superseded"]
        assert fenced, [fl["reason"] for fl in fence_flights]
        assert fenced[-1]["spans"][-1]["name"] == \
            "flight.lease_superseded", fenced[-1]["spans"][-1]
        merged = json.load(open(os.path.join(rdir, "run_manifest.json")))
        counts = merged["degradation_counts"]
        for kind in ("host_lost", "pod_failover", "epoch_advance"):
            assert counts.get(kind, 0) >= 1, (kind, counts)
        return {"ari_vs_planted": 1.0,
                "degradation_events": sum(counts.values())
                + counts1.get("lease_superseded", 0),
                "degradation_counts": {**counts,
                                       "lease_superseded":
                                       counts1.get("lease_superseded")},
                "chunk_halvings": 0, "store_scrub_corrupt": 0,
                "store_scrub_quarantined": 0}


def seat_leader_loss_promote(store: str) -> dict:
    """SIGKILL the LEADER mid-run: worker 1 must promote itself over
    the shared-filesystem plane (no XLA coordination client exists to
    fatal it), advance the epoch, re-execute solo with labels
    elementwise-equal to an uninterrupted run, and write the ONE merged
    run_manifest.json — no respawn."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import numpy as np
    from pod_harness import KILL_WORKER_PLAN, SIGKILL, cold_labels, \
        spawn_pod

    with tempfile.TemporaryDirectory() as tmp:
        cold = cold_labels(tmp, n=800, seed=13)
        store_dir = os.path.join(tmp, "store")
        rdir = os.path.join(tmp, "results")
        res = spawn_pod(tmp, store_dir, rdir, n=800, seed=13,
                        plans={0: KILL_WORKER_PLAN}, expect_finish=(1,))
        assert res[0]["rc"] == SIGKILL, res[0]["rc"]
        assert res[1]["rc"] == 0, res[1]["err"][-4000:]
        assert np.array_equal(res[1]["labels"], cold), \
            "promoted-leader labels diverged from the uninterrupted run"
        info = res[1]["info"]
        assert info["pod_survivor"] == 1 and info["pod_lost"] == [0], info
        assert info["pod_promoted_leader"] is True, info
        assert 0 in info["pod_reassigned_ranges"], info
        merged = json.load(open(os.path.join(rdir, "run_manifest.json")))
        counts = merged["degradation_counts"]
        for kind in ("host_lost", "pod_failover", "leader_promoted",
                     "epoch_advance", "shard_range_reassigned"):
            assert counts.get(kind, 0) >= 1, (kind, counts)
        assert merged["pod"]["missing"] == [0], merged["pod"]
        return {"ari_vs_planted": 1.0,
                "degradation_events": sum(counts.values()),
                "degradation_counts": counts, "chunk_halvings": 0,
                "store_scrub_corrupt": 0, "store_scrub_quarantined": 0}


def seat_serve_kill(store: str) -> dict:
    """Serving plane: SIGKILL the ingest daemon mid-batch at the
    ``serve.ingest.commit`` production seat (before the store append
    commits), then assert the durability contract — the restarted
    daemon serves every ACKNOWLEDGED row (zero lost), the killed batch
    recomputes on re-ingest, and post-quiesce membership answers equal
    a cold batch run elementwise (tests/serve_harness.py)."""
    plan_rule("serve.ingest.commit", kind="kill")  # inventory-checked
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from serve_harness import serve_kill_round

    with tempfile.TemporaryDirectory() as tmp:
        r = serve_kill_round(tmp)
    assert r["lost_acked"] == 0, r
    return {"ari_vs_planted": 1.0, "degradation_events": 0,
            "degradation_counts": {"serve_kill_acked":
                                   r["acked_before_kill"]},
            "chunk_halvings": 0, "store_scrub_corrupt": 0,
            "store_scrub_quarantined": 0}


def seat_router_shard_kill(store: str) -> dict:
    """Sharded serving plane: SIGKILL one digest-range shard writer at
    its ``serve.ingest.commit`` seat while the parent ingests through a
    ShardRouter over TCP; a watcher respawns the replacement (next
    lease epoch) and the router's retried in-flight slice — SAME
    request id — lands on it.  Asserts ZERO lost acked rows, zero
    double-absorbed batches, and labels elementwise-equal to an
    uninterrupted sharded run (tests/serve_harness.py
    ``sharded_kill_round``; the drop-window half of the contract is the
    ``serve.router.forward`` seat, replay-tested in
    tests/test_serve_sharded.py)."""
    plan_rule("serve.ingest.commit", kind="kill")  # inventory-checked
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from serve_harness import sharded_kill_round

    with tempfile.TemporaryDirectory() as tmp:
        r = sharded_kill_round(tmp)
    assert r["lost_acked"] == 0, r
    assert r["rows"] == r["oracle_rows"], r
    return {"ari_vs_planted": 1.0, "degradation_events": 0,
            "degradation_counts": {
                "router_failover_batches": r["acked_batches"],
                "router_replayed_acks": r["replayed_acks"]},
            "chunk_halvings": 0, "store_scrub_corrupt": 0,
            "store_scrub_quarantined": 0}


def seat_replica_refresh_kill(store: str) -> dict:
    """Replication plane: SIGKILL the puller at ``serve.replica.stream``
    — shard files copied, manifest NOT yet committed.  The replica must
    stay on its last ADOPTED generation (no torn view: refresh() adopts
    only committed manifests), and the next clean pull converges to
    staleness 0."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import numpy as np

    from tse1m_tpu.cluster import ClusterParams
    from tse1m_tpu.serve import (ServeDaemon, ServeReplica,
                                 replica_staleness, stream_shards)

    with tempfile.TemporaryDirectory() as tmp:
        src = os.path.join(tmp, "writer")
        dst = os.path.join(tmp, "replica")
        params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
        rng = np.random.default_rng(7)
        items = rng.integers(0, 2**32, size=(20, 16),
                             dtype=np.int64).astype(np.uint32)
        writer = ServeDaemon(src, params=params,
                             state_commit_every=1).start()
        try:
            assert writer.ingest(items[:12])["ok"]
            writer.quiesce()
            stream_shards(src, dst)  # clean bootstrap pull
            replica = ServeReplica(dst, params=params)
            gen_adopted = replica._generation_adopted
            assert writer.ingest(items[12:])["ok"]  # writer advances
            writer.quiesce()
            # The killed pull: a subprocess streamer SIGKILLs itself at
            # the seat — after shard copies, before the manifest commit.
            plan_path = os.path.join(tmp, "plan.json")
            with open(plan_path, "w") as f:
                json.dump({"rules": [plan_rule("serve.replica.stream",
                                               kind="kill")]}, f)
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       TSE1M_FAULT_PLAN=plan_path)
            env["PYTHONPATH"] = REPO + os.pathsep + env.get(
                "PYTHONPATH", "")
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import sys; from tse1m_tpu.serve import stream_shards;"
                 f" stream_shards({src!r}, {dst!r})"],
                cwd=REPO, env=env, capture_output=True, text=True,
                timeout=600)
            assert proc.returncode == -signal.SIGKILL, (
                proc.returncode, proc.stderr[-2000:])
            # No torn adoption: the manifest never committed, so the
            # replica stays on the last adopted generation and still
            # answers every row of it.
            assert replica.refresh() is False
            assert replica._generation_adopted == gen_adopted
            q = replica.query(items[:12])
            assert bool(q["known"].all())
            # The next clean pull converges.
            stream_shards(src, dst)
            assert replica.refresh() is True
            assert replica_staleness(src, replica) == 0
            assert bool(replica.query(items)["known"].all())
        finally:
            writer.stop(commit=False)
    return {"ari_vs_planted": 1.0, "degradation_events": 0,
            "degradation_counts": {"replica_torn_pulls_rejected": 1},
            "chunk_halvings": 0, "store_scrub_corrupt": 0,
            "store_scrub_quarantined": 0}


def seat_schedule_replay(store: str) -> dict:
    """graftrace: replay the committed adversarial schedule strings
    (tests/test_trace.py ADVERSARIAL_SCHEDULES) against the real
    serve/store planes — the thread-interleaving analogue of replaying
    a committed fault plan.  Each replay re-runs the exact decision
    sequence deterministically and asserts label parity, snapshot
    monotonicity and torn-free probe views; a regression prints the
    failing ``v1:fix:...`` string for local replay.  A bounded seeded
    sweep on top catches schedules the committed strings no longer
    reach after code drift."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from test_trace import ADVERSARIAL_SCHEDULES

    from tse1m_tpu.trace.explore import explore, replay

    replayed = 0
    for scenario, sched in ADVERSARIAL_SCHEDULES.items():
        out = replay(sched, scenario)
        assert out.races == 0, (scenario, sched)
        replayed += 1
    stats = explore("serve", n_seeded=30, exhaustive_bound=3)
    store_stats = explore("store", n_seeded=20, exhaustive_bound=3)
    explored = (stats["trace_schedules_explored"]
                + store_stats["trace_schedules_explored"])
    return {"ari_vs_planted": 1.0, "degradation_events": 0,
            "degradation_counts": {"schedule_replays": replayed,
                                   "schedules_explored": explored},
            "chunk_halvings": 0, "store_scrub_corrupt": 0,
            "store_scrub_quarantined": 0}


def seat_scheme_smoke(store: str) -> dict:
    """Signature-scheme family smoke (tier-1 speed): the sanitized 2k
    bench under ``--scheme cminhash`` with the scheme-comparison round
    on and one injected RESOURCE_EXHAUSTED — the BENCH_r09 contract at
    CI scale.  Asserts >=4x fewer hash evaluations for C-MinHash at
    equal n_hashes, per-scheme host/device/pallas signature bit-parity
    across the quantization rungs + a checkpointed resume, clustering-
    quality parity between families, and that the degradation ladder
    still fires (and heals with label parity — run_bench's ARI gate)
    under the non-default scheme."""
    plan = {"rules": [plan_rule("pipeline.h2d", kind="raise",
                                message="RESOURCE_EXHAUSTED: injected "
                                        "1GiB allocation failure",
                                times=1)]}
    r = run_bench(store, plan, env_extra={"BENCH_SCHEME": "cminhash",
                                          "BENCH_SCHEMES": "1",
                                          "BENCH_SCHEMES_N": "2000"})
    assert r["scheme"] == "cminhash", r
    assert r["scheme_hash_eval_ratio_cminhash"] >= 4, r
    for s in ("kminhash", "cminhash", "weighted"):
        assert r[f"scheme_{s}_sig_parity"] is True, (s, r)
        assert r[f"scheme_{s}_resume_parity"] is True, (s, r)
    assert r["scheme_label_quality_delta"] <= 0.02, r
    # One RESOURCE_EXHAUSTED answers with the FIRST applicable rung —
    # the b-bit quant drop on a storeless stream, chunk halving
    # otherwise; either proves the ladder ran under the scheme.
    assert (r["degradation_counts"].get("quant_drop", 0) >= 1
            or r["degradation_counts"].get("chunk_halving", 0) >= 1), r
    return r


SEATS = {"stall": seat_stall, "oom": seat_oom, "kill": seat_kill,
         "corrupt-shard": seat_corrupt_shard, "hostloss": seat_hostloss,
         "heartbeat-timeout": seat_heartbeat_timeout,
         "zombie": seat_zombie,
         "leader-loss-promote": seat_leader_loss_promote,
         "serve-kill": seat_serve_kill,
         "router-shard-kill": seat_router_shard_kill,
         "replica-refresh-kill": seat_replica_refresh_kill,
         "scheme-smoke": seat_scheme_smoke,
         "schedule-replay": seat_schedule_replay}


def main() -> int:
    seat = sys.argv[1] if len(sys.argv) > 1 else ""
    if seat not in SEATS:
        print(f"usage: {sys.argv[0]} {{{'|'.join(SEATS)}}}",
              file=sys.stderr)
        return 2
    with tempfile.TemporaryDirectory() as tmp:
        store = os.path.join(tmp, "sig_store")
        os.environ["TSE1M_ROUTER_CAL"] = os.path.join(tmp, "cal.json")
        r = SEATS[seat](store)
    print(f"fault-matrix[{seat}] OK:",
          json.dumps({k: r[k] for k in
                      ("ari_vs_planted", "degradation_events",
                       "degradation_counts", "chunk_halvings",
                       "store_scrub_corrupt", "store_scrub_quarantined")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
