"""North-star clustering: MinHash/LSH kernels, host-device parity, ARI gate,
mesh-sharded execution (SURVEY.md §4(d,e))."""

import jax
import numpy as np
import pytest

from tse1m_tpu.cluster import (ClusterParams, adjusted_rand_index, band_keys,
                               cluster_sessions, host_cluster,
                               make_hash_params, minhash_signatures)
from tse1m_tpu.cluster.host import host_band_keys, host_signatures
from tse1m_tpu.cluster.lsh import bucket_representatives
from tse1m_tpu.cluster.minhash_pallas import minhash_and_keys
from tse1m_tpu.data.synth import synth_session_sets


@pytest.fixture(scope="module")
def small_sets():
    return synth_session_sets(2000, set_size=32, seed=3)


def test_signatures_device_matches_host():
    rng = np.random.default_rng(0)
    items = rng.integers(0, 1 << 24, size=(257, 16), dtype=np.uint32)
    a, b = make_hash_params(64, seed=1)
    dev = np.asarray(minhash_signatures(items, a, b))
    host = host_signatures(items, a, b)
    np.testing.assert_array_equal(dev, host)


def test_band_keys_device_matches_host():
    rng = np.random.default_rng(1)
    sig = rng.integers(0, 1 << 32, size=(100, 64), dtype=np.uint32)
    dev = np.asarray(band_keys(sig, 16))
    host = host_band_keys(sig, 16)
    np.testing.assert_array_equal(dev, host)
    # distinct bands with identical rows must not collide (salting)
    same = np.tile(sig[:, :4], (1, 16))
    k = np.asarray(band_keys(same, 16))
    assert len(np.unique(k[0])) == 16


def test_minhash_jaccard_estimate_quality():
    """MinHash agreement ~ true Jaccard within Monte-Carlo error."""
    rng = np.random.default_rng(2)
    base = rng.integers(0, 1 << 20, size=512, dtype=np.uint32)
    x = base[:256][None, :]
    y = np.concatenate([base[:192], base[256:320]])[None, :]  # J = 192/320
    a, b = make_hash_params(512, seed=5)
    sx = host_signatures(x, a, b)[0]
    sy = host_signatures(y, a, b)[0]
    est = (sx == sy).mean()
    assert abs(est - 0.6) < 0.08


def test_bucket_representatives_small():
    keys = np.array([[5], [9], [5], [1], [9], [5]], dtype=np.uint32)
    reps = np.asarray(bucket_representatives(keys))[:, 0]
    np.testing.assert_array_equal(reps, [0, 1, 0, 3, 1, 0])


def test_ari_metric():
    a = [0, 0, 1, 1, 2, 2]
    assert adjusted_rand_index(a, [5, 5, 7, 7, 9, 9]) == 1.0
    assert adjusted_rand_index(a, [0, 1, 2, 3, 4, 5]) < 0.1
    assert abs(adjusted_rand_index(a, [0, 0, 1, 1, 2, 9])) < 1.0


def test_device_cluster_recovers_planted_clusters(small_sets):
    items, truth = small_sets
    labels = cluster_sessions(items, ClusterParams(use_pallas="never"))
    assert adjusted_rand_index(labels, truth) >= 0.98


def test_device_matches_host_oracle(small_sets):
    items, _ = small_sets
    dev = cluster_sessions(items, ClusterParams(use_pallas="never"))
    host = host_cluster(items)
    assert adjusted_rand_index(dev, host) >= 0.98
    # identical edge semantics -> identical min-index components
    np.testing.assert_array_equal(dev.astype(np.int64), host)


def test_pallas_interpret_matches_jax(small_sets):
    items, _ = small_sets
    items = items[:512]
    a, b = make_hash_params(64, seed=0)
    sig_j = np.asarray(minhash_signatures(items, a, b))
    keys_j = np.asarray(band_keys(sig_j, 8))
    sig_p, keys_p = minhash_and_keys(items, a, b, 8, use_pallas="interpret",
                                     block_n=128)
    np.testing.assert_array_equal(np.asarray(sig_p), sig_j)
    np.testing.assert_array_equal(np.asarray(keys_p), keys_j)


def test_h2d_chunked_minhash_matches_unchunked(small_sets):
    """The streamed (chunked-transfer) MinHash path must be bit-identical
    to the single-put path — including a short final chunk (N chosen so
    4 chunks don't divide evenly on block_n boundaries)."""
    items, _ = small_sets
    items = items[:700]
    base = ClusterParams(use_pallas="interpret", block_n=128, h2d_chunks=1)
    chunked = ClusterParams(use_pallas="interpret", block_n=128,
                            h2d_chunks=4)
    np.testing.assert_array_equal(
        cluster_sessions(items, chunked), cluster_sessions(items, base))


def test_packed24_transfer_roundtrip_and_parity(small_sets, monkeypatch):
    """3-byte packed H2D transfer must reconstruct ids exactly and yield
    the same labels as the raw uint32 path."""
    from tse1m_tpu.cluster import pipeline

    rng = np.random.default_rng(7)
    x = rng.integers(0, 1 << 24, size=(33, 5), dtype=np.uint32)
    np.testing.assert_array_equal(
        np.asarray(pipeline._unpack24(pipeline._pack24_host(x))), x)

    items, _ = small_sets
    items = items[:700]
    assert items.max() < (1 << 24)
    prm = ClusterParams(use_pallas="interpret", block_n=128, h2d_chunks=4)
    packed = cluster_sessions(items, prm)
    monkeypatch.setattr(pipeline, "_PACK_LIMIT", 0)  # force raw uint32 path
    raw = cluster_sessions(items, prm)
    np.testing.assert_array_equal(packed, raw)


def test_mesh_sharded_cluster_matches_single(small_sets):
    items, truth = small_sets
    devices = np.array(jax.devices()[:8]).reshape(8)
    mesh = jax.sharding.Mesh(devices, ("data",))
    labels = cluster_sessions(items, ClusterParams(use_pallas="never"),
                              mesh=mesh)
    single = cluster_sessions(items, ClusterParams(use_pallas="never"))
    np.testing.assert_array_equal(labels, single)
    assert adjusted_rand_index(labels, truth) >= 0.98


def test_mesh_band_padding_matches_single(small_sets):
    """n_bands not divisible by the mesh size: the band-sharded tail pads
    with per-row-unique dummy bands (singleton buckets, no edges) — labels
    must still match the single-device path exactly."""
    items, _ = small_sets
    devices = np.array(jax.devices()[:8]).reshape(8)
    mesh = jax.sharding.Mesh(devices, ("data",))
    prm = ClusterParams(use_pallas="never", n_hashes=32, n_bands=4)
    np.testing.assert_array_equal(
        cluster_sessions(items, prm, mesh=mesh),
        cluster_sessions(items, prm))


def test_mesh_sharded_cluster_with_padding():
    items, truth = synth_session_sets(1003, set_size=16, seed=11)
    devices = np.array(jax.devices()[:8]).reshape(8)
    mesh = jax.sharding.Mesh(devices, ("data",))
    labels = cluster_sessions(items, ClusterParams(use_pallas="never"),
                              mesh=mesh)
    assert labels.shape == (1003,)
    # padding-correctness test: labels must match the unpadded single-device
    # run exactly; the ARI quality gate lives in the set_size>=32 tests
    # (recall at set_size=16 hovers ~0.98 by construction).
    single = cluster_sessions(items, ClusterParams(use_pallas="never"))
    np.testing.assert_array_equal(labels, single)
    assert adjusted_rand_index(labels, truth) >= 0.95
