"""Collection layer: transport policy, checkpointing, and the C3-C6
collectors against recorded fixtures (no network)."""

import json
import os
import subprocess
from datetime import date

import pandas as pd
import pytest

from tse1m_tpu.collect.buildlogs import BuildLogAnalyzer, parse_build_log
from tse1m_tpu.collect.checkpoint import (CsvBatchCheckpointer,
                                          last_date_in_csv,
                                          processed_ids_from_csvs,
                                          resume_start_date)
from tse1m_tpu.collect.coverage import (CoverageCollector, extract_tables,
                                        fetch_day_coverage,
                                        parse_c_family_report,
                                        parse_jvm_report, parse_python_report)
from tse1m_tpu.collect.gcs_metadata import (GcsMetadataCollector,
                                            extract_log_records,
                                            is_build_log_name)
from tse1m_tpu.collect.projects import collect_project_info, first_commit_time
from tse1m_tpu.collect.transport import (DirFetcher, FetchError, FetchPolicy,
                                         HttpFetcher, Response)

UUID_NAME = "log-6259f647-370a-40e2-916b-8f4aaf105697.txt"


# -- transport ----------------------------------------------------------------

class _FakeHttpResponse:
    def __init__(self, status_code, content=b""):
        self.status_code = status_code
        self.content = content

    def raise_for_status(self):
        if self.status_code >= 400:
            raise RuntimeError(f"HTTP {self.status_code}")


class _ScriptedSession:
    """requests.Session stand-in replaying a scripted status sequence."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def get(self, url, params=None, timeout=None):
        self.calls += 1
        item = self.script.pop(0)
        if isinstance(item, Exception):
            raise item
        status, content = item
        return _FakeHttpResponse(status, content)


def _policy(**kw):
    kw.setdefault("backoff_factor", 0.0)
    return FetchPolicy(**kw)


def test_http_fetcher_retries_then_succeeds():
    session = _ScriptedSession([(503, b""), (503, b""), (200, b"ok")])
    f = HttpFetcher(_policy(retries=3), session=session)
    resp = f.get("https://x/y")
    assert resp.text == "ok"
    assert session.calls == 3


def test_http_fetcher_404_is_absent_not_error():
    f = HttpFetcher(_policy(), session=_ScriptedSession([(404, b"")]))
    assert f.get("https://x/missing") is None


def test_http_fetcher_exhausts_budget():
    session = _ScriptedSession([(503, b"")] * 3)
    f = HttpFetcher(_policy(retries=2), session=session)
    with pytest.raises(FetchError):
        f.get("https://x/y")
    assert session.calls == 3


def test_http_fetcher_retries_connection_errors():
    session = _ScriptedSession([OSError("reset"), (200, b"fine")])
    f = HttpFetcher(_policy(retries=1), session=session)
    assert f.get("https://x/y").text == "fine"


def test_dir_fetcher_maps_urls_and_params(tmp_path):
    base = tmp_path / "host" / "a"
    base.mkdir(parents=True)
    (base / "b.html").write_text("payload")
    (tmp_path / "host" / "api#c=1&d=2").parent.mkdir(exist_ok=True)
    (tmp_path / "host" / "api#c=1&d=2").write_text("{}")
    f = DirFetcher(str(tmp_path))
    assert f.get("https://host/a/b.html").text == "payload"
    assert f.get("https://host/api", params={"d": 2, "c": 1}).text == "{}"
    assert f.get("https://host/nope") is None


# -- checkpoint ---------------------------------------------------------------

def test_batch_checkpointer_flush_merge_cleanup(tmp_path):
    ckpt = CsvBatchCheckpointer(str(tmp_path / "b"), "meta", batch_size=2,
                                fieldnames=["id", "v"])
    for i in range(5):
        ckpt.add({"id": i, "v": i * 10})
    final = tmp_path / "final.csv"
    n = ckpt.merge(str(final))
    assert n == 5
    df = pd.read_csv(final)
    assert sorted(df["id"]) == [0, 1, 2, 3, 4]
    assert not list((tmp_path / "b").glob("meta_batch_*.csv"))


def test_batch_checkpointer_resumes_numbering(tmp_path):
    d = str(tmp_path / "b")
    c1 = CsvBatchCheckpointer(d, "meta", batch_size=1)
    c1.add({"id": 1})
    c2 = CsvBatchCheckpointer(d, "meta", batch_size=1)
    c2.add({"id": 2})
    files = sorted(os.path.basename(p) for p in
                   (tmp_path / "b").glob("meta_batch_*.csv"))
    assert files == ["meta_batch_1.csv", "meta_batch_2.csv"]


def test_processed_ids_plain_and_json(tmp_path):
    (tmp_path / "w").mkdir()
    pd.DataFrame({"id": [3, 4]}).to_csv(tmp_path / "w" / "a.csv", index=False)
    pd.DataFrame({"id": ['"7"', "null"]}).to_csv(tmp_path / "w" / "b.csv",
                                                 index=False)
    assert processed_ids_from_csvs(str(tmp_path)) == {3, 4, '"7"', "null"}
    assert processed_ids_from_csvs(str(tmp_path), json_encoded=True) == {3, 4, 7}


def test_resume_start_date(tmp_path):
    path = tmp_path / "proj.csv"
    assert resume_start_date(str(path), date(2025, 1, 1)) == date(2025, 1, 1)
    pd.DataFrame({"date": ["20250103", "20250105"]}).to_csv(path, index=False)
    assert last_date_in_csv(str(path)) == date(2025, 1, 5)
    assert resume_start_date(str(path), date(2025, 1, 1)) == date(2025, 1, 6)
    # default_start after the resume point wins (3_…py:266-267)
    assert resume_start_date(str(path), date(2025, 2, 1)) == date(2025, 2, 1)


# -- C4: GCS metadata pager ---------------------------------------------------

class _PagedFetcher:
    def __init__(self, pages):
        self.pages = pages  # token -> page dict

    def get(self, url, params=None):
        token = (params or {}).get("pageToken", "")
        return Response(url=url, status=200,
                        content=json.dumps(self.pages[token]).encode())


def test_gcs_name_filter():
    assert is_build_log_name(UUID_NAME)
    assert not is_build_log_name("log-not-a-uuid.txt")
    assert not is_build_log_name("x" * len(UUID_NAME))  # length-only fails
    recs = extract_log_records([
        {"name": UUID_NAME, "size": "10", "timeCreated": "2024-01-01",
         "mediaLink": "m", "selfLink": "s", "extra": "dropped"},
        {"name": "junk.txt"},
    ])
    assert len(recs) == 1
    assert set(recs[0]) == {"name", "selfLink", "mediaLink", "size",
                            "timeCreated"}


def test_gcs_collector_pages_batches_and_merges(tmp_path):
    def page(i, next_token=None):
        name = f"log-{i:08d}-370a-40e2-916b-8f4aaf105697.txt"
        d = {"items": [{"name": name, "selfLink": f"s{i}",
                        "mediaLink": f"m{i}", "size": str(i),
                        "timeCreated": "2024-01-01T00:00:00Z"}]}
        if next_token:
            d["nextPageToken"] = next_token
        return d

    fetcher = _PagedFetcher({"": page(0, "t1"), "t1": page(1, "t2"),
                             "t2": page(2)})
    coll = GcsMetadataCollector(fetcher, str(tmp_path / "batches"),
                                pages_per_batch=2)
    final = tmp_path / "buildlog_metadata.csv"
    assert coll.collect(str(final)) == 3
    df = pd.read_csv(final)
    assert len(df) == 3 and coll.pages_fetched == 3
    assert not list((tmp_path / "batches").glob("*.csv"))


# -- C5: coverage parsing + collector -----------------------------------------

C_FAMILY_HTML = """<html><body><table>
<tr><th>Filename</th><th>Function Coverage</th><th>Line Coverage</th></tr>
<tr><td>a.c</td><td>80.00% (8/10)</td><td>75.00% (30/40)</td></tr>
<tr><td>Totals</td><td>85.00% (17/20)</td><td>90.00% (180/200)</td></tr>
</table></body></html>"""

PYTHON_HTML = """<html><body><table>
<tr><th>Module</th><th>statements</th><th>missing</th><th>coverage</th></tr>
<tr><td>a.py</td><td>100</td><td>20</td><td>80%</td></tr>
<tr><td>Total</td><td>400</td><td>100</td><td>75%</td></tr>
</table></body></html>"""

JVM_HTML = """<html><body><table>
<tr><th>Element</th><th>Missed</th><th>Cov.</th><th>Lines</th><th>Missed</th></tr>
<tr><td>pkg.a</td><td>5</td><td>50%</td><td>200</td><td>40</td></tr>
<tr><td>Total</td><td>12</td><td>70%</td><td>1,000</td><td>250</td></tr>
</table></body></html>"""


def test_extract_tables_stdlib_parser():
    tables = extract_tables(C_FAMILY_HTML)
    assert len(tables) == 1
    assert tables[0][0] == ["Filename", "Function Coverage", "Line Coverage"]
    assert tables[0][-1][0] == "Totals"


def test_parse_c_family_report():
    s = parse_c_family_report(C_FAMILY_HTML)
    assert (s.coverage, s.covered_line, s.total_line) == (90.0, 180.0, 200.0)
    assert parse_c_family_report("<html><p>no table</p></html>") is None


def test_parse_python_report():
    s = parse_python_report(PYTHON_HTML)
    assert (s.coverage, s.covered_line, s.total_line) == (75.0, 300.0, 400.0)


def test_parse_jvm_report_uses_second_missed_column():
    s = parse_jvm_report(JVM_HTML)
    assert (s.covered_line, s.total_line) == (750.0, 1000.0)
    assert s.coverage == 75.0


def _coverage_fixture(tmp_path, project, day, html, page="file_view_index.html"):
    d = (tmp_path / "storage.googleapis.com" / "oss-fuzz-coverage" / project
         / "reports" / day / "linux")
    d.mkdir(parents=True, exist_ok=True)
    (d / page).write_text(html)


def test_fetch_day_coverage_missing_report(tmp_path):
    f = DirFetcher(str(tmp_path))
    assert fetch_day_coverage(f, "zlib", "c", "20250101") is None


def test_coverage_collector_walks_and_resumes(tmp_path):
    _coverage_fixture(tmp_path, "zlib", "20250101", C_FAMILY_HTML)
    _coverage_fixture(tmp_path, "zlib", "20250103", C_FAMILY_HTML)
    f = DirFetcher(str(tmp_path))
    coll = CoverageCollector(f, str(tmp_path / "per_project"),
                             finish_date=date(2025, 1, 3))
    n = coll.collect_project("zlib", "c", date(2025, 1, 1))
    assert n == 2  # the 404 day is skipped silently
    # Resume: a later day appears; only it is fetched.
    _coverage_fixture(tmp_path, "zlib", "20250104", C_FAMILY_HTML)
    coll2 = CoverageCollector(f, str(tmp_path / "per_project"),
                              finish_date=date(2025, 1, 4))
    f.requests.clear()
    assert coll2.collect_project("zlib", "c", date(2025, 1, 1)) == 1
    assert all("20250104" not in r or "20250104" in r for r in f.requests)
    df = pd.read_csv(tmp_path / "per_project" / "zlib.csv")
    assert len(df) == 3
    merged = tmp_path / "total_coverage.csv"
    assert coll2.merge(str(merged)) == 3


def test_coverage_collect_all_skips_unsupported(tmp_path):
    _coverage_fixture(tmp_path, "pyproj", "20250101", PYTHON_HTML,
                      page="index.html")
    info = pd.DataFrame({
        "project": ["pyproj", "goproj"],
        "language": ["python", "go"],
        "first_commit_datetime": ["2025-01-01T00:00:00Z"] * 2,
    })
    f = DirFetcher(str(tmp_path))
    coll = CoverageCollector(f, str(tmp_path / "pp"),
                             finish_date=date(2025, 1, 1))
    total = coll.collect_all(info, str(tmp_path / "total.csv"))
    assert total == 1  # go has no parse rule; python day collected


# -- C6: build-log analyzer ---------------------------------------------------

FUZZ_LOG = """\
starting build "abc"
Step #1: Already have image: gcr.io/oss-fuzz/zlib
Starting Step #2 - "srcmap"
Step #2: {
Step #2:   "/src/zlib": {
Step #2:     "type": "git",
Step #2:     "url": "https://github.com/madler/zlib.git",
Step #2:     "rev": "deadbeefcafe"
Step #2:   },
Step #2:   "/src/extra": {
Step #2:     "type": "git",
Step #2:     "url": "https://example.com/extra.git",
Step #2:     "rev": "0123456789ab"
Step #2:   }
Step #2: }
Starting Step #3 - "compile-libfuzzer-address-x86_64"
Step #3: jq_inplace /tmp/f.json '."/src/zlib" = { type: "git", url: "https://github.com/madler/zlib.git", rev: "deadbeefcafe" }'
Step #5: Pulling image: gcr.io/oss-fuzz-base/base-runner
PUSH
DONE
"""

COVERAGE_LOG = """\
Step #1: Already have image: gcr.io/oss-fuzz/zlib
Starting Step #3 - "compile-libfuzzer-coverage-x86_64"
Step #4: /report/linux/index.html
PUSH
DONE
"""

ERROR_LOG = """\
Step #1: No URLs matched: gs://oss-fuzz-coverage/brotli/textcov_reports
Starting Step #3 - "compile-libfuzzer-address-x86_64"
ERROR
ERROR: build step 3 failed
"""


def test_parse_fuzzing_log():
    rec = parse_build_log("b1", FUZZ_LOG)
    assert rec.project == "zlib"
    assert rec.build_type == "Fuzzing"
    assert rec.result == "Success"
    # srcmap JSON (brace-depth delimited) + jq_inplace both contribute
    assert "Zlib" in rec.modules and "Extra" in rec.modules
    assert "deadbeefcafe" in rec.revisions
    assert len(rec.paths) == 3  # 2 srcmap entries + 1 jq_inplace


def test_parse_coverage_and_error_logs():
    cov = parse_build_log("b2", COVERAGE_LOG)
    assert cov.build_type == "Coverage"   # PUSH DONE must not flip it
    assert cov.result == "Success"
    err = parse_build_log("b3", ERROR_LOG)
    assert err.project == "brotli"
    assert err.result == "Error"
    assert parse_build_log("b4", "").result == ""


def test_buildlog_analyzer_resume_and_output(tmp_path):
    logs = tmp_path / "oss-fuzz-build-logs.storage.googleapis.com"
    logs.mkdir(parents=True)
    (logs / "log-b1.txt").write_text(FUZZ_LOG)
    (logs / "log-b2.txt").write_text(COVERAGE_LOG)
    meta = pd.DataFrame({
        "name": ["b1", "b2"],
        "mediaLink": ["https://oss-fuzz-build-logs.storage.googleapis.com/"
                      f"log-{i}.txt" for i in ("b1", "b2")],
        "size": [100, 200],
        "timeCreated": ["2024-05-01T10:00:00Z", "2024-05-01T11:00:00Z"],
    })
    f = DirFetcher(str(tmp_path))
    an = BuildLogAnalyzer(f, str(tmp_path / "analyzed"), batch_size=10)
    assert an.analyze(meta) == 2
    assert an.analyze(meta) == 0  # processed-id resume
    batches = list((tmp_path / "analyzed").glob("*.csv"))
    assert len(batches) == 1
    df = pd.read_csv(batches[0])
    assert set(df["id"]) == {"b1", "b2"}
    assert set(df["build_type"]) == {"Fuzzing", "Coverage"}
    assert json.loads(df[df["id"] == "b1"]["modules"].iloc[0])[0] == "Zlib"


def test_buildlog_analyzer_threaded_matches_serial(tmp_path):
    """workers > 1 (the 1.19M-log throughput path) must produce the exact
    batch CSV the serial path does — order included, since resume state is
    derived from the written ids."""
    logs = tmp_path / "oss-fuzz-build-logs.storage.googleapis.com"
    logs.mkdir(parents=True)
    names = [f"b{i}" for i in range(12)]
    for i, name in enumerate(names):
        (logs / f"log-{name}.txt").write_text(
            FUZZ_LOG if i % 2 else COVERAGE_LOG)
    meta = pd.DataFrame({
        "name": names,
        "mediaLink": ["https://oss-fuzz-build-logs.storage.googleapis.com/"
                      f"log-{n}.txt" for n in names],
        "size": list(range(12)),
        "timeCreated": ["2024-05-01T10:00:00Z"] * 12,
    })
    outputs = {}
    for workers in (1, 4):
        f = DirFetcher(str(tmp_path))
        out = tmp_path / f"analyzed_w{workers}"
        an = BuildLogAnalyzer(f, str(out), batch_size=100, workers=workers)
        assert an.analyze(meta) == 12
        (batch,) = out.glob("*.csv")
        outputs[workers] = batch.read_text()
    assert outputs[1] == outputs[4]


# -- C3: project info (oss_fuzz_repo fixture lives in conftest) ---------------

def test_first_commit_time(oss_fuzz_repo):
    t = first_commit_time(oss_fuzz_repo, "projects/zlib")
    assert t is not None and t.year == 2021 and t.month == 3
    assert first_commit_time(oss_fuzz_repo, "projects/nope") is None


def test_collect_project_info(oss_fuzz_repo):
    df = collect_project_info(oss_fuzz_repo)
    assert list(df["project"]) == ["brotli", "zlib"]
    assert list(df.columns[:2]) == ["project", "first_commit_datetime"]
    zrow = df[df["project"] == "zlib"].iloc[0]
    assert zrow["language"] == "c"
    assert zrow["sanitizers"] == "['address', 'memory']"
    assert pd.isna(zrow["auto_ccs"])  # empty list -> None (1_…py:29-30)
    brow = df[df["project"] == "brotli"].iloc[0]
    assert json.loads(brow["vendor_ccs"]) == {"a": 1}
