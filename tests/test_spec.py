"""graftspec (tse1m_tpu/spec): the executable-spec DSL, the
explicit-state model checker, and the committed protocol specs.

The load-bearing claims:

- the DSL rejects malformed specs at construction (schedule-unsafe
  action names, unknown seat kinds, duplicate actions, unfreezable
  state);
- the checker finds invariant violations with a shortest (BFS) trace,
  liveness violations both as goal-false terminal states and as fair
  lassos — and does NOT flag behaviors weak fairness permits;
- symmetry reduction quotients interchangeable process ids without
  losing violations;
- every counterexample exports as a ``v1:fix:...`` graftrace schedule
  string that parses back and REPLAYS through the machine to the
  violating state;
- the three committed specs (lease, ingest_ack, replica) pass their
  invariants + liveness exhaustively, and every committed mutant is
  caught with a replayed counterexample.
"""

from __future__ import annotations

import pytest

from tse1m_tpu.spec import (MUTANT_BUILDERS, SPEC_BUILDERS, build_spec,
                            check_all, mutant_selftest)
from tse1m_tpu.spec.dsl import (Action, Invariant, Liveness, Spec,
                                SpecError, freeze, state_key, tupset, upd)
from tse1m_tpu.spec.mc import check, replay
from tse1m_tpu.trace.sched import Schedule

# -- DSL ---------------------------------------------------------------------

def test_action_names_must_be_schedule_safe():
    for bad in ("a,b", "a:b", "a b", "a\nb"):
        with pytest.raises(SpecError, match="schedule-safe"):
            Action(bad, guard=lambda s: True, effect=dict)


def test_action_rejects_unknown_seat_kind():
    with pytest.raises(SpecError, match="seat"):
        Action("ok", guard=lambda s: True, effect=dict, seat="oops:x")
    for good in ("fault:a.b", "verb:ingest", "call:fn", "model:crash"):
        Action("ok", guard=lambda s: True, effect=dict, seat=good)


def test_spec_rejects_duplicate_action_names():
    a = Action("step", guard=lambda s: True, effect=dict)
    with pytest.raises(SpecError, match="duplicate"):
        Spec("toy", init={"x": 0}, actions=(a, a))


def test_spec_action_lookup():
    a = Action("step", guard=lambda s: True, effect=dict)
    spec = Spec("toy", init={"x": 0}, actions=(a,))
    assert spec.action("step") is a
    with pytest.raises(SpecError, match="no action"):
        spec.action("nope")


def test_freeze_and_state_key():
    assert freeze([1, [2, 3]]) == (1, (2, 3))
    assert freeze({"b": 2, "a": {1, 3, 2}}) == (("a", (1, 2, 3)),
                                                ("b", 2))
    assert state_key({"x": [1, 2]}) == state_key({"x": (1, 2)})
    with pytest.raises(SpecError, match="non-freezable"):
        state_key({"x": bytearray(b"nope")})


def test_upd_and_tupset_are_pure():
    s = {"x": 1, "t": (0, 0)}
    s2 = upd(s, x=2, t=tupset(s["t"], 1, 9))
    assert s == {"x": 1, "t": (0, 0)}  # input untouched
    assert s2 == {"x": 2, "t": (0, 9)}


# -- toy machines for the checker --------------------------------------------

def _counter(bound: int = 3, bad_at: int | None = None) -> Spec:
    """x counts 0..bound; optionally an invariant that breaks at
    ``bad_at`` (shortest trace = bad_at increments)."""
    invs = ()
    if bad_at is not None:
        invs = (Invariant("below-bad", lambda s: s["x"] != bad_at),)
    return Spec(
        "counter", init={"x": 0},
        actions=(Action("inc", guard=lambda s: s["x"] < bound,
                        effect=lambda s: upd(s, x=s["x"] + 1)),),
        invariants=invs,
        liveness=(Liveness("saturates", lambda s: s["x"] == bound),))


def _pingpong(finish_guard) -> Spec:
    """at hops 0<->1 forever unless finish fires; goal is done."""
    return Spec(
        "pingpong", init={"at": 0, "done": False},
        actions=(
            Action("hop", fair=True,
                   guard=lambda s: not s["done"],
                   effect=lambda s: upd(s, at=1 - s["at"])),
            Action("finish", fair=True, guard=finish_guard,
                   effect=lambda s: upd(s, done=True)),
        ),
        liveness=(Liveness("eventually-done", lambda s: s["done"]),))


def test_invariant_violation_shortest_bfs_trace():
    r = check(_counter(bound=5, bad_at=3))
    assert not r.ok and r.violation.kind == "invariant"
    assert r.violation.prop == "below-bad"
    assert r.violation.trace == ("inc", "inc", "inc")  # BFS: shortest
    assert r.violation.state["x"] == 3
    # DFS finds it too (trace need not be shortest, must replay).
    rd = check(_counter(bound=5, bad_at=3), mode="dfs")
    assert not rd.ok
    assert replay(_counter(bound=5, bad_at=3),
                  rd.violation.trace)[-1]["x"] == 3


def test_clean_counter_passes_and_counts_states():
    r = check(_counter(bound=3))
    assert r.ok and r.complete
    assert r.states == 4 and r.transitions == 3 and r.depth == 3


def test_liveness_terminal_violation():
    # Counter whose goal is never reached at its terminal state.
    spec = Spec("stuck", init={"x": 0},
                actions=(Action("inc", guard=lambda s: s["x"] < 1,
                                effect=lambda s: upd(s, x=s["x"] + 1)),),
                liveness=(Liveness("reaches-two",
                                   lambda s: s["x"] == 2),))
    r = check(spec)
    assert not r.ok
    assert r.violation.kind == "liveness" and not r.violation.cycle
    assert r.violation.state["x"] == 1  # the terminal witness


def test_liveness_fair_lasso_detected():
    """Weak fairness does NOT save this machine: on the hop-hop cycle
    ``finish`` is disabled at at==1, so the lasso starves nothing that
    is CONTINUOUSLY enabled — a genuine violation, with the cycle in
    the counterexample."""
    r = check(_pingpong(lambda s: s["at"] == 0 and not s["done"]))
    assert not r.ok and r.violation.kind == "liveness"
    assert r.violation.cycle  # a lasso, not a terminal state
    assert set(r.violation.cycle) == {"hop"}
    # The exported schedule replays: trace to the cycle entry, then
    # one full cycle, all enabled in order.
    replay(_pingpong(lambda s: s["at"] == 0 and not s["done"]),
           r.violation.schedule_str)


def test_liveness_weak_fairness_excludes_always_enabled_action():
    """With ``finish`` enabled at EVERY goal-false state, any lasso
    that never takes it starves a continuously-enabled fair action —
    weak fairness excludes it, and the spec passes."""
    r = check(_pingpong(lambda s: not s["done"]))
    assert r.ok, r.violation and r.violation.describe()


def test_max_states_bound_reports_incomplete():
    r = check(_counter(bound=100), max_states=10)
    assert not r.complete and not r.ok and r.violation is None
    assert r.states == 10


def test_unknown_mode_raises():
    with pytest.raises(SpecError, match="mode"):
        check(_counter(), mode="random")


# -- symmetry reduction ------------------------------------------------------

def _two_flags(symmetric: bool) -> Spec:
    """Two interchangeable processes each raise a flag once."""
    def _sym(s, perm):
        return upd(s, flags=tuple(s["flags"][perm[i]]
                                  for i in range(2)))

    return Spec(
        "flags", init={"flags": (0, 0)},
        actions=tuple(
            Action(f"raise_p{p}",
                   guard=lambda s, p=p: s["flags"][p] == 0,
                   effect=lambda s, p=p: upd(
                       s, flags=tupset(s["flags"], p, 1)))
            for p in range(2)),
        symmetry=_sym if symmetric else None,
        n_symmetric=2 if symmetric else 0)


def test_symmetry_reduction_quotients_states():
    assert check(_two_flags(symmetric=False)).states == 4
    assert check(_two_flags(symmetric=True)).states == 3  # (1,0)~(0,1)


def test_symmetry_preserves_violations_modulo_renaming():
    spec = _two_flags(symmetric=True)
    spec = Spec(spec.name, spec.init, spec.actions,
                invariants=(Invariant("never-both",
                                      lambda s: sum(s["flags"]) < 2),),
                symmetry=spec.symmetry, n_symmetric=spec.n_symmetric)
    r = check(spec)
    assert not r.ok and len(r.violation.trace) == 2
    # The trace is valid modulo renaming — replay goes through the
    # same canonicalization, so it must run.
    states = replay(spec, r.violation.schedule_str)
    assert sum(states[-1]["flags"]) == 2


# -- counterexamples as graftrace schedules ----------------------------------

def test_schedule_string_parses_and_replays():
    r = check(_counter(bound=5, bad_at=2))
    s = r.violation.schedule_str
    assert s.startswith("v1:fix:")
    assert Schedule.from_string(s).choices == ("inc", "inc")
    states = replay(_counter(bound=5, bad_at=2), s)
    assert states[-1]["x"] == 2


def test_replay_rejects_disabled_action():
    with pytest.raises(SpecError, match="diverged"):
        replay(_counter(bound=1), ["inc", "inc"])  # second is disabled
    with pytest.raises(SpecError, match="no action"):
        replay(_counter(bound=1), ["nope"])


# -- the committed protocol specs --------------------------------------------

def test_real_specs_pass_exhaustively():
    results = check_all()
    assert {r.spec for r in results} == {"lease", "ingest_ack",
                                         "replica"}
    for r in results:
        assert r.ok and r.complete, \
            f"{r.spec}: {r.violation and r.violation.describe()}"
        assert 0 < r.states < 10_000  # bounded by design
    # DFS covers the identical state space.
    for r, rd in zip(results, check_all(mode="dfs")):
        assert (r.states, r.transitions) == (rd.states, rd.transitions)


def test_real_specs_declare_code_seats():
    """Every non-model action seat names the code it claims to model —
    the shape the spec-conformance lint pass enforces against the tree
    (tests/test_lint_interproc.py proves the tree side)."""
    for name, builder in SPEC_BUILDERS.items():
        spec = builder()
        kinds = {a.seat.split(":", 1)[0] for a in spec.actions}
        assert kinds & {"fault", "verb", "call"}, \
            f"{name} models no code at all"
        assert any(a.fair for a in spec.actions), \
            f"{name} has no fair action — liveness would be vacuous"


def test_mutant_selftest_catches_every_committed_bug():
    records = mutant_selftest()
    assert set(records) == set(MUTANT_BUILDERS) == {
        "ack-before-journal", "fence-after-append", "manifest-first"}
    for name, rec in records.items():
        assert rec["caught"] and rec["replayed"], (name, rec)
        assert Schedule.from_string(rec["schedule"]).choices
    # Each mutant trips the property guarding its bug class.
    assert records["ack-before-journal"]["prop"] == "durable-once"
    assert records["fence-after-append"]["prop"] == "fence-before-append"
    assert records["manifest-first"]["prop"] == "manifest-within-files"


def test_build_spec_names_knowns_on_typo():
    with pytest.raises(SpecError, match="lease"):
        build_spec("leese")
    with pytest.raises(SpecError, match="unknown spec"):
        check_all(["leese"])


# -- the CLI -----------------------------------------------------------------

def test_cli_spec_exit_codes(capsys):
    from tse1m_tpu.cli import main

    assert main(["spec", "check"]) == 0
    assert main(["spec", "mutants"]) == 0
    assert main(["spec", "trace", "fence-after-append"]) == 1
    out = capsys.readouterr().out
    assert "lease" in out and "replay: v1:fix:" in out
    assert main(["spec", "check", "nosuch"]) == 2
