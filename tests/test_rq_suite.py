"""Fused all-six-RQ dispatch (backend.rq_suite): one device round-trip for
the whole analysis suite.  The fused kernel shares its bodies and cached
CSR lanes with the per-RQ kernels, so every field must be bit-identical to
the individual calls — and both backends must agree on the suite dict."""

from __future__ import annotations

import numpy as np
import pytest

from tse1m_tpu.backend.jax_backend import JaxBackend
from tse1m_tpu.backend.pandas_backend import PandasBackend
from tse1m_tpu.data.columnar import StudyArrays


@pytest.fixture(scope="module")
def arrays(study_db, study_cfg):
    return StudyArrays.from_db(study_db, study_cfg)


@pytest.fixture(scope="module")
def suite_args(arrays, study_cfg):
    limit_ns = int(np.datetime64(study_cfg.limit_date, "ns").astype(np.int64))
    g1 = np.arange(0, arrays.n_projects, 2)
    g2 = np.arange(1, arrays.n_projects, 2)
    return dict(arrays=arrays, limit_date_ns=limit_ns, min_projects=1,
                g1_idx=g1, g2_idx=g2)


def _assert_results_equal(a, b, rq: str):
    assert type(a) is type(b), rq
    for f in a.__dataclass_fields__:
        x, y = getattr(a, f), getattr(b, f)
        if isinstance(x, np.ndarray):
            np.testing.assert_array_equal(x, y, err_msg=f"{rq}.{f}")
        else:
            assert x == y, f"{rq}.{f}"


def test_fused_suite_matches_individual_calls(suite_args):
    be = JaxBackend(mesh=None)
    fused = be.rq_suite(**suite_args)
    a = suite_args
    individual = {
        "rq1": be.rq1_detection(a["arrays"], a["limit_date_ns"],
                                a["min_projects"]),
        "rq2cp": be.rq2_change_points(a["arrays"], a["limit_date_ns"]),
        "rq2tr": be.rq2_trends(a["arrays"], a["limit_date_ns"]),
        "rq3": be.rq3_coverage_at_detection(a["arrays"], a["limit_date_ns"]),
        "rq4a": be.rq4a_detection_trend(a["arrays"], a["limit_date_ns"],
                                        a["g1_idx"], a["g2_idx"],
                                        a["min_projects"]),
        "rq4b": be.rq4b_group_trends(a["arrays"], a["limit_date_ns"],
                                     a["g1_idx"], a["g2_idx"]),
    }
    assert set(fused) == set(individual)
    for rq in individual:
        _assert_results_equal(fused[rq], individual[rq], rq)


def test_fused_suite_matches_pandas_backend(suite_args):
    """Cross-engine parity on the suite surface (the same fields bench.py
    gates on per RQ)."""
    fused = JaxBackend(mesh=None).rq_suite(**suite_args)
    host = PandasBackend().rq_suite(**suite_args)
    eq = np.testing.assert_array_equal
    close = np.testing.assert_allclose
    for f in ("iterations", "total_projects", "detected_counts"):
        eq(getattr(fused["rq1"], f), getattr(host["rq1"], f), err_msg=f)
    eq(fused["rq2cp"].end_i, host["rq2cp"].end_i)
    close(fused["rq2cp"].covered_i, host["rq2cp"].covered_i)
    eq(fused["rq2tr"].counts, host["rq2tr"].counts)
    close(fused["rq2tr"].percentiles, host["rq2tr"].percentiles,
          rtol=2e-5, atol=2e-5)
    eq(fused["rq3"].det_issue_idx, host["rq3"].det_issue_idx)
    close(fused["rq3"].det_diff_percent, host["rq3"].det_diff_percent)
    for f in ("iterations", "g1_total", "g1_detected", "g2_total",
              "g2_detected"):
        eq(getattr(fused["rq4a"], f), getattr(host["rq4a"], f), err_msg=f)
    close(fused["rq4b"].g1_percentiles, host["rq4b"].g1_percentiles)
    close(fused["rq4b"].g2_percentiles, host["rq4b"].g2_percentiles)


def test_suite_fallback_on_empty_study(study_cfg, tmp_path):
    """Degenerate shapes route through the six individual calls (their
    guards), not the fused kernel."""
    from tse1m_tpu.config import Config
    from tse1m_tpu.data.synth import SynthSpec, generate_study
    from tse1m_tpu.db.connection import DB

    cfg = Config(engine="sqlite", sqlite_path=str(tmp_path / "tiny.sqlite"),
                 limit_date="2020-01-01")  # cutoff before any data
    db = DB(config=cfg).connect()
    generate_study(SynthSpec(n_projects=3, days=30, seed=1)).to_db(db)
    arrays = StudyArrays.from_db(db, cfg)
    limit_ns = int(np.datetime64("2020-01-01", "ns").astype(np.int64))
    empty = np.empty(0, dtype=np.int64)
    out = JaxBackend(mesh=None).rq_suite(arrays, limit_ns, 1, empty, empty)
    assert set(out) == {"rq1", "rq2cp", "rq2tr", "rq3", "rq4a", "rq4b"}
    db.closeConnection()


def test_suite_on_mesh_backend_delegates(suite_args):
    """A mesh-bearing backend uses the sequential path (mesh kernels have
    their own collectives) and still returns the full dict."""
    import jax

    from tse1m_tpu.parallel import make_mesh

    if jax.device_count() < 2:
        pytest.skip("needs multi-device")
    be = JaxBackend(mesh=make_mesh(2))
    out = be.rq_suite(**suite_args)
    fused = JaxBackend(mesh=None).rq_suite(**suite_args)
    for rq in out:
        _assert_results_equal(out[rq], fused[rq], rq)
