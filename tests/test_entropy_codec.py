"""Wire v3 entropy codec (cluster/entropy.py + cluster/kernels/rans.py).

Round-trip contract: for every lane width (1..32) and quantization
width, host encode -> host decode and host encode -> DEVICE decode are
elementwise-exact — including empty lanes, single-symbol lanes, and
max-range values.  The win threshold is honest (uniform lanes fall back
to the bit-packed form; the forced path still round-trips), and the CRC
frame refuses a flipped byte before anything ships.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tse1m_tpu.cluster import entropy as ent  # noqa: E402
from tse1m_tpu.cluster.encode import (LaneWire, pack_chunk,  # noqa: E402
                                      pack_delta_meta, pack_lane,
                                      quantize_ids)
from tse1m_tpu.cluster.kernels.rans import decode_lane_device  # noqa: E402

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # property tests degrade to the deterministic suite
    HAVE_HYPOTHESIS = False


def _roundtrip(vals: np.ndarray, bits: int, force: bool = True,
               device: bool = True) -> None:
    lane = ent.encode_lane(vals, bits, force=force)
    if lane is None:
        return
    ent.verify_frame(lane)
    back = ent.decode_lane_host(lane)
    np.testing.assert_array_equal(back, vals.astype(np.uint32).reshape(-1))
    if device:
        arrays = [jnp.asarray(a) for a in lane.wire_arrays()]
        dev = np.asarray(decode_lane_device(lane, arrays))
        np.testing.assert_array_equal(
            dev, vals.astype(np.uint32).reshape(-1))


def _skewed(rng, n: int, bits: int) -> np.ndarray:
    """A geometric-ish lane bounded to the width — the shape the codec
    exists for."""
    v = rng.geometric(0.1, n).astype(np.uint64) % (1 << bits)
    return v.astype(np.uint32)


@pytest.mark.parametrize("bits", [1, 2, 5, 6, 8, 10, 12, 13, 16, 19, 24,
                                  31, 32])
def test_roundtrip_all_widths(bits):
    rng = np.random.default_rng(bits)
    _roundtrip(_skewed(rng, 3001, bits), bits)


@pytest.mark.parametrize("qbits", [8, 10, 16])
def test_roundtrip_quantized_universes(qbits):
    rng = np.random.default_rng(qbits)
    raw = rng.integers(0, 1 << 24, 2048, dtype=np.uint32)
    _roundtrip(quantize_ids(raw, qbits), qbits)


def test_empty_lane():
    lane = ent.encode_lane(np.zeros(0, np.uint32), 7, force=True)
    assert lane.n == 0 and ent.decode_lane_host(lane).size == 0
    arrays = [jnp.asarray(a) for a in lane.wire_arrays()]
    assert np.asarray(decode_lane_device(lane, arrays)).size == 0
    # ...and the honest path never pays for an empty lane
    assert ent.encode_lane(np.zeros(0, np.uint32), 7) is None


def test_single_symbol_lane():
    v = np.full(999, 42, np.uint32)
    lane = ent.encode_lane(v, 6, force=True)
    # one symbol at full table mass: the state never renormalizes, so
    # the word stream is EMPTY — the degenerate-lane rANS shape.
    assert all(p.words.size == 0 for p in lane.planes)
    _roundtrip(v, 6)
    # the honest gate takes it too: ~0 bits/symbol beats any bit width
    assert ent.encode_lane(v, 6) is not None


def test_max_range_values():
    rng = np.random.default_rng(0)
    v = np.concatenate([
        np.full(700, 0xFFFFFFFF, np.uint32), np.zeros(700, np.uint32),
        rng.integers(0, 1 << 32, 700, dtype=np.uint64).astype(np.uint32)])
    _roundtrip(v, 32)


def test_single_value_lane():
    _roundtrip(np.array([5], np.uint32), 3)


def test_win_threshold_is_honest():
    rng = np.random.default_rng(1)
    uniform = rng.integers(0, 64, 4000, dtype=np.uint32)
    # uniform at exactly the packed width: the codec cannot win, auto
    # declines...
    assert ent.encode_lane(uniform, 6) is None
    # ...while a genuinely skewed lane both engages and SHRINKS
    skew = _skewed(rng, 20000, 12)
    lane = ent.encode_lane(skew, 12)
    assert lane is not None
    assert lane.nbytes < ent.packed_nbytes(skew.size, 12)


def test_crc_frame_refuses_flipped_byte():
    rng = np.random.default_rng(2)
    lane = ent.encode_lane(_skewed(rng, 5000, 10), 10, force=True)
    bad = lane.planes[0].words.copy()
    bad[bad.size // 2] ^= np.uint16(0x0100)
    tampered = ent.EntropyLane(
        n=lane.n, bits=lane.bits,
        planes=(ent.PlaneCode(words=bad, x0=lane.planes[0].x0,
                              freqs=lane.planes[0].freqs),)
        + lane.planes[1:], crc=lane.crc)
    with pytest.raises(ent.EntropyFrameError):
        ent.verify_frame(tampered)


def test_pallas_interpret_decoder_matches_host():
    rng = np.random.default_rng(3)
    v = _skewed(rng, 700, 9)
    lane = ent.encode_lane(v, 9, force=True)
    arrays = [jnp.asarray(a) for a in lane.wire_arrays()]
    dev = np.asarray(decode_lane_device(lane, arrays,
                                        use_pallas="interpret"))
    np.testing.assert_array_equal(dev, v)


def test_normalize_freqs_sums_exact_with_floor():
    counts = np.array([1, 0, 10_000_000, 3, 0, 1], np.int64)
    f = ent.normalize_freqs(counts)
    assert int(f.sum()) == 1 << ent.PROB_BITS
    assert (f[counts > 0] >= 1).all() and (f[counts == 0] == 0).all()


def test_pack_lane_and_chunk_integration():
    rng = np.random.default_rng(4)
    skew = _skewed(rng, 8000, 11)
    lane = pack_lane(skew, 11, entropy="auto")
    assert isinstance(lane, LaneWire) and lane.ent is not None
    assert lane.nbytes == lane.ent.nbytes
    assert [a.nbytes for a in lane.wire_arrays()] \
        == [a.nbytes for a in lane.ent.wire_arrays()]
    # chunk form: offset-subtracted symbols, decode adds the bias back
    chunk = (skew.reshape(-1, 8) + np.uint32(1000))
    wire = pack_chunk(chunk, entropy="force")
    assert wire.ent is not None and wire.payload.size == 0
    dec = ent.decode_lane_host(wire.ent).reshape(wire.shape) \
        + np.uint32(wire.offset)
    np.testing.assert_array_equal(dec, chunk)


def test_pack_delta_meta_v3_lane_choice():
    from tse1m_tpu.cluster.encode import encode_delta
    from tse1m_tpu.data.synth import synth_session_sets

    items, _ = synth_session_sets(3000, set_size=64, seed=5)
    enc = encode_delta(items)
    assert enc is not None
    stats: dict = {}
    meta = pack_delta_meta(enc, entropy="auto", stats=stats)
    # counts is the canonically skewed lane (binomial mutation counts):
    # it must engage; whatever engaged must round-trip exactly
    assert meta.counts.ent is not None
    np.testing.assert_array_equal(
        ent.decode_lane_host(meta.counts.ent), enc.counts)
    assert stats.get("entropy_lanes", 0) >= 1
    assert stats.get("entropy_saved_bytes", 0) > 0
    # and the v2 form is still available and unchanged in meaning
    meta2 = pack_delta_meta(enc, entropy="off")
    assert all(lw.ent is None for lw in meta2.lanes())


if HAVE_HYPOTHESIS:

    @settings(max_examples=60, deadline=None)
    @given(st.data())
    def test_roundtrip_property(data):
        bits = data.draw(st.integers(1, 32), label="bits")
        n = data.draw(st.integers(0, 2000), label="n")
        seed = data.draw(st.integers(0, 2**16), label="seed")
        shape = data.draw(st.sampled_from(["uniform", "skewed", "const"]),
                          label="shape")
        rng = np.random.default_rng(seed)
        if shape == "uniform":
            v = rng.integers(0, 1 << bits, n,
                             dtype=np.uint64).astype(np.uint32)
        elif shape == "skewed":
            v = _skewed(rng, n, bits)
        else:
            v = np.full(n, (1 << bits) - 1, np.uint32)
        _roundtrip(v, bits, device=(n <= 600))

else:  # pragma: no cover - environment without hypothesis

    @pytest.mark.skip(reason="property tests need hypothesis "
                             "(pip install tse1m-tpu[test])")
    def test_roundtrip_property():
        ...
