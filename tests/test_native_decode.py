"""Native sqlite decoder vs pandas fallback — byte-for-byte parity.

The C++ decoder (tse1m_tpu/native/decode.cc) replaces the per-cell Python
object churn of Cursor.fetchall for the 1.19M-build extraction stage
(reference hot path rq1_detection_rate.py:192-203).  Its contract is that
StudyArrays built through it are indistinguishable from the pandas path —
asserted here over every table and column, plus oracle tests for the
strict ISO8601 parser and its fall-back-on-anything-else behavior.
"""

from __future__ import annotations

import os
import sqlite3

import numpy as np
import pandas as pd
import pytest

from tse1m_tpu.config import Config
from tse1m_tpu.data import columnar
from tse1m_tpu.data.columnar import StudyArrays
from tse1m_tpu.data.synth import SynthSpec, generate_study
from tse1m_tpu.db.connection import DB
from tse1m_tpu.native import fetch_table


def _native_available() -> bool:
    try:
        from tse1m_tpu import native

        return native._load() is not None
    except Exception:
        return False


needs_native = pytest.mark.skipif(not _native_available(),
                                  reason="native decoder unavailable")


@pytest.fixture(scope="module")
def synth_db(tmp_path_factory):
    d = tmp_path_factory.mktemp("native_db")
    cfg = Config(engine="sqlite", sqlite_path=str(d / "t.sqlite"),
                 limit_date="2026-01-01")
    db = DB(config=cfg).connect()
    study = generate_study(SynthSpec(n_projects=6, days=400, seed=11,
                                     ineligible_fraction=0.0))
    study.to_db(db)
    yield db, cfg
    db.closeConnection()


def _assert_arrays_equal(a: StudyArrays, b: StudyArrays):
    from tse1m_tpu.data.columnar import BytesColumn, CodedColumn

    assert a.projects == b.projects
    for table in ("fuzz", "covb", "issues", "cov"):
        sa, sb = getattr(a, table), getattr(b, table)
        np.testing.assert_array_equal(sa.offsets, sb.offsets, err_msg=table)
        assert sa.columns.keys() == sb.columns.keys()
        for col, va in sa.columns.items():
            vb = sb.columns[col]
            if isinstance(va, BytesColumn) or isinstance(vb, BytesColumn):
                # Both paths must produce the lazy form over an identical
                # arena layout (same row order -> same offsets).
                assert type(va) is type(vb), (table, col)
                np.testing.assert_array_equal(va.arena, vb.arena,
                                              err_msg=f"{table}.{col}.arena")
                np.testing.assert_array_equal(va.starts, vb.starts,
                                              err_msg=f"{table}.{col}.starts")
                np.testing.assert_array_equal(va.lens, vb.lens,
                                              err_msg=f"{table}.{col}.lens")
                continue
            if isinstance(va, CodedColumn) or isinstance(vb, CodedColumn):
                # Both paths must produce the coded form with identical
                # codes AND vocab (factorize first-appearance order ==
                # the native intern order).
                assert type(va) is type(vb), (table, col)
                np.testing.assert_array_equal(va.codes, vb.codes,
                                              err_msg=f"{table}.{col}.codes")
                np.testing.assert_array_equal(va.vocab, vb.vocab,
                                              err_msg=f"{table}.{col}.vocab")
                continue
            assert va.dtype == vb.dtype, (table, col)
            np.testing.assert_array_equal(va, vb, err_msg=f"{table}.{col}")


@needs_native
def test_from_db_native_matches_pandas(synth_db, monkeypatch):
    db, cfg = synth_db
    native = StudyArrays.from_db(db, cfg)
    assert native.native_decode  # the flag bench.py reports must be honest
    monkeypatch.setattr(columnar, "_native_db_path", lambda _db: None)
    fallback = StudyArrays.from_db(db, cfg)
    assert not fallback.native_decode
    _assert_arrays_equal(native, fallback)


@needs_native
def test_iso_parser_matches_pandas_ns(tmp_path):
    p = str(tmp_path / "ts.sqlite")
    con = sqlite3.connect(p)
    con.execute("CREATE TABLE t (ts TEXT)")
    vals = [
        "2023-06-01T04:12:33", "2023-06-02 23:59:59", "2020-02-29T00:00:00",
        "1999-12-31T12:00:00.5", "2023-01-01T01:02:03.123456789",
        "2023-01-01", "1969-07-20T20:17:40", "2038-01-19T03:14:08",
        "2024-12-31T23:59:59.999999",
    ]
    con.executemany("INSERT INTO t VALUES (?)", [(v,) for v in vals])
    con.commit()
    con.close()
    (got,) = fetch_table(p, "SELECT ts FROM t", (), "t", [])
    exp = (pd.to_datetime(pd.Series(vals), format="ISO8601").to_numpy()
           .astype("datetime64[ns]").astype(np.int64))
    np.testing.assert_array_equal(got, exp)


@needs_native
@pytest.mark.parametrize("bad", [
    "2024-01-01T00:00:00+00:00",  # timezone suffix
    "2024-01-01T00:00:00Z",
    "01/02/2024",                 # non-ISO
    "2024-13-01",                 # month out of range
    "2023-02-29T00:00:00",        # day invalid for month (non-leap year)
    "2024-04-31",                 # day invalid for month
    "not a date",
])
def test_iso_parser_rejects_rather_than_guesses(tmp_path, bad):
    p = str(tmp_path / "bad.sqlite")
    con = sqlite3.connect(p)
    con.execute("CREATE TABLE t (ts TEXT)")
    con.execute("INSERT INTO t VALUES (?)", (bad,))
    con.commit()
    con.close()
    with pytest.raises(RuntimeError):
        fetch_table(p, "SELECT ts FROM t", (), "t", [])


@needs_native
def test_from_db_falls_back_on_unparseable_data(synth_db, monkeypatch):
    """A timezone-suffixed timestamp must route the whole fetch through the
    pandas path (which handles it), not crash or mis-parse."""
    db, cfg = synth_db
    baseline = StudyArrays.from_db(db, cfg)
    proj = baseline.projects[0]
    db.execute(
        "INSERT INTO issues (project, number, rts, status, crash_type, "
        "severity, regressed_build, new_id, type) "
        "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
        (proj, 999999, "2024-01-01T00:00:00+00:00", "Fixed",
         "Heap-buffer-overflow", "High", "{}", None, "Bug"),
    )
    db.connection.commit()
    try:
        arrays = StudyArrays.from_db(db, cfg)
        # The tz row itself is present and parsed by pandas semantics.
        assert len(arrays.issues) == len(baseline.issues) + 1
        monkeypatch.setattr(columnar, "_native_db_path", lambda _db: None)
        fallback = StudyArrays.from_db(db, cfg)
        _assert_arrays_equal(arrays, fallback)
    finally:
        db.execute("DELETE FROM issues WHERE number = 999999", ())
        db.connection.commit()


@needs_native
def test_float_column_with_nulls(tmp_path):
    p = str(tmp_path / "f.sqlite")
    con = sqlite3.connect(p)
    con.execute("CREATE TABLE t (k TEXT, v REAL)")
    con.executemany("INSERT INTO t VALUES (?,?)",
                    [("a", 1.5), ("a", None), ("b", 3)])
    con.commit()
    con.close()
    codes, vals = fetch_table(p, "SELECT k, v FROM t", (), "pf", ["a", "b"])
    np.testing.assert_array_equal(codes, np.array([0, 0, 1], np.int32))
    assert vals[0] == 1.5 and np.isnan(vals[1]) and vals[2] == 3.0


@needs_native
def test_interned_and_object_columns(tmp_path):
    p = str(tmp_path / "s.sqlite")
    con = sqlite3.connect(p)
    con.execute("CREATE TABLE t (tag TEXT, num)")
    con.executemany("INSERT INTO t VALUES (?,?)",
                    [("x", 1), ("y", 2.5), ("x", "txt"), (None, None)])
    con.commit()
    con.close()
    tags, nums = fetch_table(p, "SELECT tag, num FROM t", (), "so", [])
    assert tags[0] is tags[2]  # interned: one PyUnicode per distinct value
    assert tags[3] is None
    assert nums[0] == 1 and isinstance(nums[0], int)
    assert nums[1] == 2.5 and isinstance(nums[1], float)
    assert nums[2] == "txt" and nums[3] is None


@needs_native
def test_null_text_cells_parity(tmp_path, monkeypatch):
    """NULL cells in 'b' (lazy bytes) and 'c' (coded) columns must decode
    identically on both paths — including the starts array layout (the
    native scan records start 0 for NULLs; round-4 review caught the
    fallback recording the running offset instead)."""
    from tse1m_tpu.db.schema import create_schema

    cfg = Config(engine="sqlite", sqlite_path=str(tmp_path / "n.sqlite"),
                 limit_date="2026-01-01", min_coverage_days=1)
    db = DB(config=cfg).connect()
    create_schema(db)
    db.executeMany(
        "INSERT INTO buildlog_data (name, project, timecreated, build_type,"
        " result, modules, revisions) VALUES (?,?,?,?,?,?,?)",
        [("b1", "p0", "2024-01-01 10:00:00", "Fuzzing", "Finish",
          '["m1"]', None),
         ("b2", "p0", "2024-01-02 10:00:00", "Fuzzing", "Error", None,
          '["r2"]'),
         ("c1", "p0", "2024-01-01 11:00:00", "Coverage", "Finish", None,
          '["r1"]')])
    db.executeMany(
        "INSERT INTO total_coverage (project, date, coverage, covered_line,"
        " total_line) VALUES (?,?,?,?,?)",
        [("p0", "2024-01-01", 10.0, 1.0, 10.0)])
    native = StudyArrays.from_db(db, cfg, projects=["p0"])
    assert native.native_decode
    monkeypatch.setattr(columnar, "_native_db_path", lambda _db: None)
    fallback = StudyArrays.from_db(db, cfg, projects=["p0"])
    _assert_arrays_equal(native, fallback)
    # NULL semantics through the lazy accessors
    assert native.fuzz.columns["revisions_raw"][0] is None
    assert native.fuzz.columns["modules_raw"][1] is None
    assert fallback.fuzz.columns["modules_raw"][1] is None
    assert native.covb.columns["modules_raw"][0] is None
    db.closeConnection()
