"""Signature kernel family (cluster/schemes.py): per-scheme host/device/
pallas bit-parity, estimator convergence to exact Jaccard within theory
bounds (hypothesis), weighted replica-expansion semantics, mixed-scheme
policy refusals + the absent-key migration default (store, checkpoint,
serve), and the live index's LSM delta band tables."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from tse1m_tpu.cluster import (ClusterParams, adjusted_rand_index,
                               cluster_sessions,
                               cluster_sessions_resumable, host_cluster)
from tse1m_tpu.cluster import incremental as inc
from tse1m_tpu.cluster.host import host_band_keys
from tse1m_tpu.cluster.schemes import (MAX_WEIGHT, SCHEMES, expand_weighted,
                                       get_scheme, make_params,
                                       scheme_hash_evals,
                                       scheme_host_signatures,
                                       scheme_sig_and_keys)
from tse1m_tpu.data.synth import synth_session_hitcounts, synth_session_sets


@pytest.fixture(scope="module")
def corpus():
    return synth_session_sets(1500, seed=3)


def _exact_jaccard(x: np.ndarray, y: np.ndarray) -> float:
    sx, sy = set(x.tolist()), set(y.tolist())
    return len(sx & sy) / len(sx | sy)


# -- bit-parity: host oracle == jax reference == pallas, per scheme ----------

@pytest.mark.parametrize("scheme", SCHEMES)
def test_host_device_pallas_bit_parity(scheme):
    rng = np.random.default_rng(11)
    dense = rng.integers(0, 1 << 24, size=(257, 48), dtype=np.uint32)
    sparse = rng.integers(0, 1 << 24, size=(63, 3), dtype=np.uint32)
    hp = make_params(scheme, 128, 5)
    hpd = hp.device()
    for rows in (dense, sparse):
        host = scheme_host_signatures(rows, hp)
        sig_j, keys_j = scheme_sig_and_keys(jnp.asarray(rows), hpd, 16,
                                            use_pallas="never")
        sig_p, keys_p = scheme_sig_and_keys(jnp.asarray(rows), hpd, 16,
                                            use_pallas="interpret")
        assert np.array_equal(host, np.asarray(sig_j))
        assert np.array_equal(host, np.asarray(sig_p))
        assert np.array_equal(np.asarray(keys_j), np.asarray(keys_p))
        assert np.array_equal(host_band_keys(host, 16),
                              np.asarray(keys_j))


def test_schemes_are_distinct_families():
    rng = np.random.default_rng(2)
    rows = rng.integers(0, 1 << 24, size=(16, 32), dtype=np.uint32)
    sigs = {s: scheme_host_signatures(rows, make_params(s, 64, 0))
            for s in SCHEMES}
    assert not np.array_equal(sigs["kminhash"], sigs["cminhash"])
    assert not np.array_equal(sigs["cminhash"], sigs["weighted"])


def test_kminhash_params_bit_compatible_with_legacy():
    # The kminhash constant stream must equal minhash.make_hash_params
    # exactly — stores/checkpoints written before the registry existed
    # hold signatures of THESE constants.
    from tse1m_tpu.cluster.minhash import make_hash_params

    a, b = make_hash_params(96, 13)
    hp = make_params("kminhash", 96, 13)
    assert np.array_equal(hp.arrays[0], a)
    assert np.array_equal(hp.arrays[1], b)


def test_unknown_scheme_refuses(corpus):
    with pytest.raises(ValueError, match="unknown signature scheme"):
        get_scheme("simhash")
    items, _ = corpus
    with pytest.raises(ValueError, match="unknown signature scheme"):
        cluster_sessions(items[:64], ClusterParams(scheme="simhash"))


def test_hash_eval_accounting():
    assert scheme_hash_evals("kminhash", 1000, 64, 128) == 1000 * 64 * 128
    assert scheme_hash_evals("cminhash", 1000, 64, 128) == 1000 * 64
    ratio = (scheme_hash_evals("kminhash", 1, 64, 128)
             / scheme_hash_evals("cminhash", 1, 64, 128))
    assert ratio == 128


# -- estimator convergence (theory-bound property tests) ---------------------

def _est_error(scheme: str, n_hashes: int, set_size: int, n_shared: int,
               seed: int) -> tuple[float, float]:
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 1 << 24, size=set_size, dtype=np.uint32)
    x = base.copy()
    y = base.copy()
    nm = set_size - n_shared
    if nm:
        y[:nm] = rng.integers(0, 1 << 24, size=nm, dtype=np.uint32)
    j = _exact_jaccard(x, y)
    sig = scheme_host_signatures(np.stack([x, y]),
                                 make_params(scheme, n_hashes, seed))
    return float((sig[0] == sig[1]).mean()), j


@pytest.mark.parametrize("scheme", ["kminhash", "cminhash"])
def test_estimator_converges_to_exact_jaccard(scheme):
    # Mean absolute error over independent seeds stays within the
    # binomial-theory envelope (std/sqrt(trials) head-room x4): the
    # densified one-permutation estimator is unbiased, not just "close".
    h, trials = 256, 24
    errs = []
    for t in range(trials):
        est, j = _est_error(scheme, h, 64, 40, 100 + t)
        errs.append(est - j)
    bound = 4.0 * np.sqrt(0.25 / h) / np.sqrt(trials) + 0.01
    assert abs(float(np.mean(errs))) < bound, (np.mean(errs), bound)


def test_cminhash_densification_sparse_rows_still_estimate():
    # |S| << H: most bins are empty and the estimate rides the
    # densification walk + circulant fallback; it must stay calibrated.
    h, trials = 128, 30
    errs = []
    for t in range(trials):
        est, j = _est_error("cminhash", h, 6, 4, 500 + t)
        errs.append(est - j)
    assert abs(float(np.mean(errs))) < 0.06, np.mean(errs)


try:
    from hypothesis import given, settings, strategies as st

    @settings(deadline=None, max_examples=20)
    @given(n_shared=st.integers(8, 60), seed=st.integers(0, 10_000))
    def test_cminhash_estimate_within_bounds_hypothesis(n_shared, seed):
        h = 256
        est, j = _est_error("cminhash", h, 64, n_shared, seed)
        # Single-pair concentration: 6 sigma of the H-trial binomial
        # plus a small densification allowance — a miscalibrated kernel
        # (the collapsed-donor-map bug this suite exists to catch)
        # misses this by an order of magnitude.
        assert abs(est - j) <= 6.0 * np.sqrt(max(j * (1 - j), 0.01) / h) \
            + 0.04, (est, j)
except ImportError:  # pragma: no cover - environment without hypothesis
    @pytest.mark.skip(reason="property tests need hypothesis")
    def test_cminhash_estimate_within_bounds_hypothesis():
        pass


# -- weighted minwise --------------------------------------------------------

def test_expand_weighted_semantics():
    items = np.array([[10, 20, 30]], np.uint32)
    w = np.array([[2, 0, 12]], np.uint32)  # 0 clips to 1, 12 clips to 8
    out = expand_weighted(items, w)
    assert out.shape == (1, 2 + 1 + MAX_WEIGHT)
    from tse1m_tpu.cluster.schemes import _REPLICA_MULT

    m = int(_REPLICA_MULT)
    want = {(10 * m) & 0xFFFFFFFF, (10 * m + 1) & 0xFFFFFFFF,
            (20 * m) & 0xFFFFFFFF} | {
        (30 * m + r) & 0xFFFFFFFF for r in range(MAX_WEIGHT)}
    assert set(out[0].tolist()) == want


def test_expand_weighted_padding_is_signature_neutral():
    # Rows pad with duplicates of their own first replica; duplicates
    # never move a min, so signatures of [row] and [row + pad] agree.
    rng = np.random.default_rng(5)
    items = rng.integers(0, 1 << 24, size=(1, 16), dtype=np.uint32)
    w = rng.integers(1, MAX_WEIGHT + 1, size=(1, 16), dtype=np.uint32)
    exp = expand_weighted(items, w)
    padded = np.concatenate([exp, exp[:, :1].repeat(7, axis=1)], axis=1)
    hp = make_params("weighted", 128, 0)
    assert np.array_equal(scheme_host_signatures(exp, hp),
                          scheme_host_signatures(padded, hp))


def test_weighted_estimator_matches_weighted_jaccard():
    rng = np.random.default_rng(9)
    ids = rng.integers(0, 1 << 24, size=40, dtype=np.uint32)
    wx = rng.integers(1, MAX_WEIGHT + 1, size=40)
    wy = wx.copy()
    wy[:10] = rng.integers(1, MAX_WEIGHT + 1, size=10)
    jw = np.minimum(wx, wy).sum() / np.maximum(wx, wy).sum()
    rows = expand_weighted(np.stack([ids, ids]), np.stack([wx, wy]))
    sig = scheme_host_signatures(rows, make_params("weighted", 512, 3))
    est = float((sig[0] == sig[1]).mean())
    assert abs(est - jw) <= 6.0 * np.sqrt(jw * (1 - jw) / 512) + 0.04


def test_synth_hitcounts_cluster_profile(corpus):
    items, truth = corpus
    w = synth_session_hitcounts(items, truth, seed=1)
    assert w.shape == items.shape and w.dtype == np.uint32
    assert w.min() >= 1 and w.max() <= MAX_WEIGHT
    # members of one planted cluster share the count profile
    lab = truth[0]
    members = np.flatnonzero(truth == lab)
    if members.size >= 2:
        agree = (w[members[0]] == w[members[1]]).mean()
        assert agree >= 0.8, agree


def test_weighted_cluster_end_to_end(corpus):
    items, truth = corpus
    w = synth_session_hitcounts(items, truth, seed=2)
    rows = expand_weighted(items, w)
    prm = ClusterParams(scheme="weighted", prefilter="off")
    labels = cluster_sessions(rows, prm)
    assert adjusted_rand_index(labels, truth) > 0.9
    host = host_cluster(rows[:400], scheme="weighted")
    dev = cluster_sessions(rows[:400], prm)
    assert adjusted_rand_index(dev, host) == 1.0


# -- policy plumbing: store, checkpoint, serve -------------------------------

def _store_run(tmp_path, scheme: str, n: int = 600):
    items, truth = synth_session_sets(n, seed=4)
    if scheme == "weighted":
        items = expand_weighted(
            items, synth_session_hitcounts(items, truth, seed=4))
    store = str(tmp_path / "store")
    prm = ClusterParams(scheme=scheme, sig_store=store)
    labels = cluster_sessions(items, prm)
    return items, labels, store, prm


@pytest.mark.parametrize("other", ["cminhash", "weighted"])
def test_mixed_scheme_store_refuses(tmp_path, other):
    items, _, store, _ = _store_run(tmp_path, "kminhash")
    with pytest.raises(ValueError, match="scheme"):
        cluster_sessions(items, ClusterParams(scheme=other,
                                              sig_store=store))


def test_legacy_store_manifest_opens_as_kminhash(tmp_path):
    from tse1m_tpu.cluster.store import SignatureStore

    items, labels, store, prm = _store_run(tmp_path, "kminhash")
    # Simulate a pre-scheme store: strip the key the old code never wrote.
    mpath = os.path.join(store, "store_manifest.json")
    with open(mpath, encoding="utf-8") as f:
        manifest = json.load(f)
    assert manifest["policy"]["scheme"] == "kminhash"  # explicit on write
    manifest["policy"].pop("scheme")
    with open(mpath, "w", encoding="utf-8") as f:
        json.dump(manifest, f)
    # kminhash opens (migration default) and the warm run still matches.
    warm = cluster_sessions(items, prm)
    assert np.array_equal(warm, labels)
    with open(mpath, encoding="utf-8") as f:
        rewritten = json.load(f)
    assert rewritten["policy"]["scheme"] == "kminhash"
    # ...but a cminhash open refuses on the (defaulted) scheme key.
    with pytest.raises(ValueError, match="scheme"):
        SignatureStore(store, {"n_hashes": prm.n_hashes, "seed": prm.seed,
                               "quant_bits": 0, "scheme": "cminhash"})


def test_checkpoint_scheme_refusal_and_migration(tmp_path):
    items, _ = synth_session_sets(400, seed=6)
    ck = str(tmp_path / "ckpt")
    prm = ClusterParams(scheme="kminhash", prefilter="off")
    labels = cluster_sessions_resumable(items, prm, checkpoint_dir=ck,
                                        cleanup=False)
    with pytest.raises(ValueError, match="scheme"):
        cluster_sessions_resumable(items,
                                   ClusterParams(scheme="cminhash",
                                                 prefilter="off"),
                                   checkpoint_dir=ck, cleanup=False)
    # Legacy manifest (no scheme key) resumes under kminhash.
    mpath = os.path.join(ck, "manifest.json")
    with open(mpath, encoding="utf-8") as f:
        manifest = json.load(f)
    manifest.pop("scheme")
    with open(mpath, "w", encoding="utf-8") as f:
        json.dump(manifest, f)
    resumed = cluster_sessions_resumable(items, prm, checkpoint_dir=ck)
    assert np.array_equal(resumed, labels)


def test_serve_daemon_adopts_store_scheme(tmp_path):
    from tse1m_tpu.serve.daemon import ServeDaemon

    items, labels, store, _ = _store_run(tmp_path, "cminhash")
    daemon = ServeDaemon(store)  # default params say kminhash
    try:
        assert daemon.params.scheme == "cminhash"
        assert daemon.store.policy["scheme"] == "cminhash"
        # Query known rows: answers come from the committed state and
        # must match the batch run's labels elementwise.
        r = daemon.query(items[:128])
        assert np.array_equal(np.asarray(r["labels"]), labels[:128])
        # Novel-vector path host-MinHashes under the adopted scheme —
        # a mutated member must land in its cluster, same as batch.
        mut = items[64:65].copy()
        mut[0, -1] ^= np.uint32(1)
        q = daemon.query(mut)["labels"][0]
        cold = cluster_sessions(np.concatenate([items, mut]),
                                ClusterParams(scheme="cminhash"))
        assert q == cold[-1] or q == -1 and cold[-1] == items.shape[0]
    finally:
        daemon.stop(commit=False)


# -- LiveClusterIndex LSM delta band tables ----------------------------------

def _mini_index_rows(n: int, seed: int):
    items, _ = synth_session_sets(n, seed=seed, set_size=24)
    hp = make_params("kminhash", 64, 0)
    sigs = scheme_host_signatures(items, hp)
    keys = host_band_keys(sigs, 8)
    return items, sigs, keys


def _absorb_all(index, sigs, keys, batch: int):
    gather = lambda uniq: sigs[uniq]  # noqa: E731
    for lo in range(0, sigs.shape[0], batch):
        index = index.absorb(keys[lo:lo + batch], sigs[lo:lo + batch],
                             gather, 64, 0.5)
    return index


def test_live_index_delta_runs_accumulate_and_consolidate(monkeypatch):
    monkeypatch.setenv("TSE1M_LIVE_DELTA_RUNS", "3")
    _, sigs, keys = _mini_index_rows(400, 8)
    index = inc.LiveClusterIndex.empty(8)
    seen_runs = 0
    gather = lambda uniq: sigs[uniq]  # noqa: E731
    for lo in range(0, 400, 80):
        index = index.absorb(keys[lo:lo + 80], sigs[lo:lo + 80],
                             gather, 64, 0.5)
        seen_runs = max(seen_runs, len(index.band_deltas))
    assert seen_runs >= 1          # deltas actually used
    assert len(index.band_deltas) < 3   # ...and consolidation fired
    # Consolidated view == ground-truth tables over all keys.
    bk, br = index.band_tables()
    want_bk, want_br = inc.build_band_tables(keys)
    for b in range(8):
        assert np.array_equal(bk[b], want_bk[b])
        assert np.array_equal(br[b], want_br[b])


def test_live_index_delta_labels_match_batch(monkeypatch):
    items, sigs, keys = _mini_index_rows(300, 9)
    monkeypatch.setenv("TSE1M_LIVE_DELTA_RUNS", "100")  # never consolidate
    with_deltas = _absorb_all(inc.LiveClusterIndex.empty(8), sigs, keys, 30)
    monkeypatch.setenv("TSE1M_LIVE_DELTA_RUNS", "1")    # always consolidate
    consolidated = _absorb_all(inc.LiveClusterIndex.empty(8), sigs, keys, 30)
    assert with_deltas.band_deltas and not consolidated.band_deltas
    assert np.array_equal(with_deltas.labels, consolidated.labels)
    # Batch-level contract: absorb == cold host clustering, elementwise.
    cold = host_cluster(items, n_hashes=64, n_bands=8, seed=0)
    assert np.array_equal(with_deltas.labels.astype(np.int64), cold)


def test_live_index_delta_query_parity(monkeypatch):
    items, sigs, keys = _mini_index_rows(300, 10)
    monkeypatch.setenv("TSE1M_LIVE_DELTA_RUNS", "100")
    deltas = _absorb_all(inc.LiveClusterIndex.empty(8), sigs, keys, 30)
    monkeypatch.setenv("TSE1M_LIVE_DELTA_RUNS", "1")
    solid = _absorb_all(inc.LiveClusterIndex.empty(8), sigs, keys, 30)
    # Novel query vectors (mutations of index rows) answer identically
    # whether their band keys land in the base table or a delta run.
    mut = items[::17].copy()
    mut[:, 0] ^= np.uint32(3)
    hp = make_params("kminhash", 64, 0)
    qs = scheme_host_signatures(mut, hp)
    qk = host_band_keys(qs, 8)
    gather = lambda uniq: sigs[uniq]  # noqa: E731
    a = deltas.query_labels(qs, qk, gather, 64, 0.5)
    b = solid.query_labels(qs, qk, gather, 64, 0.5)
    assert np.array_equal(a, b)
