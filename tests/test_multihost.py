"""Multi-host layer (parallel/multihost.py) in its single-process
degenerate form — the same contract the driver's virtual-device dryrun
exercises.  True multi-process runs can't be simulated in one pytest
process (jax.distributed wants one controller per process), so these tests
pin the invariants that make single- and multi-process behavior coincide:
contiguous process row-dealing, sharded global assembly, and collective
parity with the unsharded oracle.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tse1m_tpu.parallel import multihost
from tse1m_tpu.parallel.mesh import detection_hist_sharded, pad_to_devices


def test_initialize_from_env_noop_without_config(monkeypatch):
    monkeypatch.delenv("TSE1M_COORDINATOR", raising=False)
    monkeypatch.delenv("TSE1M_NUM_PROCESSES", raising=False)
    assert multihost.initialize_from_env() is False
    assert jax.process_count() == 1


def test_local_row_range_partitions_exactly():
    # Single-process: the full range.
    assert multihost.local_row_range(101) == (0, 101)
    # The dealing rule itself (what each process would compute): contiguous,
    # disjoint, covering, remainder on the last process.
    for n_rows, nproc in [(101, 4), (8, 8), (5, 8), (0, 3), (1000, 7)]:
        per = -(-n_rows // nproc) if n_rows else 0
        spans = []
        for pid in range(nproc):
            start = min(pid * per, n_rows)
            spans.append((start, min(start + per, n_rows)))
        assert spans[0][0] == 0
        assert spans[-1][1] == n_rows
        for (a, b), (c, d) in zip(spans, spans[1:]):
            assert b == c  # contiguous and disjoint


def test_put_process_local_roundtrip():
    mesh = multihost.global_mesh()
    n = 8 * 5
    lo, hi = multihost.local_row_range(n)
    data = np.arange(n * 3, dtype=np.int32).reshape(n, 3)
    arr = multihost.put_process_local(data[lo:hi], n, mesh)
    assert arr.shape == (n, 3)
    np.testing.assert_array_equal(np.asarray(arr), data)
    # Actually sharded over the mesh, not replicated.
    assert len(arr.sharding.device_set) == mesh.devices.size


def test_sharded_hist_on_process_local_array_matches_oracle():
    mesh = multihost.global_mesh()
    rng = np.random.default_rng(5)
    n = 8 * 123
    iters = rng.integers(0, 50, size=n).astype(np.int32)
    lo, hi = multihost.local_row_range(n)
    arr = multihost.put_process_local(iters[lo:hi], n, mesh)
    got = np.asarray(detection_hist_sharded(arr, 40, mesh))
    exp = np.bincount(iters[(iters >= 1) & (iters <= 40)],
                      minlength=41)[1:]
    np.testing.assert_array_equal(got, exp)


def test_all_processes_ready_noop_single_process():
    multihost.all_processes_ready("test")  # must not raise or block


def test_cluster_sessions_accepts_presharded_global_array():
    """The multi-host feeding path: a pre-sharded jax.Array (assembled via
    put_process_local) must cluster identically to the numpy-input mesh
    path."""
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.data.synth import synth_session_sets

    mesh = multihost.global_mesh()
    n = 8 * 40
    items, _ = synth_session_sets(n, set_size=16, seed=9)
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
    lo, hi = multihost.local_row_range(n)
    arr = multihost.put_process_local(
        np.ascontiguousarray(items[lo:hi], dtype=np.uint32), n, mesh)
    got = cluster_sessions(arr, params, mesh=mesh)
    want = cluster_sessions(items, params, mesh=mesh)
    np.testing.assert_array_equal(got, want)


def test_cluster_sessions_rejects_unpadded_presharded():
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.data.synth import synth_session_sets

    mesh = multihost.global_mesh()
    items, _ = synth_session_sets(8 * 3 + 1, set_size=16, seed=9)
    arr = jnp.asarray(items.astype(np.uint32))
    with pytest.raises(ValueError, match="padded"):
        cluster_sessions(arr, ClusterParams(use_pallas="never"), mesh=mesh)
