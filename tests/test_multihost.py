"""Multi-host layer (parallel/multihost.py) in its single-process
degenerate form — the same contract the driver's virtual-device dryrun
exercises.  True multi-process runs can't be simulated in one pytest
process (jax.distributed wants one controller per process), so these tests
pin the invariants that make single- and multi-process behavior coincide:
contiguous process row-dealing, sharded global assembly, and collective
parity with the unsharded oracle.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tse1m_tpu.parallel import multihost
from tse1m_tpu.parallel.mesh import detection_hist_sharded, pad_to_devices


def test_initialize_from_env_noop_without_config(monkeypatch):
    monkeypatch.delenv("TSE1M_COORDINATOR", raising=False)
    monkeypatch.delenv("TSE1M_NUM_PROCESSES", raising=False)
    assert multihost.initialize_from_env() is False
    assert jax.process_count() == 1


def _deal(n_rows: int, nproc: int, ldev: int):
    """The device-aligned dealing rule local_row_range implements, for an
    arbitrary (nproc, local-device-count) topology."""
    n_dev = nproc * ldev
    per_dev = -(-n_rows // n_dev) if n_rows else 0
    spans = []
    for pid in range(nproc):
        start = min(pid * ldev * per_dev, n_rows)
        spans.append((start, min(start + ldev * per_dev, n_rows)))
    return spans


def test_local_row_range_partitions_exactly():
    # Single-process: the full range.
    assert multihost.local_row_range(101) == (0, 101)
    # The dealing rule: contiguous, disjoint, covering, remainder at the
    # tail — for divisible and non-divisible row counts alike.
    for n_rows, nproc, ldev in [(101, 4, 2), (8, 8, 1), (5, 8, 1),
                                (0, 3, 2), (1000, 7, 3), (397, 2, 4)]:
        spans = _deal(n_rows, nproc, ldev)
        assert spans[0][0] == 0
        assert spans[-1][1] == n_rows
        for (a, b), (c, d) in zip(spans, spans[1:]):
            assert b == c  # contiguous and disjoint


def test_local_row_range_is_device_aligned():
    """ADVICE round 3: jax lays a NamedSharding out ceil-per-DEVICE, so a
    process owning >1 device must span its devices' blocks — n=10 on
    2 procs x 2 devices is [0,6) + [6,10), NOT the per-process ceil [0,5)."""
    assert _deal(10, 2, 2) == [(0, 6), (6, 10)]
    # jax's own shard layout for that case: ceil(10/4)=3 rows per device.
    per_dev = -(-10 // 4)
    dev_rows = [(min(i * per_dev, 10), min((i + 1) * per_dev, 10))
                for i in range(4)]
    assert dev_rows == [(0, 3), (3, 6), (6, 9), (9, 10)]
    # process 0 = devices 0-1, process 1 = devices 2-3
    assert _deal(10, 2, 2)[0] == (dev_rows[0][0], dev_rows[1][1])
    assert _deal(10, 2, 2)[1] == (dev_rows[2][0], dev_rows[3][1])


def test_padded_row_count_and_padded_put_roundtrip():
    mesh = multihost.global_mesh()
    n = 101  # not a multiple of the 8-device mesh
    n_pad = multihost.padded_row_count(n, mesh)
    assert n_pad == 104 and n_pad % mesh.devices.size == 0
    lo, hi = multihost.local_row_range(n_pad)
    data = np.arange(n * 3, dtype=np.int32).reshape(n, 3)
    arr, got_pad = multihost.put_process_local_padded(
        data[lo:min(hi, n)], n, mesh)
    assert got_pad == n_pad
    assert arr.shape == (n_pad, 3)
    out = np.asarray(arr)
    np.testing.assert_array_equal(out[:n], data)
    assert (out[n:] == 0).all()


def test_padded_put_rejects_wrong_slice():
    mesh = multihost.global_mesh()
    data = np.zeros((7, 2), np.int32)  # not rows [0, 101) of anything
    with pytest.raises(ValueError, match="must feed rows"):
        multihost.put_process_local_padded(data, 101, mesh)


def test_cluster_sessions_any_n_via_padded_put():
    """End-to-end: a non-mesh-multiple study clusters identically through
    the padded pre-sharded path and the plain host path."""
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.data.synth import synth_session_sets

    mesh = multihost.global_mesh()
    n = 8 * 40 + 3
    items, _ = synth_session_sets(n, set_size=16, seed=9)
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
    lo, hi = multihost.local_row_range(multihost.padded_row_count(n, mesh))
    arr, _ = multihost.put_process_local_padded(
        np.ascontiguousarray(items[lo:min(hi, n)], dtype=np.uint32), n, mesh)
    got = cluster_sessions(arr, params, mesh=mesh)[:n]
    want = cluster_sessions(items, params, mesh=mesh)
    np.testing.assert_array_equal(got, want)


def test_put_process_local_roundtrip():
    mesh = multihost.global_mesh()
    n = 8 * 5
    lo, hi = multihost.local_row_range(n)
    data = np.arange(n * 3, dtype=np.int32).reshape(n, 3)
    arr = multihost.put_process_local(data[lo:hi], n, mesh)
    assert arr.shape == (n, 3)
    np.testing.assert_array_equal(np.asarray(arr), data)
    # Actually sharded over the mesh, not replicated.
    assert len(arr.sharding.device_set) == mesh.devices.size


def test_sharded_hist_on_process_local_array_matches_oracle():
    mesh = multihost.global_mesh()
    rng = np.random.default_rng(5)
    n = 8 * 123
    iters = rng.integers(0, 50, size=n).astype(np.int32)
    lo, hi = multihost.local_row_range(n)
    arr = multihost.put_process_local(iters[lo:hi], n, mesh)
    got = np.asarray(detection_hist_sharded(arr, 40, mesh))
    exp = np.bincount(iters[(iters >= 1) & (iters <= 40)],
                      minlength=41)[1:]
    np.testing.assert_array_equal(got, exp)


def test_all_processes_ready_noop_single_process():
    multihost.all_processes_ready("test")  # must not raise or block


def test_cluster_sessions_accepts_presharded_global_array():
    """The multi-host feeding path: a pre-sharded jax.Array (assembled via
    put_process_local) must cluster identically to the numpy-input mesh
    path."""
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.data.synth import synth_session_sets

    mesh = multihost.global_mesh()
    n = 8 * 40
    items, _ = synth_session_sets(n, set_size=16, seed=9)
    params = ClusterParams(n_hashes=32, n_bands=4, use_pallas="never")
    lo, hi = multihost.local_row_range(n)
    arr = multihost.put_process_local(
        np.ascontiguousarray(items[lo:hi], dtype=np.uint32), n, mesh)
    got = cluster_sessions(arr, params, mesh=mesh)
    want = cluster_sessions(items, params, mesh=mesh)
    np.testing.assert_array_equal(got, want)


def test_cluster_sessions_rejects_unpadded_presharded():
    from tse1m_tpu.cluster import ClusterParams, cluster_sessions
    from tse1m_tpu.data.synth import synth_session_sets

    mesh = multihost.global_mesh()
    items, _ = synth_session_sets(8 * 3 + 1, set_size=16, seed=9)
    arr = jnp.asarray(items.astype(np.uint32))
    with pytest.raises(ValueError, match="padded"):
        cluster_sessions(arr, ClusterParams(use_pallas="never"), mesh=mesh)
