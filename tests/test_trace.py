"""graftrace: deterministic schedule exploration, the Eraser-style
lockset detector, and the regression schedules for the races the plane
surfaced in the existing tree.

The load-bearing claims:

- the explorer drives >= 200 distinct seeded schedules over the
  daemon's ingest-absorb-swap vs. query vs. reader-refresh critical
  sections with elementwise label parity and snapshot monotonicity on
  EVERY schedule (plus bounded-exhaustive interleavings);
- `SignatureStore.refresh()` racing `_push_delta` consolidation and
  eviction always shows a whole committed generation, never a torn
  probe index — and a PLANTED two-phase index publication (the old
  code's shape) is caught by the explorer with a replayable schedule;
- a planted unlocked write is caught by the lockset detector with both
  stacks, and the pre-fix `StageRecorder.as_dict` (unlocked dict read
  racing the producer thread's `add`) is the regression that
  previously failed;
- every schedule failure prints a ``v1:fix:...`` string that replays
  the exact interleaving.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from tse1m_tpu.cluster.store import SignatureStore, _IndexSnapshot
from tse1m_tpu.observability import StageRecorder, pop_degradation_events
from tse1m_tpu.trace import (RaceError, Schedule, ScheduleError, traced,
                             shared_access, trace_point)
from tse1m_tpu.trace.explore import (_store_scenario, explore, replay,
                                     run_scenario)

# One realized interleaving per scenario, committed for the CI
# ``schedule-replay`` fault-matrix seat (tests/ci_fault_matrix.py
# replays them in a subprocess; this module proves they stay valid).
ADVERSARIAL_SCHEDULES = {
    "serve": "v1:fix:q,r,w,q,w,r,q,w,r,q,w,q,r,w,r,q",
    "store": "v1:fix:rp,rr,w,rp,w,rr,rp,w,rr,rp,w,rr,rp,w,rp",
}


# -- schedule strings ---------------------------------------------------------

def test_schedule_string_roundtrip():
    s = Schedule.pct(123, depth=5)
    assert Schedule.from_string(s.to_string()).to_string() == \
        "v1:pct:123:5"
    f = Schedule.fixed(["w", "q", "w"])
    assert Schedule.from_string(f.to_string()).choices == ("w", "q", "w")
    with pytest.raises(ValueError):
        Schedule.from_string("v2:what")
    with pytest.raises(ValueError):
        Schedule("rr")


def test_scheduler_is_deterministic_and_replayable():
    """Same schedule -> identical realized decisions; the realized fix
    schedule replays the exact interleaving."""
    def build(tmp):
        log: list = []

        def body(name):
            def run():
                for i in range(3):
                    trace_point(f"{name}.{i}")
                    log.append(name)
            return run

        return ({"a": body("a"), "b": body("b"), "c": body("c")},
                lambda: None)

    outs = [run_scenario("serve", Schedule.pct(7), build=build)
            for _ in range(2)]
    assert outs[0].decisions == outs[1].decisions
    assert len(outs[0].decisions) > 0
    fixed = run_scenario("serve", Schedule.fixed(outs[0].decisions),
                         build=build)
    assert fixed.decisions == outs[0].decisions


# -- lockset: planted races are caught, fixed code is clean ------------------

class _UnlockedCounter:
    """Planted bug: instrumented shared write with no lock."""

    def __init__(self) -> None:
        self.n = 0

    def bump(self) -> None:
        shared_access(self, "n", write=True)
        self.n += 1


def _on_thread(fn) -> None:
    t = threading.Thread(target=fn)
    t.start()
    t.join()


def test_lockset_catches_planted_unlocked_write():
    with traced(raise_on_race=False) as tr:
        c = _UnlockedCounter()
        _on_thread(c.bump)
        _on_thread(c.bump)
    races = tr.lockset.races
    assert len(races) == 1
    r = races[0]
    assert r.name == "_UnlockedCounter.n"
    assert "NO locks" in str(r.current)
    assert r.previous is not None  # both access sites reported
    assert "test_trace.py" in r.current.site
    with pytest.raises(RaceError):
        with traced():
            c2 = _UnlockedCounter()
            _on_thread(c2.bump)
            _on_thread(c2.bump)


class _OldStageRecorder(StageRecorder):
    """The PRE-FIX ``as_dict``: iterates the live dicts without the
    lock while the producer thread adds — the unlocked read graftrace
    flagged in the real tree (fixed in observability/__init__.py)."""

    def as_dict(self) -> dict:
        shared_access(self, "stages", write=False)  # no lock held
        out: dict = {}
        for name in sorted(self.wall):
            out[f"stage_{name}_s"] = round(self.wall[name], 4)
        return out


def _stage_recorder_regression(rec: StageRecorder) -> list:
    """The regression schedule that previously failed: producer-thread
    adds interleaved with reader-thread dict reads."""
    with traced(raise_on_race=False) as tr:
        _on_thread(lambda: rec.add("h2d", 0.1, 1024))
        rec.as_dict()
        _on_thread(lambda: rec.add("encode", 0.2, 512))
        rec.as_dict()
    return tr.lockset.races


def test_stage_recorder_unlocked_read_regression():
    old = _stage_recorder_regression(_OldStageRecorder())
    assert old and old[0].name == "_OldStageRecorder.stages"
    # the fixed recorder under the exact same schedule: no race
    assert _stage_recorder_regression(StageRecorder()) == []


def test_latency_and_slo_layer_lockset_clean():
    """Audit of the remaining ISSUE suspects: LatencyRecorder bucket
    updates and the SLO counters are lock-consistent under traced()."""
    from tse1m_tpu.observability.latency import LatencyRecorder
    from tse1m_tpu.serve.slo import (AdmissionController, SloPolicy,
                                     SloTracker)

    with traced() as tr:
        lat = LatencyRecorder("audit")
        pol = SloPolicy(max_backlog_batches=2, query_p99_target_ms=0.001)
        adm = AdmissionController(pol)
        trk = SloTracker(pol)

        def hammer(seed: int):
            def run():
                for i in range(50):
                    lat.add(0.001 * ((seed + i) % 7))
                    lat.snapshot()
                    adm.try_admit((seed + i) % 4)
                    adm.stats()
                    trk.observe_query(0.5)
                    trk.stats()
                lat.reset_window()
            return run

        threads = [threading.Thread(target=hammer(s)) for s in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert tr.lockset.races == []
    pop_degradation_events()  # drop the backpressure/SLO events we made


def test_admission_transition_atomic_under_schedules():
    """The consolidated try_admit: under EVERY small-bound interleaving
    of reject/admit/reject the backpressure transition fires once per
    serialized admit->reject boundary (1 or 2 events), never zero,
    and the layer stays lockset-clean."""
    from tse1m_tpu.serve.slo import AdmissionController, SloPolicy

    def build(tmp):
        pop_degradation_events()
        adm = AdmissionController(SloPolicy(max_backlog_batches=4))
        results: dict = {}

        def reject(name):
            def run():
                results[name] = adm.try_admit(9)[0]
            return run

        def admit():
            results["a"] = adm.try_admit(0)[0]

        def validate():
            events = [e for e in pop_degradation_events()
                      if e["kind"] == "serve_backpressure"]
            assert results["r1"] is False and results["r2"] is False
            assert results["a"] is True
            assert 1 <= len(events) <= 2, events
            assert adm.stats()["ingest_backlog_max"] == 9

        return ({"r1": reject("r1"), "a": admit, "r2": reject("r2")},
                validate)

    stats = explore("serve", n_seeded=20, exhaustive_bound=6,
                    build=build)
    assert stats["trace_races_found"] == 0


# -- the explorer over the real serve/store planes ---------------------------

def test_explore_serve_200_seeded_schedules_parity_and_monotonicity():
    """The acceptance bar: >= 200 distinct seeded schedules over the
    ingest-absorb-swap / query / refresh interleaving, every one with
    elementwise label parity against the cold host clustering of each
    published generation and non-decreasing snapshot generations."""
    stats = explore("serve", n_seeded=205, exhaustive_bound=4)
    assert stats["trace_schedules_explored"] >= 200
    assert stats["trace_races_found"] == 0
    assert stats["trace_distinct_traces"] >= 8


def test_store_refresh_racing_consolidation_and_eviction():
    """SignatureStore.refresh() racing _push_delta consolidation (the
    delta threshold is forced to 2, so adoption consolidates inside the
    explored window) and LRU eviction: probes always see a whole
    committed generation."""
    stats = explore("store", n_seeded=40, exhaustive_bound=4)
    assert stats["trace_races_found"] == 0
    evict = explore("store-evict", n_seeded=30, exhaustive_bound=3)
    assert evict["trace_races_found"] == 0


class _TornRefreshStore(SignatureStore):
    """The PRE-FIX ``refresh()`` adoption: one snapshot swap per added
    shard (emulated by publishing each delta run as it is built), so a
    concurrent probe can observe the newest shard without its
    predecessors — a store view no manifest generation ever committed.
    This is exactly the bug the explorer surfaced in the real tree;
    the fix batches the runs into ONE swap per refresh."""

    def _delta_index_for(self, sid, keys2d):
        run = super()._delta_index_for(sid, keys2d)
        snap = self._snap
        self._snap = _IndexSnapshot(snap.base, snap.deltas + (run,))
        trace_point("store.index.torn-adopt")  # the pre-fix window
        return run


def test_planted_torn_index_publication_is_caught_and_replays():
    build = lambda tmp: _store_scenario(tmp, evict=True,  # noqa: E731
                                        reader_cls=_TornRefreshStore)
    with pytest.raises(ScheduleError) as ei:
        # PCT catch probability per seed is a few percent here (the
        # window is one yield wide); the first catching seed is 167
        explore("store-evict", n_seeded=200, exhaustive_bound=4,
                build=build)
    msg = str(ei.value)
    # either detection is the planted bug: a probe observing a store
    # view no manifest ever committed, or the adoption window crashing
    # on a shard the writer evicted mid-refresh
    assert "torn index" in msg or "No such file" in msg
    assert "replay: v1:fix:" in msg
    # the printed schedule string replays the exact failing interleaving
    replay_str = ei.value.schedule_str
    assert replay_str.startswith("v1:fix:")
    with pytest.raises(ScheduleError):
        run_scenario("store-evict", Schedule.from_string(replay_str),
                     build=build)
    # and the REAL store under the same schedule is torn-free
    run_scenario("store-evict", Schedule.from_string(replay_str))


def test_committed_adversarial_schedules_stay_green():
    """The strings the CI ``schedule-replay`` seat replays must hold on
    the current tree (and stay parseable)."""
    for scenario, s in ADVERSARIAL_SCHEDULES.items():
        out = replay(s, scenario)
        assert out.races == 0


def test_schedule_failure_carries_replay_string():
    def build(tmp):
        def boom():
            trace_point("boom")
            raise ValueError("planted failure")

        return ({"a": boom, "b": lambda: None}, lambda: None)

    with pytest.raises(ScheduleError) as ei:
        run_scenario("serve", Schedule.pct(3), build=build)
    assert "planted failure" in str(ei.value)
    assert "replay: v1:fix:" in str(ei.value)


def test_traced_does_not_nest():
    with traced():
        with pytest.raises(RuntimeError):
            with traced():
                pass


def test_deadlock_detection_reports_schedule():
    """Two scheduled threads taking two traced locks in opposite orders
    deadlock under some interleaving; the scheduler reports it (with
    the replay string) instead of hanging."""
    from tse1m_tpu.trace import sync as tsync

    def build(tmp):
        l1, l2 = tsync.Lock("l1"), tsync.Lock("l2")

        def ab():
            with l1:
                trace_point("ab.mid")
                with l2:
                    pass

        def ba():
            with l2:
                trace_point("ba.mid")
                with l1:
                    pass

        return ({"ab": ab, "ba": ba}, lambda: None)

    with pytest.raises(ScheduleError) as ei:
        # the bounded-exhaustive enumeration finds the inversion
        # deterministically (no luck involved)
        explore("serve", n_seeded=0, exhaustive_bound=6, build=build)
    assert "deadlock" in str(ei.value)
    assert ei.value.schedule_str.startswith("v1:fix:")
